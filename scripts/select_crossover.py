#!/usr/bin/env python
"""Measure the select_k dispatch crossover: hardware lax.top_k vs the
tournament network (VERDICT r4 #4: >= 2x at n=256k, k in {1024, 4096}).
Emits the crossover table for BASELINE.md.

Run: python scripts/select_crossover.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.bench.harness import scan_qps_time
from raft_tpu.matrix.select_k import _select_k, _tournament_topk


def time_impl(fn, x, k):
    # roll the row axis so every scan iteration sees distinct data
    def step(xx, _ops):
        v, i = fn(xx, k, True)
        return v, i

    return scan_qps_time(step, x, n1=2, n2=8, operands=None)


def main():
    print(f"devices: {jax.devices()}", flush=True)
    rows = []
    key = jax.random.PRNGKey(0)
    for n, m in ((262_144, 64), (65_536, 256)):
        x = jax.random.normal(key, (m, n), jnp.float32)
        jax.block_until_ready(x)
        for k in (256, 1024, 4096):
            if k * 8 > n:
                continue
            t_top = time_impl(_select_k, x, k)
            t_trn = time_impl(_tournament_topk, x, k)
            rows.append({
                "n": n, "m": m, "k": k,
                "top_k_ms": round(t_top * 1e3, 2),
                "tournament_ms": round(t_trn * 1e3, 2),
                "speedup": round(t_top / t_trn, 2),
            })
            print(rows[-1], flush=True)
    with open("SELECT_CROSSOVER_r05.json", "w") as f:
        json.dump(rows, f, indent=1)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()
