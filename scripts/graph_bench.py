#!/usr/bin/env python
"""Graph-build measurement battery (ISSUE 15; artifact GRAPH_r{N}.json).

Measures the nn-descent rebuild against the pre-r15 formulation on the
CURRENT host, honestly labeled (CPU today; rerun on chip day — the
stage is wired into scripts/r5_measure_all.py as ``graph_bench``):

1. **A/B: gather-then-sample vs sample-then-gather** — the old
   iteration materialized the FULL two-hop tensor ``graph[pool]``
   (``[n, 2K, K]`` int32) before sampling S columns; the rebuild
   samples first and gathers only the ``[n, S]`` chosen entries. The
   two are *algebraically identical* (same columns of the same
   tensor), so the graphs agree bitwise and the comparison is pure
   wall-clock + bytes — recall is equal by construction (asserted).
2. **Blocked 1M-row build** — wall clock + KNN-graph recall of the
   new blocked path at the ROADMAP-item-7 scale, with the analytic
   per-iteration transient columns showing the peak is bounded by
   ``graph_join_rows``, not n; one old-formulation iteration is timed
   at the same scale for the headline ratio (capped: at 1M/K=96 the
   old tensor alone is ~73 GB, beyond most hosts).

Usage:
  python scripts/graph_bench.py [out.json] [--n 1000000] [--dim 64]
      [--degree 32] [--iters 6] [--ab-n 100000] [--skip-big]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _old_iter_fn():
    """The pre-r15 iteration (gather-then-sample, unblocked), kept
    HERE — not in the library — purely as the measured baseline."""
    import functools

    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.nn_descent import (
        _make_rev,
        _merge_topk_unique,
        _score,
    )

    @functools.partial(jax.jit, static_argnums=(3, 4, 5))
    def old_iter(state, data, norms, K: int, S: int, ip: bool, key=None):
        graph_d, graph_i = state
        n = data.shape[0]
        node_ids = jnp.arange(n, dtype=jnp.int32)
        rev_i = _make_rev(graph_i)
        pool = jnp.concatenate([graph_i, rev_i], axis=1)
        pool_safe = jnp.maximum(pool, 0)
        cols = jax.random.randint(key, (S,), 0, 2 * K * K)
        two_hop = graph_i[pool_safe]                     # [n, 2K, K]
        cand = two_hop.reshape(n, 2 * K * K)[:, cols]    # [n, S]
        cand = jnp.where(
            jnp.take_along_axis(
                pool, jnp.broadcast_to(cols[None, :] // K, (n, S)), axis=1
            ) >= 0,
            cand, -1,
        )
        cand = jnp.concatenate([cand, rev_i], axis=1)
        cand = jnp.where(cand == node_ids[:, None], -1, cand)
        cand_d = _score(node_ids, jnp.maximum(cand, 0), data, norms, ip)
        cand_d = jnp.where(cand < 0, jnp.inf, cand_d)
        new_d, new_i = _merge_topk_unique(graph_d, graph_i, cand_d, cand, K)
        return (new_d, new_i), jnp.sum(new_i != graph_i)

    return old_iter


def _new_iter(state, data, norms, K, S, ip, key, block):
    """One rebuild iteration through the library's blocked join."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.nn_descent import (
        _blocked,
        _join_block,
        _make_rev,
    )

    graph_d, graph_i = state
    n = data.shape[0]
    rev_i = _make_rev(graph_i)
    pool = jnp.concatenate([graph_i, rev_i], axis=1)
    cols = jax.random.randint(key, (S,), 0, 2 * K * K)
    parts = _blocked(
        lambda s, r: _join_block(data, norms, graph_d, graph_i, pool,
                                 rev_i, cols, s, rows=r, ip=ip,
                                 impl="xla", tile_b=0),
        n, block,
    )
    gd = jnp.concatenate([p[0] for p in parts], axis=0)
    gi = jnp.concatenate([p[1] for p in parts], axis=0)
    return (gd, gi), sum(p[2] for p in parts)


def _transient_columns(n, K, S, d, block):
    """Analytic per-iteration transient bytes (the bound the blocked
    rebuild enforces): old = the full two-hop tensor; new = one block's
    sampled ids + gathered candidate vectors + merge pool."""
    C = S + K
    old = n * (2 * K) * K * 4                    # [n, 2K, K] int32
    rows = min(n, block)
    new = rows * S * 4 + rows * C * d * 4 + rows * C * 8 \
        + rows * (C + K) * 8                     # ids + gather + merge pool
    return {
        "old_two_hop_bytes": int(old),
        "new_block_transient_bytes": int(new),
        "new_bound": "graph_join_rows block (%d rows), independent of n"
                     % rows,
    }


def ab_stage(results, n, d, K, S, iters, seed=3, data=None):
    """Old vs new, iteration-for-iteration on identical state: same
    keys, bitwise-identical graphs (asserted), wall clock compared
    (first iteration carries the compile — recorded, excluded from the
    medians)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu import tuning
    from raft_tpu.neighbors.nn_descent import _blocked, _init_block

    rng = np.random.default_rng(seed)
    if data is None:
        data = rng.standard_normal((n, d)).astype(np.float32)
    data = jnp.asarray(data)
    norms = jnp.sum(data * data, axis=1)
    key = jax.random.PRNGKey(0)
    key, k0 = jax.random.split(key)
    init_i = jax.random.randint(k0, (n, K), 0, n).astype(jnp.int32)
    init_i = jnp.where(init_i == jnp.arange(n)[:, None], (init_i + 1) % n,
                       init_i)
    block = int(tuning.budget("graph_join_rows", 1 << 16))
    parts = _blocked(
        lambda s, r: _init_block(data, norms, init_i, s, rows=r,
                                 ip=False), n, block)
    state0 = (jnp.concatenate([p[0] for p in parts]),
              jnp.concatenate([p[1] for p in parts]))
    jax.block_until_ready(state0)

    old_iter = _old_iter_fn()
    keys = []
    kk = key
    for _ in range(iters):
        kk, kit = jax.random.split(kk)
        keys.append(kit)

    def run(step):
        state = state0
        t_iters = []
        for kit in keys:
            t0 = time.perf_counter()
            state, _ = step(state, kit)
            jax.block_until_ready(state)
            t_iters.append(time.perf_counter() - t0)
        return state, t_iters

    state_new, t_new = run(
        lambda st, kit: _new_iter(st, data, norms, K, S, False, kit,
                                  block))
    state_old, t_old = run(
        lambda st, kit: old_iter(st, data, norms, K, S, False, key=kit))
    same = bool((np.asarray(state_old[1]) == np.asarray(state_new[1]))
                .all())
    # steady-state per-iteration medians (first iteration carries the
    # compile; keep it in the recorded lists, exclude from the median)
    med_old = float(np.median(t_old[1:])) if iters > 1 else t_old[0]
    med_new = float(np.median(t_new[1:])) if iters > 1 else t_new[0]
    results["ab"] = {
        "n": n, "d": d, "K": K, "S": S, "iters": iters,
        "bitwise_identical_graphs": same,
        "iter_s_old": [round(t, 3) for t in t_old],
        "iter_s_new": [round(t, 3) for t in t_new],
        "iter_s_old_median": round(med_old, 3),
        "iter_s_new_median": round(med_new, 3),
        "speedup_old_over_new": round(med_old / max(med_new, 1e-9), 2),
        **_transient_columns(n, K, S, d, block),
    }
    return same


def big_stage(results, n, d, degree, iters, ab_iters=2, seed=4):
    """The ROADMAP-item-7 scale, two measurements:

    * ``iter_ab`` — the per-iteration old-vs-new A/B at the FULL scale
      (``ab_iters`` iterations each, compile-carrying first iteration
      recorded but excluded from the medians; graphs asserted bitwise
      identical). At n=1M/K=48 the old path's two-hop tensor is
      ~18.4 GB *per iteration* — the thing sample-then-gather deletes.
    * ``build`` — the rebuilt blocked build end to end: wall clock +
      KNN-graph recall at ``iters`` iterations (nn-descent needs
      ~O(log n) rounds to localize from random init — at 1M, ~6 rounds
      is still noise; pick iters from a convergence sweep)."""
    from raft_tpu import tuning
    from raft_tpu.bench.run import generate_groundtruth
    from raft_tpu.neighbors import nn_descent

    rng = np.random.default_rng(seed)
    # clustered blobs (the shape the repo's graph suites use — 2026-08-04
    # measured: a flat 16-intrinsic-dim manifold at this scale converges
    # at only ~0.04 recall/iteration from random init, a pre-existing
    # property of the sampled pull-join shared bitwise by old AND new
    # paths; blobs localize in ~10 rounds, so the build column reports a
    # converged graph instead of an iteration-budget artifact)
    centers = rng.uniform(-5, 5, (1024, d)).astype(np.float32)
    x = (centers[rng.integers(0, 1024, n)]
         + 0.6 * rng.standard_normal((n, d)).astype(np.float32))
    K = max(degree * 3 // 2, degree)
    S = 128
    sub_results = {}
    try:
        ab_stage(sub_results, n, d, K, S, ab_iters, seed=seed + 1,
                 data=x)
        results["iter_1m"] = sub_results["ab"]
    except Exception as e:  # noqa: BLE001 - OOM at scale IS the result
        results["iter_1m"] = {
            "iter_s_old": f"DNF: {type(e).__name__}: {str(e)[:160]}"}

    params = nn_descent.IndexParams(
        graph_degree=degree, max_iterations=iters)
    t0 = time.perf_counter()
    idx = nn_descent.build(params, x)
    g = np.asarray(idx.graph)                    # sync
    build_s = time.perf_counter() - t0
    sub = 200
    want = np.asarray(generate_groundtruth(
        x, x[:sub], degree + 1, "sqeuclidean", chunk=1_000_000))
    rec = float(np.mean(
        [len(set(g[i]) & set(want[i][1:degree + 1])) / degree
         for i in range(sub)]))
    block = int(tuning.budget("graph_join_rows", 1 << 16))
    results["build"] = {
        "n": n, "d": d, "graph_degree": degree, "K": K, "S": S,
        "iters": iters, "build_s_new": round(build_s, 1),
        "recall_at_degree": round(rec, 4),
        **_transient_columns(n, K, S, d, block),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("out", nargs="?", default="GRAPH_r15.json")
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--degree", type=int, default=32)
    ap.add_argument("--iters", type=int, default=14)
    ap.add_argument("--ab-n", type=int, default=100_000)
    ap.add_argument("--ab-iters", type=int, default=4)
    ap.add_argument("--big-ab-iters", type=int, default=2)
    ap.add_argument("--skip-big", action="store_true")
    args = ap.parse_args()

    import jax

    results = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "note": "old-vs-new are algebraically identical (bitwise-equal "
                "graphs), so recall is equal by construction and the "
                "comparison is wall-clock + transient bytes only",
    }
    t0 = time.time()
    K = max(args.degree * 3 // 2, args.degree)
    ok = ab_stage(results, args.ab_n, args.dim, K, 128, args.ab_iters)
    if not ok:
        results["ab"]["warning"] = "graphs diverged — investigate before " \
                                   "trusting the timing columns"
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)          # flush the A/B early
    if not args.skip_big:
        big_stage(results, args.n, args.dim, args.degree, args.iters,
                  args.big_ab_iters)
    results["elapsed_s"] = round(time.time() - t0, 1)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))
    return 0


if __name__ == "__main__":
    from raft_tpu.core.exit_guard import guarded_exit

    try:
        rc = main()
    except SystemExit as e:
        rc = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
    except BaseException:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        rc = 1
    guarded_exit(rc)
