#!/usr/bin/env python
"""Capture the per-backend dispatch table (the measurement artifact the
reference generates with cpp/scripts/heuristics/select_k and bakes into
matrix/detail/select_k-inl.cuh:51-79).

Times the competing implementations behind every tuned hot-path
dispatch — select_k / merge_topk (lax.top_k vs tournament vs
hierarchical), ivf_scan (fused Pallas kernel vs XLA bucketized scan),
ivf_scan_extract (in-kernel extraction arms incl. the unextracted
fold), fused_topk_tile (brute-force scan vs fused kernel per
variant/row-tile), pq_scan (i8/i4/pq4/rabitq cache kinds — the rabitq
arm races its whole rerank pipeline at matched recall, and arms that
cannot hit the recall band are filtered before timing),
graph_join (nn-descent local join: XLA einsum+merge vs the fused
kernel per node tile, ISSUE 15), beam_step_tile (the beam kernel's
query-tile geometry over real packed rows), and
serve_service (per-(bucket, probe-rung) end-to-end service medians the
serve deadline machinery reads, ISSUE 14) — over a shape
grid, plus the environment byte budgets, and writes
``raft_tpu/tuning/tables/<backend>.json``. Consumers pick these
winners up automatically through ``raft_tpu.tuning.choose`` (knob:
``RAFT_TPU_TUNING``; docs/dispatch_tuning.md).

Run on CPU today (committed table), re-run the moment a TPU answers —
it is part of the r5+ measurement battery (scripts/r5_measure_all.py).

    python scripts/capture_dispatch_tables.py                # quick grid
    python scripts/capture_dispatch_tables.py --full         # wide grid
    python scripts/capture_dispatch_tables.py --out /path.json
    python scripts/capture_dispatch_tables.py --ops select_k,merge_topk
    python scripts/capture_dispatch_tables.py --interpret    # time the
        # pallas kernel in interpret mode on CPU (debug-only numbers)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="output path (default: the packaged "
                         "raft_tpu/tuning/tables/<backend>.json)")
    ap.add_argument("--backend", default=None,
                    help="override the table's backend name")
    ap.add_argument("--full", action="store_true",
                    help="wide grid (quick grid is the default)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--ops", default=None,
                    help="comma list: select_k,merge_topk,ivf_scan,"
                         "pq_scan,ivf_scan_extract,fused_topk_tile,"
                         "graph_join,beam_step_tile,serve_service "
                         "(kernel arms need a TPU, or --interpret on "
                         "CPU). A subset capture MERGES into the "
                         "existing table at --out instead of "
                         "clobbering the other ops' entries")
    ap.add_argument("--interpret", action="store_true",
                    help="on CPU, also time the Pallas kernels in "
                         "interpret mode (debug-only numbers)")
    ap.add_argument("--deadline", type=float, default=1500.0,
                    help="wall-clock budget (s) for the capture incl. "
                         "one transient retry (resilience.run)")
    args = ap.parse_args(argv)

    import jax

    from raft_tpu import resilience, tuning
    from raft_tpu.tuning import microbench

    backend = args.backend or tuning.backend_name()
    print(f"devices: {jax.devices()}  backend table: {backend}",
          flush=True)
    # resilience wrap: a transient blip (tunnel reset mid-grid) costs one
    # classified retry inside --deadline instead of the whole capture;
    # OOM/fatal failures still propagate straight to the exit guard
    table = resilience.run(
        microbench.capture,
        backend=backend,
        quick=not args.full,
        include_interpret=args.interpret,
        reps=args.reps,
        ops=args.ops.split(",") if args.ops else None,
        retries=1,
        backoff_s=15,
        deadline_s=args.deadline,
        retry_on=(resilience.TRANSIENT,),
    )
    out = args.out or os.path.join(tuning.tables_dir(), backend + ".json")
    if args.ops and os.path.exists(out):
        # subset re-capture (e.g. --ops serve_service after the serve
        # layer grows a rung): fold the fresh entries into the existing
        # table — a partial capture must never throw away the other
        # ops' measured winners
        from raft_tpu.tuning.table import DispatchTable

        prior = DispatchTable.load(out)
        prior.data["captured"] = table.data["captured"]
        prior.data["device"] = table.data["device"]
        for op, body in table.data["ops"].items():
            prior.data["ops"][op] = body
        prior.data["budgets"].update(table.data["budgets"])
        table = prior
    table.save(out)
    print(f"wrote {out}: ops={table.ops()} entries={table.n_entries()} "
          f"budgets={table.data['budgets']}", flush=True)
    return 0


if __name__ == "__main__":
    # dead-backend exit guard (VERDICT next-round #7): terminate rc-clean
    # even when the axon plugin's exit-time teardown would hang — including
    # on exception paths (argparse SystemExit, mid-capture crashes)
    from raft_tpu.core.exit_guard import guarded_exit

    try:
        rc = main()
    except SystemExit as e:
        rc = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
    except BaseException:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        rc = 1
    guarded_exit(rc)
