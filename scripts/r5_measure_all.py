#!/usr/bin/env python
"""Round-5 measurement battery: run EVERYTHING the verdict asks for in
value-per-minute order, each stage in its own subprocess with a hard
timeout, artifacts written incrementally — so a partial TPU window still
captures the most important numbers (the round-4 outage taught that
lesson: a full battery staged behind one long build captured nothing).

Stages (artifact, rough budget):
  1. probe            — TPU reachable? (fast-fail JSON if not)
  2. bench.py         — BENCH_r05_local.json   (~45 min, headline configs)
  3. deep100m         — DEEP100M_r05.json      (~30 min total at 100M)
  4. r4_sweep         — SWEEP_r05.json         (~25 min, flat+cagra levers)
  5. latency_table    — LATENCY_r05.json       (~10 min, batch 1/10/100)
  6. select_crossover — SELECT_CROSSOVER_r05.json (~10 min)
  7. dispatch_tables  — raft_tpu/tuning/tables/tpu.json (~15 min)

Run: python scripts/r5_measure_all.py [--only stage1,stage2] [--skip ...]
                                      [--obs-snapshot] [--serve]

--serve appends the optional graft-serve load-generator stage
(scripts/serve_loadgen.py -> SERVE_r05.json; docs/serving.md §7).
Progress + per-stage rc stream to stdout and R5_MEASURE_STATUS.json.

--obs-snapshot runs every stage instrumented (RAFT_TPU_OBS=flight in the
child env, flight dumps under OBS_r05/) and asks bench.py for its
BENCH_r05_local.obs.json metrics sidecar — each artifact then carries
the dispatch winners, latency histograms, and retry/ladder counters that
explain it (docs/observability.md).
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def probe(timeout=120):
    sys.path.insert(0, ROOT)
    from raft_tpu.bench.harness import probe_tpu

    return probe_tpu(timeout)


STAGES = [
    # (name, argv, timeout_s)
    ("bench", [PY, "bench.py"], 5400),
    ("deep100m", [PY, "scripts/deep100m.py", "DEEP100M_r05.json"], 4200),
    ("sweep", [PY, "scripts/r4_sweep.py", "both"], 3600),
    # graph rung (ISSUE 15): nn-descent rebuild A/B (sample-then-gather
    # vs the old full-two-hop gather, bitwise-identical graphs) + the
    # 1M-row blocked build with bounded per-iteration transients —
    # GRAPH_r{N}.json re-captured at chip service times
    ("graph_bench", [PY, "scripts/graph_bench.py", "GRAPH_r15.json"],
     3600),
    ("latency", [PY, "scripts/latency_table.py"], 1800),
    ("crossover", [PY, "scripts/select_crossover.py"], 1800),
    # per-backend dispatch table (select/merge/scan winners + budgets):
    # writes raft_tpu/tuning/tables/tpu.json the instant a chip answers —
    # commit the artifact so tuning.choose serves measured winners
    ("dispatch_tables",
     [PY, "scripts/capture_dispatch_tables.py", "--full"], 1800),
]

# OPTIONAL stages (run with --serve, or name them in --only): the
# graft-serve closed-loop load generator — SERVE_r05.json latency/
# throughput sidecar + obs metrics snapshot (docs/serving.md §7) —
# and the multi-host fabric loadgen — FABRIC_r06.json (QPS, p99,
# coverage, hedges, dropouts; docs/serving.md §10)
OPTIONAL_STAGES = [
    ("serve_loadgen",
     [PY, "scripts/serve_loadgen.py", "--n", "200000", "--dim", "96",
      "--algo", "ivf_flat", "--concurrency", "32", "--duration-s", "60",
      "--k", "1,10,100", "--out", "SERVE_r05.json",
      "--obs-snapshot", "SERVE_r05.obs.json"], 900),
    ("fabric_loadgen",
     [PY, "scripts/serve_loadgen.py", "--fabric", "--n", "120000",
      "--dim", "96", "--fabric-workers", "4",
      "--fabric-replication", "2", "--concurrency", "16",
      "--duration-s", "45", "--k", "1,10,100",
      "--out", "FABRIC_r06.json",
      "--obs-snapshot", "FABRIC_r06.obs.json"], 900),
    # graft-trace acceptance (ISSUE 13): chaos fabric loadgen with the
    # tracing A/B (off-vs-on QPS recorded in FABRIC_r13.json), per-stage
    # waterfall columns, and the federated fleet snapshot archived under
    # OBS_r13/ (JSON + Prometheus text; flight dumps land there too when
    # the battery runs --obs-snapshot)
    ("fabric_trace",
     [PY, "scripts/serve_loadgen.py", "--fabric", "--n", "120000",
      "--dim", "96", "--fabric-workers", "4",
      "--fabric-replication", "2", "--concurrency", "8",
      "--duration-s", "45", "--k", "1,10,100",
      "--fault", "dead@proc:2,slow@proc:1*3", "--swap-mid-run",
      "--ab-obs", "--out", "FABRIC_r13.json",
      "--federate-out", "OBS_r13/FEDERATED_r13.json",
      "--obs-snapshot", "FABRIC_r13.obs.json"], 1200),
    # graft-plan acceptance (ISSUE 20): compiled-plan serving vs the
    # legacy library dispatch at identical batch shapes (QPS/recall/
    # retrace columns + bitwise verdict), plus the hybrid dense+sparse
    # score_fuse plan served end-to-end vs a fused numpy oracle
    ("plan_ab",
     [PY, "scripts/serve_loadgen.py", "--plan-ab", "--n", "20000",
      "--dim", "64", "--n-lists", "16", "--k", "10",
      "--query-pool", "256", "--max-batch-rows", "32",
      "--duration-s", "10", "--out", "PLAN_r20.json"], 900),
    # graft-helm acceptance (ISSUE 18): the self-healing chaos curve —
    # primary-vs-p2c balancer A/B at matched topology, then a scripted
    # slow/flap/permanent-dead schedule under the HelmController with a
    # low/high/low traffic ramp; coverage timeline, repair latency,
    # autoscale trace, and bitwise oracle checks land in FABRIC_r18.json
    ("fabric_helm",
     [PY, "scripts/serve_loadgen.py", "--chaos-curve", "--n", "60000",
      "--dim", "64", "--fabric-workers", "4",
      "--fabric-replication", "2", "--concurrency", "16",
      "--duration-s", "15", "--k", "1,10,100",
      "--out", "FABRIC_r18.json",
      "--obs-snapshot", "FABRIC_r18.obs.json"], 1200),
    # tiered-memory acceptance (ISSUE 12, ROADMAP item 3): host/mmap
    # originals + shortlist-only fetch vs the full-upload baseline,
    # then a Zipf(1.0) serve run whose hot-row hit-rate / zero-retrace
    # columns merge into the same artifact
    ("tiered_deep100m",
     [PY, "scripts/deep100m.py", "--tiered-only", "--n", "1000000",
      "--tiered-out", "TIERED_r12.json"], 2700),
    # SLO acceptance (ISSUE 14, ROADMAP item 5): the closed-loop
    # deadline harness — calibrate capacity, hold the p99 target under
    # 1x and 2x overload with adaptive probe rungs, recall band vs the
    # exhaustive baseline, mean probed-list reduction. Flags match the
    # committed SLO_r14.json so the stage REPRODUCES the artifact (on
    # chip day the same run re-captures it at TPU service times)
    ("slo_loadgen",
     [PY, "scripts/serve_loadgen.py", "--slo-p99-ms", "250",
      "--n", "20000", "--dim", "64", "--n-lists", "16", "--k", "10",
      "--query-pool", "512", "--max-batch-rows", "8",
      "--max-wait-ms", "2", "--concurrency", "8", "--duration-s", "10",
      "--out", "SLO_r14.json"], 1200),
    # flags match the committed SERVE_TIERED_r12.json exactly, so the
    # stage REPRODUCES the artifact (result cache off on purpose: with
    # it on, repeats never reach the engine and the hot-ROW tier idles
    # at ~0.4 hit rate — the result cache's own under-load evidence is
    # the r12 run recorded in CHANGES.md and tests/test_tiered.py)
    ("tiered_serve_zipf",
     [PY, "scripts/serve_loadgen.py", "--n", "20000", "--dim", "96",
      "--tiered", "--zipf", "1.0", "--query-pool", "256",
      "--refine-ratio", "3", "--result-cache", "0",
      "--hot-rows", "16384", "--max-batch-rows", "16",
      "--concurrency", "8", "--duration-s", "30", "--k", "1,10",
      "--out", "SERVE_TIERED_r12.json",
      "--merge-into", "TIERED_r12.json"], 1200),
    # graft-gauge acceptance (ISSUE 19, ROADMAP item 9): the closed-
    # loop quality drill — a loose-margin retune-recovery leg (seeded
    # serve_probe_margin/floor budgets, bounded tighten steps walk the
    # pooled Wilson estimate back inside the band), then a crippled
    # n_probes=1 hot-swap the probation window convicts and rolls
    # back. Flags match the committed QUALITY_r19.json so the stage
    # REPRODUCES the artifact (zero-retrace columns re-checked at TPU
    # service times on chip day)
    ("quality_drift",
     [PY, "scripts/serve_loadgen.py", "--drift", "--n", "1024",
      "--dim", "16", "--n-lists", "16", "--k", "8",
      "--query-pool", "256", "--duration-s", "30", "--seed", "7",
      "--out", "QUALITY_r19.json"], 1200),
    # graft-flow acceptance (ISSUE 16): serial vs pipelined memmap
    # tiered rerank under injected slow fetch — wall-clock speedup,
    # stall totals, overlap fraction, bitwise verdict (PIPE_r16.json;
    # on chip day the score-side injection is dropped and the overlap
    # hides real device scan time)
    ("pipeline",
     [PY, "scripts/deep100m.py", "--pipeline-only", "--n", "1000000",
      "--pipeline-out", "PIPE_r16.json"], 2700),
]


def main():
    sys.path.insert(0, ROOT)
    from raft_tpu import resilience

    only = skip = None
    if "--only" in sys.argv:
        only = set(sys.argv[sys.argv.index("--only") + 1].split(","))
    if "--skip" in sys.argv:
        skip = set(sys.argv[sys.argv.index("--skip") + 1].split(","))
    obs_on = "--obs-snapshot" in sys.argv
    child_env = None
    if obs_on:
        # children self-instrument in flight mode: a stage that dies with
        # a classified fatal/dead_backend leaves its flight JSONL under
        # OBS_r05/ even when its artifact never materialized
        child_env = dict(os.environ,
                         RAFT_TPU_OBS="flight",
                         RAFT_TPU_OBS_DIR=os.path.join(ROOT, "OBS_r05"))
    status = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              "stages": {}, "obs": bool(obs_on)}

    def flush():
        with open(os.path.join(ROOT, "R5_MEASURE_STATUS.json"), "w") as f:
            json.dump(status, f, indent=1)

    ok, detail = probe()
    status["tpu_probe"] = {"ok": ok, "detail": detail}
    flush()
    if not ok:
        print(f"TPU unreachable: {detail}", flush=True)
        return 1
    print(f"TPU up: {detail}", flush=True)

    stages = list(STAGES)
    if "--serve" in sys.argv or (
            only is not None
            and any(n in only for n, _, _ in OPTIONAL_STAGES)):
        stages += OPTIONAL_STAGES
    for name, argv, tmo in stages:
        if only is not None and name not in only:
            continue
        if skip is not None and name in skip:
            continue
        t0 = time.time()
        stage_argv = list(argv)
        if obs_on and argv[1] == "bench.py":
            stage_argv += ["--obs-snapshot", "BENCH_r05_local.obs.json"]
        print(f"=== {name}: {' '.join(stage_argv)} (timeout {tmo}s)",
              flush=True)

        # resilience wrap: the subprocess timeout is the HARD per-stage
        # bound (a wedged stage cannot eat the battery); resilience.run
        # adds ONE classified retry for transient-looking failures under
        # a per-stage wall-clock deadline, so a blip (UNAVAILABLE,
        # connection reset) costs one rerun instead of the stage
        def _attempt():
            r = subprocess.run(stage_argv, timeout=tmo, cwd=ROOT,
                               capture_output=True, env=child_env)
            if r.returncode != 0:
                tail = (r.stdout + r.stderr).decode(errors="replace")[-4000:]
                if resilience.classify_text(tail) == resilience.TRANSIENT:
                    raise resilience.TransientError(
                        f"{name}: rc={r.returncode}, transient tail")
            return r

        try:
            r = resilience.run(_attempt, retries=1, backoff_s=30,
                               deadline_s=tmo * 1.5,
                               retry_on=(resilience.TRANSIENT,))
            out = r.stdout.decode(errors="replace")
            err = r.stderr.decode(errors="replace")
            status["stages"][name] = {
                "rc": r.returncode, "s": round(time.time() - t0, 1),
                "tail": (out + err)[-2000:],
            }
            # bench.py prints its JSON line to stdout — persist it, and
            # thread its roofline columns (peak_fraction / bytes_per_row
            # per op, docs/kernels.md §roofline) into the stage summary
            # so the battery's status file answers "how close to the
            # hardware ceiling" without opening the artifact
            if name == "bench" and r.returncode == 0:
                last = [ln for ln in out.splitlines() if ln.startswith("{")]
                if last:
                    with open(os.path.join(ROOT, "BENCH_r05_local.json"),
                              "w") as f:
                        f.write(last[-1] + "\n")
                    try:
                        extra = json.loads(last[-1]).get("extra", {})
                        status["stages"][name]["roofline"] = {
                            kk: vv.get("value", vv)
                            if isinstance(vv, dict) else vv
                            for kk, vv in extra.items()
                            if kk.endswith(("_peak_fraction",
                                            "_bytes_per_row"))
                        }
                    except (ValueError, KeyError):
                        pass
            print(f"--- {name}: rc={r.returncode} "
                  f"{round(time.time() - t0, 1)}s", flush=True)
            print((out + err)[-1500:], flush=True)
        except subprocess.TimeoutExpired:
            status["stages"][name] = {"rc": "timeout", "s": tmo}
            print(f"--- {name}: TIMEOUT after {tmo}s", flush=True)
        except resilience.ResilienceError as e:
            # retry budget/deadline exhausted: record and move on — one
            # flaky stage must not abort the rest of the battery
            status["stages"][name] = {
                "rc": f"resilience:{type(e).__name__}",
                "s": round(time.time() - t0, 1), "tail": str(e)[-2000:],
            }
            print(f"--- {name}: {type(e).__name__}: {e}", flush=True)
        flush()
        # between stages, re-probe: if the TPU died mid-battery, stop
        # burning stage timeouts on a dead backend
        ok, detail = probe(60)
        if not ok:
            status["aborted"] = f"tpu lost after {name}: {detail}"
            flush()
            print(status["aborted"], flush=True)
            return 1
    flush()
    print("battery complete", flush=True)
    return 0


if __name__ == "__main__":
    # dead-backend exit guard (VERDICT next-round #7): with axon installed
    # but unreachable, plain sys.exit hangs in the plugin's atexit client
    # teardown and the caller reads rc=124 instead of the probe's rc=1.
    # Exception paths (argparse SystemExit, crashes mid-battery) must hit
    # the guard too, or the hang recurs exactly when things go wrong.
    sys.path.insert(0, ROOT)
    from raft_tpu.core.exit_guard import guarded_exit

    try:
        rc = main()
    except SystemExit as e:
        rc = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
    except BaseException:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        rc = 1
    guarded_exit(rc)
