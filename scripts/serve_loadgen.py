#!/usr/bin/env python
"""Closed-loop load generator for the graft-serve engine (ISSUE 5)
and the multi-host fabric (ISSUE 6, ``--fabric``).

Builds an index, stands up a :class:`raft_tpu.serve.Server`, and drives
it with ``--concurrency`` worker threads in closed loop (each worker
submits, waits, submits again) or at a target open-loop ``--qps``;
requests draw k uniformly from the mixed ``--k`` list and optionally
carry a delete/upsert mutation mix. Emits a latency/throughput sidecar
(default ``SERVE_r05.json``):

    {"config": {...}, "throughput_qps": ..., "completed": ...,
     "rejected": ..., "latency_ms": {"p50": ..., "p90": ..., "p99": ...},
     "per_k": {...}, "server": {...}}

``--obs-snapshot PATH`` additionally turns graft-scope on and writes the
full metrics snapshot (queue depth, per-bucket fill/latency histograms,
admission rejects, swap counts — docs/serving.md §7) next to it.

``--fabric`` stands up a :class:`raft_tpu.serve.Fabric` (N worker
processes owning index shards, docs/serving.md §10) instead of the
single-process Server and drives ``fab.search`` directly, emitting a
``FABRIC_r13.json`` sidecar (QPS, latency percentiles, per-row
coverage, hedge/retry/dropout counters, worker health — plus the
graft-trace columns, ISSUE 13: per-stage p50/p99 waterfall attribution
for queue_wait / rpc / worker_scan / merge / rerank, hedge-win counts
per stage, and the complete-waterfall fraction). ``--fault`` installs
a process-level fault spec (e.g. ``slow@proc:1*50``) in the workers so
degraded-mode numbers are measurable on demand. ``--ab-obs`` measures
the tracing-overhead A/B the acceptance bar (<5% on-mode overhead)
reads from the artifact: three swap-free probe legs (off / on / off,
fresh fabrics, half duration each — the off bracket cancels machine
drift) before the main instrumented run. ``--federate-out``
scrapes every worker's metrics registry through the
``collect_metrics`` RPC at the end of the run and archives the merged
fleet snapshot (JSON + Prometheus text).

``--plan-ab`` runs the graft-plan acceptance A/B (ISSUE 20,
docs/plans.md): the compiled-plan serving path vs the legacy library
dispatch it replaced, at identical batch shapes on the same
ivf_pq/rabitq index — QPS / recall@k / steady-state retrace columns
plus the bitwise verdict, then the hybrid dense+sparse ``score_fuse``
plan served end-to-end through the batcher against a fused numpy
oracle. Emits ``PLAN_r20.json`` and exits non-zero if any acceptance
bar fails.

Wired as the optional ``serve_loadgen`` / ``fabric_loadgen`` /
``plan_ab`` stages of ``scripts/r5_measure_all.py`` (pass ``--serve``
there, or select with ``--only``).

Examples:
    python scripts/serve_loadgen.py --n 20000 --dim 64 --algo ivf_flat \
        --concurrency 16 --duration-s 10 --k 1,10,32
    python scripts/serve_loadgen.py --qps 500 --swap-mid-run \
        --obs-snapshot SERVE_r05.obs.json
    python scripts/serve_loadgen.py --fabric --fabric-workers 4 \
        --concurrency 16 --duration-s 30 --k 1,10,100
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _percentiles(lat_ms):
    if not lat_ms:
        return {}
    a = np.asarray(lat_ms)
    return {
        "mean": round(float(a.mean()), 3),
        "p50": round(float(np.percentile(a, 50)), 3),
        "p90": round(float(np.percentile(a, 90)), 3),
        "p99": round(float(np.percentile(a, 99)), 3),
        "max": round(float(a.max()), 3),
        "n": int(a.size),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=20000, help="index rows")
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--algo", default="brute_force",
                    choices=["brute_force", "ivf_flat", "ivf_pq", "cagra"])
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker threads")
    ap.add_argument("--qps", type=float, default=0.0,
                    help="target aggregate QPS (0 = closed loop, no pacing)")
    ap.add_argument("--duration-s", type=float, default=5.0)
    ap.add_argument("--requests", type=int, default=0,
                    help="stop after N completed requests; a time "
                         "failsafe of max(--duration-s, 60s) still "
                         "bounds the run so persistent rejects/errors "
                         "cannot hang it")
    ap.add_argument("--k", default="1,10,32",
                    help="comma list; each request draws one uniformly")
    ap.add_argument("--zipf", type=float, default=0.0,
                    help="query-skew exponent s: requests draw from a "
                         "finite pool of --query-pool distinct queries "
                         "with rank-r probability ~ 1/r^s (0 = every "
                         "request a fresh query). The knob that makes "
                         "the tiered hot-row / result caches "
                         "measurable (docs/serving.md §12)")
    ap.add_argument("--query-pool", type=int, default=512,
                    help="distinct queries behind --zipf sampling")
    ap.add_argument("--tiered", action="store_true",
                    help="serve with the tiered-memory rerank: host-"
                         "resident originals, shortlist-only fetch, "
                         "HBM hot-row cache (forces --algo ivf_pq; "
                         "docs/serving.md §12)")
    ap.add_argument("--refine-ratio", type=int, default=3,
                    help="rerank over-fetch ratio for --tiered")
    ap.add_argument("--hot-rows", type=int, default=None,
                    help="HBM hot-row cache budget (default: the "
                         "tuning.budget('tiered_hot_rows') knob)")
    ap.add_argument("--result-cache", type=int, default=0,
                    help="serve result-cache entries (0 = off)")
    ap.add_argument("--merge-into", default=None,
                    help="also merge the tiered/zipf summary into this "
                         "existing JSON artifact under 'serve_zipf' "
                         "(the TIERED_r12.json acceptance wiring)")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    help="graft-flow dispatch pipeline depth (tickets "
                         "in flight past async dispatch; 0 = classic "
                         "synchronous dispatch, default: the "
                         "pipeline_depth tuning budget). The report's "
                         "'pipeline' section carries the stall/occupancy "
                         "columns for the depth-0-vs-N overlap A/B")
    ap.add_argument("--max-batch-rows", type=int, default=128)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-rows", type=int, default=2048)
    ap.add_argument("--delete-every", type=int, default=0,
                    help="every Nth completed request also deletes one id")
    ap.add_argument("--upsert-every", type=int, default=0,
                    help="every Nth completed request also upserts one row")
    ap.add_argument("--swap-mid-run", action="store_true",
                    help="trigger one background rebuild+hot-swap halfway")
    ap.add_argument("--fabric", action="store_true",
                    help="drive the multi-host fabric (serve.Fabric) "
                         "instead of the single-process Server")
    ap.add_argument("--fabric-workers", type=int, default=3)
    ap.add_argument("--fabric-replication", type=int, default=2)
    ap.add_argument("--fabric-group", default="proc",
                    choices=["proc", "local"],
                    help="worker transport: real processes or the "
                         "in-process thread twin")
    ap.add_argument("--fabric-algo", default="brute_force",
                    choices=["brute_force", "ivf_flat"])
    ap.add_argument("--fault", default=None,
                    help="RAFT_TPU_FAULTS-grammar spec installed in the "
                         "fabric workers (e.g. 'slow@proc:1*50')")
    ap.add_argument("--balance", default=None,
                    choices=["p2c", "primary"],
                    help="fabric replica read balancer (default: the "
                         "FabricParams default, p2c; 'primary' is the "
                         "always-first-owner A/B baseline)")
    ap.add_argument("--chaos-curve", action="store_true",
                    help="the ISSUE 18 self-healing drill (implies "
                         "--fabric): a matched-topology primary-vs-p2c "
                         "balancer A/B, then a scripted "
                         "slow/flap/permanent-dead schedule under a "
                         "running HelmController with a low/high/low "
                         "traffic ramp — coverage timeline, repair "
                         "latency, autoscale events, and oracle checks "
                         "land in FABRIC_r18.json")
    ap.add_argument("--ab-obs", action="store_true",
                    help="fabric only: run an uninstrumented "
                         "(RAFT_TPU_OBS=off) leg first and record the "
                         "off/on QPS pair as the tracing-overhead A/B")
    ap.add_argument("--federate-out", default=None,
                    help="fabric only: archive the end-of-run federated "
                         "fleet metrics snapshot here (JSON; a .prom "
                         "Prometheus exposition lands next to it)")
    ap.add_argument("--adaptive", action="store_true",
                    help="serve with SLO-aware adaptive probing "
                         "(ServeParams.adaptive_probes; docs/serving.md "
                         "§13)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request SLO deadline (ms); late work is "
                         "shed/downshifted and counted in obs")
    ap.add_argument("--slo-p99-ms", type=float, default=0.0,
                    help="closed-loop SLO mode (ISSUE 14): clustered "
                         "easy/hard query mix, a calibration leg, then "
                         "paced legs at 1x and 2x the measured capacity "
                         "with this p99 target as every request's "
                         "deadline — emits the SLO_r14.json acceptance "
                         "artifact (p99-vs-target, recall band, mean "
                         "probed-list reduction)")
    ap.add_argument("--slo-recall-band", type=float, default=0.01,
                    help="allowed recall loss vs the exhaustive "
                         "baseline in SLO mode")
    ap.add_argument("--easy-frac", type=float, default=0.85,
                    help="fraction of the SLO-mode query pool drawn "
                         "near dataset rows (easy); the rest sit at "
                         "cluster midpoints (ambiguous)")
    ap.add_argument("--n-lists", type=int, default=16,
                    help="IVF lists for the SLO-mode index (the "
                         "exhaustive baseline probes all of them)")
    ap.add_argument("--drift", action="store_true",
                    help="the graft-gauge quality drill (ISSUE 19): a "
                         "loose-margin retune-recovery leg, then a "
                         "crippled-swap probation-rollback leg, both "
                         "closed loop against the shadow-oracle recall "
                         "estimator (docs/serving.md §14) — emits the "
                         "QUALITY_r19.json acceptance artifact")
    ap.add_argument("--quality-rate", type=float, default=1.0,
                    help="shadow-oracle sample rate for --drift")
    ap.add_argument("--quality-band", type=float, default=0.9,
                    help="recall band the --drift monitor defends")
    ap.add_argument("--drift-margin-bp", type=int, default=100,
                    help="loosened serve_probe_margin budget (basis "
                         "points) the retune leg starts from — low "
                         "enough that ambiguous queries read as easy")
    ap.add_argument("--drift-floor-bp", type=int, default=50,
                    help="loosened serve_probe_floor budget (bp) for "
                         "the retune leg")
    ap.add_argument("--plan-ab", action="store_true",
                    help="graft-plan A/B (ISSUE 20): serve through the "
                         "compiled-plan dispatch vs the legacy library "
                         "entry point at identical batch shapes — "
                         "QPS/recall/retrace columns + bitwise verdict, "
                         "plus the hybrid dense+sparse score_fuse plan "
                         "served end-to-end vs a fused numpy oracle "
                         "(PLAN_r20.json)")
    ap.add_argument("--out", default=None,
                    help="report path (default SERVE_r05.json, or "
                         "FABRIC_r13.json with --fabric)")
    ap.add_argument("--obs-snapshot", default=None,
                    help="also write the graft-scope metrics snapshot here")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.chaos_curve:
        args.fabric = True

    from raft_tpu import obs, serve

    if args.tiered:
        if args.algo not in ("ivf_pq",):
            args.algo = "ivf_pq"
        if obs.mode() == "off" and not os.environ.get("RAFT_TPU_OBS"):
            # the hit-rate/bytes-moved columns need the metrics
            # registry; same env-wins contract as --obs-snapshot below
            obs.set_mode("on")
    if args.obs_snapshot and obs.mode() == "off":
        # the snapshot needs metrics recording, but an env-selected mode
        # must win: r5_measure_all runs this stage under RAFT_TPU_OBS=
        # flight so a classified fatal mid-run leaves a flight dump —
        # forcing "on" here would silently downgrade that post-mortem
        obs.set_mode("on")

    if args.fabric and obs.mode() == "off" \
            and not os.environ.get("RAFT_TPU_OBS"):
        # the waterfall stage columns need graft-trace recording; same
        # env-wins contract as --obs-snapshot (r5 children run flight)
        obs.set_mode("on")

    ks = sorted({max(1, int(s)) for s in args.k.split(",") if s.strip()})
    rng = np.random.default_rng(args.seed)
    if args.slo_p99_ms > 0:
        if obs.mode() == "off" and not os.environ.get("RAFT_TPU_OBS"):
            obs.set_mode("on")    # rung/shed/miss counters feed the report
        return _run_slo(args, ks, rng, obs, serve)
    if args.drift:
        if obs.mode() == "off" and not os.environ.get("RAFT_TPU_OBS"):
            obs.set_mode("on")    # the recall gauges ARE the drill signal
        return _run_drift(args, ks, rng, obs, serve)
    if args.plan_ab:
        return _run_plan_ab(args, ks, rng, obs, serve)
    dataset = rng.standard_normal((args.n, args.dim)).astype(np.float32)

    if args.out is None:
        args.out = ("FABRIC_r18.json" if args.chaos_curve
                    else "FABRIC_r13.json" if args.fabric
                    else "SERVE_r05.json")
    if args.chaos_curve:
        return _run_chaos_curve(args, ks, dataset, rng, obs, serve)
    if args.fabric:
        return _run_fabric(args, ks, dataset, rng, obs, serve)

    params = serve.ServeParams(
        max_batch_rows=args.max_batch_rows,
        max_wait_ms=args.max_wait_ms,
        max_queue_rows=args.max_queue_rows,
        max_k=max(ks),
        tiered_rerank=args.tiered,
        tiered_hot_rows=args.hot_rows,
        result_cache_entries=args.result_cache,
        adaptive_probes=args.adaptive,
        deadline_ms=args.deadline_ms,
        pipeline_depth=args.pipeline_depth,
    )
    srv = serve.Server(params)
    t_build = time.perf_counter()
    srv.create_index("default", dataset, algo=args.algo,
                     refine_ratio=args.refine_ratio if args.tiered else 1)
    build_s = time.perf_counter() - t_build
    print(f"index up: {args.algo} n={args.n} d={args.dim} "
          f"tiered={args.tiered} zipf={args.zipf} "
          f"(build+warmup {build_s:.1f}s)", flush=True)
    # steady state starts HERE: create_index warmed the whole ladder
    # (buckets x k-rungs x tiered fetch rungs), so any trace-cache
    # growth during the run is a zero-retrace violation worth a column
    traces_before = serve.total_trace_count()

    # --zipf: a finite pool of distinct queries, rank-r probability
    # ~ 1/r^s — the repeated-query head that makes residency and the
    # result cache do work (JUNO's skewed-workload shape)
    qpool = rng.standard_normal(
        (args.query_pool, args.dim)).astype(np.float32)
    zipf_p = None
    if args.zipf > 0:
        ranks = np.arange(1, args.query_pool + 1, dtype=np.float64)
        zipf_p = 1.0 / ranks ** args.zipf
        zipf_p /= zipf_p.sum()

    stop = threading.Event()
    lock = threading.Lock()
    lat_ms: list = []
    per_k = {k: [] for k in ks}
    counts = {"completed": 0, "rejected": 0, "errors": 0,
              "deletes": 0, "upserts": 0}
    # pacing gate for --qps: tokens added by a timer thread
    interval = (args.concurrency / args.qps) if args.qps > 0 else 0.0

    def worker(wid: int):
        wrng = np.random.default_rng(args.seed + 1000 + wid)
        next_t = time.monotonic()
        while not stop.is_set():
            if interval:
                next_t += interval
                pause = next_t - time.monotonic()
                if pause > 0:
                    time.sleep(pause)
            k = int(wrng.choice(ks))
            if zipf_p is not None:
                q = qpool[int(wrng.choice(args.query_pool, p=zipf_p))]
            else:
                q = wrng.standard_normal(args.dim).astype(np.float32)
            t0 = time.perf_counter()
            try:
                d, ids = srv.search(q, k, timeout_s=60.0)
            except serve.Overloaded:
                with lock:
                    counts["rejected"] += 1
                time.sleep(0.001 * (1 + wrng.random()))
                continue
            except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow loadgen accounting only; the server already classified the failure
                with lock:
                    counts["errors"] += 1
                continue
            ms = (time.perf_counter() - t0) * 1e3
            with lock:
                counts["completed"] += 1
                done = counts["completed"]
                lat_ms.append(ms)
                per_k[k].append(ms)
                if args.requests and done >= args.requests:
                    stop.set()
            if args.delete_every and done % args.delete_every == 0:
                srv.delete([int(wrng.integers(args.n))])
                with lock:
                    counts["deletes"] += 1
            if args.upsert_every and done % args.upsert_every == 0:
                srv.upsert(wrng.standard_normal(args.dim).astype(np.float32),
                           [args.n + done])
                with lock:
                    counts["upserts"] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    t_run = time.perf_counter()
    for t in threads:
        t.start()
    swap_version = None
    if args.swap_mid_run:
        time.sleep(args.duration_s / 2)
        print("mid-run hot swap...", flush=True)
        swap_version = srv.swap("default", dataset=dataset,
                                wait=True).result()
    deadline = t_run + (max(args.duration_s, 60.0) if args.requests
                        else args.duration_s)
    while not stop.is_set():
        if time.perf_counter() >= deadline:
            break
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    wall_s = time.perf_counter() - t_run

    stats = srv.stats()
    traces_after = serve.total_trace_count()
    snap = obs.snapshot() if obs.enabled() else {"metrics": {}}
    srv.close()

    def _metric(name, **labels):
        want = {str(k): str(v) for k, v in labels.items()}
        for p in snap["metrics"].get(name, {}).get("points", []):
            if all(p["labels"].get(k) == v for k, v in want.items()):
                return p.get("value")
        return None

    lookups = _metric("tiered.lookups_total") or 0
    hbm_hits = _metric("tiered.hits_total", tier="hbm") or 0
    tiered_cols = {
        "zipf_s": args.zipf,
        "query_pool": args.query_pool if args.zipf > 0 else None,
        "hot_hit_rate": (round(hbm_hits / lookups, 4) if lookups
                         else None),
        "hot_lookups": int(lookups),
        "bytes_moved_total": _metric("tiered.bytes_moved_total",
                                     link="host_to_device"),
        "evictions": _metric("tiered.evictions_total") or 0,
        "result_cache_hits": _metric("serve.result_cache_hits_total",
                                     index="default") or 0,
        "result_cache_misses": _metric("serve.result_cache_misses_total",
                                       index="default") or 0,
        "steady_state_retraces": int(traces_after - traces_before),
    }

    def _hist(name, **labels):
        want = {str(k): str(v) for k, v in labels.items()}
        for p in snap["metrics"].get(name, {}).get("points", []):
            if all(p["labels"].get(k) == v for k, v in want.items()):
                return p
        return None

    from raft_tpu.core import pipeline as _gf

    stall = _hist("pipeline.stall_ms", path="serve.dispatch")
    pipe_cols = {
        # backpressure stalls = the batcher blocked on a full ticket
        # queue; run the depth-0 vs depth-N A/B to derive the overlap
        # fraction 1 - stall(N)/stall(0) (docs/observability.md)
        "depth": _gf.resolve_depth(args.pipeline_depth),
        "stall_ms_total": (round(stall["sum"], 1) if stall else 0.0),
        "stalls": (int(stall["count"]) if stall else 0),
        "occupancy": _metric("pipeline.occupancy", path="serve.dispatch"),
    }
    report = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "algo": args.algo, "n": args.n, "dim": args.dim,
            "concurrency": args.concurrency, "qps_target": args.qps,
            "k": ks, "max_batch_rows": args.max_batch_rows,
            "max_wait_ms": args.max_wait_ms,
            "max_queue_rows": args.max_queue_rows,
            "tiered": args.tiered, "refine_ratio": args.refine_ratio,
            "hot_rows": args.hot_rows, "result_cache": args.result_cache,
            "pipeline_depth": pipe_cols["depth"],
            "duration_s": round(wall_s, 2), "build_s": round(build_s, 2),
        },
        "tiered": tiered_cols,
        "pipeline": pipe_cols,
        "throughput_qps": round(counts["completed"] / max(wall_s, 1e-9), 1),
        **counts,
        "swap_generation": swap_version,
        "latency_ms": _percentiles(lat_ms),
        "per_k": {str(k): _percentiles(v) for k, v in per_k.items()},
        "server": stats,
    }
    with open(os.path.join(ROOT, args.out), "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if args.obs_snapshot:
        obs.write_snapshot(os.path.join(ROOT, args.obs_snapshot))
    if args.merge_into:
        # the TIERED_r12.json acceptance wiring: the serve-level Zipf
        # numbers (hot hit rate, retraces, bytes moved) land in the
        # deep100m artifact as its 'serve_zipf' section
        merge_path = os.path.join(ROOT, args.merge_into)
        try:
            with open(merge_path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            merged = {}
        merged["serve_zipf"] = {
            "date": report["date"], "artifact": args.out,
            "throughput_qps": report["throughput_qps"],
            **tiered_cols,
        }
        with open(merge_path, "w") as f:
            json.dump(merged, f, indent=1)
            f.write("\n")
        print(f"merged serve_zipf into {args.merge_into}", flush=True)
    # every printed number names its artifact + capture date (the GL005
    # stale-claim contract: a QPS quoted from this output is citable as
    # "<qps> QPS (<date>, <artifact>)" without further archaeology)
    print(json.dumps({**{k: report[k] for k in
                         ("throughput_qps", "completed", "rejected",
                          "latency_ms", "tiered")},
                      "artifact": args.out, "date": report["date"]}),
          flush=True)
    print(f"wrote {args.out} (measured {report['date']})", flush=True)
    return 0


def _slo_pool(args, rng):
    """Clustered dataset + easy/hard query pool for the SLO harness.

    Rows sit in tight clusters (the regime where the coarse margin is
    informative — JUNO's observation that real embeddings are locally
    concentrated); "easy" pool queries perturb dataset rows (large
    margin, low rungs suffice), "hard" ones sit at cluster midpoints
    (ambiguous margin, the policy escapes them to the exhaustive
    rung)."""
    n_centers = max(args.n_lists, 8)
    centers = rng.uniform(-5, 5, (n_centers, args.dim)).astype(np.float32)
    dataset = (centers[rng.integers(0, n_centers, args.n)]
               + 0.2 * rng.standard_normal((args.n, args.dim))
               ).astype(np.float32)
    n_easy = int(round(args.query_pool * args.easy_frac))
    easy = (dataset[rng.integers(0, args.n, n_easy)]
            + 0.05 * rng.standard_normal((n_easy, args.dim)))
    a, b = (rng.integers(0, n_centers, args.query_pool - n_easy)
            for _ in range(2))
    hard = ((centers[a] + centers[b]) / 2
            + 0.2 * rng.standard_normal((args.query_pool - n_easy,
                                         args.dim)))
    pool = np.concatenate([easy, hard]).astype(np.float32)
    return dataset, pool, n_easy


def _drive_slo(srv, serve, pool, oracle, k, args, duration_s,
               qps, deadline_ms, seed):
    """One measurement leg against the adaptive server: closed loop
    when qps=0, paced open loop otherwise; every request carries
    ``deadline_ms`` when set. Returns latencies of COMPLETED requests,
    per-request recall, and the shed/reject/miss split."""
    stop = threading.Event()
    lock = threading.Lock()
    lat_ms, recalls = [], []
    counts = {"completed": 0, "shed_deadline": 0, "rejected_queue": 0,
              "errors": 0}
    interval = (args.concurrency / qps) if qps > 0 else 0.0

    def worker(wid):
        wrng = np.random.default_rng(seed + wid)
        next_t = time.monotonic()
        while not stop.is_set():
            if interval:
                next_t += interval
                pause = next_t - time.monotonic()
                if pause > 0:
                    time.sleep(pause)
            j = int(wrng.integers(pool.shape[0]))
            t0 = time.perf_counter()
            try:
                _, ids = srv.search(pool[j], k, timeout_s=60.0,
                                    deadline_ms=deadline_ms)
            except serve.Overloaded as e:
                with lock:
                    counts["shed_deadline" if e.reason == "deadline"
                           else "rejected_queue"] += 1
                if e.reason != "deadline":
                    time.sleep(0.002 * (1 + wrng.random()))
                continue
            except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow loadgen accounting only; the server already classified the failure
                with lock:
                    counts["errors"] += 1
                continue
            ms = (time.perf_counter() - t0) * 1e3
            hit = len(set(ids[0].tolist()) & oracle[j]) / k
            with lock:
                counts["completed"] += 1
                lat_ms.append(ms)
                recalls.append(hit)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    wall = time.perf_counter() - t0
    return {"counts": counts, "lat_ms": lat_ms, "recalls": recalls,
            "wall_s": wall,
            "qps": round(counts["completed"] / max(wall, 1e-9), 1)}


def _counter_points(obs, name):
    snap = obs.snapshot(runtime_gauges=False)["metrics"]
    return {tuple(sorted(p["labels"].items())): p["value"]
            for p in snap.get(name, {}).get("points", [])}


def _mean_probed(before, after):
    """Mean probed lists per request from the serve.probe_rung counter
    delta (labels carry the rung value)."""
    total = probes = 0.0
    for key, v in after.items():
        d = v - before.get(key, 0.0)
        if d <= 0:
            continue
        rung = int(dict(key)["rung"])
        total += d
        probes += d * rung
    return (probes / total) if total else None


def _run_plan_ab(args, ks, rng, obs, serve) -> int:
    """graft-plan A/B (ISSUE 20; docs/plans.md): the compiled-plan
    serving path vs the legacy library dispatch it replaced, measured
    at identical batch shapes on the SAME index — QPS, recall@k vs
    exact ground truth, steady-state retraces (the GL007 hook), and
    the bitwise verdict the test matrix pins; then the hybrid
    dense+sparse ``score_fuse`` plan served end-to-end through the
    batcher against a fused numpy oracle. Artifact: PLAN_r20.json."""
    from raft_tpu.neighbors import brute_force, hybrid, ivf_pq

    k = max(ks)
    out = args.out or "PLAN_r20.json"
    B = int(min(args.max_batch_rows, 32))
    window_s = max(args.duration_s / 2, 1.0)
    dataset = rng.standard_normal((args.n, args.dim)).astype(np.float32)
    reps = max(1, args.query_pool // B)
    pool = rng.standard_normal((reps * B, args.dim)).astype(np.float32)
    _, ti = brute_force.knn(pool, dataset, k, metric="sqeuclidean")
    truth = np.asarray(ti)

    def recall(ids):
        return float(np.mean([
            len(set(map(int, ids[r])) & set(map(int, truth[r]))) / k
            for r in range(ids.shape[0])]))

    # rabitq + dataset kept: the serving plan is the multi-stage
    # refined_tiered variant — the richest legacy path to A/B against
    bp = ivf_pq.IndexParams(
        n_lists=args.n_lists, pq_dim=max(args.dim // 8, 4),
        metric="sqeuclidean", cache_dtype="rabitq")
    sp = ivf_pq.SearchParams(n_probes=max(4, args.n_lists // 2))

    srv = serve.Server(serve.ServeParams(
        max_batch_rows=B, max_wait_ms=args.max_wait_ms, max_k=k))
    t_build = time.perf_counter()
    srv.create_index("default", dataset, algo="ivf_pq", build_params=bp,
                     search_params=sp, refine_ratio=16)
    build_s = time.perf_counter() - t_build
    h = srv.registry.get("default").handle
    print(f"plan-ab: ivf_pq/rabitq n={args.n} d={args.dim} "
          f"n_lists={args.n_lists} k={k} B={B} "
          f"(build+warmup {build_s:.1f}s)", flush=True)

    def timed(fn):
        # one untimed pass settles one-time shape work AND collects the
        # answer ids; the timed window then loops the pool
        parts = [np.asarray(fn(pool[b * B:(b + 1) * B])[1])
                 for b in range(reps)]
        ids = np.concatenate(parts, axis=0)
        tr0 = serve.total_trace_count()
        rows = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < window_s:
            for b in range(reps):
                fn(pool[b * B:(b + 1) * B])
                rows += B
        dt = time.perf_counter() - t0
        return {"qps": round(rows / dt, 1),
                "recall_at_k": round(recall(ids), 4),
                "retraces": serve.total_trace_count() - tr0}, ids

    plan_col, plan_ids = timed(lambda q: srv.search(q, k))
    rr = h.pipeline_rr()
    legacy_col, legacy_ids = timed(
        lambda q: ivf_pq.search_refined(sp, h.index, q, k,
                                        refine_ratio=rr,
                                        dataset=dataset))
    bitwise = bool(np.array_equal(plan_ids, legacy_ids))
    srv.close()

    # hybrid score_fuse leg: served end-to-end through the batcher,
    # recall vs the fused numpy oracle over the SAME rows
    dd = max(args.dim // 4, 8)
    vocab = args.dim
    n_h = int(min(args.n, 4096))
    hr = np.random.default_rng(args.seed + 5)
    dense = hr.standard_normal((n_h, dd)).astype(np.float32)
    spr = hr.standard_normal((n_h, vocab)).astype(np.float32)
    spr[hr.random((n_h, vocab)) > 0.15] = 0.0
    hx = np.concatenate([dense, spr], axis=1)
    m_h = min(reps * B, 4 * B)
    hq = np.concatenate([
        hr.standard_normal((m_h, dd)).astype(np.float32),
        np.where(hr.random((m_h, vocab)) < 0.2,
                 hr.standard_normal((m_h, vocab)), 0).astype(np.float32),
    ], axis=1)
    wd, ws = 0.8, 1.2
    srv2 = serve.Server(serve.ServeParams(
        max_batch_rows=B, max_wait_ms=args.max_wait_ms, max_k=k))
    fuse_expand = 16  # each leg over-fetches k*16 before the fuse
    srv2.create_index(
        "default", hx, algo="hybrid",
        build_params=hybrid.IndexParams(dense_dim=dd, w_dense=wd,
                                        w_sparse=ws),
        search_params=hybrid.SearchParams(fuse_expand=fuse_expand))
    hyb_parts = []
    for b in range(0, m_h, B):
        hyb_parts.append(np.asarray(srv2.search(hq[b:b + B], k)[1]))
    tr0 = serve.total_trace_count()
    for b in range(0, m_h, B):        # steady-state pass: zero retraces
        srv2.search(hq[b:b + B], k)
    hyb_retraces = serve.total_trace_count() - tr0
    srv2.close()
    hyb_ids = np.concatenate(hyb_parts, axis=0)
    fused = wd * (hq[:, :dd] @ dense.T) + ws * (hq[:, dd:] @ spr.T)
    oids = np.argsort(-fused, axis=1)[:, :k]
    hyb_recall = float(np.mean([
        len(set(map(int, hyb_ids[r])) & set(map(int, oids[r]))) / k
        for r in range(m_h)]))

    acceptance = {
        "bitwise_plan_vs_legacy": bitwise,
        "plan_zero_retraces": plan_col["retraces"] == 0,
        "hybrid_recall_ok": hyb_recall > 0.95,
        "hybrid_zero_retraces": hyb_retraces == 0,
    }
    ok = all(acceptance.values())
    report = {
        "config": {
            "n": args.n, "dim": args.dim, "n_lists": args.n_lists,
            "k": k, "batch_rows": B, "query_pool": reps * B,
            "n_probes": sp.n_probes, "refine_ratio": int(rr),
            "cache": "rabitq+tiered", "window_s": window_s,
            "seed": args.seed,
        },
        "arms": {"plan": plan_col, "legacy": legacy_col},
        "hybrid": {
            "rows": n_h, "dense_dim": dd, "vocab": vocab,
            "queries": m_h, "w_dense": wd, "w_sparse": ws,
            "fuse_expand": fuse_expand,
            "recall_vs_fused_numpy_oracle": round(hyb_recall, 4),
            "retraces_steady_state": hyb_retraces,
        },
        "acceptance": acceptance,
        "pass": ok,
    }
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"arms": report["arms"],
                      "hybrid_recall": round(hyb_recall, 4),
                      "acceptance": acceptance, "pass": ok,
                      "out": out}, indent=1))
    return 0 if ok else 1


def _run_slo(args, ks, rng, obs, serve) -> int:
    """The closed-loop SLO harness (ISSUE 14; ROADMAP item 5
    acceptance): calibrate capacity, then hold a p99 target under 1x
    and 2x overload with per-request deadlines, while tracking recall
    against the exhaustive baseline and the mean probed-list
    reduction. Artifact: SLO_r14.json."""
    from raft_tpu.neighbors import brute_force, ivf_flat

    k = max(ks)
    slo = float(args.slo_p99_ms)
    dataset, pool, n_easy = _slo_pool(args, rng)
    t_build = time.perf_counter()
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=args.n_lists, kmeans_n_iters=10),
        dataset)
    # the exhaustive baseline: the same resolved params serving's
    # non-adaptive default uses (n_probes = n_lists, f32, exact local
    # top-k) — the recall band is measured against THIS
    sp_exh = ivf_flat.SearchParams(n_probes=args.n_lists,
                                   compute_dtype="f32",
                                   local_recall_target=1.0)
    _, gt = brute_force.knn(pool, dataset, k)
    gt = np.asarray(gt)
    oracle = {j: set(gt[j].tolist()) for j in range(pool.shape[0])}
    _, exh_ids = ivf_flat.search(sp_exh, index, pool, k)
    exh_ids = np.asarray(exh_ids)
    recall_exh = float(np.mean([
        len(set(exh_ids[j].tolist()) & oracle[j]) / k
        for j in range(pool.shape[0])]))

    params = serve.ServeParams(
        max_batch_rows=args.max_batch_rows,
        max_wait_ms=args.max_wait_ms,
        max_queue_rows=args.max_queue_rows,
        max_k=k,
        adaptive_probes=True,
        deadline_action="downshift",
    )
    srv = serve.Server(params)
    srv.add_index("default", index, algo="ivf_flat", dataset=dataset)
    build_s = time.perf_counter() - t_build
    print(f"SLO harness up: ivf_flat n={args.n} d={args.dim} "
          f"n_lists={args.n_lists} ladder="
          f"{srv.stats()['probe_ladder']} pool={pool.shape[0]} "
          f"(easy {n_easy}) recall_exh={recall_exh:.4f} "
          f"(build+warmup {build_s:.1f}s)", flush=True)
    traces_before = serve.total_trace_count()

    # leg 0: calibration — closed loop, no deadlines, measures capacity
    cal = _drive_slo(srv, serve, pool, oracle, k, args,
                     max(args.duration_s / 2, 3.0), qps=0.0,
                     deadline_ms=None, seed=args.seed + 100)
    capacity = max(cal["qps"], 1.0)
    print(f"calibration: {capacity} QPS closed-loop "
          f"(p99 {_percentiles(cal['lat_ms']).get('p99')} ms)",
          flush=True)

    legs = {}
    for factor in (1.0, 2.0):
        before_rung = _counter_points(obs, "serve.probe_rung")
        before_miss = _counter_points(obs, "serve.deadline_miss_total")
        before_shed = _counter_points(obs, "serve.deadline_shed_total")
        leg = _drive_slo(srv, serve, pool, oracle, k, args,
                         args.duration_s, qps=capacity * factor,
                         deadline_ms=slo,
                         seed=args.seed + 1000 * int(factor * 10))
        after_rung = _counter_points(obs, "serve.probe_rung")
        lat = _percentiles(leg["lat_ms"])
        shed_d = {
            dict(kk).get("action"): vv - before_shed.get(kk, 0.0)
            for kk, vv in _counter_points(
                obs, "serve.deadline_shed_total").items()}
        miss = sum(_counter_points(
            obs, "serve.deadline_miss_total").values()) - sum(
            before_miss.values())
        mean_probed = _mean_probed(before_rung, after_rung)
        legs[f"{factor:g}x"] = {
            "offered_qps": round(capacity * factor, 1),
            "achieved_qps": leg["qps"],
            **leg["counts"],
            "latency_ms": lat,
            "p99_le_slo": (lat.get("p99") is not None
                           and lat["p99"] <= slo),
            "deadline_miss": int(miss),
            "downshifts": int(shed_d.get("downshift", 0)),
            "recall": (round(float(np.mean(leg["recalls"])), 4)
                       if leg["recalls"] else None),
            "mean_probed_lists": (round(mean_probed, 3)
                                  if mean_probed else None),
        }
        print(f"leg {factor:g}x: {legs[f'{factor:g}x']}", flush=True)

    traces_after = serve.total_trace_count()
    srv.close()
    two = legs["2x"]
    probed_1x = legs["1x"]["mean_probed_lists"]
    reduction = (round(args.n_lists / probed_1x, 2)
                 if probed_1x else None)
    report = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "algo": "ivf_flat", "n": args.n, "dim": args.dim,
            "n_lists": args.n_lists, "k": k,
            "query_pool": int(pool.shape[0]), "easy": n_easy,
            "easy_frac": args.easy_frac,
            "concurrency": args.concurrency,
            "max_batch_rows": args.max_batch_rows,
            "max_wait_ms": args.max_wait_ms,
            "slo_p99_ms": slo, "recall_band": args.slo_recall_band,
            "duration_s": args.duration_s, "seed": args.seed,
        },
        "exhaustive": {"recall": round(recall_exh, 4),
                       "probed_lists": args.n_lists},
        "capacity_qps": capacity,
        "legs": legs,
        "steady_state_retraces": int(traces_after - traces_before),
        "acceptance": {
            "slo_held_2x_overload": bool(two["p99_le_slo"]),
            "recall_within_band": bool(
                two["recall"] is not None
                and two["recall"] >= recall_exh - args.slo_recall_band),
            "probed_reduction_vs_exhaustive": reduction,
            "probed_reduction_ge_4x": bool(reduction is not None
                                           and reduction >= 4.0),
            "zero_retraces": traces_after == traces_before,
        },
    }
    out = args.out or "SLO_r14.json"
    with open(os.path.join(ROOT, out), "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if args.obs_snapshot:
        obs.write_snapshot(os.path.join(ROOT, args.obs_snapshot))
    # GL005 contract: every number this prints is citable with its
    # artifact + capture date
    print(json.dumps({"acceptance": report["acceptance"],
                      "capacity_qps": capacity,
                      "p99_2x": two["latency_ms"].get("p99"),
                      "artifact": out, "date": report["date"]}),
          flush=True)
    print(f"wrote {out} (measured {report['date']})", flush=True)
    return 0


def _run_drift(args, ks, rng, obs, serve) -> int:
    """The graft-gauge closed-loop quality drill (ISSUE 19; ROADMAP
    item 9 acceptance; docs/serving.md §14): two legs over clustered
    data with hard between-cluster queries, one per actuator of the
    online recall estimator.

    * **retune leg** — the ``serve_probe_margin``/``serve_probe_floor``
      budgets are seeded DOWN to ``--drift-margin-bp`` /
      ``--drift-floor-bp``, so the adaptive policy reads ambiguous
      queries as easy and serves them at the minimum rung; the pooled
      Wilson upper bound falls below the band (a proven breach, not a
      wobble) and the monitor's bounded tighten steps must walk recall
      back inside it — no human in the loop, zero new traces.
    * **rollback leg** — fresh budgets, retune disabled; a healthy
      baseline generation is hot-swapped for one pinned to
      ``n_probes=1``; the swap-probation window must convict the swap
      against the predecessor's pinned baseline, republish the healthy
      handle as a fresh monotone generation, and recover in-band.

    Artifact: QUALITY_r19.json (per-leg estimator timelines, action
    logs with evidence, acceptance booleans)."""
    from raft_tpu import tuning
    from raft_tpu.neighbors import ivf_flat

    k = max(ks)
    band = args.quality_band
    out = args.out or "QUALITY_r19.json"

    # tight clusters + between-cluster midpoint queries: the regime
    # where a too-loose margin policy measurably under-recalls (the
    # truth set splits across lists) yet the exhaustive oracle rung
    # still scores 1.0 — recall loss is attributable, not noise
    n_centers = max(args.n_lists, 8)
    centers = (5.0 * rng.standard_normal((n_centers, args.dim))
               ).astype(np.float32)
    per = max(args.n // n_centers, 8)
    dataset = np.concatenate(
        [c + rng.standard_normal((per, args.dim)).astype(np.float32)
         for c in centers], axis=0)
    a, b = (rng.integers(0, n_centers, (args.query_pool,))
            for _ in range(2))
    hard = ((centers[a] + centers[b]) / 2
            + 0.5 * rng.standard_normal((args.query_pool, args.dim))
            ).astype(np.float32)

    def qparams(**kw):
        return serve.ServeParams(
            max_batch_rows=16, max_wait_ms=0.2, max_k=max(k, 16),
            adaptive_probes=True,
            quality_sample_rate=args.quality_rate,
            quality_band=band, quality_min_samples=8,
            quality_window=16, **kw)

    def run_leg(srv, done, deadline_s, wrng, label, timeline):
        """Drive hard-query traffic until ``done(quality_stats)`` or
        the deadline, sampling the estimator into ``timeline``."""
        t0 = time.monotonic()
        st = srv.stats("t")["quality"]
        converged = done(st)
        while not converged and time.monotonic() - t0 < deadline_s:
            for _ in range(8):
                srv.submit(hard[wrng.integers(0, hard.shape[0], (4,))],
                           k=k, index="t").result(timeout=60.0)
                time.sleep(0.002)
            st = srv.stats("t")["quality"]
            timeline.append({
                "t_s": round(time.monotonic() - t0, 2),
                "estimate": st["estimate"],
                "ci_low": st["ci_low"], "ci_high": st["ci_high"],
                "samples": st["samples"],
                "retune_steps": st["retune_steps"],
                "generation": srv.generation("t"),
            })
            converged = done(st)
        print(f"{label}: {'converged' if converged else 'DEADLINE'} "
              f"after {time.monotonic() - t0:.1f}s — est="
              f"{st['estimate']} ci=[{st['ci_low']}, {st['ci_high']}] "
              f"steps={st['retune_steps']} "
              f"actions={[x[0] for x in st['actions']]}", flush=True)
        return st, converged

    deadline_s = max(args.duration_s * 4, 120.0)
    build_params = ivf_flat.IndexParams(n_lists=args.n_lists)

    # ---- leg 1: margin drift -> bounded retune recovery --------------
    tuning.record_budget("serve_probe_margin", args.drift_margin_bp)
    tuning.record_budget("serve_probe_floor", args.drift_floor_bp)
    wrng = np.random.default_rng(args.seed + 101)
    t_build = time.perf_counter()
    srv = serve.Server(qparams(quality_rollback=False))
    srv.create_index("t", dataset, algo="ivf_flat",
                     build_params=build_params)
    print(f"retune leg up: ivf_flat n={dataset.shape[0]} d={args.dim} "
          f"n_lists={args.n_lists} margins seeded to "
          f"{args.drift_margin_bp}/{args.drift_floor_bp}bp "
          f"(build+warmup {time.perf_counter() - t_build:.1f}s)",
          flush=True)
    traces0 = serve.total_trace_count()
    tl_retune: list = []
    st_r, retune_ok = run_leg(
        srv,
        lambda s: (s["retune_steps"] > 0 and s["estimate"] is not None
                   and s["samples"] >= 8 and s["estimate"] >= band),
        deadline_s, wrng, "retune", tl_retune)
    retune_traces = int(serve.total_trace_count() - traces0)
    max_retunes = qparams().quality_max_retunes
    srv.close()
    tuning.reload()        # the next leg starts from healthy defaults
    breach_r = min((p["ci_high"] for p in tl_retune
                    if p["ci_high"] is not None), default=None)

    # ---- leg 2: crippled hot-swap -> probation rollback --------------
    wrng = np.random.default_rng(args.seed + 202)
    t_build = time.perf_counter()
    srv = serve.Server(qparams(quality_retune=False))
    srv.create_index("t", dataset, algo="ivf_flat",
                     build_params=build_params)
    print(f"rollback leg up (build+warmup "
          f"{time.perf_counter() - t_build:.1f}s)", flush=True)
    tl_roll: list = []
    base_st, base_ok = run_leg(
        srv,
        lambda s: (s["estimate"] is not None and s["samples"] >= 8
                   and s["estimate"] >= band),
        deadline_s, wrng, "rollback-baseline", tl_roll)
    gen_healthy = srv.generation("t")
    # one probe cannot cover between-cluster queries; its own pinned
    # exhaustive oracle convicts it against the predecessor's baseline
    srv.swap("t", dataset=dataset,
             search_params=ivf_flat.SearchParams(n_probes=1), wait=True)
    gen_swapped = srv.generation("t")
    t_swap = time.monotonic()
    traces1 = serve.total_trace_count()
    st_b, rolled = run_leg(
        srv, lambda s: any(x[0] == "rollback" for x in s["actions"]),
        deadline_s, wrng, "rollback", tl_roll)
    detect_s = round(time.monotonic() - t_swap, 2)
    rb_detail = None
    kinds = [x[0] for x in st_b["actions"]]
    if "rollback" in kinds:
        rb_detail = dict(st_b["actions"][kinds.index("rollback")][1])
    st_b2, recovered = run_leg(
        srv, lambda s: (s["estimate"] is not None
                        and s["estimate"] >= band),
        deadline_s, wrng, "rollback-recovery", tl_roll)
    roll_traces = int(serve.total_trace_count() - traces1)
    gen_final = srv.generation("t")
    srv.close()
    tuning.reload()

    acceptance = {
        # the retune leg's breach must be PROVEN (ci_high under the
        # band), the recovery in-band, the steps bounded, and the whole
        # episode free of new trace compilation
        "retune_drift_proven": bool(breach_r is not None
                                    and breach_r < band),
        "retune_recovered_in_band": bool(retune_ok),
        "retune_steps_bounded": bool(
            0 < st_r["retune_steps"] <= max_retunes),
        "retune_zero_retraces": retune_traces == 0,
        "rollback_convicted_swap": bool(rolled),
        "rollback_detect_s": detect_s if rolled else None,
        "rollback_versions_monotone": bool(gen_final > gen_swapped
                                           > gen_healthy),
        "rollback_recovered_in_band": bool(recovered),
        "rollback_zero_retraces": roll_traces == 0,
    }
    ok = all(v for kk, v in acceptance.items()
             if kk != "rollback_detect_s")
    report = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "algo": "ivf_flat", "n": int(dataset.shape[0]),
            "dim": args.dim, "n_lists": args.n_lists, "k": k,
            "query_pool": args.query_pool,
            "quality_rate": args.quality_rate, "band": band,
            "quality_window": 16, "quality_min_samples": 8,
            "drift_margin_bp": args.drift_margin_bp,
            "drift_floor_bp": args.drift_floor_bp,
            "seed": args.seed,
        },
        "retune": {
            "actions": st_r["actions"],
            "retune_steps": st_r["retune_steps"],
            "max_retunes": max_retunes,
            "min_ci_high_seen": breach_r,
            "final": {"estimate": st_r["estimate"],
                      "ci_low": st_r["ci_low"],
                      "ci_high": st_r["ci_high"]},
            "new_traces": retune_traces,
            "timeline": tl_retune,
        },
        "rollback": {
            "baseline_estimate": base_st["estimate"],
            "baseline_in_band": bool(base_ok),
            "generations": {"healthy": gen_healthy,
                            "swapped": gen_swapped,
                            "final": gen_final},
            "detect_s": detect_s if rolled else None,
            "evidence": rb_detail,
            "actions": st_b2["actions"],
            "final": {"estimate": st_b2["estimate"],
                      "ci_low": st_b2["ci_low"],
                      "ci_high": st_b2["ci_high"]},
            "new_traces": roll_traces,
            "timeline": tl_roll,
        },
        "acceptance": acceptance,
        "pass": bool(ok),
    }
    with open(os.path.join(ROOT, out), "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if args.obs_snapshot:
        obs.write_snapshot(os.path.join(ROOT, args.obs_snapshot))
    # GL005 contract: every number this prints is citable with its
    # artifact + capture date
    print(json.dumps({"acceptance": acceptance, "pass": bool(ok),
                      "artifact": out, "date": report["date"]}),
          flush=True)
    print(f"wrote {out} (measured {report['date']})", flush=True)
    return 0 if ok else 1


def _drive_fabric(fab, args, ks, duration_s, seed_base, serve,
                  swap_mid_run=False, dataset=None):
    """One closed-loop/paced measurement leg against ``fab``; returns
    the raw counters/latencies so a leg can run twice (the --ab-obs
    off/on pair) without duplicating the loop."""
    stop = threading.Event()
    lock = threading.Lock()
    lat_ms: list = []
    per_k = {k: [] for k in ks}
    cov_sum = [0.0]
    cov_min = [1.0]
    counts = {"completed": 0, "degraded": 0, "errors": 0}
    interval = (args.concurrency / args.qps) if args.qps > 0 else 0.0

    def worker(wid: int):
        wrng = np.random.default_rng(seed_base + wid)
        next_t = time.monotonic()
        while not stop.is_set():
            if interval:
                next_t += interval
                pause = next_t - time.monotonic()
                if pause > 0:
                    time.sleep(pause)
            k = int(wrng.choice(ks))
            q = wrng.standard_normal((1, args.dim)).astype(np.float32)
            t0 = time.perf_counter()
            try:
                d, ids, cov = fab.search(q, k)
            except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow loadgen accounting only; the fabric already classified the failure
                with lock:
                    counts["errors"] += 1
                continue
            ms = (time.perf_counter() - t0) * 1e3
            c = float(cov.min()) if cov.size else 1.0
            with lock:
                counts["completed"] += 1
                done = counts["completed"]
                lat_ms.append(ms)
                per_k[k].append(ms)
                cov_sum[0] += c
                cov_min[0] = min(cov_min[0], c)
                if c < 1.0:
                    counts["degraded"] += 1
                if args.requests and done >= args.requests:
                    stop.set()

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    t_run = time.perf_counter()
    for t in threads:
        t.start()
    swap_generation = None
    if swap_mid_run:
        time.sleep(duration_s / 2)
        print("mid-run cluster hot swap...", flush=True)
        try:
            swap_generation = fab.swap(dataset)
        except serve.FabricSwapError as e:
            print(f"swap rolled back: {e}", flush=True)
            swap_generation = "aborted"
    deadline = t_run + (max(duration_s, 60.0) if args.requests
                        else duration_s)
    while not stop.is_set():
        if time.perf_counter() >= deadline:
            break
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    return {
        "counts": counts, "lat_ms": lat_ms, "per_k": per_k,
        "cov_sum": cov_sum[0], "cov_min": cov_min[0],
        "wall_s": time.perf_counter() - t_run,
        "swap_generation": swap_generation,
    }


def _waterfall_columns(obs):
    """The graft-trace stage-attribution columns (ISSUE 13): per-stage
    p50/p99 + hedge wins over the run's completed waterfalls, and the
    complete-waterfall fraction — the SAME
    ``obs.trace.waterfall_complete`` predicate the chaos acceptance
    test asserts, so the artifact and the test cannot diverge. The
    ring-eviction count rides along: a run faster than the bounded
    ring's window must say so instead of presenting the tail as the
    whole run."""
    from raft_tpu.obs.trace import (ring_stats, stage_stats,
                                    waterfall_complete)

    wfs = [w for w in obs.trace_report()
           if w.get("entry") == "fabric.search"]
    answered = [w for w in wfs if w.get("status") in ("ok", "degraded")]
    complete = sum(1 for w in answered if waterfall_complete(w))
    ring = ring_stats()
    return {
        "waterfalls": len(wfs),
        "answered": len(answered),
        "complete": complete,
        "complete_fraction": (round(complete / len(answered), 5)
                              if answered else None),
        "ring_evicted": ring["evicted"],
        "window": ("ring_tail" if ring["evicted"] else "full_run"),
        "stages": stage_stats(wfs),
    }


def _run_fabric(args, ks, dataset, rng, obs, serve) -> int:
    """The --fabric leg: closed-loop/paced load against a
    :class:`raft_tpu.serve.Fabric`, FABRIC_r13.json sidecar out."""
    params = serve.FabricParams(
        n_workers=args.fabric_workers,
        replication=args.fabric_replication,
        worker_algo=args.fabric_algo,
        **({"balance": args.balance} if args.balance else {}),
    )
    obs_ab = None
    if args.ab_obs:
        # the instrumentation-overhead A/B (the <5% acceptance bar):
        # three swap-free, FAULT-FREE probe legs on fresh fabrics —
        # off, on, off — each duration_s/2. Bracketing the instrumented
        # leg between two uninstrumented ones cancels linear machine
        # drift, and the probes deliberately skip --fault: injected
        # deaths and hedge storms add per-leg randomness far above the
        # few-percent effect being measured (single chaos off/on pairs
        # measured anywhere from -15% to +13% run-to-run on this shared
        # CPU host, r13). Workers inherit each leg's mode at spawn, so
        # the whole path (router stages + worker spans + RPC trace
        # field) flips with the leg. The swap/chaos columns come from
        # the MAIN run below, which is not part of the A/B.
        on_mode = obs.mode() if obs.mode() != "off" else "on"

        def _ab_leg(idx: int, mode: str) -> float:
            obs.set_mode(mode)
            fab = serve.Fabric(dataset, params=params,
                               group=args.fabric_group)
            leg = _drive_fabric(fab, args, ks, args.duration_s / 2,
                                args.seed + 5000 + 100 * idx, serve)
            fab.close()
            qps = leg["counts"]["completed"] / max(leg["wall_s"], 1e-9)
            print(f"A/B leg {idx} ({mode}): {qps:.1f} QPS", flush=True)
            return qps

        off1 = _ab_leg(1, "off")
        on1 = _ab_leg(2, on_mode)
        off2 = _ab_leg(3, "off")
        obs.set_mode(on_mode)
        qps_off = (off1 + off2) / 2
        obs_ab = {
            "mode_off_qps": round(qps_off, 1),
            "off_leg_qps": [round(off1, 1), round(off2, 1)],
            "mode_on": on_mode,
            "mode_on_qps": round(on1, 1),
            "overhead_fraction": (round(1.0 - on1 / qps_off, 4)
                                  if qps_off else None),
        }
        print(f"A/B: off {qps_off:.1f} (bracket {off1:.1f}/{off2:.1f}) "
              f"vs {on_mode} {on1:.1f} QPS, overhead "
              f"{obs_ab['overhead_fraction']}", flush=True)

    t_build = time.perf_counter()
    fab = serve.Fabric(dataset, params=params, group=args.fabric_group,
                       fault_spec=args.fault)
    build_s = time.perf_counter() - t_build
    print(f"fabric up: {args.fabric_workers} workers x "
          f"{args.fabric_replication} replicas, {args.fabric_algo} "
          f"n={args.n} d={args.dim} (spawn+load {build_s:.1f}s)",
          flush=True)
    # FULL obs reset (metrics + spans + flight + trace): the A/B probe
    # legs and the fabric build otherwise leave their counters and
    # histograms in the router registry, and the --obs-snapshot /
    # --federate-out artifacts would report ~1.5x the main run's
    # traffic — the columns must describe the run they ship with
    if obs.enabled():
        obs.reset()

    leg = _drive_fabric(fab, args, ks, args.duration_s, args.seed + 1000,
                        serve, swap_mid_run=args.swap_mid_run,
                        dataset=dataset)
    counts, lat_ms, per_k = leg["counts"], leg["lat_ms"], leg["per_k"]
    wall_s, swap_generation = leg["wall_s"], leg["swap_generation"]
    cov_sum = [leg["cov_sum"]]
    cov_min = [leg["cov_min"]]

    waterfall = _waterfall_columns(obs) if obs.enabled() else None
    federated = None
    if args.federate_out:
        fed = fab.collect_metrics()
        fed_path = os.path.join(ROOT, args.federate_out)
        os.makedirs(os.path.dirname(fed_path) or ".", exist_ok=True)
        with open(fed_path, "w") as f:
            json.dump(fed, f, indent=1, default=str)
            f.write("\n")
        prom_path = os.path.splitext(fed_path)[0] + ".prom"
        with open(prom_path, "w") as f:
            f.write(obs.federation.render_prometheus(fed["metrics"]))
        federated = {"json": args.federate_out,
                     "prom": os.path.relpath(prom_path, ROOT),
                     "workers": fed["workers"],
                     "worker_health": fed.get("worker_health")}
        print(f"wrote federated snapshot {args.federate_out}", flush=True)

    stats = fab.stats()
    fab.close()
    done = counts["completed"]
    report = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "mode": "fabric", "algo": args.fabric_algo, "n": args.n,
            "dim": args.dim, "workers": args.fabric_workers,
            "replication": args.fabric_replication,
            "group": args.fabric_group, "fault": args.fault,
            "balance": params.balance,
            "concurrency": args.concurrency, "qps_target": args.qps,
            "k": ks, "duration_s": round(wall_s, 2),
            "build_s": round(build_s, 2),
        },
        "throughput_qps": round(done / max(wall_s, 1e-9), 1),
        **counts,
        "swap_generation": swap_generation,
        "latency_ms": _percentiles(lat_ms),
        "per_k": {str(k): _percentiles(v) for k, v in per_k.items()},
        "coverage": {
            "mean": round(cov_sum[0] / done, 5) if done else None,
            "min": round(cov_min[0], 5) if done else None,
        },
        "hedges": stats["counters"].get("hedges", 0),
        "retries": stats["counters"].get("retries", 0),
        "dropouts": stats["counters"].get("dropouts", 0),
        "waterfall": waterfall,
        "obs_ab": obs_ab,
        "federated": federated,
        "fabric": stats,
    }
    with open(os.path.join(ROOT, args.out), "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if args.obs_snapshot:
        obs.write_snapshot(os.path.join(ROOT, args.obs_snapshot))
    # artifact + date ride the summary line (GL005 contract — see the
    # single-process leg)
    print(json.dumps({**{k: report[k] for k in
                         ("throughput_qps", "completed", "coverage",
                          "hedges", "dropouts", "latency_ms")},
                      "waterfall_complete_fraction":
                          (waterfall or {}).get("complete_fraction"),
                      "obs_ab": obs_ab,
                      "artifact": args.out, "date": report["date"]}),
          flush=True)
    print(f"wrote {args.out} (measured {report['date']})", flush=True)
    return 0


def _chaos_oracle(dataset, q, k, n_shards):
    """The surviving-owner oracle: the same per-shard build + merge the
    workers run, so a full-coverage fabric answer must match BITWISE
    (identical tie-breaking, identical reduction order)."""
    from raft_tpu.comms import procgroup
    from raft_tpu.serve import fabric as fabmod

    bounds = fabmod.shard_bounds(dataset.shape[0], n_shards)
    results = {}
    for s in range(n_shards):
        entry = procgroup.build_shard_entry(
            dataset[bounds[s]:bounds[s + 1]], bounds[s], "brute_force")
        d, i = procgroup.search_shard_entry(entry, q, k)
        results[s] = (0, d, i)
    d, i, _ = fabmod.merge_shard_results(n_shards, results, q.shape[0], k)
    return d, i


def _run_chaos_curve(args, ks, dataset, rng, obs, serve) -> int:
    """--chaos-curve (ISSUE 18): the self-healing acceptance drill.

    Leg 1 — balancer A/B: two fault-free fabrics at MATCHED topology,
    identical seeds, ``balance="primary"`` vs ``"p2c"`` — the p2c
    replica read balancer must win on throughput.

    Leg 2 — the chaos curve: one fabric under a scripted spawn-time
    schedule (``#after:N`` delays — one transient-slow worker, one
    flapping worker, one PERMANENTLY dead worker) with a
    :class:`~raft_tpu.serve.HelmController` closing the repair and
    autoscale loops, driven by a low/high/low closed-loop traffic ramp.
    A sampler thread records the coverage/membership timeline; after a
    bounded settle the report asserts coverage back at 1.0, replication
    restored over the survivors (dead rank evicted, flapping rank
    healed in place), zero mixed-generation answers, bitwise oracle
    agreement on full-coverage samples, and a grew-then-shrank
    autoscale trace with no thrash."""
    import copy

    from raft_tpu.serve.controller import HelmController, HelmParams
    from raft_tpu.serve.fabric import CLOSED

    W, R = args.fabric_workers, args.fabric_replication
    if W < 3:
        print("--chaos-curve needs --fabric-workers >= 3 (one slow, one "
              "flapping, one dead rank)", flush=True)
        return 2

    def _params(balance):
        return serve.FabricParams(
            n_workers=W, replication=R, worker_algo=args.fabric_algo,
            balance=balance)

    # -- leg 1: the balancer A/B at matched topology, fault-free ------------
    ab_qps = {}
    for balance in ("primary", "p2c"):
        fab = serve.Fabric(dataset, params=_params(balance),
                           group=args.fabric_group)
        leg = _drive_fabric(fab, args, ks, args.duration_s / 2,
                            args.seed + 7000, serve)
        fab.close()
        qps = leg["counts"]["completed"] / max(leg["wall_s"], 1e-9)
        ab_qps[balance] = round(qps, 1)
        print(f"balance A/B {balance}: {qps:.1f} QPS", flush=True)
    balance_ab = {
        "primary_qps": ab_qps["primary"],
        "p2c_qps": ab_qps["p2c"],
        "speedup": (round(ab_qps["p2c"] / ab_qps["primary"], 4)
                    if ab_qps["primary"] else None),
        "p2c_wins": ab_qps["p2c"] > ab_qps["primary"],
    }

    # -- leg 2: the chaos curve under the helm ------------------------------
    # early arming delays: the repair story should resolve during the
    # ramp, not after it — and the rebalance budget must exceed one
    # respawn + readmission round trip (process spawn + imports + shard
    # rebuild, seconds on a busy host), or a respawned worker is
    # evicted while it is still booting
    slow_rank, flap_rank, dead_rank = 0, W - 2, W - 1
    fault = (f"slow@proc:{slow_rank}#after:10*12,"
             f"flap@proc:{flap_rank}#after:60*2,"
             f"dead@proc:{dead_rank}#after:20")
    if obs.enabled():
        obs.reset()
    t_build = time.perf_counter()
    fab = serve.Fabric(dataset, params=_params(args.balance or "p2c"),
                       group=args.fabric_group, fault_spec=fault)
    build_s = time.perf_counter() - t_build
    helm = HelmController(fab, params=HelmParams(
        interval_s=0.05,
        rebalance_budget_ms=6000.0,
        restart_budget=2,
        # floor at the provisioned topology: the ramp's shrink releases
        # SURGE capacity only (and an eviction under the floor admits a
        # replacement, restoring both replication and capacity)
        min_workers=W,
        max_workers=W + 2,
        scale_up_inflight=2.0,
        scale_down_inflight=0.75,
        sustain_ticks=4,
        cooldown_s=1.0,
        retire_timeout_s=20.0,
    ))
    print(f"chaos fabric up: {W} workers x {R} replicas "
          f"(spawn+load {build_s:.1f}s), faults '{fault}'", flush=True)

    timeline: list = []
    t0 = time.monotonic()
    stop_sample = threading.Event()

    def sampler():
        while not stop_sample.is_set():
            now = time.monotonic()
            open_eps = fab.open_episodes(now)
            snap = fab.load_snapshot()
            active = fab.active_ranks()
            cov = fab.coverage_ewma()
            timeline.append({
                "t_s": round(now - t0, 3),
                "active": active,
                "open": sorted(r for r, e in open_eps.items() if e > 0.0),
                "coverage_ewma": (round(cov, 5) if cov is not None
                                  else None),
                "mean_inflight": round(
                    sum(snap["inflight"].get(r, 0) for r in active)
                    / max(len(active), 1), 3),
                "generation": fab.generation(),
            })
            stop_sample.wait(0.25)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    helm.start()
    sampler_t.start()

    # closed-loop traffic ramp: low -> high (the scale-up window) ->
    # low (the scale-down window); each phase reuses the standard
    # measurement leg against the SAME fabric while the helm runs
    low_c = max(2, args.concurrency // 4)
    phases = [
        ("ramp_low", low_c, args.duration_s * 0.5),
        ("ramp_high", max(args.concurrency, 16), args.duration_s),
        ("ramp_cool", 1, args.duration_s * 0.5),
    ]
    ver_rng = np.random.default_rng(args.seed + 1234)
    oracle = {"checked": 0, "mismatches": 0, "degraded_skipped": 0}

    def _oracle_sample(n_queries):
        k = int(max(ks))
        for _ in range(n_queries):
            q = ver_rng.standard_normal((1, args.dim)).astype(np.float32)
            try:
                d, ids, cov = fab.search(q, k)
            except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow sampling only; the fabric already classified the failure
                continue
            if float(cov.min()) < 1.0:
                oracle["degraded_skipped"] += 1
                continue
            od, oi = _chaos_oracle(dataset, q, k, fab.n_shards)
            oracle["checked"] += 1
            if not (np.array_equal(ids, oi) and np.array_equal(d, od)):
                oracle["mismatches"] += 1

    phase_rows = []
    for i, (name, conc, dur) in enumerate(phases):
        pa = copy.copy(args)
        pa.concurrency = int(conc)
        pa.requests = 0
        pa.qps = 0.0
        leg = _drive_fabric(fab, pa, ks, dur,
                            args.seed + 9000 + 100 * i, serve)
        done = leg["counts"]["completed"]
        phase_rows.append({
            "phase": name, "concurrency": int(conc),
            "qps": round(done / max(leg["wall_s"], 1e-9), 1),
            **leg["counts"],
            "cov_min": round(leg["cov_min"], 5),
            "p99_ms": _percentiles(leg["lat_ms"]).get("p99"),
        })
        _oracle_sample(8)   # between-phase spot checks, chaos included
        print(f"phase {name} (c={conc}): {phase_rows[-1]['qps']} QPS, "
              f"cov_min {phase_rows[-1]['cov_min']}", flush=True)

    # bounded settle: let the repair loop finish (respawns, eviction,
    # replacement admission) and the breakers re-close
    settle_deadline = time.monotonic() + 30.0
    while time.monotonic() < settle_deadline:
        active = fab.active_ranks()
        if active and all(fab.health[r].state == CLOSED for r in active) \
                and all(e <= 0.0 for e in fab.open_episodes().values()):
            break
        time.sleep(0.2)
    _oracle_sample(24)      # post-repair: every sample full-coverage
    waterfall = _waterfall_columns(obs) if obs.enabled() else None
    stats = fab.stats()
    helm_stats = helm.stats()
    helm.stop()
    stop_sample.set()
    sampler_t.join(timeout=5)

    actions = [{"t_s": round(a["t"] - t0, 3), "action": a["action"],
                "worker": a["worker"]} for a in helm_stats["actions"]]
    cur = fab.registry.get(fab.name)
    owners = (dict(cur.handle.owners)
              if cur is not None and cur.handle is not None else {})
    fab.close()

    active = stats["members"]
    active = [r for r in active if r not in stats["retired"]]
    want_repl = min(R, len(active))
    replication_ok = bool(owners) and all(
        len(set(o)) == want_repl
        and all(r not in stats["retired"] for r in o)
        for o in owners.values())
    first_fault_t = min(
        (s["t_s"] for s in timeline if s["open"]), default=None)
    repair_actions = [a for a in actions
                     if a["action"] in ("respawn", "evict", "admit")]
    last_repair_t = max((a["t_s"] for a in repair_actions),
                        default=first_fault_t)
    repaired_t = None
    if first_fault_t is not None:
        for s in timeline:
            if s["t_s"] >= (last_repair_t or 0.0) and not s["open"] \
                    and (s["coverage_ewma"] or 0.0) >= 0.999:
                repaired_t = s["t_s"]
                break
    ups = [a["t_s"] for a in actions if a["action"] == "scale_up"]
    downs = [a["t_s"] for a in actions if a["action"] == "scale_down"]
    respawns = helm_stats["restarts"]
    final_cov = next((s["coverage_ewma"] for s in reversed(timeline)
                      if s["coverage_ewma"] is not None), None)
    acceptance = {
        "p2c_beats_primary": balance_ab["p2c_wins"],
        "coverage_restored": repaired_t is not None,
        "final_coverage_ewma": final_cov,
        "time_to_repair_s": (round(repaired_t - first_fault_t, 3)
                             if repaired_t is not None
                             and first_fault_t is not None else None),
        "replication_restored": replication_ok,
        "evicted": helm_stats["evicted"],
        "evicted_only_dead": helm_stats["evicted"] == [dead_rank],
        "flap_healed_in_place": (flap_rank in active
                                 and respawns.get(flap_rank, 0) >= 1),
        "mixed_gen": stats["counters"].get("mixed_gen", 0),
        "oracle": oracle,
        "grew_then_shrank": (bool(ups) and bool(downs)
                             and min(ups) < max(downs)),
        "scale_actions": len(ups) + len(downs),
        "no_thrash": (len(ups) + len(downs) <= 4
                      and all(n <= 2 for n in respawns.values())),
    }
    ok = (acceptance["p2c_beats_primary"]
          and acceptance["coverage_restored"]
          and acceptance["replication_restored"]
          and acceptance["evicted_only_dead"]
          and acceptance["flap_healed_in_place"]
          and acceptance["mixed_gen"] == 0
          and oracle["checked"] > 0 and oracle["mismatches"] == 0
          and acceptance["grew_then_shrank"]
          and acceptance["no_thrash"])

    report = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "mode": "chaos_curve", "algo": args.fabric_algo,
            "n": args.n, "dim": args.dim, "workers": W,
            "replication": R, "group": args.fabric_group,
            "balance": args.balance or "p2c", "fault": fault,
            "k": ks, "duration_s": args.duration_s,
            "build_s": round(build_s, 2), "seed": args.seed,
        },
        "balance_ab": balance_ab,
        "phases": phase_rows,
        "helm": {"ticks": helm_stats["ticks"],
                 "restarts": respawns,
                 "evicted": helm_stats["evicted"],
                 "actions": actions,
                 "rebalance_budget_ms":
                     helm_stats["rebalance_budget_ms"]},
        "fabric": stats,
        "owners": {str(s): list(o) for s, o in sorted(owners.items())},
        "timeline": timeline,
        "waterfall": waterfall,
        "acceptance": acceptance,
        "pass": ok,
    }
    with open(os.path.join(ROOT, args.out), "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if args.obs_snapshot:
        obs.write_snapshot(os.path.join(ROOT, args.obs_snapshot))
    # artifact + date ride the summary line (GL005 contract)
    print(json.dumps({"pass": ok, "balance_ab": balance_ab,
                      "acceptance": {k: acceptance[k] for k in
                                     ("time_to_repair_s", "evicted",
                                      "mixed_gen", "grew_then_shrank",
                                      "no_thrash")},
                      "oracle": oracle,
                      "artifact": args.out, "date": report["date"]}),
          flush=True)
    print(f"wrote {args.out} (measured {report['date']})", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    from raft_tpu.core.exit_guard import guarded_exit

    guarded_exit(main())
