#!/usr/bin/env python
"""Render graft-trace waterfalls and federated snapshots from obs
artifacts (ISSUE 13; docs/observability.md §distributed-tracing).

Three subcommands over flight-recorder JSONL dumps and
``obs.write_snapshot`` JSON sidecars:

* ``waterfall FILE...`` — extract completed waterfalls
  (``kind="waterfall"`` lines) and render each as an ASCII timeline
  (stage bars positioned by ``t_off_ms``, hedge losers/failures
  marked), plus the per-stage p50/p99 attribution table
  (``raft_tpu.obs.trace.stage_stats``). ``--trace ID`` filters to one
  trace; ``--summary`` prints only the table.
* ``federate FILE...`` — merge metrics from snapshot sidecars (or the
  final snapshot line of flight dumps) under per-source ``worker``
  labels into one Prometheus exposition on stdout
  (``raft_tpu.obs.federation``); ``--json PATH`` also writes the
  merged JSON snapshot.
* ``stitch FILE...`` — group span/error/waterfall events from MANY
  dumps (router + each worker process) by trace id: the cross-process
  post-mortem view one flight dump per process cannot give alone.
* ``recall FILE...`` — the graft-gauge quality timeline (ISSUE 19;
  docs/serving.md §14): every ``serve.recall_estimate`` point with its
  Wilson band (``serve.recall_ci_low``/``_ci_high``) per (worker,
  index, rung), drawn as an ASCII confidence-band strip. Flight dumps
  give the full timeline (each gauge write is a ``kind="metric"``
  event); snapshot sidecars (including federated ones, whose points
  carry ``worker`` labels) each contribute their final point.
  ``--band X`` marks the stated recall band and flags proven breaches
  (``ci_high < band``); ``--json PATH`` dumps the points.

Examples:
    python scripts/obs_report.py waterfall OBS_r13/flight-*.jsonl
    python scripts/obs_report.py federate OBS_r13/*.obs.json --json FED.json
    python scripts/obs_report.py stitch OBS_r13/flight-*.jsonl --trace 1a2b.3c.4
    python scripts/obs_report.py recall flight-*.jsonl --band 0.9
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BAR_WIDTH = 48
_STATUS_MARK = {"ok": "", "hedge_win": " *hedge-win*",
                "hedge_loser": " (hedge loser)", "failed": " !FAILED",
                "timeout": " !TIMEOUT", "retry": " ~retry"}


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_events(path: str) -> List[dict]:
    """Parse one flight JSONL dump (bad lines skipped, annotated with
    their source file for the stitch view)."""
    out: List[dict] = []
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except ValueError:
                continue
            if isinstance(evt, dict):
                evt["_source"] = os.path.basename(path)
                out.append(evt)
    return out


def load_metrics(path: str) -> dict:
    """The metrics map of one artifact: an ``obs.write_snapshot`` /
    federated JSON sidecar (``{"metrics": ...}``), or a flight JSONL
    dump (its final ``kind="snapshot"`` line)."""
    if path.endswith(".jsonl"):
        snaps = [e for e in load_events(path) if e.get("kind") == "snapshot"]
        return snaps[-1].get("metrics", {}) if snaps else {}
    with open(path) as fp:
        data = json.load(fp)
    return data.get("metrics", {}) if isinstance(data, dict) else {}


def waterfalls_from_events(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("kind") == "waterfall"]


# ---------------------------------------------------------------------------
# waterfall rendering
# ---------------------------------------------------------------------------


def render_waterfall(wf: dict, width: int = BAR_WIDTH) -> str:
    """One waterfall as an ASCII timeline: a bar per stage, positioned
    by ``t_off_ms`` and scaled to the trace's total wall-clock."""
    total = float(wf.get("ms") or 0.0)
    stages = wf.get("stages", [])
    span = max([total] + [
        float(s.get("t_off_ms", 0.0)) + float(s.get("ms") or 0.0)
        for s in stages
    ]) or 1.0
    head = (f"trace {wf.get('trace_id', '?')}  entry={wf.get('entry')}  "
            f"status={wf.get('status')}  total={total:.3f} ms")
    attrs = wf.get("attrs") or {}
    if attrs:
        head += "\n  " + "  ".join(f"{k}={v}" for k, v in attrs.items())
    lines = [head]
    for s in stages:
        name = str(s.get("stage"))
        who = "".join(
            f" {k}={s[k]}" for k in ("worker", "shard", "bucket",
                                     "batch_seq", "attempt", "kind",
                                     "rung", "deadline_slack_ms")
            if k in s)
        ms = s.get("ms")
        off = float(s.get("t_off_ms", 0.0))
        if ms is None:
            bar = "?"
        else:
            start = int(round(off / span * width))
            length = max(1, int(round(float(ms) / span * width)))
            bar = " " * min(start, width - 1) + "#" * min(
                length, width - min(start, width - 1))
        mark = _STATUS_MARK.get(str(s.get("status", "ok")), "")
        ms_txt = f"{float(ms):9.3f}" if ms is not None else "        ?"
        lines.append(f"  {name:<14}{ms_txt} ms |{bar:<{width}}|"
                     f"{who}{mark}")
    if wf.get("dropped_stages"):
        lines.append(f"  ... {wf['dropped_stages']} stage(s) dropped "
                     "(per-trace cap)")
    return "\n".join(lines)


def render_stage_table(stats: Dict[str, dict]) -> str:
    lines = [f"{'stage':<14}{'count':>7}{'p50 ms':>10}{'p99 ms':>10}"
             f"{'hedge_wins':>12}{'failed':>8}{'retries':>9}"]
    for name, d in stats.items():
        p50 = "-" if d["p50_ms"] is None else f"{d['p50_ms']:.3f}"
        p99 = "-" if d["p99_ms"] is None else f"{d['p99_ms']:.3f}"
        lines.append(f"{name:<14}{d['count']:>7}{p50:>10}{p99:>10}"
                     f"{d['hedge_wins']:>12}{d['failed']:>8}"
                     f"{d['retries']:>9}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def cmd_waterfall(args) -> int:
    from raft_tpu.obs.trace import stage_stats

    wfs: List[dict] = []
    for path in args.files:
        wfs.extend(waterfalls_from_events(load_events(path)))
    if args.trace:
        wfs = [w for w in wfs if w.get("trace_id") == args.trace]
    if not wfs:
        print("no waterfall events found", file=sys.stderr)
        return 1
    if not args.summary:
        for wf in wfs[-args.limit:]:
            print(render_waterfall(wf))
            print()
    print(f"{len(wfs)} waterfall(s); per-stage attribution:")
    print(render_stage_table(stage_stats(wfs)))
    return 0


def cmd_federate(args) -> int:
    from raft_tpu.obs import federation

    parts: Dict[str, dict] = {}
    for path in args.files:
        label = os.path.splitext(os.path.basename(path))[0]
        if label.endswith(".obs"):
            label = label[:-4]
        parts[label] = load_metrics(path)
    fed = federation.federated_snapshot(parts)
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(fed, fp, indent=1, default=str)
            fp.write("\n")
        print(f"wrote {args.json}", file=sys.stderr)
    sys.stdout.write(federation.render_prometheus(fed["metrics"]))
    return 0


def _event_trace_id(evt: dict) -> Optional[str]:
    kind = evt.get("kind")
    if kind == "waterfall":
        return evt.get("trace_id")
    if kind == "span":
        tree = evt.get("tree") or {}
        return (tree.get("attrs") or {}).get("trace_id")
    # breadcrumbs/errors that chose to carry one
    tid = evt.get("trace_id")
    return tid if isinstance(tid, str) else None


def cmd_stitch(args) -> int:
    by_trace: Dict[str, List[dict]] = {}
    for path in args.files:
        for evt in load_events(path):
            tid = _event_trace_id(evt)
            if tid is not None:
                by_trace.setdefault(tid, []).append(evt)
    if args.trace:
        by_trace = {k: v for k, v in by_trace.items() if k == args.trace}
    if not by_trace:
        print("no trace-stamped events found", file=sys.stderr)
        return 1
    for tid in sorted(by_trace):
        evts = sorted(by_trace[tid], key=lambda e: e.get("t", 0.0))
        sources = sorted({e["_source"] for e in evts})
        print(f"trace {tid}: {len(evts)} event(s) across "
              f"{len(sources)} dump(s) {sources}")
        for e in evts:
            kind = e.get("kind")
            if kind == "span":
                tree = e.get("tree") or {}
                detail = f"{tree.get('name')} {tree.get('ms', '?')} ms"
            elif kind == "waterfall":
                detail = (f"{e.get('entry')} status={e.get('status')} "
                          f"{len(e.get('stages', []))} stages "
                          f"{e.get('ms', '?')} ms")
            else:
                detail = e.get("event") or e.get("error_kind") or ""
            print(f"  [{e['_source']}] {kind}: {detail}")
        print()
    return 0


# ---------------------------------------------------------------------------
# recall timeline (graft-gauge, ISSUE 19)
# ---------------------------------------------------------------------------


_RECALL_EST = "serve.recall_estimate"
_RECALL_LO = "serve.recall_ci_low"
_RECALL_HI = "serve.recall_ci_high"


def _source_label(path: str) -> str:
    label = os.path.splitext(os.path.basename(path))[0]
    return label[:-4] if label.endswith(".obs") else label


def recall_points(paths: List[str]) -> List[dict]:
    """Every recall-estimate point the artifacts hold, as
    ``{"t", "worker", "index", "rung", "estimate", "ci_low",
    "ci_high"}`` rows sorted by series then time.

    Flight JSONL dumps yield the full timeline: the monitor writes the
    three gauges together (estimate, ci_low, ci_high — in that order),
    so a point closes on each ``ci_high`` metric event. Snapshot
    sidecars yield their single last-value point per series; a
    federated sidecar's ``worker`` label wins over the filename."""
    points: List[dict] = []
    for path in paths:
        src = _source_label(path)
        if path.endswith(".jsonl"):
            open_pts: Dict[tuple, dict] = {}
            for evt in load_events(path):
                if evt.get("kind") != "metric":
                    continue
                name = evt.get("name")
                if name not in (_RECALL_EST, _RECALL_LO, _RECALL_HI):
                    continue
                lbl = evt.get("labels") or {}
                key = (str(lbl.get("worker", src)),
                       str(lbl.get("index")), str(lbl.get("rung")))
                d = open_pts.setdefault(key, {})
                d[name] = float(evt.get("value", 0.0))
                d["t"] = evt.get("t")
                if name == _RECALL_HI and _RECALL_EST in d:
                    points.append({
                        "t": d.get("t"), "worker": key[0],
                        "index": key[1], "rung": key[2],
                        "estimate": d.get(_RECALL_EST),
                        "ci_low": d.get(_RECALL_LO),
                        "ci_high": d.get(_RECALL_HI)})
                    open_pts[key] = {}
        else:
            try:
                with open(path) as fp:
                    data = json.load(fp)
            except (OSError, ValueError):
                continue
            if not isinstance(data, dict):
                continue
            t = data.get("time_unix")
            metrics = data.get("metrics", {})
            series: Dict[tuple, dict] = {}
            for name in (_RECALL_EST, _RECALL_LO, _RECALL_HI):
                entry = metrics.get(name) or {}
                for pt in entry.get("points", []):
                    lbl = pt.get("labels") or {}
                    key = (str(lbl.get("worker", src)),
                           str(lbl.get("index")), str(lbl.get("rung")))
                    series.setdefault(key, {})[name] = pt.get("value")
            for key, d in series.items():
                if _RECALL_EST not in d:
                    continue
                points.append({
                    "t": t, "worker": key[0], "index": key[1],
                    "rung": key[2], "estimate": d.get(_RECALL_EST),
                    "ci_low": d.get(_RECALL_LO),
                    "ci_high": d.get(_RECALL_HI)})
    points.sort(key=lambda p: (p["worker"], p["index"], p["rung"],
                               p["t"] or 0.0))
    return points


def render_recall_strip(pts: List[dict], band: Optional[float],
                        width: int = BAR_WIDTH) -> str:
    """One series' timeline: a row per point with the Wilson band drawn
    as ``[-----*----]`` over a fixed axis from the series' CI floor to
    1.0 (recall's natural ceiling), the band threshold as ``|``, and
    proven breaches (``ci_high < band``) flagged."""
    floor = min([p["ci_low"] for p in pts
                 if p.get("ci_low") is not None] + [band or 1.0])
    floor = max(0.0, min(floor - 0.02, 0.98))
    span = 1.0 - floor

    def col(v: float) -> int:
        return max(0, min(width - 1,
                          int(round((v - floor) / span * (width - 1)))))

    t0 = next((p["t"] for p in pts if p["t"] is not None), 0.0) or 0.0
    lines = [f"  axis [{floor:.2f} .. 1.00]"
             + (f"  band={band:.2f}" if band is not None else "")]
    for p in pts:
        cells = [" "] * width
        if band is not None:
            cells[col(band)] = "|"
        lo, hi, est = p.get("ci_low"), p.get("ci_high"), p["estimate"]
        if lo is not None and hi is not None:
            for c in range(col(lo), col(hi) + 1):
                cells[c] = "-"
            cells[col(lo)] = "["
            cells[col(hi)] = "]"
        cells[col(est)] = "*"
        t_txt = (f"{p['t'] - t0:8.2f}s" if p["t"] is not None
                 else "       ? ")
        ci_txt = ("" if lo is None or hi is None
                  else f"  [{lo:.4f}, {hi:.4f}]")
        breach = (" ALARM" if band is not None and hi is not None
                  and hi < band else "")
        lines.append(f"  {t_txt} {''.join(cells)} "
                     f"{est:.4f}{ci_txt}{breach}")
    return "\n".join(lines)


def cmd_recall(args) -> int:
    points = recall_points(args.files)
    if args.index:
        points = [p for p in points if p["index"] == args.index]
    if args.rung:
        points = [p for p in points if p["rung"] == args.rung]
    if not points:
        print("no recall-estimate points found (is the quality lane "
              "on? serve.quality_sample_rate > 0, RAFT_TPU_OBS=flight "
              "for timelines)", file=sys.stderr)
        return 1
    if args.json:
        with open(args.json, "w") as fp:
            json.dump({"points": points}, fp, indent=1, default=str)
            fp.write("\n")
        print(f"wrote {args.json}", file=sys.stderr)
    groups: Dict[tuple, List[dict]] = {}
    for p in points:
        groups.setdefault((p["worker"], p["index"], p["rung"]),
                          []).append(p)
    for (worker, index, rung), pts in sorted(groups.items()):
        pts = pts[-args.limit:]
        print(f"recall estimate  worker={worker}  index={index}  "
              f"rung={rung}  ({len(pts)} point(s))")
        print(render_recall_strip(pts, args.band))
        print()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_report", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    wp = sub.add_parser("waterfall",
                        help="render waterfalls from flight dumps")
    wp.add_argument("files", nargs="+")
    wp.add_argument("--trace", default=None, help="filter to one trace id")
    wp.add_argument("--limit", type=int, default=16,
                    help="render at most the newest N (table uses all)")
    wp.add_argument("--summary", action="store_true",
                    help="per-stage table only, no timelines")
    wp.set_defaults(fn=cmd_waterfall)

    fp = sub.add_parser("federate",
                        help="merge snapshots into one exposition")
    fp.add_argument("files", nargs="+")
    fp.add_argument("--json", default=None,
                    help="also write the merged JSON snapshot here")
    fp.set_defaults(fn=cmd_federate)

    st = sub.add_parser("stitch",
                        help="group events across dumps by trace id")
    st.add_argument("files", nargs="+")
    st.add_argument("--trace", default=None)
    st.set_defaults(fn=cmd_stitch)

    rc = sub.add_parser("recall",
                        help="graft-gauge recall timeline with CI bands")
    rc.add_argument("files", nargs="+")
    rc.add_argument("--index", default=None, help="filter to one index")
    rc.add_argument("--rung", default=None,
                    help='filter to one rung label (e.g. "all")')
    rc.add_argument("--band", type=float, default=None,
                    help="stated recall band: drawn on the axis, "
                         "proven breaches (ci_high < band) flagged")
    rc.add_argument("--limit", type=int, default=32,
                    help="render at most the newest N points per series")
    rc.add_argument("--json", default=None,
                    help="also dump the points as JSON here")
    rc.set_defaults(fn=cmd_recall)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
