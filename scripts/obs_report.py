#!/usr/bin/env python
"""Render graft-trace waterfalls and federated snapshots from obs
artifacts (ISSUE 13; docs/observability.md §distributed-tracing).

Three subcommands over flight-recorder JSONL dumps and
``obs.write_snapshot`` JSON sidecars:

* ``waterfall FILE...`` — extract completed waterfalls
  (``kind="waterfall"`` lines) and render each as an ASCII timeline
  (stage bars positioned by ``t_off_ms``, hedge losers/failures
  marked), plus the per-stage p50/p99 attribution table
  (``raft_tpu.obs.trace.stage_stats``). ``--trace ID`` filters to one
  trace; ``--summary`` prints only the table.
* ``federate FILE...`` — merge metrics from snapshot sidecars (or the
  final snapshot line of flight dumps) under per-source ``worker``
  labels into one Prometheus exposition on stdout
  (``raft_tpu.obs.federation``); ``--json PATH`` also writes the
  merged JSON snapshot.
* ``stitch FILE...`` — group span/error/waterfall events from MANY
  dumps (router + each worker process) by trace id: the cross-process
  post-mortem view one flight dump per process cannot give alone.

Examples:
    python scripts/obs_report.py waterfall OBS_r13/flight-*.jsonl
    python scripts/obs_report.py federate OBS_r13/*.obs.json --json FED.json
    python scripts/obs_report.py stitch OBS_r13/flight-*.jsonl --trace 1a2b.3c.4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BAR_WIDTH = 48
_STATUS_MARK = {"ok": "", "hedge_win": " *hedge-win*",
                "hedge_loser": " (hedge loser)", "failed": " !FAILED",
                "timeout": " !TIMEOUT", "retry": " ~retry"}


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_events(path: str) -> List[dict]:
    """Parse one flight JSONL dump (bad lines skipped, annotated with
    their source file for the stitch view)."""
    out: List[dict] = []
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except ValueError:
                continue
            if isinstance(evt, dict):
                evt["_source"] = os.path.basename(path)
                out.append(evt)
    return out


def load_metrics(path: str) -> dict:
    """The metrics map of one artifact: an ``obs.write_snapshot`` /
    federated JSON sidecar (``{"metrics": ...}``), or a flight JSONL
    dump (its final ``kind="snapshot"`` line)."""
    if path.endswith(".jsonl"):
        snaps = [e for e in load_events(path) if e.get("kind") == "snapshot"]
        return snaps[-1].get("metrics", {}) if snaps else {}
    with open(path) as fp:
        data = json.load(fp)
    return data.get("metrics", {}) if isinstance(data, dict) else {}


def waterfalls_from_events(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("kind") == "waterfall"]


# ---------------------------------------------------------------------------
# waterfall rendering
# ---------------------------------------------------------------------------


def render_waterfall(wf: dict, width: int = BAR_WIDTH) -> str:
    """One waterfall as an ASCII timeline: a bar per stage, positioned
    by ``t_off_ms`` and scaled to the trace's total wall-clock."""
    total = float(wf.get("ms") or 0.0)
    stages = wf.get("stages", [])
    span = max([total] + [
        float(s.get("t_off_ms", 0.0)) + float(s.get("ms") or 0.0)
        for s in stages
    ]) or 1.0
    head = (f"trace {wf.get('trace_id', '?')}  entry={wf.get('entry')}  "
            f"status={wf.get('status')}  total={total:.3f} ms")
    attrs = wf.get("attrs") or {}
    if attrs:
        head += "\n  " + "  ".join(f"{k}={v}" for k, v in attrs.items())
    lines = [head]
    for s in stages:
        name = str(s.get("stage"))
        who = "".join(
            f" {k}={s[k]}" for k in ("worker", "shard", "bucket",
                                     "batch_seq", "attempt", "kind",
                                     "rung", "deadline_slack_ms")
            if k in s)
        ms = s.get("ms")
        off = float(s.get("t_off_ms", 0.0))
        if ms is None:
            bar = "?"
        else:
            start = int(round(off / span * width))
            length = max(1, int(round(float(ms) / span * width)))
            bar = " " * min(start, width - 1) + "#" * min(
                length, width - min(start, width - 1))
        mark = _STATUS_MARK.get(str(s.get("status", "ok")), "")
        ms_txt = f"{float(ms):9.3f}" if ms is not None else "        ?"
        lines.append(f"  {name:<14}{ms_txt} ms |{bar:<{width}}|"
                     f"{who}{mark}")
    if wf.get("dropped_stages"):
        lines.append(f"  ... {wf['dropped_stages']} stage(s) dropped "
                     "(per-trace cap)")
    return "\n".join(lines)


def render_stage_table(stats: Dict[str, dict]) -> str:
    lines = [f"{'stage':<14}{'count':>7}{'p50 ms':>10}{'p99 ms':>10}"
             f"{'hedge_wins':>12}{'failed':>8}{'retries':>9}"]
    for name, d in stats.items():
        p50 = "-" if d["p50_ms"] is None else f"{d['p50_ms']:.3f}"
        p99 = "-" if d["p99_ms"] is None else f"{d['p99_ms']:.3f}"
        lines.append(f"{name:<14}{d['count']:>7}{p50:>10}{p99:>10}"
                     f"{d['hedge_wins']:>12}{d['failed']:>8}"
                     f"{d['retries']:>9}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def cmd_waterfall(args) -> int:
    from raft_tpu.obs.trace import stage_stats

    wfs: List[dict] = []
    for path in args.files:
        wfs.extend(waterfalls_from_events(load_events(path)))
    if args.trace:
        wfs = [w for w in wfs if w.get("trace_id") == args.trace]
    if not wfs:
        print("no waterfall events found", file=sys.stderr)
        return 1
    if not args.summary:
        for wf in wfs[-args.limit:]:
            print(render_waterfall(wf))
            print()
    print(f"{len(wfs)} waterfall(s); per-stage attribution:")
    print(render_stage_table(stage_stats(wfs)))
    return 0


def cmd_federate(args) -> int:
    from raft_tpu.obs import federation

    parts: Dict[str, dict] = {}
    for path in args.files:
        label = os.path.splitext(os.path.basename(path))[0]
        if label.endswith(".obs"):
            label = label[:-4]
        parts[label] = load_metrics(path)
    fed = federation.federated_snapshot(parts)
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(fed, fp, indent=1, default=str)
            fp.write("\n")
        print(f"wrote {args.json}", file=sys.stderr)
    sys.stdout.write(federation.render_prometheus(fed["metrics"]))
    return 0


def _event_trace_id(evt: dict) -> Optional[str]:
    kind = evt.get("kind")
    if kind == "waterfall":
        return evt.get("trace_id")
    if kind == "span":
        tree = evt.get("tree") or {}
        return (tree.get("attrs") or {}).get("trace_id")
    # breadcrumbs/errors that chose to carry one
    tid = evt.get("trace_id")
    return tid if isinstance(tid, str) else None


def cmd_stitch(args) -> int:
    by_trace: Dict[str, List[dict]] = {}
    for path in args.files:
        for evt in load_events(path):
            tid = _event_trace_id(evt)
            if tid is not None:
                by_trace.setdefault(tid, []).append(evt)
    if args.trace:
        by_trace = {k: v for k, v in by_trace.items() if k == args.trace}
    if not by_trace:
        print("no trace-stamped events found", file=sys.stderr)
        return 1
    for tid in sorted(by_trace):
        evts = sorted(by_trace[tid], key=lambda e: e.get("t", 0.0))
        sources = sorted({e["_source"] for e in evts})
        print(f"trace {tid}: {len(evts)} event(s) across "
              f"{len(sources)} dump(s) {sources}")
        for e in evts:
            kind = e.get("kind")
            if kind == "span":
                tree = e.get("tree") or {}
                detail = f"{tree.get('name')} {tree.get('ms', '?')} ms"
            elif kind == "waterfall":
                detail = (f"{e.get('entry')} status={e.get('status')} "
                          f"{len(e.get('stages', []))} stages "
                          f"{e.get('ms', '?')} ms")
            else:
                detail = e.get("event") or e.get("error_kind") or ""
            print(f"  [{e['_source']}] {kind}: {detail}")
        print()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_report", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    wp = sub.add_parser("waterfall",
                        help="render waterfalls from flight dumps")
    wp.add_argument("files", nargs="+")
    wp.add_argument("--trace", default=None, help="filter to one trace id")
    wp.add_argument("--limit", type=int, default=16,
                    help="render at most the newest N (table uses all)")
    wp.add_argument("--summary", action="store_true",
                    help="per-stage table only, no timelines")
    wp.set_defaults(fn=cmd_waterfall)

    fp = sub.add_parser("federate",
                        help="merge snapshots into one exposition")
    fp.add_argument("files", nargs="+")
    fp.add_argument("--json", default=None,
                    help="also write the merged JSON snapshot here")
    fp.set_defaults(fn=cmd_federate)

    st = sub.add_parser("stitch",
                        help="group events across dumps by trace id")
    st.add_argument("files", nargs="+")
    st.add_argument("--trace", default=None)
    st.set_defaults(fn=cmd_stitch)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
