#!/usr/bin/env python
"""graft-lint launcher (repo checkout form of the ``graft-lint`` console
script): AST + jaxpr + graft-race + graft-kern static analysis for TPU
correctness, lock-discipline, and Pallas kernel-geometry hazards.

    python scripts/graft_lint.py --format=json raft_tpu/
    python scripts/graft_lint.py --engine=both raft_tpu/
    python scripts/graft_lint.py --engine=kern raft_tpu/
    python scripts/graft_lint.py --engine=all raft_tpu/
    python scripts/graft_lint.py --list-rules

See docs/static_analysis.md for the rule catalog and suppression syntax.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
