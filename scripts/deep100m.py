#!/usr/bin/env python
"""DEEP-100M IVF-PQ north star (BASELINE.json config #4): 100M x 96,
pq_dim=64, n_probes=128, k=10 — run once per round on the real chip,
artifact committed as DEEP100M_r{N}.json.

The reference demonstrates this scale via mmap + batch_load_iterator
(python/raft-ann-bench/.../conf/deep-100M.json; dataset.hpp:45-128); at
f32 the dataset is 38 GB — bigger than HBM *and* than what the dev
tunnel could upload in hours — so batches are GENERATED on device from
a fixed seed (the bench-wide synthetic manifold recipe) and streamed
through ``ivf_pq.build_streamed``'s donated-scatter encoder; ground
truth runs the same generator through a streaming brute-force merge.

Usage: python scripts/deep100m.py [out.json] [--n 100000000]

Tiered-memory acceptance (ISSUE 12, ROADMAP item 3): ``--tiered-out
TIERED_r12.json`` appends a stage that materializes the dataset to a
host memmap (the SSD/host tier), reranks through
``neighbors.tiered``'s shortlist-only fetch under a Zipf query mix,
and records recall / QPS / bytes-moved (vs the full-upload baseline)
/ hot-row hit-rate — asserting the tiered path is bitwise identical
to the device full-upload rerank on the same shortlists.
``--tiered-only`` skips the main battery (the CPU-smoke acceptance
shape; pair with --n 200000 and DEEP100M_FORCE_CPU=1).

graft-flow acceptance (ISSUE 16): ``--pipeline-out PIPE_r16.json``
(with ``--pipeline-only`` to skip the main battery) measures the
prefetch pipeline on the memmap tiered rerank leg — depth 0 (serial)
vs ``--pipeline-depth`` (default 2) wall-clock under an injected slow
fetch, with the stall/occupancy columns and the overlap fraction
``1 - stall(depth)/stall(0)`` — asserting bitwise-identical results
between the legs.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

if os.environ.get("DEEP100M_FORCE_CPU"):
    # env-var JAX_PLATFORMS does not override the axon plugin; the
    # config update does — CPU smoke only (--scan-impl pallas_interpret)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def tiered_stage(out_path: str, n: int, cpu_smoke: bool) -> dict:
    """ISSUE 12 acceptance: the tiered-memory rerank measured at a
    DEEP-smoke shape — host/memmap originals, shortlist-only fetch,
    Zipf query mix, hot-row residency — vs the full-upload baseline.

    Writes ``out_path`` (TIERED_r12.json) with recall / QPS /
    bytes-moved / hit-rate, a bitwise-identity verdict, and the
    steady-state retrace count. Every number is dated and carries the
    platform (GL005: CPU-smoke QPS is CPU QPS, labeled as such)."""
    import tempfile

    from raft_tpu import obs, serve
    from raft_tpu.bench.run import _gen_device_block
    from raft_tpu.bench.harness import compute_recall
    from raft_tpu.neighbors import ivf_pq, tiered

    d, k, rr = 96, 10, 3
    bs = 50_000
    # lists capped so the CPU-smoke xla scan stays minutes-scale: the
    # bytes/bitwise/hit-rate columns are shape-independent, only the
    # QPS columns carry the smoke's reduced probe work
    n_lists = max(64, min(1024, n // 256))
    n_probes = max(16, n_lists // 16)
    pool_q, batch_q, n_batches = 1024, 256, 16
    hot_rows = 65_536
    gen = _gen_device_block(bs, d, 16)
    key0 = jax.random.PRNGKey(71)
    nb = -(-n // bs)

    res = {"date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "platform": jax.devices()[0].platform,
           "config": {"n": n, "dim": d, "n_lists": n_lists,
                      "n_probes": n_probes, "k": k, "refine_ratio": rr,
                      "cache_dtype": "i4", "zipf_s": 1.0,
                      "query_pool": pool_q, "query_batches": n_batches,
                      "batch_rows": batch_q, "hot_rows": hot_rows}}

    # ---- materialize the host tier: stream-generate -> memmap --------
    tmp = tempfile.NamedTemporaryFile(suffix=".f32", delete=False)
    mm = np.memmap(tmp.name, dtype=np.float32, mode="w+", shape=(n, d))
    for b in range(nb):
        blk = np.asarray(gen(jax.random.fold_in(key0, b)))
        rows = min(bs, n - b * bs)
        mm[b * bs:b * bs + rows] = blk[:rows]
    mm.flush()
    mm = np.memmap(tmp.name, dtype=np.float32, mode="r", shape=(n, d))
    print(f"tiered: host tier materialized ({n}x{d} f32, "
          f"{mm.nbytes / 1e6:.0f} MB memmap)", flush=True)

    # ---- build: streamed, cache-only i4 (HBM holds codes ONLY) -------
    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=64, pq_bits=8, kmeans_n_iters=4,
        cache_dtype="i4",
    )
    t0 = time.time()

    def make_batches():
        for b in range(nb):
            yield jnp.asarray(np.asarray(mm[b * bs:(b + 1) * bs]))

    trainset = jnp.asarray(np.asarray(mm[:min(n, 4 * bs)]))
    index = ivf_pq.build_streamed(
        params, make_batches, n, d, trainset, keep_codes=False,
        cap_rows=int(1.4 * n / n_lists), verbose=False,
    )
    jax.block_until_ready(index.list_sizes)
    res["build_s"] = round(time.time() - t0, 1)
    print(f"tiered: build {res['build_s']}s", flush=True)

    # ---- Zipf(s=1.0) query mix over a finite pool --------------------
    qgen = _gen_device_block(pool_q, d, 16)
    pool = np.asarray(qgen(jax.random.fold_in(key0, 10_000)))
    rng = np.random.default_rng(12)
    ranks = np.arange(1, pool_q + 1, dtype=np.float64)
    p = 1.0 / ranks
    p /= p.sum()
    draws = rng.choice(pool_q, size=(n_batches, batch_q), p=p)

    # ---- ground truth on the pool (exact, streamed brute force) ------
    t0 = time.time()
    qd = jnp.asarray(pool)
    qn = jnp.sum(qd.astype(jnp.float32) ** 2, axis=1, keepdims=True)

    @jax.jit
    def partial_knn(batch, off):
        b32 = batch.astype(jnp.float32)
        dots = jnp.dot(qd, b32.T, preferred_element_type=jnp.float32)
        dist = qn + jnp.sum(b32 * b32, axis=1)[None, :] - 2.0 * dots
        valid = off + jnp.arange(batch.shape[0]) < n
        dist = jnp.where(valid[None, :], dist, jnp.inf)
        dd, ii = jax.lax.top_k(-dist, k)
        return -dd, ii + off

    from raft_tpu.neighbors.common import merge_topk

    cur_d = jnp.full((pool_q, k), jnp.inf)
    cur_i = jnp.full((pool_q, k), -1, jnp.int32)
    for b in range(nb):
        bd, bi = partial_knn(jnp.asarray(
            np.asarray(mm[b * bs:(b + 1) * bs])), jnp.int32(b * bs))
        cur_d, cur_i = merge_topk(
            jnp.concatenate([cur_d, bd], axis=1),
            jnp.concatenate([cur_i, bi], axis=1), k, True)
    gt = np.asarray(jnp.where(cur_i < n, cur_i, -1))
    res["groundtruth_s"] = round(time.time() - t0, 1)
    print(f"tiered: groundtruth {res['groundtruth_s']}s", flush=True)

    sp = ivf_pq.SearchParams(n_probes=n_probes, scan_impl="xla")
    obs.set_mode("on")
    obs.reset()

    def run(dataset, label):
        outs = []
        t0 = time.perf_counter()
        for b in range(n_batches):
            qb = jnp.asarray(pool[draws[b]])
            d_, i_ = ivf_pq.search_refined(sp, index, qb, k,
                                           refine_ratio=rr,
                                           dataset=dataset)
            outs.append((np.asarray(d_), np.asarray(i_)))
        wall = time.perf_counter() - t0
        qps = n_batches * batch_q / wall
        print(f"tiered: {label} {wall:.1f}s ({qps:.0f} qps)", flush=True)
        return outs, qps

    # ---- baseline: full-upload device rerank -------------------------
    ds_dev = jnp.asarray(np.asarray(mm))
    jax.block_until_ready(ds_dev)
    bytes_full = int(mm.nbytes)          # what the upload actually moves
    base, qps_full = run(ds_dev, "full-upload baseline")
    del ds_dev

    # ---- tiered: shortlist-only fetch + hot-row residency ------------
    src = tiered.HostArraySource(mm, hot_rows=hot_rows, promote_after=1,
                                 promote_batch=1024)
    # trace the full fetched-block rung ladder up front (what serve's
    # warmup does), so BOTH epochs below run at zero added traces
    kc = ivf_pq.refined_shortlist_width(sp, index, k, rr)
    src.warm(batch_q, kc, k, index.metric)
    tiered_out, qps_warm = run(src, "tiered (cold+warming)")
    bitwise = all(
        np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        for a, b in zip(base, tiered_out))
    # steady state: the hot set is resident, every rung traced — a
    # second epoch must add ZERO XLA traces and hit the hot tier
    st_warm = src.stats()
    traces0 = serve.total_trace_count()
    steady, qps_steady = run(src, "tiered (steady state)")
    retraces = serve.total_trace_count() - traces0
    bitwise = bitwise and all(
        np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        for a, b in zip(base, steady))

    st = src.stats()
    bytes_tiered = int(st["bytes_moved"])
    recall = compute_recall(
        np.concatenate([draw_i for _, draw_i in steady]),
        gt[draws.reshape(-1)])
    res.update({
        "bitwise_identical_to_full_upload": bool(bitwise),
        "recall_at_10": round(float(recall), 4),
        "qps_full_upload": round(qps_full, 1),
        "qps_tiered_warming": round(qps_warm, 1),
        "qps_tiered_steady": round(qps_steady, 1),
        "bytes_full_upload": bytes_full,
        "bytes_moved_tiered": bytes_tiered,
        "bytes_ratio": round(bytes_full / max(bytes_tiered, 1), 1),
        "bytes_per_query_tiered": round(
            bytes_tiered / (2 * n_batches * batch_q), 1),
        "hot_hit_rate": round(st["hit_rate_hbm"], 4),
        "hot_hit_rate_steady": round(
            (st["hbm_hits"] - st_warm["hbm_hits"])
            / max(st["lookups"] - st_warm["lookups"], 1), 4),
        "evictions": int(st["evictions"]),
        "promotions": int(st["promotions"]),
        "steady_state_retraces": int(retraces),
        "timing": "wall-clock over %d x %d Zipf(1.0) query batches"
                  % (n_batches, batch_q),
    })
    if cpu_smoke:
        res["note"] = ("CPU smoke (xla scan): QPS columns are CPU-host "
                       "numbers; bytes/bitwise/hit-rate are "
                       "platform-independent")
    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
        f.write("\n")
    os.unlink(tmp.name)
    print(json.dumps(res))
    return res


def pipeline_stage(out_path: str, n: int, cpu_smoke: bool,
                   depth: int = 2) -> dict:
    """ISSUE 16 acceptance: graft-flow prefetch on the memmap tiered
    rerank leg. Runs the SAME Zipf-free query battery through
    ``ivf_pq.search_refined_stream`` serially (depth 0) and pipelined
    (``depth``) under an injected slow fetch, and records wall-clock
    speedup, stall totals, and the overlap fraction
    ``1 - stall(depth)/stall(0)`` in a dated ``PIPE_r16.json``.

    The injection models both sides of the overlap on the CPU smoke:
    ``slow@stage:tiered.fetch`` is the host/SSD tier's fetch latency
    (producer side), ``slow@stage:tiered.score`` stands in for the
    device scan time the CPU host-loop lacks (consumer side) — on TPU
    the score side is real device time and needs no injection. The
    sleep length is calibrated to 2x the measured uninjected per-batch
    time, so the serial leg pays fetch+score stacked while the
    pipelined leg pays only the longer of the two. Results must be
    bitwise identical between the legs (GL005: every number dated and
    platform-labeled)."""
    import tempfile

    from raft_tpu import obs
    from raft_tpu.bench.run import _gen_device_block
    from raft_tpu.neighbors import ivf_pq, tiered
    from raft_tpu.resilience import faultinject

    d, k, rr = 96, 10, 3
    bs = 50_000
    n_lists = max(32, min(512, n // 512))
    # lighter probe work than tiered_stage: the overlap ratio is
    # shape-independent and the CPU-smoke xla scan is the bottleneck
    n_probes = max(8, n_lists // 32)
    batch_q, n_batches = 256, 8
    m = batch_q * n_batches
    hot_rows = 4096          # small on purpose: misses keep the gather real
    gen = _gen_device_block(bs, d, 16)
    key0 = jax.random.PRNGKey(71)
    nb = -(-n // bs)

    tmp = tempfile.NamedTemporaryFile(suffix=".f32", delete=False)
    mm = np.memmap(tmp.name, dtype=np.float32, mode="w+", shape=(n, d))
    for b in range(nb):
        blk = np.asarray(gen(jax.random.fold_in(key0, b)))
        rows = min(bs, n - b * bs)
        mm[b * bs:b * bs + rows] = blk[:rows]
    mm.flush()
    mm = np.memmap(tmp.name, dtype=np.float32, mode="r", shape=(n, d))
    print(f"pipeline: host tier materialized ({n}x{d} f32, "
          f"{mm.nbytes / 1e6:.0f} MB memmap)", flush=True)

    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=64, pq_bits=8, kmeans_n_iters=4,
        cache_dtype="i4",
    )

    def make_batches():
        for b in range(nb):
            yield jnp.asarray(np.asarray(mm[b * bs:(b + 1) * bs]))

    trainset = jnp.asarray(np.asarray(mm[:min(n, 4 * bs)]))
    index = ivf_pq.build_streamed(
        params, make_batches, n, d, trainset, keep_codes=False,
        cap_rows=int(1.4 * n / n_lists), verbose=False,
        pipeline_depth=depth,
    )
    jax.block_until_ready(index.list_sizes)

    qgen = _gen_device_block(m, d, 16)
    queries = np.asarray(qgen(jax.random.fold_in(key0, 10_000)))
    sp = ivf_pq.SearchParams(n_probes=n_probes, scan_impl="xla")
    kc = ivf_pq.refined_shortlist_width(sp, index, k, rr)
    obs.set_mode("on")

    def leg(depth_leg):
        src = tiered.HostArraySource(mm, hot_rows=hot_rows,
                                     promote_after=1, promote_batch=1024)
        src.warm(batch_q, kc, k, index.metric)
        obs.reset()
        t0 = time.perf_counter()
        d_, i_ = ivf_pq.search_refined_stream(
            sp, index, queries, k, refine_ratio=rr, dataset=src,
            batch_rows=batch_q, pipeline_depth=depth_leg)
        wall = time.perf_counter() - t0
        snap = obs.snapshot()
        stall = 0.0
        occ = None
        for p in snap["metrics"].get("pipeline.stall_ms",
                                     {}).get("points", []):
            if p["labels"].get("path") == "tiered.rerank":
                stall += p.get("sum", 0.0)
        for p in snap["metrics"].get("pipeline.occupancy",
                                     {}).get("points", []):
            if p["labels"].get("path") == "tiered.rerank":
                occ = p.get("value")
        return d_, i_, wall, stall, occ

    # warmup pass (compiles every rung), THEN an uninjected serial pass
    # whose per-batch time sizes the injected sleep at 2x the real work
    # — calibrating on the warmup pass would fold the XLA compile into
    # the sleep and balloon the injected legs
    leg(0)
    _, _, wall_cal, _, _ = leg(0)
    slow_ms = max(25.0, round(2e3 * wall_cal / n_batches, 1))
    if "RAFT_TPU_FAULTS_SLOW_MS" not in os.environ:
        os.environ["RAFT_TPU_FAULTS_SLOW_MS"] = str(slow_ms)
    strikes = 1000 * n_batches
    spec = (f"slow@stage:tiered.fetch*{strikes},"
            f"slow@stage:tiered.score*{strikes}")
    with faultinject.inject(spec):
        d0, i0, wall0, stall0, _ = leg(0)
    with faultinject.inject(spec):
        dN, iN, wallN, stallN, occN = leg(depth)
    bitwise = bool(np.array_equal(d0, dN) and np.array_equal(i0, iN))
    res = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": jax.devices()[0].platform,
        "config": {"n": n, "dim": d, "n_lists": n_lists,
                   "n_probes": n_probes, "k": k, "refine_ratio": rr,
                   "batch_rows": batch_q, "n_batches": n_batches,
                   "hot_rows": hot_rows, "pipeline_depth": depth,
                   "slow_ms": float(os.environ["RAFT_TPU_FAULTS_SLOW_MS"]),
                   "fault_spec": spec},
        "bitwise_identical_serial_vs_pipelined": bitwise,
        "wall_serial_s": round(wall0, 3),
        "wall_pipelined_s": round(wallN, 3),
        "speedup": round(wall0 / max(wallN, 1e-9), 2),
        "stall_serial_ms": round(stall0, 1),
        "stall_pipelined_ms": round(stallN, 1),
        "overlap_fraction": round(1.0 - stallN / max(stall0, 1e-9), 3),
        "occupancy_pipelined": (round(occN, 2)
                                if occN is not None else None),
        "timing": "wall-clock over %d x %d query batches, injected "
                  "slow fetch+score" % (n_batches, batch_q),
    }
    if cpu_smoke:
        res["note"] = ("CPU smoke: tiered.score slow-injection models "
                       "the device scan the host loop lacks; on TPU the "
                       "score side is real device time")
    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
        f.write("\n")
    os.unlink(tmp.name)
    print(json.dumps(res))
    return res


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    out_path = args[0] if args else "DEEP100M.json"
    n = 100_000_000
    if "--n" in sys.argv:
        n = int(sys.argv[sys.argv.index("--n") + 1])
    tiered_out = None
    if "--tiered-out" in sys.argv:
        tiered_out = sys.argv[sys.argv.index("--tiered-out") + 1]
    pipe_out = None
    if "--pipeline-out" in sys.argv:
        pipe_out = sys.argv[sys.argv.index("--pipeline-out") + 1]
    pipe_depth = 2
    if "--pipeline-depth" in sys.argv:
        pipe_depth = int(sys.argv[sys.argv.index("--pipeline-depth") + 1])
    if "--tiered-only" in sys.argv:
        tiered_stage(tiered_out or "TIERED_r12.json", n,
                     bool(os.environ.get("DEEP100M_FORCE_CPU")))
        return
    if "--pipeline-only" in sys.argv:
        pipeline_stage(pipe_out or "PIPE_r16.json", n,
                       bool(os.environ.get("DEEP100M_FORCE_CPU")),
                       depth=pipe_depth)
        return
    scan_impl = "pallas"
    if "--scan-impl" in sys.argv:   # CPU smoke: pass pallas_interpret
        scan_impl = sys.argv[sys.argv.index("--scan-impl") + 1]
    # cache rung: i4 (0.5 B/comp, the 100M default — 6.4 GB at rot128)
    # or i8 with pq_dim=96/rot=96 (9.6 GB cache-only; the rehearsal
    # measured i8-raw ~0.95 vs i4 ~0.9 recall on IP-like data —
    # SHARDED_r05.json) for a second recall/QPS Pareto point on chip
    cache_dtype = "i4"
    if "--cache-dtype" in sys.argv:
        cache_dtype = sys.argv[sys.argv.index("--cache-dtype") + 1]
    pq_dim = 96 if cache_dtype == "i8" else 64   # i8: rot=96 keeps the
    # cache at 9.6 GB (rot128 would be 12.8 GB and miss HBM)
    d, nq, k = 96, 10_000, 10
    bs = 500_000
    n_lists = 32768 if n > 20_000_000 else 4096
    n_probes = 128

    from raft_tpu.bench.run import _gen_device_block
    from raft_tpu.bench.harness import compute_recall
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.common import merge_topk

    gen = _gen_device_block(bs, d, 16)
    key0 = jax.random.PRNGKey(71)
    nb = -(-n // bs)

    def make_batches():
        for b in range(nb):
            yield gen(jax.random.fold_in(key0, b))

    qgen = _gen_device_block(nq, d, 16)
    queries = qgen(jax.random.fold_in(key0, 10_000))
    jax.block_until_ready(queries)

    res = {"config": {"n": n, "dim": d, "n_lists": n_lists,
                      "pq_dim": pq_dim, "pq_bits": 8,
                      "cache_dtype": cache_dtype, "n_probes": n_probes,
                      "k": k, "batch_rows": bs}}

    # ---- build ---------------------------------------------------------
    # trainset: 4M rows (125 rows/list at 32k lists). Cache-only int4
    # index (keep_codes=False): the packed-int4 residual cache (~9 GB at
    # 100M x rot128) is the only storage, scanned by the fused Pallas
    # kernel with in-kernel nibble decode — the round-4 answer to the
    # round-3 195-QPS decode-gather fallback.
    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=8, kmeans_n_iters=10,
        cache_dtype=cache_dtype,
    )
    t0 = time.time()

    def make_trainset():
        return jnp.concatenate(
            [gen(jax.random.fold_in(key0, b)) for b in range(8)]
        )   # 4M rows at bs=500k

    # cap lists at 1.4x the mean: the codes accumulator must fit HBM
    # beside the batch transients; outlier-list overflow rows are dropped
    # (reported in stored_rows). The trainset is passed as a temporary so
    # build_streamed can free it before the accumulators go up.
    index = ivf_pq.build_streamed(
        params, make_batches, n, d, make_trainset(),
        keep_codes=False, cap_rows=int(1.4 * n / n_lists), verbose=True,
    )
    jax.block_until_ready(index.list_sizes)
    build_s = time.time() - t0
    sizes = np.asarray(index.list_sizes)
    res["build_s"] = round(build_s, 1)
    res["cap"] = int(index.indices.shape[1])
    res["list_size_mean"] = float(sizes.mean())
    res["list_size_max"] = int(sizes.max())
    res["stored_rows"] = int(sizes.sum())
    print(f"build: {build_s:.0f} s  cap={res['cap']} "
          f"stored={res['stored_rows']}", flush=True)

    # ---- ground truth: streaming exact brute force ---------------------
    t0 = time.time()
    sub = 1000
    qs = queries[:sub]
    qn = jnp.sum(qs.astype(jnp.float32) ** 2, axis=1, keepdims=True)

    @jax.jit
    def partial_knn(batch, off):
        b32 = batch.astype(jnp.float32)
        dots = jnp.dot(qs, b32.T, preferred_element_type=jnp.float32)
        dist = qn + jnp.sum(b32 * b32, axis=1)[None, :] - 2.0 * dots
        # mask padded tail rows (global id >= n) BEFORE the merge so they
        # cannot evict real neighbors when --n isn't batch-aligned
        valid = off + jnp.arange(batch.shape[0]) < n
        dist = jnp.where(valid[None, :], dist, jnp.inf)
        dd, ii = jax.lax.top_k(-dist, k)
        return -dd, ii + off

    cur_d = jnp.full((sub, k), jnp.inf)
    cur_i = jnp.full((sub, k), -1, jnp.int32)
    for b in range(nb):
        bd, bi = partial_knn(gen(jax.random.fold_in(key0, b)),
                             jnp.int32(b * bs))
        gd = jnp.concatenate([cur_d, bd], axis=1)
        gi = jnp.concatenate([cur_i, bi], axis=1)
        cur_d, cur_i = merge_topk(gd, gi, k, True)
        if b % 8 == 7:
            np.asarray(cur_i[0, 0])    # throttle the async queue
    # mask padded tail rows (ids >= n)
    cur_i = np.asarray(jnp.where(cur_i < n, cur_i, -1))
    res["groundtruth_s"] = round(time.time() - t0, 1)
    print(f"groundtruth: {res['groundtruth_s']} s", flush=True)

    # ---- search --------------------------------------------------------
    sp = ivf_pq.SearchParams(n_probes=n_probes, scan_impl=scan_impl)
    dist, idx = ivf_pq.search(sp, index, queries, k)
    np.asarray(idx[0, 0])
    recall = compute_recall(np.asarray(idx[:sub]), cur_i)
    res["recall_at_10"] = round(float(recall), 4)
    print(f"recall={recall:.4f}", flush=True)
    # scan-chained on-device timing (the repo's standard methodology —
    # the fused int4 kernel is fast enough to fit iterations under the
    # platform watchdog, unlike round 3's decode fallback). CPU smokes
    # (interpret-mode kernel, ~minutes per search pass) skip the timing
    # blocks: their numbers would be meaningless and cost hours.
    cpu_smoke = bool(os.environ.get("DEEP100M_FORCE_CPU"))
    from raft_tpu.bench.harness import scan_qps_time

    def step(qb, ops):
        return ivf_pq.search(sp, ops, qb, k)

    if not cpu_smoke:
        s = scan_qps_time(step, queries, n1=2, n2=6, operands=index)
        res["qps"] = round(nq / s, 1)
        res["timing"] = "scan-chained (iters 2->6 slope)"
        print(f"qps={res['qps']} recall={res['recall_at_10']}", flush=True)

    # ---- cache-resident refine point (search_refined: slot-substituted
    # search + f32 re-rank decoded from the same i4 cache — removes the
    # kernel's bf16/extraction losses at no extra index bytes) ----------
    rq = queries[:sub] if cpu_smoke else queries
    _, idx_r = ivf_pq.search_refined(sp, index, rq, k, refine_ratio=3)
    np.asarray(idx_r[0, 0])
    res["refined_recall_at_10"] = round(
        float(compute_recall(np.asarray(idx_r[:sub]), cur_i)), 4)
    print(f"refined recall={res['refined_recall_at_10']}", flush=True)

    if not cpu_smoke:
        def step_r(qb, ops):
            return ivf_pq.search_refined(sp, ops, qb, k, refine_ratio=3)

        s = scan_qps_time(step_r, queries, n1=2, n2=6, operands=index)
        res["refined_qps"] = round(nq / s, 1)
        print(f"refined qps={res['refined_qps']}", flush=True)

    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    if tiered_out:
        tiered_stage(tiered_out, n, cpu_smoke)
    if pipe_out:
        pipeline_stage(pipe_out, n, cpu_smoke, depth=pipe_depth)


if __name__ == "__main__":
    main()
