#!/usr/bin/env python
"""On-TPU Pallas kernel parity check (run once per round; artifact
committed as PALLAS_PARITY_r{N}.json).

CI exercises the Pallas kernels in interpret mode on CPU
(tests/test_ivf_flat.py, test_ivf_pq.py, test_beam_step.py); this script
closes the remaining gap by running the SAME parity assertions against
the real Mosaic-compiled kernels on the TPU:

* ivf_scan.fused_list_scan_topk (exact + binned + binned-deep + fold)
  vs the XLA bucketized scan on identical inputs,
* the rabitq sign-bit first stage (packed_bits kernel arm) vs the XLA
  estimator scan + the multi-stage rerank pipeline vs the i4 band
  (check_rabitq — chip day picks the ISSUE-11 rung up with no code
  change),
* the SLO-aware adaptive rung policy (check_adaptive, ISSUE 14):
  coarse-margin easy/hard separation + the rung ladder's recall band
  at a real probed-work reduction, on the compiled coarse scan,
* fused_topk.fused_topk (exact + fold brute-force kernel) vs the
  hardware-top_k oracle (ids bitwise on the exact arm),
* beam_step.beam_merge_step (scored + packed variants) vs the numpy
  merge oracle from tests/test_beam_step.py,
* the graph rung (check_graph, ISSUE 15): nn-descent builds through
  the fused local-join kernel vs the XLA fallback (graph recall +
  id agreement + wall clock), one compiled graph_local_join block vs
  the fallback bitwise, and the graph_join / beam_step_tile candidate
  races — the same numbers capture_dispatch_tables.py records,
* cagra pallas search vs the scattered XLA search (recall agreement),
* the full kernel-contract adversarial sweep (ISSUE 10): every
  registered contract's cases — the same shapes tier-1 runs in
  interpret mode via tests/test_kernel_contracts.py — compiled on the
  chip against their XLA oracles.

The CPU shadow of these assertions rides tier-1 as
tests/test_pallas_parity.py + tests/test_kernel_contracts.py (markers
pallas_parity / kernel_contract, interpret mode).

Usage: python scripts/tpu_parity.py [out.json]
"""

import json
import os
import sys
import time

# run from anywhere: the repo root (one level up) must be importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def check_ivf_scan(results):
    from raft_tpu.neighbors import ivf_flat
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(7)
    n, d, m, k = 20_000, 64, 512, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=5), x)
    outs = {}
    for impl in ("xla", "pallas"):
        sp = ivf_flat.SearchParams(n_probes=32, local_recall_target=1.0,
                                   scan_impl=impl)
        dd, ii = ivf_flat.search(sp, index, q, k)
        outs[impl] = (np.asarray(dd), np.asarray(ii))
    _, want = naive_knn(q, x, k)
    r_x = eval_recall(outs["xla"][1], want)
    r_p = eval_recall(outs["pallas"][1], want)
    ids_equal = float((outs["xla"][1] == outs["pallas"][1]).mean())
    results["ivf_scan_exact"] = {
        "recall_xla": round(r_x, 4), "recall_pallas": round(r_p, 4),
        "id_agreement": round(ids_equal, 4),
        "ok": bool(r_p > 0.99 and r_x > 0.99 and ids_equal > 0.99),
    }
    # approx (lane-binned) path: bounded loss vs exact
    sp = ivf_flat.SearchParams(n_probes=32, local_recall_target=0.95,
                               scan_impl="pallas")
    _, ia = ivf_flat.search(sp, index, q, k)
    r_a = eval_recall(np.asarray(ia), want)
    results["ivf_scan_binned"] = {
        "recall": round(r_a, 4), "ok": bool(r_a > 0.93),
    }


def check_ivf_pq_scan(results):
    from raft_tpu.neighbors import ivf_pq
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(8)
    n, d, m, k = 20_000, 64, 512, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=32, pq_dim=32, kmeans_n_iters=5), x)
    _, want = naive_knn(q, x, k)
    recalls = {}
    for impl in ("xla", "pallas"):
        sp = ivf_pq.SearchParams(n_probes=32, local_recall_target=1.0,
                                 scan_impl=impl)
        _, ii = ivf_pq.search(sp, index, q, k)
        recalls[impl] = eval_recall(np.asarray(ii), want)
    results["ivf_pq_scan"] = {
        "recall_xla": round(recalls["xla"], 4),
        "recall_pallas": round(recalls["pallas"], 4),
        "ok": bool(recalls["pallas"] > recalls["xla"] - 0.05
                   and recalls["pallas"] > 0.7),
    }


def check_rabitq(results):
    """The rabitq rung on real Mosaic (ISSUE 11): sign-bit first-stage
    kernel vs the XLA estimator scan (recall agreement — separate
    implementations of the same estimator), plus the full multi-stage
    pipeline (first stage + codes rerank) against the exact oracle at
    refine_ratio 4 vs the i4 rung's band."""
    from raft_tpu.neighbors import ivf_pq
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(12)
    n, d, m, k = 20_000, 96, 512, 64
    # blob rows + perturbed-row queries (the tier-1 acceptance shape,
    # tests/test_ivf_pq.py::test_rabitq_pipeline_recall_band): a query
    # near its true neighbors gives the 1-bit estimator distance gaps
    # to resolve — pure-noise queries at this dim are the documented
    # hostile regime (docs/kernels.md §rabitq) and sit ~0.13 below
    centers = rng.uniform(-5, 5, (64, d)).astype(np.float32)
    x = (centers[rng.integers(0, 64, n)]
         + rng.standard_normal((n, d))).astype(np.float32)
    q = (x[rng.integers(0, n, m)]
         + 0.3 * rng.standard_normal((m, d))).astype(np.float32)
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=32, pq_dim=48, kmeans_n_iters=5,
                           cache_dtype="rabitq"), x)
    _, want = naive_knn(q, x, k)
    recalls = {}
    for impl in ("xla", "pallas"):
        sp = ivf_pq.SearchParams(n_probes=32, local_recall_target=1.0,
                                 scan_impl=impl)
        _, ii = ivf_pq.search(sp, index, q, k)
        recalls[impl] = eval_recall(np.asarray(ii), want)
    sp = ivf_pq.SearchParams(n_probes=32)
    _, ir = ivf_pq.search_refined(sp, index, q, k, refine_ratio=4)
    r_pipe = eval_recall(np.asarray(ir), want)
    index_i4 = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=32, pq_dim=48, kmeans_n_iters=5,
                           cache_dtype="i4"), x)
    _, i4ids = ivf_pq.search(sp, index_i4, q, k)
    r_i4 = eval_recall(np.asarray(i4ids), want)
    results["rabitq"] = {
        "recall_stage1_xla": round(recalls["xla"], 4),
        "recall_stage1_pallas": round(recalls["pallas"], 4),
        "recall_pipeline_rr4": round(r_pipe, 4),
        "recall_i4": round(r_i4, 4),
        "ok": bool(abs(recalls["pallas"] - recalls["xla"]) < 0.05
                   and r_pipe > r_i4 - 0.01),
    }


def check_adaptive(results):
    """The SLO-aware adaptive rung policy on real hardware (ISSUE 14):
    chip day re-validates that the coarse-margin thresholds captured on
    the CPU host still separate easy from ambiguous queries on the
    compiled coarse scan, and that the rung ladder holds the recall
    band at a real probed-work reduction (docs/serving.md §13)."""
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.serve.adaptive import AdaptivePolicy
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(14)
    n, d, m, k, n_lists = 20_000, 64, 256, 10, 16
    centers = rng.uniform(-5, 5, (n_lists, d)).astype(np.float32)
    x = (centers[rng.integers(0, n_lists, n)]
         + 0.2 * rng.standard_normal((n, d))).astype(np.float32)
    easy = (x[rng.integers(0, n, m)]
            + 0.05 * rng.standard_normal((m, d))).astype(np.float32)
    a, b = (rng.integers(0, n_lists, m) for _ in range(2))
    hard = ((centers[a] + centers[b]) / 2
            + 0.2 * rng.standard_normal((m, d))).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=10), x)
    m_easy = np.asarray(ivf_flat.coarse_margins(index, easy))
    m_hard = np.asarray(ivf_flat.coarse_margins(index, hard))
    pol = AdaptivePolicy.build(ceiling=n_lists,
                               list_cap=int(index.storage.shape[1]))
    # serve the mix per-rung exactly like the engine's split-by-rung
    q = np.concatenate([easy, hard])
    margins = np.concatenate([m_easy, m_hard])
    rungs = np.asarray([pol.rung(pol.choose_idx(float(mm), k))
                        for mm in margins])
    out = np.full((q.shape[0], k), -1, np.int64)
    for rung in np.unique(rungs):
        sel = rungs == rung
        sp = ivf_flat.SearchParams(n_probes=int(rung),
                                   compute_dtype="f32",
                                   local_recall_target=1.0)
        _, ii = ivf_flat.search(sp, index, q[sel], k)
        out[sel] = np.asarray(ii)
    _, want = naive_knn(q, x, k)
    sp_exh = ivf_flat.SearchParams(n_probes=n_lists, compute_dtype="f32",
                                   local_recall_target=1.0)
    _, exh = ivf_flat.search(sp_exh, index, q, k)
    r_adapt = eval_recall(out, want)
    r_exh = eval_recall(np.asarray(exh), want)
    mean_probed = float(rungs.mean())
    results["adaptive"] = {
        "margin_easy_p50": round(float(np.median(m_easy)), 4),
        "margin_hard_p50": round(float(np.median(m_hard)), 4),
        "recall_adaptive": round(r_adapt, 4),
        "recall_exhaustive": round(r_exh, 4),
        "mean_probed_lists": round(mean_probed, 3),
        "ok": bool(np.median(m_easy) > np.median(m_hard) * 2
                   and r_adapt >= r_exh - 0.01
                   and mean_probed <= n_lists / 2),
    }


def check_fused_topk(results):
    from raft_tpu.ops.fused_topk import L2, fused_topk
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(9)
    m, n, d, k = 512, 20_000, 64, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    qn = (q ** 2).sum(1)
    xn = (x ** 2).sum(1)
    dist = np.maximum(qn[:, None] + xn[None, :] - 2.0 * (q @ x.T),
                      0.0).astype(np.float32)
    _, oracle = jax.lax.top_k(-jnp.asarray(dist), k)
    oracle = np.asarray(oracle)
    _, want = naive_knn(q, x, k)
    out = {}
    for variant in ("exact", "fold"):
        _, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), k,
                           metric_kind=L2, variant=variant)
        oi = np.asarray(oi)
        out[f"id_agreement_{variant}"] = round(
            float((oi == oracle).mean()), 4)
        out[f"recall_{variant}"] = round(eval_recall(oi, want), 4)
    out["ok"] = bool(out["id_agreement_exact"] > 0.999
                     and out["recall_fold"] > 0.98)
    results["fused_topk"] = out


def check_beam_step(results):
    from tests.test_beam_step import _np_merge_oracle
    from raft_tpu.ops.beam_step import beam_merge_step

    rng = np.random.default_rng(3)
    L, C, m, width = 16, 32, 128, 4
    bi = rng.permutation(np.arange(0, 4096))[: L * m].reshape(L, m)
    bi = bi.astype(np.int32)
    be = (rng.random((L, m)) < 0.5).astype(np.int32)
    ci = rng.permutation(np.arange(4096, 16384))[: C * m].reshape(C, m)
    ci = ci.astype(np.int32)
    for c in range(m):
        ndup = C // 4
        slots = rng.choice(C, size=ndup, replace=False)
        rows = rng.choice(L, size=ndup, replace=False)
        ci[slots, c] = bi[rows, c]
    bd = bi.astype(np.float32)
    cd = ci.astype(np.float32)
    order = np.argsort(bd, axis=0, kind="stable")
    bd = np.take_along_axis(bd, order, axis=0)
    bi = np.take_along_axis(bi, order, axis=0)
    be = np.take_along_axis(be, order, axis=0)

    od, oi, oe, par = beam_merge_step(
        jnp.asarray(bd), jnp.asarray(bi), jnp.asarray(be),
        cand_d=jnp.asarray(cd), cand_i=jnp.asarray(ci),
        width=width, g=128,
    )
    wd, wi, we, wpar = _np_merge_oracle(bd, bi, be, cd, ci, L, width)
    ok = (np.array_equal(np.asarray(oi), wi)
          and np.allclose(np.asarray(od), wd, rtol=1e-6)
          and np.array_equal(np.asarray(par), wpar)
          and np.array_equal(np.asarray(oe), we))
    results["beam_merge_step_oracle"] = {"ok": bool(ok)}


def check_graph(results):
    """The graph rung compiled on chip (ISSUE 15): the fused nn-descent
    local-join kernel against its XLA fallback — one join block must
    agree bitwise on ids (tie-free keys), whole builds must agree on
    recall — plus the dispatch-table candidate races for the two new op
    keys, so chip day records the winners with no extra tooling."""
    import time as _time

    from raft_tpu.neighbors import nn_descent
    from raft_tpu.tuning import microbench
    from tests.oracles import naive_knn

    rng = np.random.default_rng(15)
    n, d, k = 60_000, 64, 32
    centers = rng.uniform(-5, 5, (32, d)).astype(np.float32)
    x = (centers[rng.integers(0, 32, n)]
         + 0.8 * rng.standard_normal((n, d))).astype(np.float32)
    out = {}
    graphs = {}
    for impl in ("xla", "pallas"):
        t0 = _time.time()
        idx = nn_descent.build(nn_descent.IndexParams(
            graph_degree=k, max_iterations=10, join_impl=impl), x)
        graphs[impl] = np.asarray(idx.graph)         # sync
        out[f"build_s_{impl}"] = round(_time.time() - t0, 2)
    sub = 500
    _, want = naive_knn(x[:sub], x, k + 1)
    for impl, g in graphs.items():
        rec = float(np.mean(
            [len(set(g[i]) & set(want[i][1:k + 1])) / k
             for i in range(sub)]))
        out[f"recall_{impl}"] = round(rec, 4)
    out["id_agreement"] = round(
        float((graphs["xla"] == graphs["pallas"]).mean()), 4)
    # candidate races at the dispatch-table shapes (the rows a
    # capture_dispatch_tables.py run would persist)
    out["graph_join_race_ms"] = {
        kk: round(vv, 3) for kk, vv in microbench.bench_graph_join(
            {"rows": 4096, "K": 48, "S": 128, "d": d}, reps=3).items()}
    out["beam_step_race_ms"] = {
        kk: round(vv, 3) for kk, vv in microbench.bench_beam_step(
            {"m": 1024, "itopk": 64, "width": 4, "deg": 32, "d": d},
            reps=3).items()}
    out["ok"] = bool(
        out["recall_pallas"] > 0.9 and out["recall_xla"] > 0.9
        and abs(out["recall_pallas"] - out["recall_xla"]) < 0.02)
    results["graph"] = out


def check_cagra(results):
    from raft_tpu.neighbors import cagra
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(11)
    centers = rng.uniform(-5, 5, (16, 32)).astype(np.float32)
    n, m, k = 20_000, 256, 10
    x = (centers[rng.integers(0, 16, n)]
         + 0.7 * rng.standard_normal((n, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 16, m)]
         + 0.7 * rng.standard_normal((m, 32))).astype(np.float32)
    idx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16), x)
    _, want = naive_knn(q, x, k)
    recalls = {}
    for impl in ("xla", "pallas"):
        sp = cagra.SearchParams(itopk_size=64, scan_impl=impl)
        _, ii = cagra.search(sp, idx, q, k)
        recalls[impl] = eval_recall(np.asarray(ii), want)
    results["cagra_beam"] = {
        "recall_xla": round(recalls["xla"], 4),
        "recall_pallas": round(recalls["pallas"], 4),
        "ok": bool(recalls["pallas"] > 0.9
                   and abs(recalls["pallas"] - recalls["xla"]) < 0.05),
    }


def check_kernel_contracts(results):
    """The adversarial kernel-contract sweep, COMPILED (ISSUE 10): the
    exact shapes tier-1 drives in interpret mode
    (tests/test_kernel_contracts.py) rerun against real Mosaic — the
    same non-divisible tails, k==n, k==1, single-row, sublane-boundary
    ±1 and lane-boundary-k corner cases, per case-seeded rng, so an
    on-chip divergence reproduces standalone."""
    from raft_tpu.analysis import contracts

    out = {"cases": 0, "failures": []}
    for name, c in contracts.load_all().items():
        drv = c.resolve_driver()
        for case in contracts.adversarial_cases(c):
            if case.get("static_only"):
                continue
            out["cases"] += 1
            try:
                rep = drv(c, case, interpret=False)
            except Exception as e:  # noqa: BLE001 - record, keep sweeping
                rep = None
                out["failures"].append(
                    {"contract": name, "case": _case_key(case),
                     "error": repr(e)[:200]})
                continue
            if not rep.ok:
                out["failures"].append(
                    {"contract": name, "case": _case_key(case),
                     "kind": rep.kind, "detail": rep.detail[:200]})
    out["ok"] = not out["failures"]
    out["failures"] = out["failures"][:20]
    results["kernel_contracts"] = out


def _case_key(case):
    return {k: v for k, v in case.items()
            if isinstance(v, (int, str, bool))}


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "PALLAS_PARITY.json"
    t0 = time.time()
    results = {"platform": jax.devices()[0].platform,
               "device": str(jax.devices()[0])}
    for fn in (check_ivf_scan, check_ivf_pq_scan, check_rabitq,
               check_adaptive, check_fused_topk, check_beam_step,
               check_graph, check_cagra, check_kernel_contracts):
        try:
            fn(results)
        except Exception as e:  # noqa: BLE001 - record, keep going
            results[fn.__name__] = {"ok": False, "error": repr(e)[:300]}
    results["all_ok"] = all(
        v.get("ok", True) for v in results.values() if isinstance(v, dict)
    )
    results["elapsed_s"] = round(time.time() - t0, 1)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
