#!/usr/bin/env python
"""Round-4 single-chip perf sweep (VERDICT #2): IVF-Flat and CAGRA
throughput levers measured back-to-back in one session — storage dtype,
query grouping, beam width/iteration trades — each with recall so the
QPS targets (ivfflat >= 160k, cagra >= 240k at current recalls) are
checked at equal accuracy.

Run: python scripts/r4_sweep.py [flat|cagra|both]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _sift_like as sift_like
from raft_tpu.bench.harness import compute_recall, scan_qps_time


def sweep_flat(x, q, want, rows):
    from raft_tpu.neighbors import ivf_flat

    nq, k = q.shape[0], 10
    for sd in ("f32", "bf16"):
        t0 = time.time()
        params = ivf_flat.IndexParams(n_lists=1024, metric="sqeuclidean",
                                      storage_dtype=sd)
        index = ivf_flat.build(params, x)
        jax.block_until_ready(index.list_sizes)
        print(f"[flat {sd}] build {time.time()-t0:.0f}s", flush=True)
        for grp, bb, lrt, mrt in [
            (256, 32, 0.95, 1.0),
            (256, 32, 0.95, 0.95),
            (512, 32, 0.95, 1.0),
            (256, 64, 0.95, 1.0),
            (128, 32, 0.95, 1.0),
        ]:
            sp = ivf_flat.SearchParams(
                n_probes=64, query_group=grp, bucket_batch=bb,
                local_recall_target=lrt, merge_recall_target=mrt)
            try:
                _, idx = ivf_flat.search(sp, index, q, k)
                rec = compute_recall(np.asarray(idx[:1000]), want)
                s = scan_qps_time(
                    lambda qq, ix: ivf_flat.search(sp, ix, qq, k), q,
                    operands=index)
                print(f"[flat {sd}] grp={grp} bb={bb} lrt={lrt} mrt={mrt}: "
                      f"{nq/s:.0f} QPS r={rec:.3f}", flush=True)
                rows.append({"algo": "ivf_flat", "storage": sd,
                             "query_group": grp, "bucket_batch": bb,
                             "lrt": lrt, "mrt": mrt,
                             "qps": round(nq / s, 1),
                             "recall_at_10": round(float(rec), 4)})
            except Exception as e:  # noqa: BLE001
                print(f"[flat {sd}] grp={grp} bb={bb}: FAIL {e!r}"[:200],
                      flush=True)


def sweep_cagra(x, q, want, rows):
    from raft_tpu.neighbors import cagra

    nq, k = q.shape[0], 10
    t0 = time.time()
    index = cagra.build(
        cagra.IndexParams(graph_degree=32, intermediate_graph_degree=64), x)
    jax.block_until_ready(index.graph)
    print(f"[cagra] build {time.time()-t0:.0f}s", flush=True)
    for width, iters, seeds, itopk in [
        (2, 15, 64, 64),
        (2, 12, 64, 64),
        (4, 8, 64, 64),
        (4, 6, 64, 64),
        (2, 15, 64, 48),
        (1, 24, 64, 64),
    ]:
        sp = cagra.SearchParams(itopk_size=itopk, search_width=width,
                                max_iterations=iters, n_seeds=seeds)
        try:
            _, idx = cagra.search(sp, index, q, k)
            rec = compute_recall(np.asarray(idx[:1000]), want)
            s = scan_qps_time(
                lambda qq, ix: cagra.search(sp, ix, qq, k), q,
                operands=index)
            print(f"[cagra] w={width} it={iters} seeds={seeds} "
                  f"itopk={itopk}: {nq/s:.0f} QPS r={rec:.3f}", flush=True)
            rows.append({"algo": "cagra", "search_width": width,
                         "iters": iters, "n_seeds": seeds, "itopk": itopk,
                         "qps": round(nq / s, 1),
                         "recall_at_10": round(float(rec), 4)})
        except Exception as e:  # noqa: BLE001
            print(f"[cagra] w={width} it={iters}: FAIL {e!r}"[:200],
                  flush=True)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    n, d, nq = 1_000_000, 128, 10_000
    print(f"devices: {jax.devices()}", flush=True)
    x = jax.device_put(sift_like(n, d, seed=1))
    q = jax.device_put(sift_like(nq, d, seed=2))
    jax.block_until_ready(x)
    from raft_tpu.neighbors import brute_force

    _, bf_idx = brute_force.knn(q[:1000], x, 10)
    want = np.asarray(bf_idx)
    print("oracle done", flush=True)
    rows = []
    if which in ("flat", "both"):
        sweep_flat(x, q, want, rows)
    if which in ("cagra", "both"):
        sweep_cagra(x, q, want, rows)
    import json

    with open("SWEEP_r05.json", "w") as f:
        json.dump(rows, f, indent=1)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()
