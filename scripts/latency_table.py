#!/usr/bin/env python
"""Small-batch latency table for the three flagship indexes (VERDICT r3
#5): per-call p50/p95 at batch 1 and 10 on the real chip, the analog of
the reference's `--mode latency` runs (raft_ann_benchmarks.md:240-254).

Also settles the multi-CTA question empirically: the reference ships a
multi-CTA-per-query kernel family so ONE query can use many SMs. On TPU
the whole batch is one XLA program on one core — if batch-1 latency is
dominated by the same fixed cost as batch-10 (dispatch + the sequential
beam/scan structure), intra-query parallelism has nothing to win and the
latency lever is fewer/fused steps instead. The printed fixed-cost share
is that argument, measured.

Run: python scripts/latency_table.py [out.json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from bench import _sift_like as sift_like
from raft_tpu.bench.harness import latency_percentiles


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "LATENCY_r05.json"
    n, d, k = 1_000_000, 128, 10
    print(f"devices: {jax.devices()}", flush=True)
    x = jax.device_put(sift_like(n, d, seed=1))
    q = jax.device_put(sift_like(4096, d, seed=2))
    jax.block_until_ready(x)

    rows = {}

    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

    t0 = time.time()
    fi = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024), x)
    jax.block_until_ready(fi.list_sizes)
    print(f"ivf_flat build {time.time()-t0:.0f}s", flush=True)
    fsp = ivf_flat.SearchParams(n_probes=64)
    rows["ivf_flat"] = {
        f"b{b}": latency_percentiles(
            lambda qq, ops: ivf_flat.search(fsp, ops, qq, k), q, b,
            operands=fi)
        for b in (1, 10)
    }
    print("ivf_flat", rows["ivf_flat"], flush=True)

    t0 = time.time()
    pi = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=1024, pq_dim=64, pq_bits=8,
                           kmeans_trainset_fraction=0.2), x)
    jax.block_until_ready(pi.list_sizes)
    print(f"ivf_pq build {time.time()-t0:.0f}s", flush=True)
    psp = ivf_pq.SearchParams(n_probes=64)
    rows["ivf_pq"] = {
        f"b{b}": latency_percentiles(
            lambda qq, ops: ivf_pq.search(psp, ops, qq, k), q, b,
            operands=pi)
        for b in (1, 10)
    }
    print("ivf_pq", rows["ivf_pq"], flush=True)

    t0 = time.time()
    ci = cagra.build(cagra.IndexParams(graph_degree=32,
                                       intermediate_graph_degree=64), x)
    jax.block_until_ready(ci.graph)
    print(f"cagra build {time.time()-t0:.0f}s", flush=True)
    csp = cagra.SearchParams(n_seeds=64, max_iterations=15)
    rows["cagra"] = {
        f"b{b}": latency_percentiles(
            lambda qq, ops: cagra.search(csp, ops, qq, k), q, b,
            operands=ci)
        for b in (1, 10)
    }
    print("cagra", rows["cagra"], flush=True)

    # the multi-CTA argument: share of batch-1 latency that is fixed cost
    for name, r in rows.items():
        fixed = r["b1"]["p50"] / max(r["b10"]["p50"], 1e-9)
        r["b1_over_b10_p50"] = round(fixed, 3)

    res = {"config": {"n": n, "dim": d, "k": k, "chip": "v5e (axon)"},
           "latency_s": rows}
    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
