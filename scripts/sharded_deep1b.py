#!/usr/bin/env python
"""DEEP-1B sharded rehearsal (VERDICT r3 #6): the largest sharded
IVF-PQ build+search this host can hold — 8M x 96 over an 8-device
virtual mesh (1M rows/shard) — plus the HBM accounting that extrapolates
the layout to DEEP-1B on a v5e-64 pod.

Mirrors the reference's DEEP-1B recipe (raft-ann-bench
run/conf/deep-1B.json: faiss_gpu_ivf_pq M48 nlist=50K over sharded
GPUs): pq_dim=48, inner_product, lists sharded over the mesh, queries
replicated, per-shard top-k merged over the mesh collective.

Run (CPU mesh): python scripts/sharded_deep1b.py [SHARDED_r05.json]
Timing on the virtual CPU mesh is NOT a TPU throughput claim — the
artifact records correctness (recall vs the exact sharded oracle) and
the memory model; per-chip QPS comes from the single-chip bench.

The refined numbers here use NO raw-dataset read anywhere in the
search+refine path: the index carries a per-list-scaled RAW-residual
cache (attach_raw_residual_cache dtype='i8' — 96 B/row at rot=96,
1.8 GB/chip in the DEEP-1B budget below; int4 at the same role measured
only ~0.58 recall on this quantization-hostile unit-norm synthetic),
each shard's scan ranks from its cache shard, and ``refine_ratio``
re-ranks the candidates at f32 decoded from that same cache
(ivf_pq._refine_slots). The reference gets the equivalent recall lever
by streaming the raw dataset through host refine
(detail/refine_host-inl.hpp) — impossible at 1B scale on HBM.
"""

import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SHARDED_r05.json"
    n, d, nq, k = 8_000_000, 96, 1024, 10
    n_lists, pq_dim, n_probes = 4096, 48, 64
    if os.environ.get("SHARDED_SMOKE"):      # fast CI/dev smoke
        n, n_lists, nq = 512_000, 256, 256

    from raft_tpu.comms import (
        sharded_ivf_pq_build, sharded_ivf_pq_search, sharded_knn,
    )
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.bench.harness import compute_recall

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("shard",))
    nshards = 8

    # low-intrinsic-dim manifold data (the bench-wide synthetic recipe):
    # pure gaussians are IVF's worst case — no cluster structure, so
    # neighbors spread over all lists and probe recall collapses (~0.25
    # measured); real embedding datasets (DEEP's CNN features) are
    # manifold-like, which this generator matches
    from raft_tpu.bench.run import _gen_device_block

    key = jax.random.PRNGKey(4)
    blk = min(1_000_000, n)
    gen = _gen_device_block(blk, d, 16)
    x = jnp.concatenate(
        [gen(jax.random.fold_in(key, b)) for b in range(n // blk)]
    )
    q = _gen_device_block(nq, d, 16)(jax.random.fold_in(key, 999))
    # L2-normalize: DEEP's CNN features are near-unit-norm, which is what
    # makes its inner_product metric well-posed — on unnormalized data
    # IP coarse assignment degenerates (big-norm centers capture
    # everything; measured 61x list skew vs 3.5x normalized)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    q = q / jnp.linalg.norm(q, axis=1, keepdims=True)

    res = {"config": {
        "n": n, "dim": d, "n_lists": n_lists, "pq_dim": pq_dim,
        "pq_bits": 8, "n_probes": n_probes, "k": k, "metric": "inner_product",
        "mesh": "8-device virtual CPU (1M rows/shard)",
        "reference_conf": "raft-ann-bench run/conf/deep-1B.json "
                          "(faiss_gpu_ivf_pq M48-nlist50K)",
    }}

    # ---- sharded build (row-sharded encode, shared quantizers) -------
    t0 = time.time()
    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=8, metric="inner_product",
        kmeans_n_iters=10, kmeans_trainset_fraction=0.1,
        cache_decoded=False,   # raw-residual cache attached below instead
    )
    index = sharded_ivf_pq_build(params, x, mesh)
    jax.block_until_ready(index.list_sizes)
    res["build_s"] = round(time.time() - t0, 1)
    cap = int(index.indices.shape[1])
    res["cap"] = cap
    res["stored_rows"] = int(np.asarray(index.list_sizes).sum())
    print(f"build: {res['build_s']}s cap={cap}", flush=True)

    # ---- raw-residual i8 cache (the DEEP-1B scan + refine source;
    # 96 B/row — see the per-chip budget below) ------------------------
    t0 = time.time()
    index = ivf_pq.attach_raw_residual_cache(index, x, block_lists=128,
                                             dtype="i8")
    jax.block_until_ready(index.recon_cache)
    res["raw_cache_s"] = round(time.time() - t0, 1)
    print(f"i8 raw cache: {res['raw_cache_s']}s", flush=True)

    # ---- exact oracle over the same mesh -----------------------------
    t0 = time.time()
    _, want = sharded_knn(q, x, k, mesh, metric="inner_product")
    want = np.asarray(want)
    res["oracle_s"] = round(time.time() - t0, 1)
    print(f"oracle: {res['oracle_s']}s", flush=True)

    # ---- sharded search: probe sweep (the reference deep-1B conf
    # sweeps nprobe 1..2000 — recall at a fixed small probe count is
    # meaningless at this lists/probes ratio) -------------------------
    if os.environ.get("SHARDED_SAVE_INDEX"):
        ivf_pq.save(os.environ["SHARDED_SAVE_INDEX"], index)
    res["probe_sweep"] = []
    for np_ in (64, 128, 256):
        # lut_dtype='f32' forces the PQ-code decode scan: the raw-PQ
        # baseline the r4 artifact measured (quantization-limited)
        sp = ivf_pq.SearchParams(n_probes=np_, local_recall_target=1.0,
                                 lut_dtype="f32")
        t0 = time.time()
        _, idx = sharded_ivf_pq_search(sp, index, q, k, mesh)
        idx = np.asarray(idx)
        rec = round(float(compute_recall(idx, want)), 4)
        entry = {
            "n_probes": np_, "recall_at_10": rec,
            "search_s_cpu_mesh": round(time.time() - t0, 1),
        }
        # the same probes scanning the raw-i8 cache (lut auto)
        sp_i8 = ivf_pq.SearchParams(n_probes=np_, local_recall_target=1.0)
        t0 = time.time()
        _, idx = sharded_ivf_pq_search(sp_i8, index, q, k, mesh)
        entry["recall_at_10_rawscan"] = round(
            float(compute_recall(np.asarray(idx), want)), 4)
        entry["search_s_rawscan"] = round(time.time() - t0, 1)
        # + per-shard cache-decoded refine (committed path, no f32 read)
        t0 = time.time()
        _, idx = sharded_ivf_pq_search(sp_i8, index, q, k, mesh,
                                       refine_ratio=5)
        entry["recall_at_10_refined"] = round(
            float(compute_recall(np.asarray(idx), want)), 4)
        entry["search_s_refined"] = round(time.time() - t0, 1)
        res["probe_sweep"].append(entry)
        print(f"nprobe={np_} pq={rec} raw={entry['recall_at_10_rawscan']} "
              f"refined={entry['recall_at_10_refined']}", flush=True)
    res["recall_at_10"] = res["probe_sweep"][-1]["recall_at_10"]
    res["recall_at_10_refined"] = (
        res["probe_sweep"][-1]["recall_at_10_refined"])
    res["refined_note"] = (
        "refine_ratio=5 per-shard cache-decoded re-rank "
        "(sharded_ivf_pq_search refine_ratio; no raw-dataset read in the "
        "search+refine path)")

    # ---- per-shard HBM accounting + DEEP-1B extrapolation ------------
    nw = index.codes.shape[-1]
    per_shard = {
        "lists": n_lists // nshards,
        "codes_mb": round(n_lists // nshards * cap * nw * 4 / 2**20, 1),
        "indices_mb": round(n_lists // nshards * cap * 4 / 2**20, 1),
        "rec_norms_mb": round(n_lists // nshards * cap * 4 / 2**20, 1),
        "centers_mb": round(n_lists // nshards * d * 4 / 2**20, 2),
    }
    res["per_shard_mb"] = per_shard

    # DEEP-1B on v5e-64: 1e9 rows, 64 chips, nlist=50k rounded to 51.2k
    # (divisible), pq48x8 codes + raw-residual i8 cache (96 B/row — the
    # scan+refine fidelity source measured above; int4 halves it but
    # measured ~0.58 recall on this synthetic), 1.3x list padding
    # (measured paddings run 1.05-1.4x)
    rows_chip = 1e9 / 64 * 1.3
    deep1b = {
        "chips": 64,
        "rows_per_chip_padded": int(rows_chip),
        "codes_gb": round(rows_chip * pq_dim / 2**30, 2),
        "i8_raw_cache_gb": round(rows_chip * 96 / 2**30, 2),
        "ids_norms_gb": round(rows_chip * 8 / 2**30, 2),
        "centers_rot_gb": round(51_200 * (96 + 96) * 4 / 2**30, 3),
        "total_gb": round(
            rows_chip * (pq_dim + 96 + 8) / 2**30
            + 51_200 * 192 * 4 / 2**30, 2),
        "hbm_per_chip_gb": 16,
    }
    deep1b["fits"] = deep1b["total_gb"] < deep1b["hbm_per_chip_gb"]
    res["deep1b_extrapolation_v5e64"] = deep1b

    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
