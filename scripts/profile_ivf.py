"""Profile the IVF-Flat search pipeline component-by-component on the
real chip. Round-2 perf work: find where the 3053-QPS round-1 number went.

Run: python scripts/profile_ivf.py [n] [nq]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


from bench import _sift_like as sift_like  # same workload the bench measures
from raft_tpu.bench.harness import time_fn


def timeit(fn, *args, iters=5, warmup=2):
    return time_fn(lambda: fn(*args), iters=iters, warmup=warmup)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    nq = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    d, k, n_lists, n_probes = 128, 10, 1024, 64

    print(f"devices: {jax.devices()}", flush=True)
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.matrix.select_k import select_k

    x = jax.device_put(sift_like(n, d, seed=1))
    q = jax.device_put(sift_like(nq, d, seed=2))

    t0 = time.perf_counter()
    params = ivf_flat.IndexParams(n_lists=n_lists, metric="sqeuclidean")
    index = ivf_flat.build(params, x)
    jax.block_until_ready(index.storage)
    print(f"build: {time.perf_counter()-t0:.1f}s  cap={index.storage.shape[1]}",
          flush=True)

    C, cap, _ = index.storage.shape
    sizes = np.asarray(index.list_sizes)
    print(f"list sizes: min={sizes.min()} max={sizes.max()} mean={sizes.mean():.0f}",
          flush=True)

    # --- raw MXU reference: what would brute force cost? ------------------
    xb = index.storage.reshape(-1, d).astype(jnp.bfloat16)

    @jax.jit
    def bf_dots(q):
        return (q.astype(jnp.bfloat16) @ xb.T).sum(axis=1)  # avoid materializing topk

    t = timeit(bf_dots, q, iters=3, warmup=1)
    flops = 2.0 * nq * (C * cap) * d
    print(f"brute dots: {t*1e3:.1f} ms  ({flops/t/1e12:.1f} TFLOP/s)", flush=True)

    # --- full current search ---------------------------------------------
    for bb, grp, lrt, cd in [(8, 256, 0.95, "bf16"),
                             (32, 256, 0.95, "bf16"),
                             (64, 256, 1.0, "bf16"),
                             (32, 512, 0.95, "bf16")]:
        sp = ivf_flat.SearchParams(n_probes=n_probes, bucket_batch=bb,
                                   query_group=grp, local_recall_target=lrt,
                                   compute_dtype=cd)
        try:
            t = timeit(lambda: ivf_flat.search(sp, index, q, k)[1], iters=3,
                       warmup=1)
            print(f"search bb={bb} grp={grp} lrt={lrt} {cd}: "
                  f"{t*1e3:.1f} ms  ({nq/t:.0f} QPS)", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"search bb={bb} grp={grp}: FAILED {type(e).__name__}: {e}",
                  flush=True)

    # --- components -------------------------------------------------------
    q32 = q.astype(jnp.float32)

    @jax.jit
    def coarse(q32):
        cdot = q32 @ index.centers.T
        qn2 = jnp.sum(q32 * q32, axis=1, keepdims=True)
        cn2 = jnp.sum(index.centers * index.centers, axis=1)
        return select_k(qn2 + cn2[None, :] - 2.0 * cdot, n_probes)[1]

    t = timeit(coarse, q32)
    print(f"coarse+select: {t*1e3:.1f} ms", flush=True)

    probes = coarse(q32)

    from raft_tpu.neighbors.ivf_flat import bucketize_pairs

    bk = jax.jit(lambda p: bucketize_pairs(p, nq, n_probes, C, 256, 8)[:2])
    t = timeit(bk, probes)
    print(f"bucketize: {t*1e3:.1f} ms", flush=True)

    bl, bq = bk(probes)
    nb = bl.shape[0]
    print(f"n_buckets(padded)={nb}", flush=True)

    # gather cost alone
    @jax.jit
    def gather_blocks(bl):
        def body(c, blc):
            blk = index.storage[blc]  # [bb, cap, d]
            return c + blk.sum(), None
        c, _ = jax.lax.scan(body, 0.0, bl.reshape(-1, 8))
        return c

    t = timeit(gather_blocks, bl, iters=3, warmup=1)
    print(f"scan gather-only (bb=8): {t*1e3:.1f} ms", flush=True)

    # gather + matmul, no select
    qg = q32[jnp.maximum(bq, 0)]  # [nb, grp, d] pre-gathered queries

    @jax.jit
    def scan_matmul(bl, qg):
        def body(c, inp):
            blc, qv = inp
            blk = index.storage[blc].astype(jnp.bfloat16)
            dots = jnp.einsum("bgd,bcd->bgc", qv.astype(jnp.bfloat16), blk,
                              preferred_element_type=jnp.float32)
            return c + dots.sum(), None
        c, _ = jax.lax.scan(body, 0.0, (bl.reshape(-1, 8), qg.reshape(-1, 8, 256, d)))
        return c

    t = timeit(scan_matmul, bl, qg, iters=3, warmup=1)
    print(f"scan gather+matmul (bb=8): {t*1e3:.1f} ms", flush=True)

    # matmul + approx topk
    @jax.jit
    def scan_matmul_topk(bl, qg):
        def body(c, inp):
            blc, qv = inp
            blk = index.storage[blc].astype(jnp.bfloat16)
            dots = jnp.einsum("bgd,bcd->bgc", qv.astype(jnp.bfloat16), blk,
                              preferred_element_type=jnp.float32)
            v, i = jax.lax.approx_min_k(dots, k, recall_target=0.95)
            return c + v.sum(), None
        c, _ = jax.lax.scan(body, 0.0, (bl.reshape(-1, 8), qg.reshape(-1, 8, 256, d)))
        return c

    t = timeit(scan_matmul_topk, bl, qg, iters=3, warmup=1)
    print(f"scan gather+matmul+approxtopk (bb=8): {t*1e3:.1f} ms", flush=True)

    @jax.jit
    def scan_matmul_exact_topk(bl, qg):
        def body(c, inp):
            blc, qv = inp
            blk = index.storage[blc].astype(jnp.bfloat16)
            dots = jnp.einsum("bgd,bcd->bgc", qv.astype(jnp.bfloat16), blk,
                              preferred_element_type=jnp.float32)
            v, i = jax.lax.top_k(-dots, k)
            return c + v.sum(), None
        c, _ = jax.lax.scan(body, 0.0, (bl.reshape(-1, 8), qg.reshape(-1, 8, 256, d)))
        return c

    t = timeit(scan_matmul_exact_topk, bl, qg, iters=3, warmup=1)
    print(f"scan gather+matmul+exact topk (bb=8): {t*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
