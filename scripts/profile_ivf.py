"""Profile the IVF-Flat (Pallas-path) and CAGRA search pipelines
component-by-component on the real chip.

Round-4 perf work (VERDICT #2): the bench configs sit at 0.30x/0.28x of
the A100 baseline while the HBM-bound scan itself should reach ~0.4x —
find which stage eats the difference. Stages measured independently with
scan-chained timing where possible:

  IVF-Flat: coarse+select | bucketize | qv-gather | fused kernel |
            unbucketize+final-merge | end-to-end
  CAGRA:    seed slab | per-iter pack gather | per-iter kernel |
            final rescore | end-to-end

Run: python scripts/profile_ivf.py [n] [nq]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _sift_like as sift_like
from raft_tpu.bench.harness import time_fn


def timeit(fn, *args, iters=5, warmup=2):
    return time_fn(lambda: fn(*args), iters=iters, warmup=warmup)


def profile_ivf_flat(x, q, n_lists=1024, n_probes=64, k=10):
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.neighbors.ivf_flat import (
        adaptive_query_group, bucketize_pairs, unbucketize_merge,
    )
    from raft_tpu.matrix.select_k import select_k
    from raft_tpu.neighbors.common import sentinel_for
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops import ivf_scan

    nq, d = q.shape
    t0 = time.perf_counter()
    params = ivf_flat.IndexParams(n_lists=n_lists, metric="sqeuclidean")
    index = ivf_flat.build(params, x)
    jax.block_until_ready(index.storage)
    C, cap, _ = index.storage.shape
    print(f"[flat] build {time.perf_counter()-t0:.1f}s cap={cap}", flush=True)

    sp = ivf_flat.SearchParams(n_probes=n_probes)
    t = timeit(lambda: ivf_flat.search(sp, index, q, k)[1], iters=5, warmup=2)
    print(f"[flat] end-to-end: {t*1e3:.1f} ms ({nq/t:.0f} QPS)", flush=True)

    q32 = q.astype(jnp.float32)
    group = adaptive_query_group(nq, n_probes, C, sp.query_group)
    print(f"[flat] group={group}", flush=True)

    @jax.jit
    def coarse(q32):
        cdot = q32 @ index.centers.T
        qn2 = jnp.sum(q32 * q32, axis=1, keepdims=True)
        cn2 = jnp.sum(index.centers * index.centers, axis=1)
        return select_k(qn2 + cn2[None, :] - 2.0 * cdot, n_probes)[1]

    print(f"[flat] coarse+select: {timeit(coarse, q32)*1e3:.1f} ms", flush=True)
    probes = coarse(q32)

    bk = jax.jit(lambda p: bucketize_pairs(p, nq, n_probes, C, group,
                                           sp.bucket_batch))
    t = timeit(lambda: bk(probes)[0], iters=5)
    print(f"[flat] bucketize: {t*1e3:.1f} ms", flush=True)
    (bl, bq, pair_bucket, pair_pos, order, total, nb_pad) = bk(probes)
    print(f"[flat] n_buckets={bl.shape[0]}", flush=True)

    @jax.jit
    def qv_gather(q32, bq):
        qs = jnp.maximum(bq, 0)
        qv = q32[qs].astype(jnp.bfloat16)
        qaux = jnp.sum(q32[qs] * q32[qs], axis=2)
        return qv, qaux

    t = timeit(lambda: qv_gather(q32, bq)[0], iters=5)
    print(f"[flat] qv gather: {t*1e3:.1f} ms", flush=True)
    qv, qaux = qv_gather(q32, bq)

    storage = index.storage
    norms = jnp.sum(storage.astype(jnp.float32) ** 2, axis=2)

    def kern(bl, qv, qaux):
        return ivf_scan.fused_list_scan_topk(
            storage, index.indices, index.list_sizes, bl, qv, qaux, norms,
            None, k=k, metric_kind=ivf_scan.L2, approx=True)[0]

    t = timeit(lambda: jax.jit(kern)(bl, qv, qaux), iters=5)
    print(f"[flat] fused kernel: {t*1e3:.1f} ms", flush=True)
    out_d, out_i = jax.jit(
        lambda bl, qv, qaux: ivf_scan.fused_list_scan_topk(
            storage, index.indices, index.list_sizes, bl, qv, qaux, norms,
            None, k=k, metric_kind=ivf_scan.L2, approx=True)
    )(bl, qv, qaux)

    sentinel = sentinel_for(DistanceType.L2Expanded, jnp.float32)

    @jax.jit
    def unb(out_d, out_i):
        # candidate width off the kernel output — the fold extraction
        # arm returns R*128-wide buffers instead of k
        return unbucketize_merge(
            out_d, out_i, pair_bucket, pair_pos, order, total, nq,
            n_probes, int(out_d.shape[2]), k, True, sentinel)[1]

    t = timeit(lambda: unb(out_d, out_i), iters=5)
    print(f"[flat] unbucketize+merge: {t*1e3:.1f} ms", flush=True)


def profile_cagra(x, q, k=10):
    from raft_tpu.neighbors import cagra

    nq, d = q.shape
    t0 = time.perf_counter()
    params = cagra.IndexParams(graph_degree=32, intermediate_graph_degree=64)
    index = cagra.build(params, x)
    jax.block_until_ready(index.graph)
    print(f"[cagra] build {time.perf_counter()-t0:.1f}s", flush=True)

    sp = cagra.SearchParams(itopk_size=64, search_width=2)
    t = timeit(lambda: cagra.search(sp, index, q, k)[1], iters=5, warmup=2)
    print(f"[cagra] end-to-end: {t*1e3:.1f} ms ({nq/t:.0f} QPS)", flush=True)

    # stage split: iterations vs fixed cost — vary max_iterations
    for iters in (6, 12, 24):
        spi = cagra.SearchParams(itopk_size=64, search_width=2,
                                 max_iterations=iters)
        t = timeit(lambda: cagra.search(spi, index, q, k)[1], iters=5,
                   warmup=1)
        print(f"[cagra] iters={iters}: {t*1e3:.1f} ms", flush=True)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    nq = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    print(f"devices: {jax.devices()}", flush=True)
    x = jax.device_put(sift_like(n, 128, seed=1))
    q = jax.device_put(sift_like(nq, 128, seed=2))
    jax.block_until_ready(x)
    profile_ivf_flat(x, q)
    profile_cagra(x, q)


if __name__ == "__main__":
    main()
