#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Current headline: IVF-Flat-class search throughput on a synthetic SIFT-1M
workload. Until IVF-Flat lands, falls back to brute-force KNN on SIFT-10K
(BASELINE.md north-star config #1). Runs on whatever jax.devices()[0] is
(the real TPU chip under the driver).

Baseline (vs_baseline denominator): see BASELINE.md — A100-class reference
throughput for the same config. Values are estimates until the reference
harness is run on GPU hardware; documented per-config in _BASELINES.
"""

import json
import time

import numpy as np


# Estimated A100/raft-24.02 reference throughputs (queries/s) for the
# BASELINE.md north-star configs. Marked estimates: the reference publishes
# no numeric tables (BASELINE.md), so these are FLOP/bandwidth-derived
# A100 figures to normalize against until real GPU runs are recorded.
_BASELINES = {
    "bruteforce_sift10k_qps": 2.0e6,   # 10k x 10k x 128 L2 + top-k, batch 10k
    "ivfflat_sift1m_qps": 4.0e5,       # nlist=1024, nprobe=64, batch 10k, r@10>0.95
}


def _sift_like(n, d, seed=0):
    rng = np.random.default_rng(seed)
    # SIFT-ish: non-negative, clustered-ish fp32
    centers = rng.uniform(0, 128, (64, d))
    x = centers[rng.integers(0, 64, n)] + rng.normal(0, 12, (n, d))
    return np.clip(x, 0, 255).astype(np.float32)


def bench_bruteforce_sift10k():
    import jax
    from raft_tpu.neighbors import brute_force
    from raft_tpu.bench.harness import compute_recall, time_fn
    from tests.oracles import naive_knn  # numpy oracle

    n, d, nq, k = 10_000, 128, 10_000, 10
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(nq, d, seed=2))

    index = brute_force.build(x, "sqeuclidean")
    dist, idx = brute_force.search(index, q, k)
    jax.block_until_ready(idx)

    # recall sanity on a subset (exact method -> ~1.0)
    sub = 500
    _, want = naive_knn(np.asarray(q[:sub]), np.asarray(x), k)
    recall = compute_recall(np.asarray(idx[:sub]), want)

    search_s = time_fn(lambda: brute_force.search(index, q, k)[1], iters=20, warmup=3)
    qps = nq / search_s
    return {
        "metric": "bruteforce_sift10k_qps",
        "value": round(qps, 1),
        "unit": "QPS (k=10, batch=10k, L2, recall=%.3f)" % recall,
        "vs_baseline": round(qps / _BASELINES["bruteforce_sift10k_qps"], 3),
    }


def bench_ivfflat_sift1m():
    import jax
    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.bench.harness import compute_recall, time_fn

    n, d, nq, k = 1_000_000, 128, 10_000, 10
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(nq, d, seed=2))

    params = ivf_flat.IndexParams(n_lists=1024, metric="sqeuclidean")
    index = ivf_flat.build(params, x)
    # scan_impl="auto" dispatches to the fused Pallas scan kernel on TPU
    sp = ivf_flat.SearchParams(n_probes=64)
    dist, idx = ivf_flat.search(sp, index, q, k)
    jax.block_until_ready(idx)

    # recall vs exact on a query subset
    sub = 1000
    _, bf_idx = brute_force.knn(q[:sub], x, k)
    recall = compute_recall(np.asarray(idx[:sub]), np.asarray(bf_idx))

    search_s = time_fn(lambda: ivf_flat.search(sp, index, q, k)[1], iters=20, warmup=3)
    qps = nq / search_s
    return {
        "metric": "ivfflat_sift1m_qps",
        "value": round(qps, 1),
        "unit": "QPS (nlist=1024, nprobe=64, k=10, batch=10k, recall=%.3f)" % recall,
        "vs_baseline": round(qps / _BASELINES["ivfflat_sift1m_qps"], 3),
    }


def main():
    try:
        from raft_tpu.neighbors import ivf_flat  # noqa: F401
    except ImportError:
        result = bench_bruteforce_sift10k()
    else:
        result = bench_ivfflat_sift1m()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
