#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric
(IVF-Flat SIFT-1M-class QPS @ recall) plus the other BASELINE.md
north-star configs in "extra".

Timing methodology (important on the tunnelled `axon` platform):
`jax.block_until_ready` does not reliably synchronize across the tunnel,
host fetches carry hundreds of ms of round-trip latency, and re-fetching
an identical computation can be served from a cache — so per-call host
timing is untrustworthy in *both* directions. Every QPS number here is
measured as a **scan-chained on-device loop**: N search iterations run
inside one jitted program, each on a rolled (distinct) query batch, all
folded into a returned checksum so XLA cannot elide any iteration. Wall
time is taken at two iteration counts (N1 < N2) and the per-iteration
time is (T2-T1)/(N2-N1), cancelling the constant dispatch + RTT + fetch
overhead. This reports steady-state on-device throughput — what a batch
search service would sustain.

Baselines (vs_baseline denominator): documented per-config in _BASELINES;
see BASELINE.md for the derivations. The reference publishes no numeric
tables (only a Pareto plot), so these are roofline-derived A100 figures,
explicitly labeled as estimates.
"""

import json
import os
import sys
import time

import numpy as np

# A100/raft-24.02 reference throughput estimates for the north-star
# configs. The reference publishes NO numeric tables (only the H100
# recall-vs-QPS Pareto plot, docs/source/raft_ann_benchmarks.md:254) and
# this environment has no network to fetch public runs, so every
# denominator below is a FLOP/bandwidth roofline for an A100-80GB
# [312 TF/s fp16 tensor, 2.0 TB/s HBM] with its derivation and
# confidence documented per entry (BASELINE.md "Baseline provenance").
_BASELINES = {
    # 10k x 10k x 128 L2 + top-k = 33 GFLOP/batch; at ~50% tensor peak
    # plus selection overhead -> ~2e6 QPS. Confidence MEDIUM (pure
    # roofline; public GPU brute-force numbers at this shape are scarce).
    "bruteforce_sift10k_qps": 2.0e6,
    # nlist=1024, nprobe=64, batch 10k, r@10~0.95: scans ~1/16 of 512 MB
    # per query batch -> HBM-bound ~4e5 QPS. Confidence MEDIUM-HIGH
    # (consistent with the H100 Pareto plot's IVF-Flat band scaled to
    # A100 bandwidth).
    "ivfflat_sift1m_qps": 4.0e5,
    # pairwise 10k x 10k x 128 f32: bound by the 400 MB output write,
    # ~0.7x of 2 TB/s effective. Confidence HIGH (straight bandwidth).
    "pairwise_l2_gbps": 1400.0,
    # DEEP-10M pq48x8, nprobe=128: LUT-gather bound; scaled from the
    # reference's DEEP-100M positioning. Confidence LOW-MEDIUM (config
    # scaled down from the published 100M benchmarks).
    "ivfpq_deep10m_qps": 2.0e5,
    # CAGRA deg32 SIFT-1M r@10~0.95 batch 10k: the CAGRA paper
    # (arXiv:2308.15136, fig. batch-throughput) places A100 large-batch
    # SIFT-1M throughput in the 5e5-1e6 band at 0.95. Confidence MEDIUM
    # (anchored to the paper's published order of magnitude).
    "cagra_sift1m_qps": 6.0e5,
}


def _sift_like(n, d, seed=0, intrinsic=16):
    """SIFT-like synthetic: points near a low-intrinsic-dimension manifold
    (real SIFT has intrinsic dim ~15 in 128 ambient dims). A
    few-isolated-blobs mixture is *adversarial* for graph ANN (the KNN
    graph disconnects); this matches realistic ANN difficulty instead.
    Generated ON DEVICE (synthetic_dataset_device): the dev tunnel moves
    host arrays at ~20 MB/s, so host generation was charging minutes of
    fake transfer time to every build. Ground truth is computed from
    these same arrays, so recall stays consistent."""
    from raft_tpu.bench.run import synthetic_dataset_device

    base, _ = synthetic_dataset_device(n, d, n_queries=1, seed=seed,
                                       intrinsic_dim=intrinsic)
    return base


from raft_tpu.bench.harness import scan_qps_time  # noqa: E402


def _emit_roofline(results, stub, *, bytes_moved, flops, seconds,
                   rows=None):
    """Roofline columns next to each QPS number (ROADMAP item 1): the
    op's cost model (ideal HBM bytes + FLOPs as implemented) against
    the measured seconds, scored vs the backend peak spec
    (raft_tpu.bench.harness.PEAK_SPECS; methodology docs/kernels.md).
    ``rows`` = dataset rows scanned per timed iteration, for the
    bytes_per_row column (the quantization ladder's figure of merit)."""
    from raft_tpu.bench.harness import roofline

    r = roofline(bytes_moved, flops, seconds)
    results[f"{stub}_roofline"] = r
    results[f"{stub}_peak_fraction"] = r["peak_fraction"]
    if rows:
        results[f"{stub}_bytes_per_row"] = round(bytes_moved / rows, 2)


def _median_s(results, key_stub, timer, n_draws=5):
    """Variance-honest timing: run ``timer()`` (one scan-chained
    two-point measurement = one draw) ``n_draws`` times, record EVERY
    draw under ``{key_stub}_draws_s`` and return the median seconds.
    Tunnel jitter spreads single draws by up to ~2x (BASELINE.md round-3
    spread: pairwise 41-868 GB/s); medians of >=5 draws are stable to
    ~10% and the full list keeps the spread auditable."""
    draws = [timer() for _ in range(n_draws)]
    results[f"{key_stub}_draws_s"] = [round(s, 6) for s in draws]
    return float(np.median(draws))


def bench_bruteforce_sift10k(results):
    import jax
    from raft_tpu.neighbors import brute_force

    n, d, nq, k = 10_000, 128, 10_000, 10
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(nq, d, seed=2))
    index = brute_force.build(x, "sqeuclidean")
    s = _median_s(results, "bruteforce_sift10k", lambda: scan_qps_time(
        lambda qq, ix: brute_force.search(ix, qq, k), q, operands=index))
    results["bruteforce_sift10k_qps"] = round(nq / s, 1)
    from raft_tpu.distance.types import DistanceType, pair_flops

    # cost model: one full dataset stream + query/output traffic per
    # batch; the fused kernel's whole point is that the [nq, n] distance
    # matrix is NOT in this byte count (it never reaches HBM)
    _emit_roofline(
        results, "bruteforce_sift10k",
        bytes_moved=n * d * 4 + nq * d * 4 + nq * k * 8,
        flops=nq * n * pair_flops(DistanceType.L2Expanded, d),
        seconds=s, rows=n)


def bench_pairwise(results):
    import jax
    from raft_tpu.distance import pairwise_distance

    n, d = 10_000, 128
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(n, d, seed=2))
    s = _median_s(results, "pairwise_l2", lambda: scan_qps_time(
        lambda qq, xx: (pairwise_distance(qq, xx, "sqeuclidean"),
                        jax.numpy.zeros((1,), jax.numpy.int32)),
        q, operands=x))
    bytes_moved = n * d * 4 * 2 + n * n * 4
    results["pairwise_l2_gbps"] = round(bytes_moved / s / 1e9, 1)
    results["pairwise_l2_gflops"] = round(2 * n * n * d / s / 1e9, 1)
    # pairwise MATERIALIZES its output, so the n*n*4 write dominates the
    # byte model — the bandwidth-bound contrast to the fused search ops
    _emit_roofline(results, "pairwise_l2", bytes_moved=bytes_moved,
                   flops=2 * n * n * d, seconds=s, rows=n)


def bench_ivfflat_sift1m(results):
    import jax
    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.bench.harness import compute_recall

    n, d, nq, k = 1_000_000, 128, 10_000, 10
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(nq, d, seed=2))
    t0 = time.time()
    params = ivf_flat.IndexParams(n_lists=1024, metric="sqeuclidean")
    index = ivf_flat.build(params, x)
    np.asarray(index.list_sizes)  # sync build
    results["ivfflat_build_s"] = round(time.time() - t0, 1)

    sp = ivf_flat.SearchParams(n_probes=64)
    dist, idx = ivf_flat.search(sp, index, q, k)
    sub = 1000
    _, bf_idx = brute_force.knn(q[:sub], x, k)
    recall = compute_recall(np.asarray(idx[:sub]), np.asarray(bf_idx))
    s = _median_s(results, "ivfflat_sift1m", lambda: scan_qps_time(
        lambda qq, ix: ivf_flat.search(sp, ix, qq, k), q, operands=index))
    results["ivfflat_sift1m_qps"] = round(nq / s, 1)
    results["ivfflat_recall"] = round(float(recall), 3)
    from raft_tpu.distance.types import DistanceType, pair_flops

    # cost model: coarse centers GEMM + probed-list block streams
    # (storage row f32 + stored id + precomputed norm per row)
    cap = int(index.storage.shape[1])
    rows = nq * sp.n_probes * cap
    pf = pair_flops(DistanceType.L2Expanded, d)
    _emit_roofline(
        results, "ivfflat_sift1m",
        bytes_moved=rows * (d * 4 + 4 + 4) + nq * d * 4,
        flops=rows * pf + nq * index.n_lists * pf,
        seconds=s, rows=rows)


def bench_cagra_sift1m(results):
    import jax
    from raft_tpu.neighbors import brute_force, cagra
    from raft_tpu.bench.harness import compute_recall

    n, d, nq, k = 1_000_000, 128, 10_000, 10
    x = jax.device_put(_sift_like(n, d, seed=1))
    q = jax.device_put(_sift_like(nq, d, seed=2))
    t0 = time.time()
    index = cagra.build(
        cagra.IndexParams(graph_degree=32, intermediate_graph_degree=64), x
    )
    np.asarray(index.graph[0, 0])  # sync build
    results["cagra_build_s"] = round(time.time() - t0, 1)
    # n_seeds=64 + 15 iterations: measured 0.960 recall @ 181k QPS on the
    # fused Pallas beam path (auto-iters=17 buys 0.971 at 151k)
    sp = cagra.SearchParams(n_seeds=64, max_iterations=15)
    dist, idx = cagra.search(sp, index, q, k)
    sub = 1000
    _, bf_idx = brute_force.knn(q[:sub], x, k)
    recall = compute_recall(np.asarray(idx[:sub]), np.asarray(bf_idx))
    s = _median_s(results, "cagra_sift1m", lambda: scan_qps_time(
        lambda qq, ix: cagra.search(sp, ix, qq, k), q, operands=index))
    results["cagra_sift1m_qps"] = round(nq / s, 1)
    results["cagra_recall"] = round(float(recall), 3)
    from raft_tpu.distance.types import DistanceType, pair_flops

    # cost model: seeds + per-iteration beam expansion (graph row of 32
    # neighbor ids + each neighbor's vector) — a graph walk's traffic is
    # gather-shaped, so this is the IDEAL byte floor, not a stream
    deg = int(index.graph.shape[1])
    visited = nq * (sp.n_seeds + 15 * deg)
    _emit_roofline(
        results, "cagra_sift1m",
        bytes_moved=visited * (d * 4 + 4) + nq * 15 * deg * 4,
        flops=visited * pair_flops(DistanceType.L2Expanded, d),
        seconds=s, rows=visited)


def bench_cagra_graph_build(results):
    """Graph-build roofline (ISSUE 15, ROADMAP item 7): time the
    rebuilt nn-descent at the 1M scale and score it against the
    gather byte floor — per iteration every node gathers S+K candidate
    vectors (+ the sampled two-hop ids), so the ideal traffic is
    ``iters * n * (S+K) * (d*4 + 4)`` bytes against
    ``iters * n * (S+K) * pair_flops`` FLOPs. The old formulation
    added ``n*2K*K*4`` bytes of two-hop tensor per iteration on top —
    deleted by sample-then-gather, which is why it is not in this
    model (the cost model is the algorithm as implemented)."""
    import jax
    import jax.numpy as jnp
    from raft_tpu.neighbors import nn_descent
    from raft_tpu.distance.types import DistanceType, pair_flops

    n, d, deg, iters = 1_000_000, 128, 32, 14
    # clustered blobs, generated ON DEVICE (tunnel moves host arrays at
    # ~20 MB/s): the sampled pull-join localizes blobs in ~10-16 rounds
    # but flat low-intrinsic-dim manifolds crawl at ~0.04
    # recall/iteration (GRAPH_r15.json sweep, 2026-08-04) — _sift_like
    # here would publish an iteration-budget artifact, not a build
    # property (ROADMAP item 7b tracks the convergence-rate work)
    kc, ka, kn = jax.random.split(jax.random.PRNGKey(5), 3)
    centers = jax.random.uniform(kc, (1024, d), jnp.float32, -5.0, 5.0)
    x = (centers[jax.random.randint(ka, (n,), 0, 1024)]
         + 0.6 * jax.random.normal(kn, (n, d), jnp.float32))
    x = jax.block_until_ready(x)
    params = nn_descent.IndexParams(
        graph_degree=deg, max_iterations=iters,
        termination_threshold=0.0)
    t0 = time.time()
    index = nn_descent.build(params, x)
    g = np.asarray(index.graph)                 # sync
    s = time.time() - t0
    results["graph_build_s"] = round(s, 1)
    from raft_tpu.neighbors import brute_force

    sub = 500
    _, want = brute_force.knn(x[:sub], x, deg + 1)
    want = np.asarray(want)[:, 1:]
    results["graph_build_recall"] = round(float(np.mean(
        [len(set(g[i]) & set(want[i])) / deg for i in range(sub)])), 3)
    K = deg * 3 // 2
    S = int(params.n_candidates)
    C = S + K
    _emit_roofline(
        results, "graph_build",
        bytes_moved=iters * n * (C * (d * 4 + 4) + S * 4),
        flops=iters * n * C * pair_flops(DistanceType.L2Expanded, d),
        seconds=s, rows=iters * n * C)


def bench_ivfpq_deep10m(results):
    import jax
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.bench.harness import compute_recall

    n, d, nq, k = 10_000_000, 96, 10_000, 10
    x = _sift_like(n, d, seed=3)
    q = jax.device_put(_sift_like(nq, d, seed=4))
    t0 = time.time()
    # streaming build: per-batch encode keeps the full-dataset rotation /
    # residual intermediates (≈12 GB at 10M x 96) out of HBM
    # trainset fraction 0.1: 1M training rows are plenty for 1024 coarse
    # centers + codebooks and cut the dominant kmeans/upload cost
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=1024, pq_dim=48, pq_bits=8,
                           kmeans_trainset_fraction=0.1), x,
        batch_size=2_000_000,
    )
    np.asarray(index.list_sizes)
    results["ivfpq_build_s"] = round(time.time() - t0, 1)
    sp = ivf_pq.SearchParams(n_probes=128)
    dist, idx = ivf_pq.search(sp, index, q, k)
    np.asarray(idx[0, 0])  # first call: compile + warm
    t0 = time.time()
    # DISTINCT queries: an identical repeat can be served from the
    # platform result cache, under-measuring by ~30x and mis-sizing the
    # scan right into the program watchdog
    import jax.numpy as jnp

    _, idx2 = ivf_pq.search(sp, index, jnp.roll(q, 1, axis=0), k)
    np.asarray(idx2[0, 0])
    rough_s = max(time.time() - t0, 0.1)  # warm order-of-magnitude + RTT
    # chunked exact oracle on a query subset
    sub = 500
    from raft_tpu.bench.run import generate_groundtruth

    mi = generate_groundtruth(
        x, np.asarray(q[:sub]), k, "sqeuclidean", chunk=2_000_000
    )
    recall = compute_recall(np.asarray(idx[:sub]), np.asarray(mi))
    # size the scan so one timed program stays well under the remote
    # platform's ~2 min single-program watchdog
    n2 = int(np.clip(45.0 / rough_s, 2, 13))
    n1 = max(1, n2 // 3)
    s = _median_s(results, "ivfpq_deep10m", lambda: scan_qps_time(
        lambda qq, ix: ivf_pq.search(sp, ix, qq, k), q,
        n1=n1, n2=n2, operands=index), n_draws=3)
    results["ivfpq_deep10m_qps"] = round(nq / s, 1)
    results["ivfpq_recall"] = round(float(recall), 3)
    # cost model: probed lists stream pq codes (pq_dim * pq_bits/8
    # bytes) + stored id per row, plus the coarse GEMM — the
    # rows-per-HBM-byte ceiling the quantization ladder multiplies
    cap_pq = int(index.indices.shape[1])
    rows_pq = nq * sp.n_probes * cap_pq
    code_bytes = 48 * 8 // 8            # pq48x8
    _emit_roofline(
        results, "ivfpq_deep10m",
        bytes_moved=rows_pq * (code_bytes + 4) + nq * d * 4,
        flops=rows_pq * 2 * int(index.rot_dim),
        seconds=s, rows=rows_pq)

    # + exact refine (the reference's standard recall lever: its bench
    # runs IVF-PQ with refine_ratio, raft_ivf_pq_wrapper.h) — recall
    # plateaus at 0.893 on raw pq48 codes regardless of n_probes
    # (measured at 128/160/192), so the re-rank is what clears 0.90
    from raft_tpu.neighbors.refine import refine

    x_dev = jnp.asarray(x)

    def search_refined(qq, ops):
        ix, xs = ops   # dataset rides operands: closure capture would
        # bake the 3.8 GB array into the HLO as a constant (harness doc)
        _, cand = ivf_pq.search(sp, ix, qq, 3 * k)
        return refine(xs, qq, cand, k, "sqeuclidean")

    dist_r, idx_r = search_refined(q, (index, x_dev))
    recall_r = compute_recall(np.asarray(idx_r[:sub]), np.asarray(mi))
    s = _median_s(results, "ivfpq_refined", lambda: scan_qps_time(
        search_refined, q, n1=n1, n2=n2, operands=(index, x_dev)),
        n_draws=3)
    results["ivfpq_refined_qps"] = round(nq / s, 1)
    results["ivfpq_refined_recall"] = round(float(recall_r), 3)

    # + cache-resident refine: raw-residual i8 cache as both scan operand
    # and refine source — the billion-scale pattern (SHARDED_r05.json)
    # measured here as a DATASET-FREE Pareto point (the f32-refined
    # config above reads the 3.8 GB dataset per query batch; this one
    # reads only the 1 B/dim cache)
    try:
        index_raw = ivf_pq.attach_raw_residual_cache(index, x_dev,
                                                     dtype="i8")
        np.asarray(index_raw.cache_scales[0, 0])   # sync the attach

        def search_cache_refined(qq, ix):
            return ivf_pq.search_refined(sp, ix, qq, k, refine_ratio=3)

        _, idx_cr = search_cache_refined(q, index_raw)
        results["ivfpq_cache_refined_recall"] = round(float(
            compute_recall(np.asarray(idx_cr[:sub]), np.asarray(mi))), 3)
        s = _median_s(results, "ivfpq_cache_refined", lambda: scan_qps_time(
            search_cache_refined, q, n1=n1, n2=n2, operands=index_raw),
            n_draws=3)
        results["ivfpq_cache_refined_qps"] = round(nq / s, 1)
        del index_raw
    except Exception as e:  # noqa: BLE001 - keep the headline alive
        results["ivfpq_cache_refined_error"] = repr(e)[:200]

    # + tiered host-tier refine (ISSUE 12, docs/serving.md §12): the
    # f32 originals stay HOST-resident — only each batch's unique
    # shortlist rows cross the link (vs the x_dev full upload the
    # f32-refined config above is built on). Wall-clock timed: the
    # host gather sits outside the jit chain, so scan_qps_time's
    # scan-chained methodology cannot carry it. Emits the
    # bytes-moved-per-query column ROADMAP item 3 budgets against.
    try:
        from raft_tpu.neighbors import tiered as _tiered

        src_t = _tiered.HostArraySource(x, hot_rows=65536)

        def search_tiered(qq):
            return ivf_pq.search_refined(sp, index, qq, k,
                                         refine_ratio=3, dataset=src_t)

        dist_t, idx_t = search_tiered(q)
        jax.block_until_ready(idx_t)
        assert np.array_equal(np.asarray(idx_t), np.asarray(idx_r)), \
            "tiered rerank diverged from the full-upload refine"
        results["ivfpq_tiered_refined_recall"] = round(float(
            compute_recall(np.asarray(idx_t[:sub]), np.asarray(mi))), 3)
        st0 = src_t.stats()
        t0 = time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(search_tiered(q))
        s = (time.perf_counter() - t0) / 3
        st1 = src_t.stats()
        results["ivfpq_tiered_refined_qps"] = round(nq / s, 1)
        results["ivfpq_tiered_bytes_per_query"] = round(
            (st1["bytes_moved"] - st0["bytes_moved"]) / (3 * nq), 1)
        results["ivfpq_tiered_hot_hit_rate"] = round(
            st1["hit_rate_hbm"], 4)
        results["ivfpq_tiered_timing"] = "wall-clock (host gather)"
        del src_t
    except Exception as e:  # noqa: BLE001 - keep the headline alive
        results["ivfpq_tiered_refined_error"] = repr(e)[:200]

    # + the rabitq rung (ISSUE 11): 1-bit sign-code first stage + exact
    # rerank from the PQ codes — the rows-per-HBM-byte ladder's bottom
    # step. Emits TWO byte columns per arm (cost model:
    # ivf_pq.scan_bytes_per_row): the roofline row carries the honest
    # total traffic (codes + estimator scalars + id/slot row), and
    # *_code_bytes_per_row carries the quantized payload alone — the
    # ladder figure where i4 → rabitq is the full 4x (rot/2 vs rot/8)
    try:
        index_rbq = ivf_pq.attach_rabitq_cache(index)
        np.asarray(index_rbq.cache_fac[0, 0])              # sync attach
        rot = int(index.rot_dim)
        kc_rb = 4 * k

        def search_rabitq(qq, ix):
            return ivf_pq.search_refined(sp, ix, qq, k, refine_ratio=4)

        _, idx_rb = search_rabitq(q, index_rbq)
        results["ivfpq_rabitq_recall"] = round(float(
            compute_recall(np.asarray(idx_rb[:sub]), np.asarray(mi))), 3)
        s = _median_s(results, "ivfpq_rabitq", lambda: scan_qps_time(
            search_rabitq, q, n1=n1, n2=n2, operands=index_rbq),
            n_draws=3)
        results["ivfpq_rabitq_qps"] = round(nq / s, 1)
        # first-stage-only roofline (the scan the compression ladder
        # multiplies): timed at the pipeline's shortlist width
        s1 = _median_s(results, "ivfpq_rabitq_stage1",
                       lambda: scan_qps_time(
                           lambda qq, ix: ivf_pq.search(sp, ix, qq, kc_rb),
                           q, n1=n1, n2=n2, operands=index_rbq),
                       n_draws=3)
        rb_code, rb_total = ivf_pq.scan_bytes_per_row("rabitq", rot)
        i4_code, i4_total = ivf_pq.scan_bytes_per_row("i4", rot)
        _emit_roofline(
            results, "ivfpq_rabitq_stage1",
            bytes_moved=rows_pq * rb_total + nq * d * 4,
            flops=rows_pq * 2 * rot,
            seconds=s1, rows=rows_pq)
        results["ivfpq_rabitq_code_bytes_per_row"] = rb_code
        results["ivfpq_i4_code_bytes_per_row"] = i4_code
        results["ivfpq_i4_scan_bytes_per_row"] = i4_total
        del index_rbq
    except Exception as e:  # noqa: BLE001 - keep the headline alive
        results["ivfpq_rabitq_error"] = repr(e)[:200]


def main():
    # --obs-snapshot [PATH]: run instrumented (graft-scope, RAFT_TPU_OBS
    # at least "on") and write the metrics-snapshot sidecar next to the
    # headline JSON line — dispatch winners, per-algo latency histograms,
    # OOM-ladder/retry counts, device memory gauges (docs/observability.md)
    obs_path = None
    if "--obs-snapshot" in sys.argv:
        i = sys.argv.index("--obs-snapshot")
        obs_path = (sys.argv[i + 1] if i + 1 < len(sys.argv)
                    and not sys.argv[i + 1].startswith("-")
                    else "BENCH_obs.json")
        from raft_tpu import obs

        if not obs.enabled():
            obs.set_mode("on")

    # Fail fast and parseably when the TPU backend is unreachable (the
    # round-4 outage left BENCH_r04.json holding a 40-line traceback;
    # the driver's record should stay one JSON line either way).
    from raft_tpu.bench.harness import probe_tpu

    ok, detail = probe_tpu(float(os.environ.get("BENCH_INIT_TIMEOUT_S",
                                                "120")))
    if not ok:
        print(json.dumps({
            "metric": "ivfflat_sift1m_qps",
            "value": 0,
            "unit": "QPS",
            "vs_baseline": 0.0,
            "error": "tpu_unavailable",
            "detail": detail[:200],
        }))
        return

    results = {}
    full = os.environ.get("BENCH_FULL", "1") != "0"
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "4500"))
    t_start = time.time()
    bench_bruteforce_sift10k(results)
    bench_pairwise(results)
    bench_ivfflat_sift1m(results)
    if full:
        try:
            bench_cagra_sift1m(results)
        except Exception as e:  # keep the headline alive on partial failure
            results["cagra_error"] = repr(e)[:200]
        try:
            bench_cagra_graph_build(results)
        except Exception as e:
            results["graph_build_error"] = repr(e)[:200]
        # the PQ bench needs ~2400s end to end (BASELINE.md measurement);
        # only start it if that fits in what's left of the budget
        if budget_s - (time.time() - t_start) > 2400:
            try:
                bench_ivfpq_deep10m(results)
            except Exception as e:
                results["ivfpq_error"] = repr(e)[:200]
        else:
            results["ivfpq_skipped"] = "insufficient bench time budget"

    qps = results["ivfflat_sift1m_qps"]
    out = {
        "metric": "ivfflat_sift1m_qps",
        "value": qps,
        "unit": "QPS (nlist=1024, nprobe=64, k=10, batch=10k, recall=%.3f)"
        % results.get("ivfflat_recall", -1.0),
        "vs_baseline": round(qps / _BASELINES["ivfflat_sift1m_qps"], 3),
        "extra": {
            kk: {
                "value": vv,
                "vs_baseline": (
                    round(vv / _BASELINES[kk], 4) if kk in _BASELINES else None
                ),
            }
            for kk, vv in results.items()
        },
    }
    if obs_path is not None:
        from raft_tpu.bench.harness import write_obs_snapshot

        write_obs_snapshot(obs_path)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
