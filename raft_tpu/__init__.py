"""raft_tpu — a TPU-native vector-search and ML-primitives framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of RAPIDS RAFT
(reference: rhdong/raft 24.02; see SURVEY.md): pairwise distances, fused
k-selection, balanced k-means, IVF-Flat / IVF-PQ / CAGRA ANN indexes,
brute-force KNN, refine, nn-descent, sparse primitives, stats, and a
distributed layer built on JAX collectives over ICI/DCN.

Layer map (mirrors the reference's cpp/include/raft/<layer> — SURVEY.md §1):

    core       resources handle, bitset, serialization, logging, tracing
    utils      tiling/alignment math, misc device helpers
    linalg     gemm/svd/eig/qr wrappers, map/reduce/norm engines
    matrix     matrix utilities + the select_k top-k engine
    random     RNG state, make_blobs, rmat, sampling
    distance   pairwise distances (all reference metrics), fused_l2_nn, gram
    sparse     COO/CSR types, sparse linalg/distance, MST, Lanczos
    cluster    kmeans, kmeans_balanced, single_linkage, spectral
    neighbors  brute_force, ivf_flat, ivf_pq, cagra, nn_descent, refine, ...
    stats      summary stats + metrics incl. neighborhood_recall
    solver     linear assignment (LAP), label utilities
    comms      collectives facade over jax.lax/shard_map (NCCL/UCX analog)
    ops        Pallas TPU kernels for the hot paths
    bench      ANN benchmark harness (raft-ann-bench analog)
    obs        graft-scope: spans, metrics registry, flight recorder
    serve      graft-serve: online serving engine — micro-batching,
               versioned index hot-swap, tombstone mutation
"""

__version__ = "0.1.0"

import os as _os

import jax as _jax

# Persistent XLA compilation cache: index builds compile a handful of large
# EM/scan programs (~70 s cold on the tunnelled TPU, ~0 warm); caching them
# on disk makes every process after the first pay only runtime. Opt out
# with RAFT_TPU_NO_COMPILE_CACHE=1.
if (not _os.environ.get("RAFT_TPU_NO_COMPILE_CACHE")
        and not _os.environ.get("JAX_COMPILATION_CACHE_DIR")
        and getattr(_jax.config, "jax_compilation_cache_dir", None) is None):
    # never override a cache the user already configured
    try:
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.environ.get(
                "RAFT_TPU_COMPILE_CACHE",
                _os.path.join(_os.path.expanduser("~"), ".raft_tpu_cache"),
            ),
        )
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass

from raft_tpu.core.resources import Resources, DeviceResources

__all__ = ["Resources", "DeviceResources", "__version__"]
