"""raft_tpu — a TPU-native vector-search and ML-primitives framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of RAPIDS RAFT
(reference: rhdong/raft 24.02; see SURVEY.md): pairwise distances, fused
k-selection, balanced k-means, IVF-Flat / IVF-PQ / CAGRA ANN indexes,
brute-force KNN, refine, nn-descent, sparse primitives, stats, and a
distributed layer built on JAX collectives over ICI/DCN.

Layer map (mirrors the reference's cpp/include/raft/<layer> — SURVEY.md §1):

    core       resources handle, bitset, serialization, logging, tracing
    utils      tiling/alignment math, misc device helpers
    linalg     gemm/svd/eig/qr wrappers, map/reduce/norm engines
    matrix     matrix utilities + the select_k top-k engine
    random     RNG state, make_blobs, rmat, sampling
    distance   pairwise distances (all reference metrics), fused_l2_nn, gram
    sparse     COO/CSR types, sparse linalg/distance, MST, Lanczos
    cluster    kmeans, kmeans_balanced, single_linkage, spectral
    neighbors  brute_force, ivf_flat, ivf_pq, cagra, nn_descent, refine, ...
    stats      summary stats + metrics incl. neighborhood_recall
    solver     linear assignment (LAP), label utilities
    comms      collectives facade over jax.lax/shard_map (NCCL/UCX analog)
    ops        Pallas TPU kernels for the hot paths
    bench      ANN benchmark harness (raft-ann-bench analog)
"""

__version__ = "0.1.0"

from raft_tpu.core.resources import Resources, DeviceResources

__all__ = ["Resources", "DeviceResources", "__version__"]
