"""graft-plan IR: a search pipeline as a DAG of typed stages.

The serving stack composes the same handful of stages everywhere —
coarse scan, probe-rung selection, first-stage scan, prefilter,
shortlist rerank, tiered fetch, score fusion, top-k merge — but until
ISSUE 20 every composition was hand-wired per algorithm
(``ivf_pq.search_refined``, the serve ``_Handle`` adapters, and the
``comms/sharded`` variants each re-plumbed the same sequence).  This
module is the declarative half of the fix: a :class:`Plan` is a small,
JSON-serializable DAG of :class:`Node` objects, each carrying the
stage it plays, the dispatch-table op key that names its kernel
family (``tuning.choose`` keeps picking implementations per node
through the ops the executor calls), and static parameters.  The
imperative half — binding a plan to an index and producing one traced
program per (bucket, k, rung) — lives in
:mod:`raft_tpu.plan.compiler`.

Validation enforces the stage contracts the hand-wired code used to
enforce by construction: the graph must be acyclic, every node must
feed the output, filters compose only *upstream* of candidate
selection (a filter after a merge would un-delete rows the tombstone
overlay already removed — the classic fan-in bug), ``score_fuse``
takes exactly two candidate legs, and candidate widths only narrow
downstream (a rerank that *widens* its shortlist would read rows the
first stage never scored).  See docs/plans.md for the node catalog
and the add-a-node guide.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Mapping, Optional, Tuple

# the stage vocabulary (ROADMAP item 8): every node plays exactly one
STAGES = ("coarse", "probe", "scan", "filter", "rerank", "fetch",
          "score_fuse", "merge")

# stages whose value is a candidate set — a (distances, ids) pair of
# some width. ``fetch`` rides with them: its value is an opaque
# prepared-shortlist handle, but contract-wise it sits on the
# candidate path between a scan and the rerank that scores it.
CANDIDATE_STAGES = frozenset(
    {"scan", "rerank", "fetch", "score_fuse", "merge"})

# who may consume whom: stage -> allowed CONSUMER stages. ``filter``
# deliberately cannot feed score_fuse/merge (filters compose into the
# first stage so a filtered row never reaches a shortlist —
# docs/serving.md §5), and nothing downstream of a merge may feed a
# filter (the "filter-after-merge" negative the tests pin).
_ALLOWED_CONSUMERS = {
    "coarse": {"probe", "scan"},
    "probe": {"scan"},
    "filter": {"scan", "rerank", "filter"},
    "scan": {"rerank", "fetch", "score_fuse", "merge"},
    "fetch": {"rerank"},
    "rerank": {"score_fuse", "merge", "rerank", "fetch"},
    "score_fuse": {"merge"},
    "merge": {"rerank", "fetch", "merge", "score_fuse"},
}

# symbolic candidate widths a node may declare instead of a literal
# int, resolved by the compiler against its (k, refine_ratio, index)
# bindings: "k" = the caller's k; "shortlist" = the canonical
# first-stage over-fetch (ivf_pq.refined_shortlist_width); "refine" =
# min(k * refine_ratio, rows) (the serve raw-refine over-fetch);
# "fuse" = the hybrid per-leg candidate width.
WIDTH_SYMBOLS = ("k", "shortlist", "refine", "fuse")

_WIDTH_RANK = {"k": 0, "refine": 1, "shortlist": 1, "fuse": 1}


class PlanError(ValueError):
    """A plan failed validation (malformed DAG or a stage-contract
    violation). Raised at plan build / compile time — never from the
    compiled program's hot path."""


@dataclasses.dataclass(frozen=True)
class Node:
    """One typed stage in a plan DAG.

    ``op`` is the dispatch-table key naming the kernel family the
    compiler binds (e.g. ``"ivf_pq.first_stage"``); the executor it
    resolves to calls the same tuned entry points the hand-wired
    pipelines called, so ``tuning.choose`` keeps picking
    implementations per node.  ``params`` holds static, JSON-able
    configuration (widths may be symbolic — see
    :data:`WIDTH_SYMBOLS`); anything runtime-bound (the index, the
    queries, a prefilter) arrives through the compiler, never the IR.
    """

    id: str
    stage: str
    op: str
    params: Mapping = dataclasses.field(default_factory=dict)
    inputs: Tuple[str, ...] = ()

    def __post_init__(self):
        # normalize mutable containers so Plans hash/compare sanely
        object.__setattr__(self, "params", dict(self.params))
        object.__setattr__(self, "inputs", tuple(self.inputs))


@dataclasses.dataclass(frozen=True)
class Plan:
    """A validated-on-demand DAG of :class:`Node`; ``output`` names the
    node whose value — a (distances, ids) candidate pair at width k —
    the compiled program returns."""

    name: str
    nodes: Tuple[Node, ...]
    output: str

    def __post_init__(self):
        object.__setattr__(self, "nodes", tuple(self.nodes))

    def node(self, node_id: str) -> Node:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(node_id)


def _toposort(plan: Plan) -> List[Node]:
    """Kahn topological order; raises :class:`PlanError` on a cycle."""
    by_id: Dict[str, Node] = {n.id: n for n in plan.nodes}
    indeg = {n.id: 0 for n in plan.nodes}
    consumers: Dict[str, List[str]] = {n.id: [] for n in plan.nodes}
    for n in plan.nodes:
        for src in n.inputs:
            if src not in by_id:
                raise PlanError(
                    f"plan {plan.name!r}: node {n.id!r} reads "
                    f"unknown input {src!r}")
            indeg[n.id] += 1
            consumers[src].append(n.id)
    ready = sorted(nid for nid, d in indeg.items() if d == 0)
    order: List[Node] = []
    while ready:
        nid = ready.pop(0)
        order.append(by_id[nid])
        for c in consumers[nid]:
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
        ready.sort()   # deterministic order for a given plan
    if len(order) != len(plan.nodes):
        stuck = sorted(nid for nid, d in indeg.items() if d > 0)
        raise PlanError(
            f"plan {plan.name!r}: cycle through nodes {stuck}")
    return order


def _width_rank(value) -> Optional[int]:
    """Comparable coarse rank for a declared candidate width: literal
    ints compare exactly; symbolic widths compare by role ("k" is the
    final width, everything else an over-fetch). None = undeclared
    (no contract to check)."""
    if value is None:
        return None
    if isinstance(value, bool):
        raise PlanError(f"width must be an int or one of "
                        f"{WIDTH_SYMBOLS}, got {value!r}")
    if isinstance(value, int):
        if value < 1:
            raise PlanError(f"width must be >= 1, got {value}")
        return None          # literal-vs-symbol never comparable
    if value in _WIDTH_RANK:
        return _WIDTH_RANK[value]
    raise PlanError(
        f"width must be an int or one of {WIDTH_SYMBOLS}, got {value!r}")


def validate(plan: Plan) -> List[Node]:
    """Validate ``plan`` and return its nodes in topological order.

    Checks: unique non-empty ids, known stages, resolvable inputs,
    acyclicity, full reachability of the output, per-stage consumer
    contracts (:data:`_ALLOWED_CONSUMERS` — including the
    filter-after-merge rule), arity contracts (``score_fuse`` takes
    exactly two candidate legs; ``rerank`` consumes a candidate or a
    fetch), and the narrowing-width contract between candidate
    stages."""
    if not isinstance(plan.output, str) or not plan.output:
        raise PlanError(f"plan {plan.name!r}: empty output")
    seen = set()
    for n in plan.nodes:
        if not n.id or not isinstance(n.id, str):
            raise PlanError(f"plan {plan.name!r}: empty node id")
        if n.id in seen:
            raise PlanError(
                f"plan {plan.name!r}: duplicate node id {n.id!r}")
        seen.add(n.id)
        if n.stage not in STAGES:
            raise PlanError(
                f"plan {plan.name!r}: node {n.id!r} has unknown stage "
                f"{n.stage!r} (want one of {STAGES})")
        if not n.op or not isinstance(n.op, str):
            raise PlanError(
                f"plan {plan.name!r}: node {n.id!r} has no op key")
        _width_rank(n.params.get("width"))
    if plan.output not in seen:
        raise PlanError(
            f"plan {plan.name!r}: output {plan.output!r} is not a node")
    order = _toposort(plan)
    by_id = {n.id: n for n in plan.nodes}

    out = by_id[plan.output]
    if out.stage not in CANDIDATE_STAGES or out.stage == "fetch":
        raise PlanError(
            f"plan {plan.name!r}: output node {out.id!r} must be a "
            f"candidate-producing stage (scan/rerank/score_fuse/merge), "
            f"got {out.stage!r}")

    # edge contracts
    for n in plan.nodes:
        for src_id in n.inputs:
            src = by_id[src_id]
            allowed = _ALLOWED_CONSUMERS[src.stage]
            if n.stage not in allowed:
                raise PlanError(
                    f"plan {plan.name!r}: {src.stage} node {src.id!r} "
                    f"cannot feed {n.stage} node {n.id!r} "
                    f"(allowed consumers: {sorted(allowed)})")
        cand_inputs = [by_id[s] for s in n.inputs
                       if by_id[s].stage in CANDIDATE_STAGES]
        if n.stage == "score_fuse" and len(cand_inputs) != 2:
            raise PlanError(
                f"plan {plan.name!r}: score_fuse node {n.id!r} needs "
                f"exactly 2 candidate legs, got {len(cand_inputs)}")
        if n.stage in ("rerank", "fetch", "merge") and not cand_inputs:
            raise PlanError(
                f"plan {plan.name!r}: {n.stage} node {n.id!r} has no "
                f"candidate input to consume")
        # narrowing-width contract: a candidate consumer never declares
        # a wider set than any producer it reads
        if n.stage in CANDIDATE_STAGES:
            w_n = n.params.get("width")
            for src in cand_inputs:
                w_s = src.params.get("width")
                if isinstance(w_n, int) and isinstance(w_s, int):
                    if n.stage != "merge" and w_n > w_s:
                        raise PlanError(
                            f"plan {plan.name!r}: node {n.id!r} widens "
                            f"its candidate set ({w_s} -> {w_n}); "
                            f"widths only narrow downstream")
                else:
                    r_n, r_s = _width_rank(w_n), _width_rank(w_s)
                    if (r_n is not None and r_s is not None
                            and r_n > r_s):
                        raise PlanError(
                            f"plan {plan.name!r}: node {n.id!r} "
                            f"(width {w_n!r}) widens over {src.id!r} "
                            f"(width {w_s!r})")

    # reachability: every node must feed the output (dead nodes are a
    # spec bug, not an optimization opportunity)
    live = {plan.output}
    frontier = [plan.output]
    while frontier:
        nid = frontier.pop()
        for src in by_id[nid].inputs:
            if src not in live:
                live.add(src)
                frontier.append(src)
    dead = sorted(seen - live)
    if dead:
        raise PlanError(
            f"plan {plan.name!r}: nodes {dead} do not feed the "
            f"output {plan.output!r}")
    return order


# ---------------------------------------------------------------------------
# serialization — plans ship to workers (comms/sharded) and into
# artifacts, so the wire format is plain JSON
# ---------------------------------------------------------------------------

_SCHEMA_VERSION = 1


def to_dict(plan: Plan) -> dict:
    """Plain-dict form (JSON-able; ``from_dict`` round-trips it)."""
    return {
        "schema": _SCHEMA_VERSION,
        "name": plan.name,
        "output": plan.output,
        "nodes": [
            {"id": n.id, "stage": n.stage, "op": n.op,
             "params": dict(n.params), "inputs": list(n.inputs)}
            for n in plan.nodes
        ],
    }


def from_dict(d: Mapping) -> Plan:
    """Inverse of :func:`to_dict`; validates the result."""
    if int(d.get("schema", 1)) != _SCHEMA_VERSION:
        raise PlanError(
            f"unknown plan schema {d.get('schema')!r} "
            f"(this build speaks {_SCHEMA_VERSION})")
    try:
        nodes = tuple(
            Node(id=nd["id"], stage=nd["stage"], op=nd["op"],
                 params=dict(nd.get("params", {})),
                 inputs=tuple(nd.get("inputs", ())))
            for nd in d["nodes"])
        plan = Plan(name=str(d.get("name", "plan")), nodes=nodes,
                    output=d["output"])
    except (KeyError, TypeError) as e:
        raise PlanError(f"malformed plan dict: {e!r}") from e
    validate(plan)
    return plan


def to_json(plan: Plan) -> str:
    return json.dumps(to_dict(plan), sort_keys=True)


def from_json(s: str) -> Plan:
    return from_dict(json.loads(s))
