"""graft-plan: a declarative query-plan IR + compiler for every search
pipeline (ISSUE 20; ROADMAP item 8; docs/plans.md).

A search is a :class:`Plan` — a small JSON-able DAG of typed stages
(``coarse`` / ``probe`` / ``scan`` / ``filter`` / ``rerank`` /
``fetch`` / ``score_fuse`` / ``merge``), each node carrying the
dispatch-table op key naming its kernel family.  :func:`compile` binds
a plan to an index at one (bucket, k, rung) point and returns the
executable program; :mod:`~raft_tpu.plan.canonical` spells the
pipelines the stack used to hand-wire (refined ivf_pq, the serve
dispatch variants, hybrid dense+sparse fusion, the sharded
worker/router split) as data.
"""

from raft_tpu.plan.canonical import (
    hybrid_plan,
    refined_plan,
    serve_plan,
    sharded_ivf_pq_plan,
    split_at_merge,
)
from raft_tpu.plan.compiler import (
    OPS,
    CompiledPlan,
    compile_plan,
    register_op,
)
from raft_tpu.plan.ir import (
    CANDIDATE_STAGES,
    STAGES,
    WIDTH_SYMBOLS,
    Node,
    Plan,
    PlanError,
    from_dict,
    from_json,
    to_dict,
    to_json,
    validate,
)

# the public compile entry point the tentpole names: plan.compile(...)
compile = compile_plan  # noqa: A001 — deliberate, scoped to this package

__all__ = [
    "CANDIDATE_STAGES", "CompiledPlan", "Node", "OPS", "Plan",
    "PlanError", "STAGES", "WIDTH_SYMBOLS", "compile", "compile_plan",
    "from_dict", "from_json", "hybrid_plan", "refined_plan",
    "register_op", "serve_plan", "sharded_ivf_pq_plan",
    "split_at_merge", "to_dict", "to_json", "validate",
]
