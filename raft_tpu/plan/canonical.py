"""Canonical plans: the DAGs the re-plumbed consumers compile.

Every pipeline the stack used to hand-wire is spelled here ONCE as
data — ``ivf_pq.search_refined``'s three rerank shapes, the serve
``_Handle`` dispatch variants, the hybrid dense+sparse fusion
(ROADMAP 6(a)), and the sharded worker/router split.  Tests pin each
compiled canonical plan bitwise against the dispatch it replaced;
graft-lint GL024 keeps serve/comms from growing new hand-wired
pipelines beside them.
"""

from __future__ import annotations

from typing import Optional, Tuple

from raft_tpu.plan.ir import Node, Plan, PlanError, validate

__all__ = [
    "refined_plan", "serve_plan", "hybrid_plan", "sharded_ivf_pq_plan",
    "split_at_merge",
]


def refined_plan(source: str) -> Plan:
    """The :func:`raft_tpu.neighbors.ivf_pq.search_refined` pipeline
    for one rerank ``source``:

    * ``"tiered"`` — explicit dataset / RerankSource: first stage at
      the shortlist width emits global ids, the tiered fetch gathers
      the unique shortlist rows, the source scores them exactly;
    * ``"cache"`` / ``"codes"`` — cacheless: the slot-translated
      prefilter feeds a slot-substituted first stage, and the rerank
      decodes the slot shortlist from the i8/i4 residual cache or the
      packed PQ codes.
    """
    if source == "tiered":
        nodes = (
            Node("pre", "filter", "prefilter"),
            Node("coarse", "coarse", "ivf.centers"),
            Node("probe", "probe", "rung", inputs=("coarse",)),
            Node("stage1", "scan", "ivf_pq.search",
                 params={"width": "shortlist", "first_stage": True},
                 inputs=("probe", "pre")),
            Node("fetch", "fetch", "tiered.prepare", inputs=("stage1",)),
            Node("rerank", "rerank", "tiered.score",
                 params={"width": "k"}, inputs=("stage1", "fetch")),
        )
        return Plan("ivf_pq.refined.tiered", nodes, "rerank")
    if source in ("cache", "codes"):
        nodes = (
            Node("pre", "filter", "slot_prefilter"),
            Node("coarse", "coarse", "ivf.centers"),
            Node("probe", "probe", "rung", inputs=("coarse",)),
            Node("stage1", "scan", "ivf_pq.first_stage",
                 params={"width": "shortlist"}, inputs=("probe", "pre")),
            Node("rerank", "rerank", f"ivf_pq.{source}",
                 params={"width": "k"}, inputs=("stage1",)),
        )
        return Plan(f"ivf_pq.refined.{source}", nodes, "rerank")
    raise PlanError(f"unknown refined rerank source {source!r} "
                    f"(want tiered | cache | codes)")


def serve_plan(algo: str, variant: str = "plain") -> Plan:
    """The serve engine's per-handle dispatch as a plan.  ``variant``
    selects among the shapes ``_Handle.search_main`` used to branch
    between:

    * ``"plain"`` — the single-stage scan every algo has;
    * ``"refined_tiered"`` / ``"refined_cache"`` / ``"refined_codes"``
      — ivf_pq multi-stage rerank (tiered source / residual cache /
      packed codes);
    * ``"raw_refine"`` — ivf_pq over-fetch + exact device rerank
      against the generation's raw rows;
    * ``"exact"`` — the quality monitor's oracle: exhaustive probing
      (rung pins n_probes = n_lists) re-ranked from the exact tier
      (ROADMAP 9(a); same DAG as refined_tiered — the bias fix is in
      what the rung binds, not in the shape).
    """
    if algo == "hybrid":
        return hybrid_plan()
    if algo in ("brute_force", "cagra"):
        nodes = (
            Node("pre", "filter", "prefilter"),
            Node("scan", "scan", f"{algo}.search",
                 params={"width": "k"}, inputs=("pre",)),
        )
        return Plan(f"serve.{algo}", nodes, "scan")
    if algo == "ivf_flat" or (algo == "ivf_pq" and variant == "plain"):
        nodes = (
            Node("pre", "filter", "prefilter"),
            Node("coarse", "coarse", "ivf.centers"),
            Node("probe", "probe", "rung", inputs=("coarse",)),
            Node("scan", "scan", f"{algo}.search",
                 params={"width": "k"}, inputs=("probe", "pre")),
        )
        return Plan(f"serve.{algo}", nodes, "scan")
    if algo != "ivf_pq":
        raise PlanError(f"no serve plan for algo {algo!r}")
    if variant in ("refined_tiered", "exact"):
        base = refined_plan("tiered")
        return Plan(f"serve.ivf_pq.{variant}", base.nodes, base.output)
    if variant in ("refined_cache", "refined_codes"):
        base = refined_plan(variant.split("_", 1)[1])
        return Plan(f"serve.ivf_pq.{variant}", base.nodes, base.output)
    if variant == "raw_refine":
        nodes = (
            Node("pre", "filter", "prefilter"),
            Node("coarse", "coarse", "ivf.centers"),
            Node("probe", "probe", "rung", inputs=("coarse",)),
            Node("scan", "scan", "ivf_pq.search",
                 params={"width": "refine"}, inputs=("probe", "pre")),
            Node("rerank", "rerank", "exact.device",
                 params={"width": "k"}, inputs=("scan",)),
        )
        return Plan("serve.ivf_pq.raw_refine", nodes, "rerank")
    raise PlanError(f"unknown ivf_pq serve variant {variant!r}")


def hybrid_plan(fuse_expand: Optional[int] = None) -> Plan:
    """ROADMAP 6(a) as a plan, not a code path: a dense brute-force leg
    and a sparse CSR lexical leg each over-fetch at the fuse width, the
    ``score_fuse`` node re-scores each leg's candidates on the OTHER
    leg and weight-merges (union semantics, duplicates masked), and one
    ``merge_topk`` keeps the fused top-k."""
    fuse_params = {"width": "fuse"}
    if fuse_expand is not None:
        fuse_params["expand"] = int(fuse_expand)
    nodes = (
        Node("pre", "filter", "prefilter"),
        Node("dense", "scan", "hybrid.dense", params=dict(fuse_params),
             inputs=("pre",)),
        Node("sparse", "scan", "sparse.brute_force",
             params=dict(fuse_params), inputs=("pre",)),
        Node("fuse", "score_fuse", "weighted",
             inputs=("dense", "sparse")),
        Node("merge", "merge", "topk", params={"width": "k"},
             inputs=("fuse",)),
    )
    return Plan("serve.hybrid", nodes, "merge")


def sharded_ivf_pq_plan(k: int, k_search: int, k_merge: int,
                        local_rerank: bool = False,
                        tail: Optional[str] = None) -> Plan:
    """The ``comms/sharded`` ivf_pq pipeline: everything up to and
    including the ``collective.topk`` merge executes per worker inside
    ``shard_map`` (the pre-merge subplan), everything after executes
    once on the router (:func:`split_at_merge` cuts it there — the
    plan, not a bespoke RPC surface, is what ships to workers).

    ``local_rerank`` inserts the per-shard cache-decoded exact rerank
    (i8/i4 caches, ``refine_ratio > 1``); ``tail`` adds a router-side
    rerank over the merged shortlist — ``"tiered"`` for an explicit
    ``rerank_source``, ``"codes"`` for the rabitq slot shortlist
    re-scored at full PQ fidelity against the full index."""
    nodes = [
        Node("coarse", "coarse", "ivf.centers"),
        Node("probe", "probe", "rung", inputs=("coarse",)),
        Node("scan", "scan", "ivf_pq.local",
             params={"width": int(k_search)}, inputs=("probe",)),
    ]
    pre_merge = "scan"
    if local_rerank:
        nodes.append(Node("local_rerank", "rerank", "ivf_pq.cache.local",
                          params={"width": int(k)}, inputs=("scan",)))
        pre_merge = "local_rerank"
    nodes.append(Node("merge", "merge", "collective.topk",
                      params={"width": int(k_merge)},
                      inputs=(pre_merge,)))
    output = "merge"
    if tail == "tiered":
        nodes.append(Node("tail", "rerank", "tiered.rerank",
                          params={"width": int(k)}, inputs=("merge",)))
        output = "tail"
    elif tail == "codes":
        nodes.append(Node("tail", "rerank", "ivf_pq.codes",
                          params={"width": int(k)}, inputs=("merge",)))
        output = "tail"
    elif tail is not None:
        raise PlanError(f"unknown sharded tail {tail!r}")
    name = "sharded.ivf_pq" + (f".{tail}" if tail else "")
    return Plan(name, tuple(nodes), output)


def split_at_merge(plan: Plan) -> Tuple[Plan, Optional[Plan]]:
    """Split a sharded plan at its ``collective.topk`` node: the head
    (everything up to and including the merge) runs per worker inside
    the collective program; the tail (if any) runs once on the router,
    seeded with the merged candidates through an identity scan node
    that keeps the tail a valid DAG."""
    order = validate(plan)
    cut = None
    for n in order:
        if n.op == "collective.topk":
            cut = n
            break
    if cut is None:
        raise PlanError(f"plan {plan.name!r} has no collective.topk "
                        f"merge to split at")
    pos = order.index(cut)
    head = Plan(plan.name + ".head", tuple(order[:pos + 1]), cut.id)
    validate(head)
    rest = order[pos + 1:]
    if not rest:
        return head, None
    seed = Node(cut.id, "scan", "identity",
                params={"width": dict(cut.params).get("width")})
    tail = Plan(plan.name + ".tail", (seed,) + tuple(rest), plan.output)
    validate(tail)
    return head, tail
