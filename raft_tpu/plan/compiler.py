"""graft-plan compiler: bind a declarative :class:`~raft_tpu.plan.ir.Plan`
to an index and produce one executable program per (bucket, k, rung).

The compiled program is a closure pipeline over the SAME tuned, jitted
entry points the hand-wired pipelines called (``ivf_pq.search`` /
``_refine_slots`` / ``_refine_slots_codes`` / ``RerankSource.prepare``
+ ``score`` / ``brute_force.search`` / ``merge_topk`` / ...), so two
properties hold *by construction* rather than by test luck:

* **bitwise identity** — a compiled canonical plan runs the exact same
  kernel sequence with the exact same arguments as the legacy dispatch
  it replaced (tests/test_plan.py pins the matrix);
* **zero steady-state retraces** — compilation itself never calls
  ``jax.jit``; every device program belongs to an already-warmed entry
  point on ``serve.TRACKED_JITS``, so serve warmup walks compiled
  plans exactly like today's ladder and the GL007 ``_cache_size`` hook
  stays flat (docs/plans.md §4).

Each node's ``op`` key is the dispatch-table name of its kernel
family; the underlying ops keep calling ``tuning.choose`` per node, so
the dispatch table keeps picking kernels stage by stage.  Executors
are looked up in :data:`OPS` — adding a workload is adding an op (and
a canonical plan), not a new code path (ROADMAP item 8).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.plan.ir import (
    CANDIDATE_STAGES,
    Node,
    Plan,
    PlanError,
    validate,
)

__all__ = ["CompiledPlan", "compile_plan", "OPS", "register_op"]


class _Ctx:
    """Per-execution scratch: node values, runtime operands, and the
    stage-stat side channel the rerank observability block reads.
    One instance per call — compiled plans are stateless and safe to
    share across serving threads."""

    __slots__ = ("queries", "prefilter", "arrays", "extra", "values",
                 "stats")

    def __init__(self, queries, prefilter, arrays, extra):
        self.queries = queries
        self.prefilter = prefilter
        self.arrays = arrays
        self.extra = extra or {}
        self.values: Dict[str, object] = {}
        self.stats: Dict[str, object] = {}


@dataclasses.dataclass
class _Binds:
    """Everything a plan needs beyond the IR: the index, resolved
    search params, widths, and optional rerank source — bound once at
    compile, shared by every execution."""

    index: object
    k: int
    bucket: Optional[int]
    rung: object
    search_params: object
    refine_ratio: int
    source: object            # RerankSource or None
    raw_dev: object           # device raw rows (serve refine) or None
    memo: Dict[str, object]   # cross-variant shared derived arrays
    extra: Dict[str, object]  # op-family statics (sharded, hybrid, ...)

    def rows(self) -> int:
        idx = self.index
        size = getattr(idx, "size", None)
        if size is not None:
            return int(size)
        return int(idx.dataset.shape[0])


# (stage, op) -> builder(node, binds, plan) -> executor(ctx) -> value
OPS: Dict[Tuple[str, str], Callable] = {}


def register_op(stage: str, op: str):
    def deco(fn):
        OPS[(stage, op)] = fn
        return fn
    return deco


def _filter_input(ctx: _Ctx, node: Node, by_id: Mapping[str, Node]):
    """The value of this node's filter input, if it declares one; a
    plan without an explicit filter node falls back to the call-time
    prefilter untouched (identical composition either way)."""
    for src in node.inputs:
        if by_id[src].stage == "filter":
            return ctx.values[src]
    return ctx.prefilter


def _candidate_inputs(ctx: _Ctx, node: Node, by_id: Mapping[str, Node]):
    return [ctx.values[src] for src in node.inputs
            if by_id[src].stage in CANDIDATE_STAGES]


def _resolve_width(node: Node, binds: _Binds) -> int:
    """A node's candidate width: literal, or one of the symbolic
    widths (ir.WIDTH_SYMBOLS) resolved against the compile bindings —
    each formula byte-identical to the hand-wired pipeline it came
    from."""
    w = node.params.get("width", "k")
    if isinstance(w, int):
        return int(w)
    if w == "k":
        return int(binds.k)
    if w == "shortlist":
        from raft_tpu.neighbors import ivf_pq

        return ivf_pq.refined_shortlist_width(
            binds.search_params, binds.index, int(binds.k),
            int(binds.refine_ratio))
    if w == "refine":
        # serve's raw-refine over-fetch (engine._Handle.search_main)
        return min(int(binds.k) * int(binds.refine_ratio), binds.rows())
    if w == "fuse":
        expand = int(node.params.get("expand",
                                     binds.extra.get("fuse_expand", 4)))
        return min(binds.rows(), max(int(binds.k) * expand, 16))
    raise PlanError(f"node {node.id!r}: unresolvable width {w!r}")


# ---------------------------------------------------------------------------
# filter stage
# ---------------------------------------------------------------------------

@register_op("filter", "prefilter")
def _build_prefilter(node, binds, plan):
    """The user/tombstone prefilter, passed through untouched — the
    composition into keep-bits happens inside the consuming scan
    (resolve_filter_bits caching idiom)."""
    def run(ctx):
        return ctx.prefilter
    return run


@register_op("filter", "slot_prefilter")
def _build_slot_prefilter(node, binds, plan):
    """Translate the stored-id prefilter into SLOT space for a
    slot-substituted first stage (ivf_pq._slot_prefilter, with its
    long-lived-bitset cache intact)."""
    from raft_tpu.neighbors import ivf_pq

    index = binds.index

    def run(ctx):
        return ivf_pq._slot_prefilter(index, ctx.prefilter)
    return run


# ---------------------------------------------------------------------------
# coarse / probe — annotation nodes, fused into the scan kernel
# ---------------------------------------------------------------------------

def _build_fused_marker(node, binds, plan):
    """Coarse scan and probe-rung selection live INSIDE the scan
    kernels (one traced program — splitting them out would retrace
    per stage and double-pay the centers matmul). The IR still spells
    them as nodes so plans are honest about the pipeline and
    graft-lint/graft-kern can audit the DAG as a unit; the compiler
    fuses them: the marker contributes nothing at runtime, and the
    scan consumes its effective n_probes from the compile-time rung
    binding instead."""
    def run(ctx):
        return None
    return run


register_op("coarse", "ivf.centers")(_build_fused_marker)
register_op("probe", "rung")(_build_fused_marker)


# ---------------------------------------------------------------------------
# scan stage
# ---------------------------------------------------------------------------

@register_op("scan", "brute_force.search")
def _build_bf_scan(node, binds, plan):
    from raft_tpu.neighbors import brute_force

    index = binds.index
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        return brute_force.search(index, ctx.queries, width,
                                  prefilter=_filter_input(ctx, node,
                                                          by_id))
    return run


@register_op("scan", "ivf_flat.search")
def _build_ivf_flat_scan(node, binds, plan):
    from raft_tpu.neighbors import ivf_flat

    index, sp = binds.index, binds.search_params
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        return ivf_flat.search(sp, index, ctx.queries, width,
                               prefilter=_filter_input(ctx, node, by_id))
    return run


@register_op("scan", "cagra.search")
def _build_cagra_scan(node, binds, plan):
    from raft_tpu.neighbors import cagra

    index, sp = binds.index, binds.search_params
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        return cagra.search(sp, index, ctx.queries, width,
                            prefilter=_filter_input(ctx, node, by_id))
    return run


@register_op("scan", "ivf_pq.search")
def _build_ivf_pq_scan(node, binds, plan):
    """Plain IVF-PQ scan (coarse + probe + list scan in one traced
    program); also the refined pipeline's first stage when the rerank
    source is an explicit dataset (stage 1 then returns global ids —
    no slot indirection)."""
    from raft_tpu.neighbors import ivf_pq

    index, sp = binds.index, binds.search_params
    width = _resolve_width(node, binds)
    first_stage = bool(node.params.get("first_stage", False))
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        filt = _filter_input(ctx, node, by_id)
        if not first_stage:
            return ivf_pq.search(sp, index, ctx.queries, width,
                                 prefilter=filt)
        with obs.span("ivf_pq.first_stage", kc=width) as s1:
            d, ids = ivf_pq.search(sp, index, ctx.queries, width,
                                   prefilter=filt)
            if obs.enabled():
                s1.sync(ids)
        ctx.stats["shortlist"] = ids
        ctx.stats["kc"] = width
        ctx.stats["first_stage_ms"] = getattr(s1, "device_ms", None)
        return d, ids
    return run


@register_op("scan", "ivf_pq.first_stage")
def _build_ivf_pq_first_stage(node, binds, plan):
    """Slot-substituted first stage of the cacheless refined pipeline:
    the scan emits WHERE each candidate lives (flat slot) instead of
    its id, so the rerank can decode it straight from the cache/codes
    without an O(n_rows) inverse map (ivf_pq._slot_indices)."""
    from raft_tpu.neighbors import ivf_pq

    index, sp = binds.index, binds.search_params
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def slot_index():
        # shared across this handle's compiled (k, rung) variants —
        # the substituted [C, cap] block is identical for all of them
        cached = binds.memo.get("slot_index")
        if cached is None:
            cached = dataclasses.replace(
                index, indices=ivf_pq._slot_indices(index.indices))
            binds.memo["slot_index"] = cached
        return cached

    def run(ctx):
        slot_filt = _filter_input(ctx, node, by_id)
        with obs.span("ivf_pq.first_stage", kc=width) as s1:
            d, slots = ivf_pq.search(sp, slot_index(), ctx.queries,
                                     width, prefilter=slot_filt)
            if obs.enabled():
                s1.sync(slots)
        ctx.stats["shortlist"] = slots
        ctx.stats["kc"] = width
        ctx.stats["first_stage_ms"] = getattr(s1, "device_ms", None)
        return d, slots
    return run


# ---------------------------------------------------------------------------
# fetch / rerank stages — the refined pipeline's tail
# ---------------------------------------------------------------------------

def _emit_rerank_obs(ctx: _Ctx, m: int, source: str, row_bytes: int,
                     fetch_info=None) -> None:
    """The rerank-stage observability block (docs/observability.md):
    bytes ACTUALLY moved at fidelity (valid slots; unique rows on the
    tiered path) + the first_stage/fetch/rerank latency split —
    byte-identical metric names/labels to the hand-wired
    search_refined emission so dashboards survive the re-plumb."""
    if not obs.enabled():
        return
    shortlist = ctx.stats.get("shortlist")
    if source == "host" and fetch_info is not None:
        valid_slots = int(fetch_info.valid_slots)
        fetched_rows = int(fetch_info.unique_rows)
    else:
        valid_slots = int(np.count_nonzero(np.asarray(shortlist) >= 0)) \
            if shortlist is not None else 0
        fetched_rows = valid_slots
    obs.counter("rerank.queries_total", m, algo="ivf_pq")
    obs.counter("rerank.shortlist_rows", valid_slots, algo="ivf_pq")
    obs.counter("rerank.bytes_fetched_total", fetched_rows * row_bytes,
                source=source)
    obs.gauge("rerank.bytes_per_query",
              fetched_rows * row_bytes / max(m, 1), source=source)
    if ctx.stats.get("first_stage_ms") is not None:
        obs.observe("rerank.stage_ms", ctx.stats["first_stage_ms"],
                    stage="first_stage")
    if ctx.stats.get("fetch_ms") is not None:
        obs.observe("rerank.stage_ms", ctx.stats["fetch_ms"],
                    stage="fetch")
    if ctx.stats.get("rerank_ms") is not None:
        obs.observe("rerank.stage_ms", ctx.stats["rerank_ms"],
                    stage="rerank")


@register_op("fetch", "tiered.prepare")
def _build_tiered_prepare(node, binds, plan):
    """The host-gather half of the tiered rerank: shortlist sync +
    dedup + (mmap) read + upload dispatch, timed under its own span
    (the latency graft-flow overlaps on the streaming path)."""
    src = binds.source
    if src is None:
        raise PlanError(f"node {node.id!r}: fetch needs a bound "
                        f"rerank source (compile(source=...))")
    by_id = {n.id: n for n in plan.nodes}
    label = "host" if getattr(src, "kind", "") == "host" else "dataset"

    def run(ctx):
        _, ids1 = _candidate_inputs(ctx, node, by_id)[0]
        with obs.span("ivf_pq.fetch", source=label) as sf:
            prepared = src.prepare(ctx.queries, ids1)
        # fetch is HOST work (no device compute to sync on): wall ms
        ctx.stats["fetch_ms"] = getattr(sf, "ms", None)
        ctx.stats["shortlist"] = ids1
        return prepared
    return run


@register_op("rerank", "tiered.score")
def _build_tiered_score(node, binds, plan):
    """Exact rerank from the bound RerankSource over a prepared
    shortlist (HostArraySource hot-cache path or DeviceSource full
    upload — bitwise-identical scoring either way)."""
    src = binds.source
    if src is None:
        raise PlanError(f"node {node.id!r}: rerank source not bound")
    index = binds.index
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}
    label = "host" if getattr(src, "kind", "") == "host" else "dataset"

    def run(ctx):
        prepared = None
        for s in node.inputs:
            if by_id[s].stage == "fetch":
                prepared = ctx.values[s]
        with obs.span("ivf_pq.rerank", source=label) as s2:
            d, ids, fetch = src.score(prepared, int(k), index.metric)
            if obs.enabled():
                s2.sync(ids)
        ctx.stats["rerank_ms"] = getattr(s2, "device_ms", None)
        _emit_rerank_obs(ctx, int(ctx.queries.shape[0]), label,
                         int(src.row_bytes), fetch_info=fetch)
        return d, ids
    return run


@register_op("rerank", "ivf_pq.cache")
def _build_cache_rerank(node, binds, plan):
    """Decode the slot shortlist from the i8/i4 residual cache at f32
    and rank exactly; slots resolve to global ids by one flat gather
    (the billion-scale source: the dataset is never HBM-resident)."""
    from raft_tpu.neighbors import ivf_pq

    index = binds.index
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}
    rot = index.rot_dim
    row_bytes = (rot // 2 if index.cache_kind == "i4" else rot) + 4

    def run(ctx):
        _, slots = _candidate_inputs(ctx, node, by_id)[0]
        with obs.span("ivf_pq.rerank", source="cache") as s2:
            d, s = ivf_pq._refine_slots(
                jnp.asarray(ctx.queries), slots, int(k),
                int(index.metric), index.recon_cache,
                index.cache_scales, index.centers_rot, index.rotation,
                jnp.float32(index.recon_scale))
            ids = jnp.where(
                s >= 0, index.indices.reshape(-1)[jnp.maximum(s, 0)], -1)
            if obs.enabled():
                s2.sync(ids)
        ctx.stats["rerank_ms"] = getattr(s2, "device_ms", None)
        _emit_rerank_obs(ctx, int(ctx.queries.shape[0]), "cache",
                         row_bytes)
        return d, ids
    return run


@register_op("rerank", "ivf_pq.codes")
def _build_codes_rerank(node, binds, plan):
    """Re-score the slot shortlist at full PQ fidelity from the packed
    codes — the rabitq pipeline's rerank when the index kept them
    (1-bit first stage, PQ-exact second)."""
    from raft_tpu.neighbors import ivf_pq

    index = binds.index
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}
    row_bytes = ivf_pq.packed_words(index.pq_dim, index.pq_bits) * 4

    def run(ctx):
        _, slots = _candidate_inputs(ctx, node, by_id)[0]
        with obs.span("ivf_pq.rerank", source="codes") as s2:
            d, s = ivf_pq._refine_slots_codes(
                jnp.asarray(ctx.queries), slots, int(k),
                int(index.metric), index.codes, index.pq_centers,
                index.centers_rot, int(index.codebook_kind),
                int(index.pq_dim), int(index.pq_bits),
                rotation=index.rotation)
            ids = jnp.where(
                s >= 0, index.indices.reshape(-1)[jnp.maximum(s, 0)], -1)
            if obs.enabled():
                s2.sync(ids)
        ctx.stats["rerank_ms"] = getattr(s2, "device_ms", None)
        _emit_rerank_obs(ctx, int(ctx.queries.shape[0]), "codes",
                         row_bytes)
        return d, ids
    return run


@register_op("rerank", "exact.device")
def _build_exact_device_rerank(node, binds, plan):
    """Serve's raw-refine tail: exact re-rank of an id shortlist
    against the generation's device-resident raw rows
    (neighbors.refine — the full-upload fast path)."""
    from raft_tpu.neighbors.refine import refine

    raw = binds.raw_dev
    if raw is None:
        raise PlanError(f"node {node.id!r}: exact.device rerank needs "
                        f"compile(raw_dev=...)")
    index = binds.index
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}
    metric = index.metric

    def run(ctx):
        _, ids = _candidate_inputs(ctx, node, by_id)[0]
        return refine(raw, ctx.queries, ids, int(k), metric)
    return run


# ---------------------------------------------------------------------------
# merge / score_fuse stages
# ---------------------------------------------------------------------------

@register_op("merge", "topk")
def _build_merge_topk(node, binds, plan):
    from raft_tpu.distance.types import is_min_close
    from raft_tpu.neighbors.common import merge_topk

    k = _resolve_width(node, binds)
    select_min = bool(binds.extra.get("select_min",
                                      is_min_close(binds.index.metric)))
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        legs = _candidate_inputs(ctx, node, by_id)
        d = jnp.concatenate([leg[0] for leg in legs], axis=1)
        i = jnp.concatenate([leg[1].astype(jnp.int32) for leg in legs],
                            axis=1)
        return merge_topk(d, i, int(k), select_min)
    return run


@register_op("score_fuse", "weighted")
def _build_score_fuse(node, binds, plan):
    """Weight-fuse a dense leg with a sparse lexical leg over the
    UNION of their candidates: each leg's candidates are re-scored
    exactly on the OTHER leg (dense rows by gather+dot, sparse rows
    from the index's padded ELL sidecar), duplicates are masked out of
    the second leg, and both legs emerge carrying the same fused
    score ``w_dense * dense + w_sparse * sparse`` — ready for one
    ``merge_topk`` (neighbors.hybrid, ISSUE 20 / ROADMAP 6(a))."""
    from raft_tpu.neighbors import hybrid

    index = binds.index
    w_dense = float(node.params.get("w_dense", index.w_dense))
    w_sparse = float(node.params.get("w_sparse", index.w_sparse))
    by_id = {n.id: n for n in plan.nodes}
    order = [s for s in node.inputs
             if by_id[s].stage in CANDIDATE_STAGES]

    def run(ctx):
        (dd, di) = ctx.values[order[0]]
        (sd, si) = ctx.values[order[1]]
        qd, qs = hybrid.split_queries(index, ctx.queries)
        return hybrid._fuse_rescore(
            qd, qs, index.dense, index.ell_cols, index.ell_vals,
            dd, di, sd, si, jnp.float32(w_dense), jnp.float32(w_sparse))
    return run


@register_op("scan", "hybrid.dense")
def _build_hybrid_dense(node, binds, plan):
    """The hybrid plan's dense leg: brute-force top-c over the dense
    columns (the index's internal brute_force sub-index, so the tuned
    scan kernels and the prefilter path are the same ones every other
    dense search uses)."""
    from raft_tpu.neighbors import brute_force, hybrid

    index = binds.index
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        qd, _ = hybrid.split_queries(index, ctx.queries)
        return brute_force.search(index.dense_bf, qd, width,
                                  prefilter=_filter_input(ctx, node,
                                                          by_id))
    return run


@register_op("scan", "sparse.brute_force")
def _build_hybrid_sparse(node, binds, plan):
    """The hybrid plan's sparse lexical leg: blockwise brute force
    over the CSR document matrix (raft_tpu/sparse), densifying one
    row block at a time — the docs stay sparse at rest."""
    from raft_tpu.neighbors import hybrid
    from raft_tpu.sparse import neighbors as sparse_neighbors

    index = binds.index
    width = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        _, qs = hybrid.split_queries(index, ctx.queries)
        return sparse_neighbors.brute_force_knn_dense_queries(
            qs, index.docs, width,
            prefilter=_filter_input(ctx, node, by_id))
    return run


# ---------------------------------------------------------------------------
# sharded (comms) ops: the worker-local pre-merge subplan + the
# collective merge executed inside shard_map, and the router tail
# ---------------------------------------------------------------------------

@register_op("scan", "identity")
def _build_identity(node, binds, plan):
    """Seed node for a split tail plan: stands for the candidates the
    head already produced (the router hands them in per call as
    ``extra={"candidates": (d, ids)}``)."""
    def run(ctx):
        try:
            cand = ctx.extra["candidates"]
        except KeyError:
            raise PlanError(
                f"node {node.id!r}: identity seed needs "
                f"extra={{'candidates': (d, ids)}} at call time"
            ) from None
        # the merged shortlist IS the rerank tail's shortlist — stash
        # it so _emit_rerank_obs counts the real rows moved
        ctx.stats["shortlist"] = cand[1]
        return cand
    return run


@register_op("scan", "ivf_pq.local")
def _build_ivf_pq_local(node, binds, plan):
    """Worker-local first stage inside shard_map: the 15-tuple operand
    pack arrives per shard through the call (ctx.arrays), the statics
    were bound at compile — one _pq_search, exactly the hand-wired
    local() body."""
    from raft_tpu.neighbors import ivf_pq

    st = binds.extra
    width = _resolve_width(node, binds)

    def run(ctx):
        return ivf_pq._pq_search(
            ctx.arrays, int(width), st["n_probes"], st["metric"],
            st["group"], st["bucket_batch"], st["codebook_kind"], 0,
            st["compute_dtype"], st["local_recall_target"],
            st["merge_recall_target"], st["lut"], st["internal"],
            st["pq_dim"], st["pq_bits"], "xla")
    return run


@register_op("rerank", "ivf_pq.cache.local")
def _build_cache_local_rerank(node, binds, plan):
    """Per-shard cache-decoded exact rerank inside shard_map; slots
    resolve against the SHARD-local indices block handed through
    ctx.extra."""
    from raft_tpu.neighbors import ivf_pq

    st = binds.extra
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        _, slots = _candidate_inputs(ctx, node, by_id)[0]
        d, s = ivf_pq._refine_slots(
            ctx.queries, slots, int(k), st["metric"],
            ctx.extra["cache"], ctx.extra["scales"],
            ctx.arrays[2], ctx.arrays[3],
            jnp.float32(st["recon_scale"]))
        indices = ctx.extra["indices"]
        i = jnp.where(s >= 0, indices.reshape(-1)[jnp.maximum(s, 0)], -1)
        return d, i
    return run


@register_op("merge", "collective.topk")
def _build_collective_merge(node, binds, plan):
    """The cross-shard merge: all-gather each shard's top-k over the
    mesh axis and keep the global best — the node every sharded plan
    splits at (workers run everything upstream, the router everything
    downstream)."""
    from raft_tpu.neighbors.common import merge_topk

    st = binds.extra
    k = _resolve_width(node, binds)
    axis = st["axis_name"]
    select_min = bool(st["select_min"])
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        d, i = _candidate_inputs(ctx, node, by_id)[0]
        # fault-injection / partial-coverage masking is the CALLER's
        # concern (comms/sharded owns the dead-rank bookkeeping): an
        # optional per-call hook runs just before the collective so a
        # dead shard's rows sink at the merge
        hook = ctx.extra.get("pre_merge")
        if hook is not None:
            d, i = hook(d, i)
        gd = jax.lax.all_gather(d, axis, axis=1, tiled=True)
        gi = jax.lax.all_gather(i, axis, axis=1, tiled=True)
        return merge_topk(gd, gi, int(k), select_min)
    return run


@register_op("rerank", "tiered.rerank")
def _build_tiered_rerank_tail(node, binds, plan):
    """Router-side tiered rerank over an already-merged id shortlist
    (the sharded tail: only the merged shortlist's unique rows are
    fetched, host-side of the collective)."""
    src = binds.source
    if src is None:
        raise PlanError(f"node {node.id!r}: rerank source not bound")
    index = binds.index
    k = _resolve_width(node, binds)
    by_id = {n.id: n for n in plan.nodes}

    def run(ctx):
        _, ids = _candidate_inputs(ctx, node, by_id)[0]
        with obs.span("sharded_ivf_pq.tiered_rerank",
                      kc=int(np.shape(ids)[-1])):
            return src.rerank(ctx.queries, ids, int(k), index.metric)
    return run


# ---------------------------------------------------------------------------
# the compiled program
# ---------------------------------------------------------------------------

class CompiledPlan:
    """One executable search program: topologically ordered node
    executors over shared compile bindings.  Stateless per call —
    safe to share across serving/shadow threads; trace caches belong
    to the underlying jitted entry points, never to this object."""

    __slots__ = ("plan", "binds", "_order", "_runs", "output")

    def __init__(self, plan: Plan, binds: _Binds):
        self.plan = plan
        self.binds = binds
        self._order = validate(plan)
        self.output = plan.output
        self._runs = []
        for node in self._order:
            builder = OPS.get((node.stage, node.op))
            if builder is None:
                raise PlanError(
                    f"plan {plan.name!r}: no executor for "
                    f"({node.stage!r}, {node.op!r}) — register one "
                    f"with plan.register_op (docs/plans.md §5)")
            self._runs.append((node.id, builder(node, binds, plan)))

    @property
    def k(self) -> int:
        return int(self.binds.k)

    @property
    def rung(self):
        return self.binds.rung

    def __call__(self, queries, prefilter=None, *, arrays=None,
                 extra=None, stats=None):
        ctx = _Ctx(queries, prefilter, arrays, extra)
        with obs.span("plan.execute", plan=self.plan.name,
                      k=int(self.binds.k)):
            for node_id, run in self._runs:
                ctx.values[node_id] = run(ctx)
        if stats is not None:
            stats.update(ctx.stats)
        return ctx.values[self.output]


def compile_plan(plan: Plan, index, bucket: Optional[int] = None,
                 k: Optional[int] = None, rung=None, *,
                 search_params=None, refine_ratio: int = 1,
                 source=None, raw_dev=None, memo=None,
                 **extra) -> CompiledPlan:
    """Bind ``plan`` to ``index`` at one (bucket, k, rung) point and
    return the executable program (exported as ``plan.compile``).

    ``rung`` follows serve's trace-key-is-the-value discipline: an int
    replaces only ``n_probes`` in ``search_params`` (idempotent with a
    caller that already resolved it — the top rung compiles the exact
    program ``rung=None`` does), and the ``"exact"`` oracle rung pins
    exhaustive probing (``n_probes = n_lists``).  ``bucket`` is
    warmup metadata: executors never read it — shape stability comes
    from the caller padding queries to the bucket ladder, exactly like
    the hand-wired dispatch.  ``source``/``raw_dev`` bind the rerank
    tier; ``memo`` (a dict) shares derived device arrays — e.g. the
    slot-substituted indices — across one handle's compiled
    variants."""
    if k is None:
        raise PlanError("compile needs k")
    sp = search_params
    if rung is not None and sp is not None and hasattr(sp, "n_probes"):
        n_lists = int(index.n_lists)
        n_probes = n_lists if rung == "exact" else int(rung)
        sp = dataclasses.replace(sp, n_probes=n_probes)
    binds = _Binds(index=index, k=int(k), bucket=bucket, rung=rung,
                   search_params=sp, refine_ratio=int(refine_ratio),
                   source=source, raw_dev=raw_dev,
                   memo=memo if memo is not None else {}, extra=extra)
    return CompiledPlan(plan, binds)
