"""BLAS-level ops (reference linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh —
cuBLAS wrappers there; MXU matmuls here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gemm(a, b, alpha: float = 1.0, beta: float = 0.0, c=None, trans_a=False, trans_b=False) -> jax.Array:
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = alpha * jnp.dot(a, b, preferred_element_type=jnp.float32)
    if c is not None and beta != 0.0:
        out = out + beta * jnp.asarray(c)
    return out


def gemv(a, x, alpha: float = 1.0, beta: float = 0.0, y=None, trans=False) -> jax.Array:
    a = jnp.asarray(a)
    if trans:
        a = a.T
    out = alpha * jnp.dot(a, jnp.asarray(x), preferred_element_type=jnp.float32)
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out


def axpy(alpha: float, x, y) -> jax.Array:
    return alpha * jnp.asarray(x) + jnp.asarray(y)


def dot(x, y) -> jax.Array:
    return jnp.dot(jnp.asarray(x), jnp.asarray(y), preferred_element_type=jnp.float32)
