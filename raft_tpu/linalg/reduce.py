"""Elementwise / map / reduce engine.

The reference hand-writes this family as CUDA kernels
(linalg/{unary_op,binary_op,map,map_reduce,reduce,coalesced_reduction,
strided_reduction,norm,normalize,matrix_vector_op,reduce_rows_by_key,
reduce_cols_by_key}.cuh). In XLA all of it is fused automatically; these
wrappers preserve the reference's API names and semantics.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def unary_op(x, op: Callable) -> jax.Array:
    return op(jnp.asarray(x))


def binary_op(x, y, op: Callable) -> jax.Array:
    return op(jnp.asarray(x), jnp.asarray(y))


def map_op(op: Callable, *arrays) -> jax.Array:
    return op(*[jnp.asarray(a) for a in arrays])


def map_reduce(x, map_fn: Callable, reduce_fn: Callable = jnp.sum, axis=None) -> jax.Array:
    return reduce_fn(map_fn(jnp.asarray(x)), axis=axis)


def add(x, y):
    return jnp.asarray(x) + jnp.asarray(y)


def subtract(x, y):
    return jnp.asarray(x) - jnp.asarray(y)


def multiply(x, y):
    return jnp.asarray(x) * jnp.asarray(y)


def reduce(x, axis=1, op: Callable = jnp.sum, map_fn: Callable | None = None):
    """Row/col reduction with optional pre-map (reference linalg/reduce.cuh)."""
    x = jnp.asarray(x)
    if map_fn is not None:
        x = map_fn(x)
    return op(x, axis=axis)


def coalesced_reduction(x, op: Callable = jnp.sum):
    """Reduce along the contiguous (last) axis (linalg/coalesced_reduction.cuh)."""
    return op(jnp.asarray(x), axis=-1)


def strided_reduction(x, op: Callable = jnp.sum):
    """Reduce along the strided (first) axis (linalg/strided_reduction.cuh)."""
    return op(jnp.asarray(x), axis=0)


def norm(x, norm_type: str = "l2", axis: int = 1, sqrt: bool = False) -> jax.Array:
    x = jnp.asarray(x)
    if norm_type == "l2":
        out = jnp.sum(x * x, axis=axis)
        return jnp.sqrt(out) if sqrt else out
    if norm_type == "l1":
        return jnp.sum(jnp.abs(x), axis=axis)
    if norm_type == "linf":
        return jnp.max(jnp.abs(x), axis=axis)
    raise ValueError(norm_type)


def normalize(x, axis: int = 1, norm_type: str = "l2", eps: float = 1e-12) -> jax.Array:
    x = jnp.asarray(x)
    if norm_type == "l2":
        n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    elif norm_type == "l1":
        n = jnp.sum(jnp.abs(x), axis=axis, keepdims=True)
    else:
        raise ValueError(norm_type)
    return x / jnp.maximum(n, eps)


def matrix_vector_op(matrix, vec, op: Callable = jnp.add, along_rows: bool = True) -> jax.Array:
    """Broadcast op of a vector over a matrix (linalg/matrix_vector_op.cuh).
    along_rows=True: vec has one entry per column."""
    m = jnp.asarray(matrix)
    v = jnp.asarray(vec)
    return op(m, v[None, :] if along_rows else v[:, None])


def reduce_rows_by_key(x, keys, n_keys: int, weights=None) -> jax.Array:
    """Sum rows sharing a key (linalg/reduce_rows_by_key.cuh) → [n_keys, d]."""
    x = jnp.asarray(x)
    if weights is not None:
        x = x * jnp.asarray(weights)[:, None]
    return jax.ops.segment_sum(x, jnp.asarray(keys), num_segments=n_keys)


def reduce_cols_by_key(x, keys, n_keys: int) -> jax.Array:
    """Sum columns sharing a key (linalg/reduce_cols_by_key.cuh) → [rows, n_keys]."""
    x = jnp.asarray(x)
    return jax.ops.segment_sum(x.T, jnp.asarray(keys), num_segments=n_keys).T


def mean_squared_error(a, b) -> jax.Array:
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    return jnp.mean((a - b) ** 2)
