"""Lanczos iterative eigensolver for large sparse/implicit symmetric
operators (reference linalg/lanczos.cuh / sparse/solver/lanczos.cuh —
computes the smallest eigenpairs powering spectral partitioning).

Works on any matvec closure so it serves both dense and CSR/COO operators.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def lanczos_tridiag(
    matvec: Callable[[jax.Array], jax.Array],
    n: int,
    n_iters: int,
    key=None,
    v0=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run `n_iters` Lanczos steps with full reorthogonalization.

    Returns (alphas [m], betas [m-1], V [m, n]) of the tridiagonal
    projection. Full reorth is the right trade on TPU — it converts the
    numerically fragile three-term recurrence into GEMMs.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    if v0 is None:
        v0 = jax.random.normal(key, (n,), jnp.float32)
    v0 = v0 / jnp.linalg.norm(v0)
    m = n_iters

    V = jnp.zeros((m, n), jnp.float32).at[0].set(v0)
    alphas = jnp.zeros((m,), jnp.float32)
    betas = jnp.zeros((m,), jnp.float32)

    def body(i, state):
        V, alphas, betas = state
        v = V[i]
        w = matvec(v)
        alpha = jnp.dot(w, v)
        w = w - alpha * v - jnp.where(i > 0, betas[i - 1], 0.0) * V[jnp.maximum(i - 1, 0)]
        # full reorthogonalization against all previous vectors
        mask = (jnp.arange(m) <= i)[:, None]
        proj = (V * mask) @ w
        w = w - (V * mask).T @ proj
        beta = jnp.linalg.norm(w)
        w = jnp.where(beta > 1e-10, w / jnp.maximum(beta, 1e-30), w)
        V = jax.lax.cond(
            i + 1 < m, lambda V: V.at[i + 1].set(w), lambda V: V, V
        )
        return V, alphas.at[i].set(alpha), betas.at[i].set(beta)

    V, alphas, betas = jax.lax.fori_loop(0, m, body, (V, alphas, betas))
    return alphas, betas[: m - 1], V


def lanczos_eigsh(
    matvec: Callable[[jax.Array], jax.Array],
    n: int,
    k: int,
    n_iters: int | None = None,
    key=None,
    which: str = "smallest",
) -> Tuple[jax.Array, jax.Array]:
    """Smallest (or largest) k eigenpairs of a symmetric operator.

    Reference: ``computeSmallestEigenvectors``
    (sparse/solver/detail/lanczos.cuh). Returns (eigenvalues [k],
    eigenvectors [n, k]).
    """
    m = n_iters if n_iters is not None else min(n, max(4 * k, 32))
    m = min(m, n)
    alphas, betas, V = lanczos_tridiag(matvec, n, m, key=key)
    T = jnp.diag(alphas) + jnp.diag(betas, 1) + jnp.diag(betas, -1)
    w, s = jnp.linalg.eigh(T)
    if which == "smallest":
        sel = jnp.arange(k)
    else:
        sel = jnp.arange(m - k, m)[::-1]
    evals = w[sel]
    evecs = (s[:, sel].T @ V).T  # [n, k]
    evecs = evecs / jnp.maximum(jnp.linalg.norm(evecs, axis=0, keepdims=True), 1e-30)
    return evals, evecs
