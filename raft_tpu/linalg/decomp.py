"""Dense decompositions (reference linalg/{svd,rsvd,eig,qr,lstsq}.cuh —
cuSOLVER wrappers there; XLA-native factorizations here)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def svd(a, full_matrices: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (U, S, V) with a = U @ diag(S) @ V.T (reference svd.cuh
    svdQR convention returns V not V^T; we match that)."""
    u, s, vt = jnp.linalg.svd(jnp.asarray(a, jnp.float32), full_matrices=full_matrices)
    return u, s, vt.T


def rsvd(a, k: int, p: int = 10, n_iter: int = 2, key=None):
    """Randomized SVD (reference linalg/rsvd.cuh): range finding with power
    iterations then exact SVD on the small projection."""
    a = jnp.asarray(a, jnp.float32)
    m, n = a.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    l = min(k + p, n)
    omega = jax.random.normal(key, (n, l), jnp.float32)
    y = a @ omega
    for _ in range(n_iter):
        y = a @ (a.T @ y)
    q, _ = jnp.linalg.qr(y)
    b = q.T @ a
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k].T


def eigh(a) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, ascending eigenvalues
    (reference linalg/eig.cuh eigDC)."""
    w, v = jnp.linalg.eigh(jnp.asarray(a, jnp.float32))
    return w, v


# reference eig.cuh only handles symmetric matrices (cusolverDnsyevd)
eig = eigh


def qr(a) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(jnp.asarray(a, jnp.float32))


def lstsq(a, b) -> jax.Array:
    """Least squares via normal equations w/ QR fallback semantics
    (reference linalg/lstsq.cuh lstsqEig/lstsqSvdQR)."""
    sol, *_ = jnp.linalg.lstsq(jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32))
    return sol


def cholesky(a, lower: bool = True) -> jax.Array:
    c = jnp.linalg.cholesky(jnp.asarray(a, jnp.float32))
    return c if lower else c.T


def cholesky_r1_update(l, x, lower: bool = True) -> jax.Array:
    """Rank-1 Cholesky update: chol(A + x x^T) given L = chol(A)
    (reference linalg/cholesky_r1_update.cuh). Small-n host-style loop is
    fine — the reference also runs O(n^2) sequential updates."""
    l = jnp.asarray(l, jnp.float32)
    if not lower:
        l = l.T
    x = jnp.asarray(x, jnp.float32).copy()
    n = l.shape[0]

    def body(carry, k):
        l, x = carry
        lkk = l[k, k]
        xk = x[k]
        r = jnp.sqrt(lkk * lkk + xk * xk)
        c = r / lkk
        s = xk / lkk
        row = l[:, k]
        idx = jnp.arange(n)
        below = idx > k
        new_col = jnp.where(below, (row + s * x) / c, row)
        new_col = new_col.at[k].set(r)
        x = jnp.where(below, c * x - s * new_col, x)
        l = l.at[:, k].set(new_col)
        return (l, x), None

    (l, _), _ = jax.lax.scan(body, (l, x), jnp.arange(n))
    return l if lower else l.T
