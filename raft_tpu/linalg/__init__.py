"""Linear algebra layer (SURVEY.md §2.3).

The reference wraps cuBLAS/cuSOLVER (linalg/gemm.cuh, svd.cuh, eig.cuh,
qr.cuh) and hand-writes an elementwise/map/reduce kernel family. On TPU the
decompositions come from ``jax.lax.linalg``/``jnp.linalg`` (XLA-native) and
the elementwise/reduce family is free in XLA — these wrappers exist to give
consumers the reference's API surface with jit-compatible semantics.
"""

from raft_tpu.linalg.blas import gemm, gemv, axpy, dot
from raft_tpu.linalg.decomp import svd, rsvd, eig, eigh, qr, lstsq, cholesky, cholesky_r1_update
from raft_tpu.linalg.reduce import (
    add,
    binary_op,
    coalesced_reduction,
    map_op,
    map_reduce,
    matrix_vector_op,
    mean_squared_error,
    multiply,
    norm,
    normalize,
    reduce,
    reduce_cols_by_key,
    reduce_rows_by_key,
    strided_reduction,
    subtract,
    unary_op,
)
from raft_tpu.linalg.lanczos import lanczos_eigsh

__all__ = [
    "gemm", "gemv", "axpy", "dot",
    "svd", "rsvd", "eig", "eigh", "qr", "lstsq", "cholesky", "cholesky_r1_update",
    "add", "binary_op", "coalesced_reduction", "map_op", "map_reduce",
    "matrix_vector_op", "mean_squared_error", "multiply", "norm", "normalize",
    "reduce", "reduce_cols_by_key", "reduce_rows_by_key", "strided_reduction",
    "subtract", "unary_op", "lanczos_eigsh",
]
