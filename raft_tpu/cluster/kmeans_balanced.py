"""Hierarchical balanced k-means — the ANN coarse quantizer trainer.

TPU-native analog of the reference's ``raft::cluster::kmeans_balanced``
(cpp/include/raft/cluster/kmeans_balanced.cuh:76,134,199; impl
cpp/include/raft/cluster/detail/kmeans_balanced.cuh). The reference trains
IVF coarse centroids with a two-level scheme: fit sqrt(C) "mesoclusters"
over the trainset, partition the C fine clusters among mesoclusters
proportionally to their size, fit each mesocluster's points into its share
of fine clusters, then run balancing iterations over the full set with
starved-cluster reseeding (``adjust_centers``,
detail/kmeans_balanced.cuh:524).

TPU design: predict is fused-L2-NN (MXU GEMM + argmin epilogue); center
update is the one-hot-matmul accumulation from ``cluster.kmeans``; the
per-mesocluster gathers are host-orchestrated (data-dependent shapes) while
every inner loop is a single jitted program. ``adjust_centers`` is
vectorized: starved clusters are reseeded from random data rows in one
``where`` instead of the reference's serial host loop.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster.kmeans import _centers_and_sizes, _predict_labels
from raft_tpu.distance.types import DistanceType
from raft_tpu.utils.precision import dist_dot


@dataclasses.dataclass
class KMeansBalancedParams:
    """Aggregate params (reference kmeans_balanced_params: n_iters, metric)."""

    n_clusters: int = 8
    n_iters: int = 20
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0


def _as_f32(x) -> jax.Array:
    return jnp.asarray(x).astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _predict_metric(x, centers, metric: int, batch_rows: int = 1 << 16):
    """Nearest-center labels under L2 or InnerProduct (reference
    detail/kmeans_balanced.cuh:371 predict). Row-batched so peak memory
    stays at batch_rows x n_clusters."""
    if metric == int(DistanceType.InnerProduct):
        from raft_tpu.cluster.kmeans import _row_batches

        xb, _, n = _row_batches(x.astype(jnp.float32), batch_rows)

        def body(_, batch):
            scores = dist_dot(batch, centers.T)
            return None, jnp.argmax(scores, axis=1).astype(jnp.int32)

        _, labels = jax.lax.scan(body, None, xb)
        return labels.reshape(-1)[:n]
    labels, _ = _predict_labels(x, centers, batch_rows)
    return labels


@functools.partial(jax.jit, static_argnums=(4, 5))
def _balancing_em_iter(
    x, centers, key, ratio_threshold, n_clusters: int,
    metric: int = int(DistanceType.L2Expanded),
):
    """One predict → update → adjust_centers iteration, fully jitted.

    ``adjust_centers`` (reference detail/kmeans_balanced.cuh:524): clusters
    whose size falls below ``ratio_threshold x average`` are reseeded from a
    random data row, pulling centers out of starvation so list sizes stay
    balanced (what "balanced" k-means means here).
    """
    n = x.shape[0]
    labels = _predict_metric(x, centers, metric, min(n, 1 << 16))
    sums, sizes = _centers_and_sizes(x, labels, None, n_clusters, min(n, 1 << 16))
    new_centers = jnp.where(
        sizes[:, None] > 0, sums / jnp.maximum(sizes, 1.0)[:, None], centers
    )
    average = jnp.float32(n) / jnp.float32(n_clusters)
    starved = sizes < ratio_threshold * average
    reseed_rows = jax.random.randint(key, (n_clusters,), 0, n)
    new_centers = jnp.where(starved[:, None], x[reseed_rows], new_centers)
    return new_centers, sizes, starved.sum()


def build_clusters(
    x,
    n_clusters: int,
    n_iters: int,
    key,
    metric: DistanceType = DistanceType.L2Expanded,
    init_centers=None,
) -> Tuple[jax.Array, jax.Array]:
    """EM-balanced clustering of one dataset (reference
    detail/kmeans_balanced.cuh:705 build_clusters).

    Returns (centers [C, d] f32, sizes [C] f32)."""
    x = _as_f32(x)
    n = x.shape[0]
    if init_centers is None:
        key, sub = jax.random.split(key)
        idx = jax.random.choice(sub, n, shape=(n_clusters,), replace=n < n_clusters)
        centers = x[idx]
    else:
        centers = _as_f32(init_centers)
    # the reference decays the reseed threshold over iterations so late
    # iterations converge; early iterations rebalance aggressively
    sizes = jnp.zeros((n_clusters,), jnp.float32)
    for it in range(n_iters):
        key, sub = jax.random.split(key)
        ratio = jnp.float32(0.25 * (1.0 - it / max(n_iters, 1)))
        centers, sizes, _ = _balancing_em_iter(
            x, centers, sub, ratio, n_clusters, int(metric)
        )
    return centers, sizes


def _arrange_fine_clusters(
    n_clusters: int, n_mesoclusters: int, meso_sizes: np.ndarray
) -> np.ndarray:
    """Partition C fine clusters among mesoclusters proportional to size
    (reference detail/kmeans_balanced.cuh:758 arrange_fine_clusters).

    Guarantees each nonempty mesocluster gets >= 1 and the counts sum to C.
    """
    meso_sizes = meso_sizes.astype(np.float64)
    total = max(meso_sizes.sum(), 1.0)
    counts = np.zeros(n_mesoclusters, np.int64)
    remaining_c, remaining_n = n_clusters, total
    order = np.argsort(-meso_sizes)  # largest first, like the reference
    for i in order:
        if remaining_c <= 0:
            break
        c = int(round(remaining_c * meso_sizes[i] / max(remaining_n, 1.0)))
        c = max(1 if meso_sizes[i] > 0 else 0, min(c, remaining_c))
        counts[i] = c
        remaining_c -= c
        remaining_n -= meso_sizes[i]
    # dump any remainder on the largest mesocluster
    if remaining_c > 0:
        counts[order[0]] += remaining_c
    return counts


def build_hierarchical(
    x,
    n_clusters: int,
    n_iters: int = 20,
    metric: DistanceType = DistanceType.L2Expanded,
    seed: int = 0,
) -> jax.Array:
    """Two-level balanced training (reference
    detail/kmeans_balanced.cuh:955 build_hierarchical). Returns centers.

    TPU adaptation: the reference runs full per-mesocluster fine fits; here
    the hierarchy only *initializes* the centers — meso fit and per-meso
    fine fits run on fixed-size subsamples (so every fine fit shares one
    compiled shape instead of jit-recompiling per mesocluster), then the
    real work happens in full-dataset balancing EM iterations, which are a
    single compiled program. On TPU the full predict GEMM is cheap enough
    that the hierarchy's FLOP savings don't matter; compile time does.
    """
    x_np = np.asarray(x, dtype=np.float32)
    n, d = x_np.shape
    key = jax.random.PRNGKey(seed)
    rng = np.random.default_rng(seed)

    n_meso = int(math.ceil(math.sqrt(n_clusters)))
    if n_clusters <= n_meso or n <= 4 * n_clusters:
        centers, _ = build_clusters(x_np, n_clusters, n_iters, key, metric)
        return centers

    # --- meso pass on a bounded subsample --------------------------------
    meso_sample = min(n, max(64 * n_meso, 1 << 14))
    sel = rng.choice(n, meso_sample, replace=False)
    key, k_meso = jax.random.split(key)
    meso_centers, _ = build_clusters(
        x_np[sel], n_meso, max(n_iters // 2, 4), k_meso, metric
    )
    meso_labels = np.asarray(
        _predict_metric(jnp.asarray(x_np[sel]), meso_centers, int(metric),
                        min(meso_sample, 1 << 16))
    )
    meso_sizes = np.bincount(meso_labels, minlength=n_meso)
    fine_counts = _arrange_fine_clusters(n_clusters, n_meso, meso_sizes)

    # --- fine init: fixed-size subsample per mesocluster -----------------
    c_max = int(fine_counts.max())
    S = max(32 * c_max, 256)  # one shared shape for all fine fits
    fine_centers = []
    for m in range(n_meso):
        c = int(fine_counts[m])
        if c == 0:
            continue
        members = np.nonzero(meso_labels == m)[0]
        if members.size == 0:
            fine_centers.append(x_np[rng.choice(n, c, replace=n < c)])
            continue
        rows = x_np[sel[rng.choice(members, S, replace=members.size < S)]]
        key, sub = jax.random.split(key)
        # few iterations — this is only an init for the balancing phase
        centers_m, _ = build_clusters(rows, c_max, 4, sub, metric)
        fine_centers.append(np.asarray(centers_m[:c]))
    centers = jnp.asarray(np.concatenate(fine_centers, axis=0))
    assert centers.shape[0] == n_clusters

    # --- full-dataset balancing EM (the real training) -------------------
    x_dev = jnp.asarray(x_np)
    iters = max(n_iters // 2, 2)
    for it in range(iters):
        key, sub = jax.random.split(key)
        ratio = jnp.float32(0.25 * (1.0 - it / max(iters, 1)))
        centers, _, _ = _balancing_em_iter(
            x_dev, centers, sub, ratio, n_clusters, int(metric)
        )
    return centers


# ---------------------------------------------------------------------------
# public API (reference kmeans_balanced.cuh:76,134,199)
# ---------------------------------------------------------------------------


def fit(params: KMeansBalancedParams, x) -> jax.Array:
    """Train balanced centers (kmeans_balanced.cuh:76). Returns [C, d]."""
    return build_hierarchical(
        x, params.n_clusters, params.n_iters, params.metric, params.seed
    )


def predict(params: KMeansBalancedParams, centers, x) -> jax.Array:
    """Nearest-center labels (kmeans_balanced.cuh:134)."""
    x = _as_f32(x)
    return _predict_metric(
        x, _as_f32(centers), int(params.metric), min(x.shape[0], 1 << 16)
    )


def fit_predict(params: KMeansBalancedParams, x):
    """fit + predict (kmeans_balanced.cuh:199)."""
    centers = fit(params, x)
    return centers, predict(params, centers, x)


def calc_centers_and_sizes(x, labels, n_clusters: int):
    """Per-cluster means and sizes (reference helper
    detail/kmeans_balanced.cuh:257). Returns (centers, sizes)."""
    x = _as_f32(x)
    sums, sizes = _centers_and_sizes(
        x, jnp.asarray(labels), None, int(n_clusters), min(x.shape[0], 1 << 16)
    )
    centers = sums / jnp.maximum(sizes, 1.0)[:, None]
    return centers, sizes
