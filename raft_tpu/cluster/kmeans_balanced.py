"""Hierarchical balanced k-means — the ANN coarse quantizer trainer.

TPU-native analog of the reference's ``raft::cluster::kmeans_balanced``
(cpp/include/raft/cluster/kmeans_balanced.cuh:76,134,199; impl
cpp/include/raft/cluster/detail/kmeans_balanced.cuh). The reference trains
IVF coarse centroids with a two-level scheme: fit sqrt(C) "mesoclusters"
over the trainset, partition the C fine clusters among mesoclusters
proportionally to their size, fit each mesocluster's points into its share
of fine clusters, then run balancing iterations over the full set with
starved-cluster reseeding (``adjust_centers``,
detail/kmeans_balanced.cuh:524).

TPU design: predict is an MXU GEMM + argmin epilogue (the ||x||^2 term is
dropped — it never changes the argmin); center update is a one-hot-matmul
accumulation; the per-mesocluster gathers are host-orchestrated
(data-dependent shapes) while every inner loop is a single jitted program.
``adjust_centers`` is vectorized: all starved clusters blend onto sampled
large clusters in one ``where`` instead of the reference's per-warp loop.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster.kmeans import _centers_and_sizes
from raft_tpu.distance.types import DistanceType


@dataclasses.dataclass
class KMeansBalancedParams:
    """Aggregate params (reference kmeans_balanced_params: n_iters, metric).

    ``compute_dtype``: matmul operand dtype for predict/update GEMMs.
    "f32" (default) runs them at HIGH precision (bf16x3 passes) — needed
    when clusters are tight relative to coordinate magnitudes; "bf16"
    single-pass is ~3x faster (r2, v5e) and fine for coarse ANN quantizers on
    natural data.
    """

    n_clusters: int = 8
    n_iters: int = 20
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0
    compute_dtype: str = "f32"


# reference constants (detail/kmeans_balanced.cuh)
_ADJUST_CENTERS_WEIGHT = 7.0   # kAdjustCentersWeight (:61)
_BALANCING_THRESHOLD = 0.25    # build_clusters default (:755)


def _as_f32(x) -> jax.Array:
    return jnp.asarray(x).astype(jnp.float32)


def _mm_dtype(compute_dtype: str):
    return jnp.bfloat16 if compute_dtype == "bf16" else jnp.float32


def _mm_precision(compute_dtype: str):
    # f32 operands at DEFAULT precision would still run one bf16 pass on
    # the MXU; HIGH (bf16x3) recovers near-f32 distances at 1/2 the cost
    # of HIGHEST. bf16 operands: precision is moot, pass DEFAULT.
    return (
        jax.lax.Precision.DEFAULT if compute_dtype == "bf16"
        else jax.lax.Precision.HIGH
    )


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _predict_metric(
    x, centers, metric: int, batch_rows: int = 1 << 16,
    compute_dtype: str = "bf16",
):
    """Nearest-center labels under L2, InnerProduct or Cosine (reference
    detail/kmeans_balanced.cuh:371 predict). Row-batched so peak memory
    stays at batch_rows x n_clusters.

    TPU formulation: the per-row term ||x||^2 never changes the argmin, so
    L2 predict is ``argmin(||c||^2 - 2 x·c)`` — one bf16 MXU pass per batch
    plus an f32 center-norm correction. Cosine = max normalized dot (the
    query norm is constant per row, so only centers need normalizing).
    """
    from raft_tpu.cluster.kmeans import _row_batches

    mm = _mm_dtype(compute_dtype)
    c32 = centers.astype(jnp.float32)
    if metric == int(DistanceType.CosineExpanded):
        c32 = c32 / jnp.maximum(
            jnp.linalg.norm(c32, axis=1, keepdims=True), 1e-30
        )
    cT = c32.astype(mm).T
    ip_like = metric in (
        int(DistanceType.InnerProduct), int(DistanceType.CosineExpanded)
    )
    cn2 = None if ip_like else jnp.sum(c32 * c32, axis=1)

    xb, _, n = _row_batches(x.astype(mm), batch_rows)

    prec = _mm_precision(compute_dtype)

    def body(_, batch):
        dots = jnp.dot(batch, cT, preferred_element_type=jnp.float32,
                       precision=prec)
        if ip_like:
            return None, jnp.argmax(dots, axis=1).astype(jnp.int32)
        return None, jnp.argmin(cn2[None, :] - 2.0 * dots, axis=1).astype(
            jnp.int32
        )

    _, labels = jax.lax.scan(body, None, xb)
    return labels.reshape(-1)[:n]


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _update_centers(x, labels, n_clusters: int, batch_rows: int,
                    compute_dtype: str = "bf16"):
    """Per-cluster sums/sizes via batched one-hot MXU matmuls (the
    reference's calc_centers_and_sizes, detail/kmeans_balanced.cuh:257,
    without atomics). One-hot entries are exact in bf16; sums accumulate
    in f32."""
    from raft_tpu.cluster.kmeans import _row_batches

    mm = _mm_dtype(compute_dtype)
    xb, valid, n = _row_batches(x.astype(mm), batch_rows)
    nb, b, d = xb.shape
    lp = jnp.pad(labels, (0, nb * b - n), constant_values=-1).reshape(nb, b)

    prec = _mm_precision(compute_dtype)

    def body(carry, inp):
        sums, sizes = carry
        batch, lab = inp
        one_hot = (lab[:, None] == jnp.arange(n_clusters)[None, :]).astype(mm)
        sums = sums + jnp.dot(one_hot.T, batch,
                              preferred_element_type=jnp.float32,
                              precision=prec)
        sizes = sizes + jnp.sum(one_hot, axis=0, dtype=jnp.float32)
        return (sums, sizes), None

    (sums, sizes), _ = jax.lax.scan(
        body,
        (jnp.zeros((n_clusters, d), jnp.float32),
         jnp.zeros((n_clusters,), jnp.float32)),
        (xb, lp),
    )
    return sums, sizes


@functools.partial(jax.jit, static_argnums=(5,))
def _adjust_centers(x, labels, sizes, centers, key, n_clusters: int):
    """Vectorized adjust_centers (reference detail/kmeans_balanced.cuh:438):
    every starved cluster (size <= threshold x average) has its center
    moved to a weighted blend of a *large* cluster's center and one of that
    cluster's points — splitting oversized clusters instead of reseeding
    into random space. All starved clusters adjust in one shot (the
    reference does the same, one warp per cluster)."""
    n = x.shape[0]
    average = jnp.float32(n) / jnp.float32(n_clusters)
    starved = sizes <= _BALANCING_THRESHOLD * average
    # candidate rows: uniform row sampling is already size-biased toward
    # large clusters; take the best of 4 to match the reference's
    # "size >= average" acceptance loop
    cand = jax.random.randint(key, (n_clusters, 4), 0, n)
    cand_sizes = sizes[labels[cand]]
    pick = jnp.argmax(cand_sizes, axis=1)
    i = jnp.take_along_axis(cand, pick[:, None], axis=1)[:, 0]  # [C]
    li = labels[i]
    wc = jnp.minimum(sizes, _ADJUST_CENTERS_WEIGHT)[:, None]
    blend = (wc * centers[li] + x[i].astype(jnp.float32)) / (wc + 1.0)
    centers = jnp.where(starved[:, None], blend, centers)
    return centers, starved.sum()


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _em_loop(x, centers, key, n_iters: int, n_clusters: int, metric: int,
             compute_dtype: str):
    """The whole balancing EM loop as ONE compiled program: seed iteration
    (predict + update, no adjustment — the reference's iter==0 guard),
    then ``n_iters`` adjust → normalize → predict → update rounds under
    ``lax.scan``. No host synchronization anywhere in the loop — on a
    remote-tunnel device a per-iteration host readback costs more than the
    entire fit."""
    n = x.shape[0]
    br = min(n, 1 << 16)
    ip_like = metric in (
        int(DistanceType.InnerProduct), int(DistanceType.CosineExpanded)
    )

    def normalize(centers):
        if not ip_like:
            return centers
        # reference L2-normalizes centers every iteration for IP/Cosine
        # (detail/kmeans_balanced.cuh:659)
        norms = jnp.linalg.norm(centers, axis=1, keepdims=True)
        return centers / jnp.maximum(norms, 1e-30)

    def em_update(centers):
        labels = _predict_metric(x, centers, metric, br, compute_dtype)
        sums, sizes = _update_centers(x, labels, n_clusters, br, compute_dtype)
        centers = jnp.where(
            sizes[:, None] > 0, sums / jnp.maximum(sizes, 1.0)[:, None],
            centers,
        )
        return centers, labels, sizes

    centers, labels, sizes = em_update(normalize(centers))

    def body(carry, kk):
        centers, labels, sizes = carry
        centers, n_adj = _adjust_centers(
            x, labels, sizes, centers, kk, n_clusters
        )
        centers, labels, sizes = em_update(normalize(centers))
        return (centers, labels, sizes), n_adj

    (centers, labels, sizes), _ = jax.lax.scan(
        body, (centers, labels, sizes), jax.random.split(key, n_iters)
    )
    return centers, labels, sizes


def balancing_em_iters(
    x,
    centers,
    n_iters: int,
    n_clusters: int,
    key,
    metric: DistanceType = DistanceType.L2Expanded,
    compute_dtype: str = "bf16",
) -> Tuple[jax.Array, jax.Array]:
    """Run the balancing EM loop (detail/kmeans_balanced.cuh:618
    balancing_em_iters).

    The reference's pullback rule extends the budget while rebalancing
    keeps firing; that needs a per-iteration device→host readback of the
    adjustment count, which on a tunnelled TPU costs more than the whole
    fit. Instead the loop runs a *fixed* ``n_iters + n_iters//2`` rounds
    on device (the extra half-budget plays the pullback's role of
    guaranteeing convergence iterations after the last reseed) as one
    compiled program."""
    x = jnp.asarray(x)
    rounds = max(int(n_iters) + int(n_iters) // 2, 1)
    centers, labels, sizes = _em_loop(
        x, _as_f32(centers), key, rounds, int(n_clusters), int(metric),
        compute_dtype,
    )
    return centers, sizes


def build_clusters(
    x,
    n_clusters: int,
    n_iters: int,
    key,
    metric: DistanceType = DistanceType.L2Expanded,
    init_centers=None,
    compute_dtype: str = "bf16",
) -> Tuple[jax.Array, jax.Array]:
    """EM-balanced clustering of one dataset (reference
    detail/kmeans_balanced.cuh:705 build_clusters).

    Returns (centers [C, d] f32, sizes [C] f32)."""
    x = jnp.asarray(x)
    n = x.shape[0]
    if init_centers is None:
        key, sub = jax.random.split(key)
        idx = jax.random.choice(sub, n, shape=(n_clusters,), replace=n < n_clusters)
        centers = _as_f32(x[idx])
    else:
        centers = _as_f32(init_centers)
    key, sub = jax.random.split(key)
    return balancing_em_iters(
        x, centers, n_iters, n_clusters, sub, metric, compute_dtype
    )


def _arrange_fine_clusters(
    n_clusters: int, n_mesoclusters: int, meso_sizes: np.ndarray
) -> np.ndarray:
    """Partition C fine clusters among mesoclusters proportional to size
    (reference detail/kmeans_balanced.cuh:758 arrange_fine_clusters).

    Guarantees each nonempty mesocluster gets >= 1 and the counts sum to C.
    """
    # graft-lint: allow-f64 host-side NumPy proportional split; never enters device code
    meso_sizes = meso_sizes.astype(np.float64)
    total = max(meso_sizes.sum(), 1.0)
    counts = np.zeros(n_mesoclusters, np.int64)
    remaining_c, remaining_n = n_clusters, total
    order = np.argsort(-meso_sizes)  # largest first, like the reference
    for i in order:
        if remaining_c <= 0:
            break
        c = int(round(remaining_c * meso_sizes[i] / max(remaining_n, 1.0)))
        c = max(1 if meso_sizes[i] > 0 else 0, min(c, remaining_c))
        counts[i] = c
        remaining_c -= c
        remaining_n -= meso_sizes[i]
    # dump any remainder on the largest mesocluster
    if remaining_c > 0:
        counts[order[0]] += remaining_c
    return counts


def build_hierarchical(
    x,
    n_clusters: int,
    n_iters: int = 20,
    metric: DistanceType = DistanceType.L2Expanded,
    seed: int = 0,
    compute_dtype: str = "bf16",
) -> jax.Array:
    """Two-level balanced training (reference
    detail/kmeans_balanced.cuh:955 build_hierarchical). Returns centers.

    TPU adaptation: the reference runs full per-mesocluster fine fits; here
    the hierarchy only *initializes* the centers — meso fit and per-meso
    fine fits run on fixed-size subsamples (so every fine fit shares one
    compiled shape instead of jit-recompiling per mesocluster), then the
    real work happens in full-dataset balancing EM iterations, which are a
    single compiled program. On TPU the full predict GEMM is cheap enough
    that the hierarchy's FLOP savings don't matter; compile time does.

    The dataset NEVER crosses the host boundary: only small index/label
    arrays do (a full-array ``np.asarray`` round-trip measured ~10 s of
    tunnel traffic at 1M x 96 — it dominated every index build).
    """
    x_dev = jnp.asarray(x)
    if x_dev.dtype != jnp.float32:
        x_dev = x_dev.astype(jnp.float32)
    n, d = x_dev.shape
    key = jax.random.PRNGKey(seed)
    rng = np.random.default_rng(seed)

    n_meso = int(math.ceil(math.sqrt(n_clusters)))
    if n_clusters <= n_meso or n <= 4 * n_clusters:
        centers, _ = build_clusters(
            x_dev, n_clusters, n_iters, key, metric,
            compute_dtype=compute_dtype,
        )
        return centers

    # --- meso pass on a bounded subsample (device-side gather) -----------
    meso_sample = min(n, max(64 * n_meso, 1 << 14))
    sel = rng.choice(n, meso_sample, replace=False)
    x_meso = x_dev[jnp.asarray(sel)]
    key, k_meso = jax.random.split(key)
    meso_centers, _ = build_clusters(
        x_meso, n_meso, max(n_iters // 2, 4), k_meso, metric,
        compute_dtype=compute_dtype,
    )
    meso_labels = np.asarray(                       # [meso_sample] — small
        _predict_metric(x_meso, meso_centers, int(metric),
                        min(meso_sample, 1 << 16), compute_dtype)
    )
    meso_sizes = np.bincount(meso_labels, minlength=n_meso)
    fine_counts = _arrange_fine_clusters(n_clusters, n_meso, meso_sizes)

    # --- fine init: fixed-size subsample per mesocluster, ALL fine fits
    # batched into one compiled program (build_clusters_batched) — the
    # per-meso host loop of separate fits costs one dispatch round-trip
    # per mesocluster, which dominates on a tunnelled device. Row picking
    # happens on host over the small label array; rows are gathered on
    # device in one shot. ------------------------------------------------
    c_max = int(fine_counts.max())
    S = max(32 * c_max, 256)  # one shared shape for all fine fits
    active = [m for m in range(n_meso) if fine_counts[m] > 0]
    pick = np.empty((len(active), S), np.int64)
    for bi, m in enumerate(active):
        members = np.nonzero(meso_labels == m)[0]
        if members.size == 0:
            pick[bi] = rng.choice(n, S, replace=n < S)
        else:
            pick[bi] = sel[rng.choice(members, S, replace=members.size < S)]
    rows_all = x_dev[jnp.asarray(pick.reshape(-1))].reshape(len(active), S, d)
    key, sub = jax.random.split(key)
    # few iterations — this is only an init for the balancing phase
    books = build_clusters_batched(rows_all, c_max, 4, sub, int(metric))
    # slice each book's share on device; concatenate stays on device
    centers = jnp.concatenate(
        [books[bi, : int(fine_counts[m])] for bi, m in enumerate(active)],
        axis=0,
    )
    assert centers.shape[0] == n_clusters

    # --- full-dataset balancing EM (the real training) -------------------
    key, sub = jax.random.split(key)
    centers, _ = balancing_em_iters(
        x_dev, centers, max(n_iters // 2, 2), n_clusters, sub, metric,
        compute_dtype,
    )
    return centers


# ---------------------------------------------------------------------------
# public API (reference kmeans_balanced.cuh:76,134,199)
# ---------------------------------------------------------------------------


def fit(params: KMeansBalancedParams, x) -> jax.Array:
    """Train balanced centers (kmeans_balanced.cuh:76). Returns [C, d]."""
    return build_hierarchical(
        x, params.n_clusters, params.n_iters, params.metric, params.seed,
        params.compute_dtype,
    )


def predict(params: KMeansBalancedParams, centers, x) -> jax.Array:
    """Nearest-center labels (kmeans_balanced.cuh:134)."""
    x = jnp.asarray(x)
    return _predict_metric(
        x, _as_f32(centers), int(params.metric), min(x.shape[0], 1 << 16),
        params.compute_dtype,
    )


def fit_predict(params: KMeansBalancedParams, x):
    """fit + predict (kmeans_balanced.cuh:199)."""
    centers = fit(params, x)
    return centers, predict(params, centers, x)


@functools.partial(jax.jit, static_argnums=(1, 2, 4))
def build_clusters_batched(xs, n_clusters: int, n_iters: int, key,
                           metric: int = int(DistanceType.L2Expanded)):
    """Train B independent codebooks in one compiled program — the batched
    replacement for the reference's per-subspace / per-cluster
    ``build_clusters`` loops (detail/ivf_pq_build.cuh:395 train_per_subset,
    :472 train_per_cluster, which launch one trainer per book) and for the
    hierarchical trainer's per-mesocluster fine fits.

    ``xs`` [B, n, d] -> centers [B, K, d]. Sequential scan over B (one
    compile, bounded memory); each book runs ``n_iters`` Lloyd iterations
    with starved-cluster reseeding from random rows. IP/Cosine metrics
    assign by max dot with per-iteration center normalization (matching
    build_clusters' angular geometry).
    """
    B, n, d = xs.shape
    ip_like = metric in (
        int(DistanceType.InnerProduct), int(DistanceType.CosineExpanded)
    )

    def one_book(_, inp):
        x, key = inp
        k_init, k_iters = jax.random.split(key)
        idx = jax.random.randint(k_init, (n_clusters,), 0, n)
        centers = x[idx]

        def iter_body(centers, kk):
            if ip_like:
                cnorm = jnp.linalg.norm(centers, axis=1, keepdims=True)
                centers = centers / jnp.maximum(cnorm, 1e-30)
            dots = jnp.dot(x, centers.T, preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGH)
            if ip_like:
                labels = jnp.argmax(dots, axis=1)
            else:
                cn2 = jnp.sum(centers * centers, axis=1)
                labels = jnp.argmin(cn2[None, :] - 2.0 * dots, axis=1)
            one_hot = (
                labels[:, None] == jnp.arange(n_clusters)[None, :]
            ).astype(jnp.float32)
            sums = jnp.dot(one_hot.T, x, preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGH)
            sizes = one_hot.sum(axis=0)
            reseed = x[jax.random.randint(kk, (n_clusters,), 0, n)]
            centers = jnp.where(
                sizes[:, None] > 0,
                sums / jnp.maximum(sizes, 1.0)[:, None],
                reseed,
            )
            return centers, None

        centers, _ = jax.lax.scan(
            iter_body, centers, jax.random.split(k_iters, n_iters)
        )
        return None, centers

    _, books = jax.lax.scan(
        one_book, None, (xs.astype(jnp.float32), jax.random.split(key, B))
    )
    return books


def calc_centers_and_sizes(x, labels, n_clusters: int):
    """Per-cluster means and sizes (reference helper
    detail/kmeans_balanced.cuh:257). Returns (centers, sizes)."""
    x = _as_f32(x)
    sums, sizes = _centers_and_sizes(
        x, jnp.asarray(labels), None, int(n_clusters), min(x.shape[0], 1 << 16)
    )
    centers = sums / jnp.maximum(sizes, 1.0)[:, None]
    return centers, sizes
