"""Clustering: Lloyd k-means, hierarchical balanced k-means, single-linkage.

Reference layer: cpp/include/raft/cluster/ (SURVEY.md §2.8).
"""

from raft_tpu.cluster import kmeans, kmeans_balanced
from raft_tpu.cluster.single_linkage import SingleLinkageOutput, single_linkage
from raft_tpu.cluster.kmeans import (
    KMeansParams,
    cluster_cost,
    compute_new_centroids,
    find_k,
    fit,
    fit_predict,
    init_plus_plus,
    predict,
    transform,
)
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansParams",
    "KMeansBalancedParams",
    "fit",
    "predict",
    "fit_predict",
    "transform",
    "cluster_cost",
    "compute_new_centroids",
    "init_plus_plus",
    "find_k",
    "single_linkage",
    "SingleLinkageOutput",
]
