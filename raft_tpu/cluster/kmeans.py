"""Lloyd's k-means — fit / predict / transform with random and k-means++ init.

TPU-native analog of the reference's ``raft::cluster::kmeans``
(cpp/include/raft/cluster/kmeans.cuh:88,152,215 and
cpp/include/raft/cluster/detail/kmeans.cuh:64,90,361,434). The reference's
hot loop — ``minClusterAndDistanceCompute`` (fused-L2-NN based) followed by
a weighted scatter of points into centroid sums — maps to:

  * predict: ``fused_l2_nn_argmin`` (a tiled MXU GEMM + argmin epilogue),
    row-batched with ``lax.scan`` so peak memory stays at batch x n_clusters;
  * update: one-hot matmul (``one_hot.T @ X``) instead of atomics — a
    [B, C] x [B, d] MXU contraction per batch, accumulated across batches.

The whole fit loop runs under one ``jit`` with ``lax.while_loop`` on the
inertia-change tolerance, like the reference's batched ``kmeans_fit_main``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType
from raft_tpu.distance.fused_l2_nn import _fused_l2_nn
from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot


@dataclasses.dataclass
class KMeansParams:
    """Aggregate param struct (reference cluster/kmeans_types.hpp KMeansParams;
    pylibraft cluster/kmeans.pyx:368)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "k-means++"  # 'k-means++' | 'random' | 'array'
    n_init: int = 1
    seed: int = 0
    metric: DistanceType = DistanceType.L2Expanded
    batch_rows: int = 1 << 16
    oversampling_factor: float = 2.0  # accepted for API parity (scalable init)


# ---------------------------------------------------------------------------
# jitted primitives
# ---------------------------------------------------------------------------


def _row_batches(x: jax.Array, batch_rows: int) -> Tuple[jax.Array, jax.Array, int]:
    """Pad x to a multiple of batch_rows and reshape to [nb, B, d].

    Returns (batches, valid_mask [nb, B], n)."""
    n, d = x.shape
    b = min(batch_rows, n)
    npad = round_up_to_multiple(n, b)
    xp = jnp.pad(x, ((0, npad - n), (0, 0)))
    valid = (jnp.arange(npad) < n).reshape(npad // b, b)
    return xp.reshape(npad // b, b, d), valid, n


@functools.partial(jax.jit, static_argnums=(2,))
def _predict_labels(x, centers, batch_rows: int):
    """argmin_c ||x_i - center_c||^2 per row, batched over rows.

    Returns (labels [n] int32, min_sq_dist [n] f32)."""
    xb, valid, n = _row_batches(x.astype(jnp.float32), batch_rows)

    def body(_, batch):
        dist, idx = _fused_l2_nn(batch, centers, False, centers.shape[0])
        return None, (idx, dist)

    _, (labels, dists) = jax.lax.scan(body, None, xb)
    return labels.reshape(-1)[:n], dists.reshape(-1)[:n]


_L2_METRICS = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
)


def _check_metric(metric: DistanceType) -> DistanceType:
    metric = DistanceType(metric)
    if metric not in _L2_METRICS and metric != DistanceType.CosineExpanded:
        raise ValueError(
            f"kmeans supports L2 and cosine metrics, got {metric!r} "
            "(reference kmeans has the same restriction)"
        )
    return metric


@functools.partial(jax.jit, static_argnums=(2, 3))
def _predict_metric_labels(x, centers, metric_val: int, batch_rows: int):
    """Metric-aware predict: L2 via fused-L2-NN, cosine via normalized
    argmax-dot. Returns (labels, dists) where dists is the per-row cost
    contribution (squared L2 or 1 - cos)."""
    metric = DistanceType(metric_val)
    if metric in _L2_METRICS:
        return _predict_labels(x, centers, batch_rows)
    # CosineExpanded
    x = x.astype(jnp.float32)
    cn = centers / jnp.maximum(
        jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30
    )
    xb, valid, n = _row_batches(x, batch_rows)

    def body(_, batch):
        bn = batch / jnp.maximum(jnp.linalg.norm(batch, axis=1, keepdims=True), 1e-30)
        scores = dist_dot(bn, cn.T)
        lab = jnp.argmax(scores, axis=1).astype(jnp.int32)
        return None, (lab, 1.0 - jnp.max(scores, axis=1))

    _, (labels, dists) = jax.lax.scan(body, None, xb)
    return labels.reshape(-1)[:n], dists.reshape(-1)[:n]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _centers_and_sizes(x, labels, weights, n_clusters: int, batch_rows: int):
    """Weighted per-cluster sums and sizes via batched one-hot MXU matmuls.

    Analog of the reference's ``calc_centers_and_sizes``
    (cluster/detail/kmeans_balanced.cuh:257) without atomics.
    Returns (sums [C, d], sizes [C])."""
    x = x.astype(jnp.float32)
    xb, valid, n = _row_batches(x, batch_rows)
    nb, b, d = xb.shape
    lp = jnp.pad(labels, (0, nb * b - n), constant_values=-1).reshape(nb, b)
    if weights is None:
        wp = valid.astype(jnp.float32)
    else:
        wp = jnp.pad(weights.astype(jnp.float32), (0, nb * b - n)).reshape(nb, b)
        wp = wp * valid

    def body(carry, inp):
        sums, sizes = carry
        batch, lab, w = inp
        one_hot = (lab[:, None] == jnp.arange(n_clusters)[None, :]).astype(
            jnp.float32
        ) * w[:, None]
        sums = sums + dist_dot(one_hot.T, batch)
        sizes = sizes + one_hot.sum(axis=0)
        return (sums, sizes), None

    init = (jnp.zeros((n_clusters, d), jnp.float32), jnp.zeros((n_clusters,), jnp.float32))
    (sums, sizes), _ = jax.lax.scan(body, init, (xb, lp, wp))
    return sums, sizes


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _fit_loop(
    x, init_centers, weights, max_iter: int, tol: float, batch_rows: int,
    metric_val: int = int(DistanceType.L2Expanded),
):
    """Full Lloyd loop under jit (reference detail/kmeans.cuh kmeans_fit_main)."""
    n_clusters = init_centers.shape[0]

    def cond(state):
        it, _, prev_inertia, inertia, _ = state
        first = it == 0
        # strict relative-improvement test; prev=inf (first real iter)
        # always passes since any finite inertia < inf * (1 - tol)
        improving = inertia < prev_inertia * (1.0 - tol)
        return (it < max_iter) & (first | improving)

    def body(state):
        it, centers, _, inertia, _ = state
        labels, dists = _predict_metric_labels(x, centers, metric_val, batch_rows)
        w = None if weights is None else weights
        sums, sizes = _centers_and_sizes(x, labels, w, n_clusters, batch_rows)
        new_centers = jnp.where(
            sizes[:, None] > 0, sums / jnp.maximum(sizes, 1.0)[:, None], centers
        )
        if weights is None:
            new_inertia = dists.sum()
        else:
            new_inertia = (dists * weights).sum()
        return it + 1, new_centers, inertia, new_inertia, labels

    n = x.shape[0]
    state = (
        jnp.int32(0),
        init_centers.astype(jnp.float32),
        jnp.float32(jnp.inf),
        jnp.float32(jnp.inf),
        jnp.zeros((n,), jnp.int32),
    )
    it, centers, _, inertia, labels = jax.lax.while_loop(cond, body, state)
    return centers, inertia, it, labels


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_random(x, n_clusters: int, key) -> jax.Array:
    """Random-sample init (reference detail/kmeans.cuh:64 initRandom)."""
    n = x.shape[0]
    idx = jax.random.choice(key, n, shape=(n_clusters,), replace=n < n_clusters)
    return jnp.asarray(x)[idx].astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(1,))
def _init_plus_plus(x, n_clusters: int, key):
    x = jnp.asarray(x).astype(jnp.float32)
    n, d = x.shape
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centers0 = jnp.zeros((n_clusters, d), jnp.float32).at[0].set(x[first])
    xn = jnp.sum(x * x, axis=1)

    def sq_dist_to(c):
        return jnp.maximum(xn - 2.0 * dist_dot(x, c) + jnp.sum(c * c), 0.0)

    def body(carry, key_c):
        centers, min_d2, c = carry
        # sample next center ~ min_d2 (D^2 weighting)
        p = min_d2 / jnp.maximum(min_d2.sum(), 1e-30)
        nxt = jax.random.choice(key_c, n, p=p)
        centers = centers.at[c].set(x[nxt])
        min_d2 = jnp.minimum(min_d2, sq_dist_to(x[nxt]))
        return (centers, min_d2, c + 1), None

    min_d2 = sq_dist_to(x[first])
    keys = jax.random.split(key, n_clusters - 1)
    (centers, _, _), _ = jax.lax.scan(body, (centers0, min_d2, jnp.int32(1)), keys)
    return centers


def init_plus_plus(x, n_clusters: int, seed: int = 0, key=None) -> jax.Array:
    """k-means++ D^2-weighted seeding (reference detail/kmeans.cuh:90
    kmeansPlusPlus; pylibraft cluster/kmeans.pyx:198 init_plus_plus)."""
    if key is None:
        key = jax.random.PRNGKey(seed)
    return _init_plus_plus(jnp.asarray(x), int(n_clusters), key)


# ---------------------------------------------------------------------------
# public API (pylibraft cluster/kmeans.pyx parity)
# ---------------------------------------------------------------------------


def fit(
    params: Union[KMeansParams, int],
    x,
    centroids=None,
    sample_weights=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fit k-means. Returns (centroids [C, d], inertia, n_iter).

    Mirrors pylibraft ``cluster.kmeans.fit`` (kmeans.pyx:482). ``params`` may
    be a KMeansParams or a bare n_clusters int.
    """
    return _fit_impl(params, x, centroids, sample_weights)[:3]


def _fit_impl(params, x, centroids=None, sample_weights=None):
    """fit() that also returns the final-iteration labels (used by find_k
    to avoid a second full predict pass)."""
    if not isinstance(params, KMeansParams):
        params = KMeansParams(n_clusters=int(params))
    metric = _check_metric(params.metric)
    x = jnp.asarray(x)
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    if params.init == "array" and centroids is None:
        raise ValueError("init='array' requires explicit centroids")

    best = None
    # explicit centroids make every trial identical — run just one
    n_trials = 1 if centroids is not None else max(1, params.n_init)
    key = jax.random.PRNGKey(params.seed)
    for trial in range(n_trials):
        key, k_init = jax.random.split(key)
        if centroids is not None:
            init_c = jnp.asarray(centroids).astype(jnp.float32)
        elif params.init == "random":
            init_c = init_random(x, params.n_clusters, k_init)
        else:
            init_c = _init_plus_plus(x, params.n_clusters, k_init)
        centers, inertia, n_iter, labels = _fit_loop(
            x, init_c, w, params.max_iter, params.tol, params.batch_rows,
            int(metric),
        )
        if best is None or float(inertia) < float(best[1]):
            best = (centers, inertia, n_iter, labels)
    return best


def predict(
    params: Union[KMeansParams, int],
    centroids,
    x,
    sample_weights=None,
    normalize_weights: bool = True,
) -> jax.Array:
    """Label each row with its nearest centroid (kmeans.cuh:152)."""
    if not isinstance(params, KMeansParams):
        params = KMeansParams(n_clusters=int(params))
    metric = _check_metric(params.metric)
    labels, _ = _predict_metric_labels(
        jnp.asarray(x).astype(jnp.float32),
        jnp.asarray(centroids).astype(jnp.float32),
        int(metric),
        params.batch_rows,
    )
    return labels


def fit_predict(params, x, centroids=None, sample_weights=None):
    """fit + predict (kmeans.cuh:215)."""
    centers, inertia, n_iter = fit(params, x, centroids, sample_weights)
    labels = predict(params, centers, x)
    return labels, centers, inertia, n_iter


def transform(params, centroids, x) -> jax.Array:
    """Pairwise distance of every row to every centroid (kmeans transform)."""
    from raft_tpu.distance import pairwise_distance

    if not isinstance(params, KMeansParams):
        params = KMeansParams(n_clusters=int(params))
    return pairwise_distance(x, centroids, metric=params.metric)


def cluster_cost(x, centroids) -> jax.Array:
    """Total inertia: sum of squared distance to nearest centroid
    (pylibraft kmeans.pyx:280 cluster_cost)."""
    _, dists = _predict_labels(
        jnp.asarray(x).astype(jnp.float32),
        jnp.asarray(centroids).astype(jnp.float32),
        1 << 16,
    )
    return dists.sum()


def compute_new_centroids(x, centroids, labels=None, sample_weights=None):
    """One centroid-update step (pylibraft kmeans.pyx:54
    compute_new_centroids)."""
    x = jnp.asarray(x)
    centroids = jnp.asarray(centroids).astype(jnp.float32)
    if labels is None:
        labels, _ = _predict_labels(x.astype(jnp.float32), centroids, 1 << 16)
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    sums, sizes = _centers_and_sizes(x, labels, w, centroids.shape[0], 1 << 16)
    return jnp.where(
        sizes[:, None] > 0, sums / jnp.maximum(sizes, 1.0)[:, None], centroids
    )


def find_k(
    x,
    kmax: int,
    kmin: int = 1,
    max_iter: int = 100,
    tol: float = 1e-2,
    seed: int = 0,
) -> Tuple[int, jax.Array, jax.Array]:
    """Auto-find-k by maximizing the Calinski-Harabasz-style objective
    ``(n-k)/(k-1) * cluster_dispersion(k) / inertia(k)`` with a bisection
    on its slope — the reference's dispersion-based method
    (cluster/detail/kmeans_auto_find_k.cuh: compute_dispersion + the
    objective[0/1] slope test). Returns (k, inertia, n_iter)."""
    from raft_tpu.stats.moments import cluster_dispersion

    x = jnp.asarray(x)
    n = x.shape[0]
    cache = {}

    def eval_k(k: int):
        if k not in cache:
            centers, inertia, n_iter, labels = _fit_impl(
                KMeansParams(n_clusters=k, max_iter=max_iter, tol=tol, seed=seed),
                x,
            )
            sizes = jnp.bincount(labels, length=k)
            disp = float(cluster_dispersion(centers, sizes, n))
            ch = (n - k) / max(k - 1, 1) * disp / max(float(inertia), 1e-30)
            cache[k] = (ch, float(inertia), n_iter)
        return cache[k]

    left, right = max(2, int(kmin)), int(kmax)
    if right <= left:
        _, inertia, n_iter = eval_k(max(left, 2))
        return max(left, 2), jnp.float32(inertia), n_iter
    eval_k(left)
    eval_k(right)
    while left < right - 1:
        mid = (left + right) // 2
        slope_l = (eval_k(mid)[0] - eval_k(left)[0]) / (mid - left)
        if slope_l <= 0:
            right = mid  # CH already falling: peak is at or left of mid
            continue
        slope_r = (eval_k(right)[0] - eval_k(mid)[0]) / (right - mid)
        if slope_r < 0:
            right = mid  # interior peak, left side
        else:
            left = mid
    # every evaluated k is a candidate — the bracket walk can step past
    # the peak when the curve is noisy
    best_k = max(cache, key=lambda k: cache[k][0])
    _, inertia, n_iter = eval_k(best_k)
    return best_k, jnp.float32(inertia), n_iter
