"""Single-linkage agglomerative clustering
(reference cluster/single_linkage.cuh; impl detail/{single_linkage,
connectivities,mst,agglomerative}.cuh).

Pipeline (same stages as the reference):
  1. connectivity graph — either a KNN graph (``LinkageDistance::KNN_GRAPH``,
     detail/connectivities.cuh) or the full pairwise geometry;
  2. MST of the connectivity (detail/mst.cuh), with disconnected KNN
     graphs repaired by cross-component nearest-neighbor edges
     (the reference's FixConnectivitiesRedOp loop);
  3. dendrogram build + flat cluster extraction
     (detail/agglomerative.cuh build_dendrogram_host / extract_flattened_clusters).

TPU design notes: the KNN path's heavy stages (graph, MST segment-mins,
repair 1-NNs) run on device. For the pairwise path the reference runs MST
over the dense distance matrix; here it is a *geometric Borůvka* — each of
the ≤ ⌈log₂ n⌉ rounds finds every component's lightest outgoing edge with
one masked cross-component 1-NN sweep (tiled MXU pairwise + segment-min),
so the complete graph is never materialized. The final dendrogram is an
inherently sequential union-find over n-1 sorted edges — O(n α(n)) on
host, negligible next to the O(n²) device work (the reference also builds
the dendrogram on host: build_dendrogram_host).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.sparse import neighbors as sparse_neighbors
from raft_tpu.sparse import op as sparse_op
from raft_tpu.sparse import solver as sparse_solver
from raft_tpu.sparse.types import COO


@dataclasses.dataclass
class SingleLinkageOutput:
    """Mirrors the reference's ``linkage_output`` (cluster/single_linkage.cuh):
    flat labels plus the dendrogram (children / deltas / sizes)."""

    labels: np.ndarray      # [n] int32
    children: np.ndarray    # [n-1, 2] merged cluster ids (scipy convention)
    deltas: np.ndarray      # [n-1] merge distances
    sizes: np.ndarray       # [n-1] size of the merged cluster
    n_clusters: int


class _UnionFind:
    def __init__(self, n):
        self.parent = np.arange(n, dtype=np.int64)

    def find(self, a):
        p = self.parent
        root = a
        while p[root] != root:
            root = p[root]
        while p[a] != root:
            p[a], a = root, p[a]
        return root

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        self.parent[rb] = ra
        return True


def build_dendrogram_host(
    src: np.ndarray, dst: np.ndarray, w: np.ndarray, n: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Union-find dendrogram from MST edges
    (detail/agglomerative.cuh build_dendrogram_host). scipy linkage
    convention: new clusters get ids n, n+1, ...; returns
    (children [n-1, 2], deltas, sizes)."""
    order = np.argsort(w, kind="stable")
    src, dst, w = src[order], dst[order], w[order]
    uf = _UnionFind(2 * n - 1)
    cluster_of = np.arange(n, dtype=np.int64)  # root -> current cluster id
    size = np.ones(2 * n - 1, np.int64)
    children = np.zeros((n - 1, 2), np.int64)
    # graft-lint: allow-f64 host-side SciPy-parity dendrogram accumulation
    deltas = np.zeros(n - 1, np.float64)
    sizes = np.zeros(n - 1, np.int64)
    t = 0
    for a, b, wt in zip(src, dst, w):
        ra, rb = uf.find(int(a)), uf.find(int(b))
        if ra == rb:
            continue
        ca, cb = cluster_of[ra], cluster_of[rb]
        new = n + t
        children[t] = (min(ca, cb), max(ca, cb))
        deltas[t] = wt
        sizes[t] = size[ca] + size[cb]
        size[new] = sizes[t]
        uf.union(ra, rb)
        cluster_of[uf.find(ra)] = new
        t += 1
    return children[:t], deltas[:t], sizes[:t]


def extract_flattened_clusters(
    children: np.ndarray, n: int, n_clusters: int
) -> np.ndarray:
    """Cut the dendrogram into ``n_clusters`` flat labels
    (detail/agglomerative.cuh extract_flattened_clusters): apply the first
    n - n_clusters merges (they are in ascending distance order for
    single linkage) and label the resulting forests 0..n_clusters-1."""
    uf = _UnionFind(n)
    n_merges = max(0, min(len(children), n - n_clusters))

    def leaf_reps(cid):
        # one representative leaf per cluster id
        while cid >= n:
            cid = int(children[cid - n][0])
        return cid

    for t in range(n_merges):
        a = leaf_reps(int(children[t][0]))
        b = leaf_reps(int(children[t][1]))
        uf.union(a, b)
    roots = np.array([uf.find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int32)


def _geometric_mst(x, metric) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """MST of the complete geometric graph by Borůvka over cross-component
    1-NN sweeps (no materialized pairwise matrix)."""
    n = x.shape[0]
    colors = np.arange(n, dtype=np.int32)
    uf = _UnionFind(n)
    src_out, dst_out, w_out = [], [], []
    rounds = 0
    while rounds <= int(np.ceil(np.log2(max(n, 2)))) + 1:
        n_comp = np.unique(colors).size
        if n_comp <= 1:
            break
        src, dst, w = sparse_solver.connect_components(x, colors, metric)
        merged_any = False
        for s, t, wt in zip(src, dst, w):
            if uf.union(int(s), int(t)):
                src_out.append(int(s))
                dst_out.append(int(t))
                w_out.append(float(wt))
                merged_any = True
        if not merged_any:
            break
        colors = np.array([uf.find(i) for i in range(n)], np.int32)
        rounds += 1
    return (
        np.asarray(src_out, np.int64),
        np.asarray(dst_out, np.int64),
        # graft-lint: allow-f64 host-side SciPy-parity linkage output dtype
        np.asarray(w_out, np.float64),
    )


def single_linkage(
    x,
    n_clusters: int = 2,
    metric="sqeuclidean",
    connectivity: str = "knn",
    c: int = 15,
) -> SingleLinkageOutput:
    """Single-linkage clustering (reference cluster/single_linkage.cuh:80
    ``single_linkage<KNN_GRAPH|PAIRWISE>``).

    Parameters mirror the reference: ``c`` is the KNN-connectivity
    neighbor count control (detail/connectivities.cuh uses
    min(c, n-1) neighbors).
    """
    x = jnp.asarray(x, jnp.float32)
    n = int(x.shape[0])
    metric = resolve_metric(metric)
    if n < 2:
        return SingleLinkageOutput(
            np.zeros(n, np.int32), np.zeros((0, 2), np.int64),
            np.zeros(0), np.zeros(0, np.int64), n_clusters,
        )

    if connectivity == "pairwise":
        src, dst, w = _geometric_mst(x, metric)
    elif connectivity == "knn":
        k = max(2, min(int(c), n - 1))
        graph = sparse_neighbors.knn_graph(x, k, metric=metric)
        sym = sparse_op.symmetrize(graph, mode="max")
        src_d, dst_d, w_d, colors_dev = sparse_solver.mst(sym)
        src = src_d.astype(np.int64)
        dst = dst_d.astype(np.int64)
        # graft-lint: allow-f64 host-side SciPy-parity linkage output dtype
        w = w_d.astype(np.float64)
        # repair disconnected KNN graphs (cross_component_nn loop);
        # Borůvka's final colors give the components for free — the host
        # union-find is only built if a repair round is actually needed
        colors = np.asarray(colors_dev, np.int32)
        uf = None
        guard = 0
        while np.unique(colors).size > 1 and guard < n:
            if uf is None:
                uf = _UnionFind(n)
                for s, t in zip(src, dst):
                    uf.union(int(s), int(t))
            bs, bt, bw = sparse_solver.connect_components(x, colors, metric)
            added = False
            for s, t, wt in zip(bs, bt, bw):
                if uf.union(int(s), int(t)):
                    src = np.append(src, int(s))
                    dst = np.append(dst, int(t))
                    w = np.append(w, float(wt))
                    added = True
            if not added:
                break
            colors = np.array([uf.find(i) for i in range(n)], np.int32)
            guard += 1
    else:
        raise ValueError(f"connectivity must be 'knn' or 'pairwise', got "
                         f"{connectivity!r}")

    children, deltas, sizes = build_dendrogram_host(src, dst, w, n)
    labels = extract_flattened_clusters(children, n, n_clusters)
    return SingleLinkageOutput(labels, children, deltas, sizes, n_clusters)
