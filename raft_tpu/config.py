"""Global output-format hook (pylibraft config.py:20 ``set_output_as``
analog).

raft_tpu functions natively return ``jax.Array``. Consumers embedding the
library in torch/numpy pipelines can install a process-wide converter so
``raft_tpu.config.as_output(x)`` (used by the interop surfaces, e.g.
``device_ndarray``) hands back their framework's arrays — zero-copy via
DLPack where the frameworks allow it.

    import raft_tpu.config as config
    config.set_output_as("torch")       # or "numpy" | "jax" | callable
"""

from __future__ import annotations

from typing import Callable, Union

_output_as: Union[str, Callable] = "jax"


def set_output_as(kind: Union[str, Callable]) -> None:
    """Install the global output converter: "jax" (default, no-op),
    "numpy", "torch", or any callable ``jax.Array -> Any``."""
    global _output_as
    if not callable(kind) and kind not in ("jax", "numpy", "torch"):
        raise ValueError(
            f"set_output_as expects 'jax' | 'numpy' | 'torch' | callable, "
            f"got {kind!r}"
        )
    _output_as = kind


def get_output_as() -> Union[str, Callable]:
    return _output_as


def as_output(x):
    """Convert a jax array per the installed hook."""
    if callable(_output_as):
        return _output_as(x)
    if _output_as == "jax":
        return x
    if _output_as == "numpy":
        import numpy as np

        return np.asarray(x)
    # torch — zero-copy via DLPack when the device allows, else via host
    import torch

    try:
        return torch.from_dlpack(x)
    except Exception:  # noqa: BLE001 - cross-device fallback
        import numpy as np

        return torch.from_numpy(np.asarray(x))
