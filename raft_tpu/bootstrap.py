"""Multi-host bootstrap — import-light by design.

``jax.distributed.initialize`` must run before ANYTHING initializes the
XLA backend, and importing the heavier raft_tpu subpackages (comms,
neighbors) traces jitted helpers that do. This module imports only jax,
so a multi-host program can safely do:

    from raft_tpu.bootstrap import init_multihost
    init_multihost(coordinator_address=..., num_processes=N, process_id=i)
    from raft_tpu.comms import Comms, sharded_knn   # now safe

(the raft-dask ``Comms.init`` analog; the TPU runtime owns rank
discovery, so there is no NCCL unique-id exchange to implement —
reference python/raft-dask/raft_dask/common/comms.py:173).
"""

from __future__ import annotations

from typing import Optional

import jax


def init_multihost(coordinator_address: Optional[str] = None, **kwargs) -> None:
    """Process-group bootstrap: thin wrapper over
    ``jax.distributed.initialize`` (auto-discovery on TPU pods when no
    coordinator is given)."""
    jax.distributed.initialize(coordinator_address=coordinator_address, **kwargs)
