"""Measurement-driven dispatch for the select/scan/merge hot paths.

The reference library chooses between its radix and warpsort ``select_k``
backends with a heuristic *learned from benchmark measurements*
(matrix/detail/select_k-inl.cuh:51-79). This package is the TPU analog,
generalized to every hot-path dispatch the repo used to hard-code:

* ``select_k``   — hardware ``lax.top_k`` vs the compacting tournament
* ``merge_topk`` — the cross-probe/parts merge's selection backend
* ``ivf_scan``   — fused Pallas list scan vs the XLA bucketized scan
* ``pq_scan``    — IVF-PQ cache/scoring kind (i8 / i4 / pq4 one-hot)
* budgets        — e.g. CAGRA's inline packed-table byte budget, the
  tiered rerank's ``tiered_hot_rows`` HBM hot-row cache capacity

Consumers call ``choose(op, key, candidates, fallback)`` with a static
shape key; the answer comes from a **persisted per-backend table** of
microbenchmark measurements (``tables/<backend>.json``, captured by
``scripts/capture_dispatch_tables.py``), falling back to the caller's
analytic projection when no measurement covers the key. Behavior is
frozen with ``RAFT_TPU_TUNING``:

    RAFT_TPU_TUNING=off       always use the analytic fallback
    RAFT_TPU_TUNING=table     consult the persisted table (default)
    RAFT_TPU_TUNING=measure   table mode + measure cheap ops (select_k /
                              merge_topk) on first use at uncovered keys,
                              caching the winner in-process

``RAFT_TPU_TUNING_TABLE=/path.json`` overrides the packaged table — the
user-writable slot for site-captured tables (point
``capture_dispatch_tables.py --out`` there).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from raft_tpu.tuning.table import DispatchTable

_MODES = ("off", "table", "measure")

# Canonical row-tile candidates for the fused brute-force kernel
# (ops/fused_topk.py, op key ``fused_topk_tile``). ONE home on purpose:
# brute_force._resolve_bf_impl builds its dispatch candidate strings
# ("fused_<variant>:<tile>") from this set, microbench races exactly the
# same set, and the graft-kern static verifier (analysis/kernels.py)
# evaluates kernel geometry over every value that can flow in from a
# table winner — a tile added here is automatically raced, dispatched,
# and statically audited.
FUSED_TOPK_TILES = (512, 1024, 2048)
# tile_geometry's analytic fallback halves below the raced set down to
# this floor; it is part of the reachable-value domain the verifier
# must cover even though it is never raced by name
FUSED_TOPK_TILE_FLOOR = 256

# Canonical node-tile candidates for the fused nn-descent local-join
# kernel (ops/graph_join.py, op key ``graph_join``; winner strings
# ``pallas:<tile_b>``). Same one-home rule as FUSED_TOPK_TILES: the
# dispatch resolver (neighbors.nn_descent._resolve_join_impl), the
# microbench race (bench_graph_join) and the graft-kern static audit
# (kernel_shape_candidates + the contract's per-tile cases) all consume
# this tuple — a tile added here is raced, dispatched, and audited.
GRAPH_JOIN_TILES = (8, 16, 32)

# Canonical query-tile (lane) candidates for the fused CAGRA beam-step
# kernel (ops/beam_step.py, op key ``beam_step_tile``; winner strings
# ``pallas:<g>``) — cagra._resolve_beam_tile dispatches over them,
# bench_beam_step races them, and the beam contract carries one static
# geometry case per value so the audit covers every injectable tile.
BEAM_STEP_TILES = (128, 256)

# ops cheap enough to measure synchronously at first use in "measure"
# mode; scan-path ops need an index built around them — capture those
# with scripts/capture_dispatch_tables.py instead
MEASURABLE_INLINE = ("select_k", "merge_topk")

_lock = threading.Lock()
_mode_override: Optional[str] = None
_table_path_override: Optional[str] = None
_table_cache: Dict[str, Optional[DispatchTable]] = {}
_measured: Dict = {}
# in-process budget ceilings learned the hard way (the resilience OOM
# ladder records the chunk size that survived a RESOURCE_EXHAUSTED here
# so later calls in the same process start safe instead of re-OOMing)
_runtime_budgets: Dict[str, int] = {}


def mode() -> str:
    """Active tuning mode: the ``set_mode`` override if any, else
    ``RAFT_TPU_TUNING`` (default "table")."""
    if _mode_override is not None:
        return _mode_override
    m = os.environ.get("RAFT_TPU_TUNING", "table").strip().lower()
    return m if m in _MODES else "table"


def set_mode(m: Optional[str]) -> None:
    """Override the env knob in-process (None restores env control)."""
    global _mode_override
    if m is not None and m not in _MODES:
        raise ValueError(f"mode must be one of {_MODES}, got {m!r}")
    _mode_override = m


def backend_name() -> str:
    """Table filename stem for the active backend. The axon-tunnelled
    TPU is still a TPU for dispatch purposes."""
    try:
        import jax

        p = jax.devices()[0].platform.lower()
    except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow dispatch must never fail a search; cpu fallback is the safe answer
        return "cpu"
    return "tpu" if p in ("tpu", "axon") else p


def tables_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tables")


def table_path() -> Optional[str]:
    """Resolved table path: ``set_table_path`` override, then
    ``RAFT_TPU_TUNING_TABLE``, then the packaged per-backend table.
    None when none of those files exist."""
    if _table_path_override is not None:
        return _table_path_override
    env = os.environ.get("RAFT_TPU_TUNING_TABLE", "").strip()
    if env:
        return env
    packaged = os.path.join(tables_dir(), backend_name() + ".json")
    return packaged if os.path.exists(packaged) else None


def set_table_path(path: Optional[str]) -> None:
    """Point dispatch at a specific table file (None restores the
    default resolution) and drop the cache."""
    global _table_path_override
    _table_path_override = path
    reload()


def reload() -> None:
    """Drop the cached table, in-process measurements, and runtime
    budgets (tests, or after re-capturing a table)."""
    with _lock:
        _table_cache.clear()
        _measured.clear()
        _runtime_budgets.clear()


def get_table() -> Optional[DispatchTable]:
    """The active DispatchTable, or None when no table file resolves or
    the file is unreadable (dispatch then always falls back)."""
    path = table_path()
    if path is None:
        return None
    with _lock:
        if path not in _table_cache:
            try:
                _table_cache[path] = DispatchTable.load(path)
            except Exception:  # noqa: BLE001 - bad table == no table
                _table_cache[path] = None
        return _table_cache[path]


def _tracing() -> bool:
    """True while under a jax trace — measure mode must not launch
    microbenchmarks from inside someone else's jit."""
    try:
        import jax

        return not jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow trace-state probe only gates measure mode; not-tracing is the safe fallback
        return False


def _freeze_key(op: str, key: Dict) -> tuple:
    return (op,) + tuple(sorted(key.items()))


def _measure_inline(op: str, key: Dict,
                    candidates: List[str]) -> Optional[str]:
    fk = _freeze_key(op, key)
    with _lock:
        if fk in _measured:
            return _measured[fk]
    try:
        from raft_tpu.tuning import microbench

        times = microbench.measure_op(op, key, candidates)
        winner = min(times, key=times.get) if times else None
    except Exception:  # noqa: BLE001 - measurement failure => fallback
        winner = None
    with _lock:
        _measured[fk] = winner
    return winner


def choose(op: str, key: Dict, candidates: List[str],
           fallback: Optional[str]) -> Optional[str]:
    """Pick an implementation for ``op`` at static shape ``key``.

    ``candidates`` is the ELIGIBLE set at this call site (dtype/layout
    constraints already applied); a table winner outside it is ignored.
    ``fallback`` is the caller's analytic projection — returned verbatim
    in ``off`` mode, on a table miss, or on any error. ``key`` values
    must be static python scalars (shapes at trace time are), so a
    choice is a pure trace-time decision.
    """
    from raft_tpu import obs

    m = mode()
    if m == "off" or not candidates:
        obs.counter("tuning.dispatch", op=op, impl=str(fallback),
                    source="off" if m == "off" else "no_candidates")
        return fallback
    t = get_table()
    if t is not None:
        w = t.lookup(op, key, candidates)
        if w in candidates:
            obs.counter("tuning.dispatch", op=op, impl=str(w),
                        source="table")
            return w
    # only genuinely UNCOVERED keys get measured in measure mode — a
    # persisted measurement always wins over an ad-hoc in-process one
    if (m == "measure" and op in MEASURABLE_INLINE and len(candidates) > 1
            and not _tracing()):
        w = _measure_inline(op, key, candidates)
        if w in candidates:
            obs.counter("tuning.dispatch", op=op, impl=str(w),
                        source="measured")
            return w
    obs.counter("tuning.dispatch", op=op, impl=str(fallback),
                source="fallback")
    return fallback


def fused_topk_candidate_impls(k: int, approx_ok: bool) -> List[str]:
    """The fused brute-force impl strings eligible at ``k`` —
    ``fused_<variant>:<tile>`` over :data:`FUSED_TOPK_TILES` within
    each variant's extraction budget (exact k <= 128, fold k <= 256;
    fold only for approx-opted callers). The shared enumeration behind
    brute_force's dispatch and microbench's race."""
    out: List[str] = []
    if k <= 128:
        out += [f"fused_exact:{t}" for t in FUSED_TOPK_TILES]
    if approx_ok and k <= 256:
        out += [f"fused_fold:{t}" for t in FUSED_TOPK_TILES]
    return out


def _winner_tiles(table, op: str, prefix: str) -> set:
    """Integer tile suffixes of an op's ``<prefix><tile>`` winner
    strings in an active table (``fused_exact:1024``, ``pallas:16``)."""
    tiles: set = set()
    if table is None:
        return tiles
    try:
        for entry in table.data.get("ops", {}).get(op, {}).get(
                "entries", []):
            w = str(entry.get("winner", ""))
            if w.startswith(prefix):
                tail = w[len(prefix):].split(":", 1)[0]
                if tail.isdigit():
                    tiles.add(int(tail))
    except Exception:  # noqa: BLE001 — malformed table entries only shrink the audited domain to the canonical set
        pass
    return tiles


def kernel_shape_candidates() -> Dict[str, tuple]:
    """Shape-parameter domains reachable through ``tuning.choose``
    winners, keyed by kernel parameter NAME — consumed by the
    graft-kern static verifier (docs/static_analysis.md §engine-4) so
    table-dispatched tile geometry is audited at every value it can
    take, not just the analytic default. Includes any extra tiles an
    active site-captured table carries in its ``fused_topk_tile`` /
    ``graph_join`` / ``beam_step_tile`` winner strings."""
    t = get_table()
    tiles = set(FUSED_TOPK_TILES)
    tiles.add(FUSED_TOPK_TILE_FLOOR)          # analytic halving floor
    for variant in ("fused_exact:", "fused_fold:"):
        tiles |= _winner_tiles(t, "fused_topk_tile", variant)
    join_tiles = set(GRAPH_JOIN_TILES) | _winner_tiles(
        t, "graph_join", "pallas:")
    beam_tiles = set(BEAM_STEP_TILES) | _winner_tiles(
        t, "beam_step_tile", "pallas:")
    return {
        "tile_n": tuple(sorted(tiles)),
        # tile_geometry rounds the query tile to a pow2 in [8, 128];
        # the corners bound both the VMEM max and the alignment screen
        "tile_q": (8, 128),
        "variant": ("exact", "fold"),
        # graph_join node tiles / beam_step query tiles: the contracts
        # pin the canonical values in explicit cases; these domains let
        # a site-captured winner outside them still enter the audit
        "tile_b": tuple(sorted(join_tiles)),
        "g": tuple(sorted(beam_tiles)),
    }


def record_budget(name: str, value: int) -> None:
    """Record a runtime budget CEILING for ``name`` (in-process only).

    The resilience OOM ladder calls this with the chunk/batch size that
    survived a RESOURCE_EXHAUSTED; :func:`budget` then clamps every
    later lookup of ``name`` to the recorded minimum so subsequent
    dispatches in this process start at a size known to fit. Repeated
    records keep the minimum. Cleared by :func:`reload`.
    """
    v = int(value)
    with _lock:
        prior = _runtime_budgets.get(name)
        _runtime_budgets[name] = v if prior is None else min(prior, v)
        recorded = _runtime_budgets[name]
    from raft_tpu import obs

    obs.gauge("runtime_budget", recorded, budget=name)
    obs.event("budget_record", budget=name, value=v, effective=recorded)


def runtime_budget(name: str) -> Optional[int]:
    """The recorded runtime ceiling for ``name``, if any."""
    with _lock:
        return _runtime_budgets.get(name)


def budget(name: str, default: int) -> int:
    """A tuned byte budget (e.g. ``cagra_inline_bytes``), or ``default``
    when tuning is off or the table has no entry. A runtime ceiling
    recorded by :func:`record_budget` (an OOM survivor size) clamps the
    answer in every mode — a learned hard limit outranks projections."""
    out = int(default)
    if mode() != "off":
        t = get_table()
        if t is not None:
            v = t.budget(name)
            if v is not None:
                out = int(v)
    ceil = runtime_budget(name)
    return out if ceil is None else min(out, ceil)


__all__ = [
    "BEAM_STEP_TILES", "DispatchTable", "FUSED_TOPK_TILES",
    "FUSED_TOPK_TILE_FLOOR", "GRAPH_JOIN_TILES", "MEASURABLE_INLINE",
    "backend_name", "budget", "choose", "fused_topk_candidate_impls",
    "get_table", "kernel_shape_candidates", "mode", "record_budget",
    "reload", "runtime_budget", "set_mode", "set_table_path",
    "table_path", "tables_dir",
]
