"""Microbenchmark harness behind the dispatch tables.

Times the competing implementations behind each hot-path dispatch over a
grid of static shape keys and records the winners into a
``DispatchTable`` (the measurement half of the reference's learned
``select_k`` heuristic, matrix/detail/select_k-inl.cuh:51-79 /
cpp/scripts/heuristics/select_k). Ops:

``select_k`` / ``merge_topk``
    ``lax.top_k`` (hardware sort) vs the compacting tournament network
    vs the hierarchical tile/merge-tree rung, at selection shapes
    (large n, moderate k) and merge shapes (n = n_probes x kl candidate
    pools) respectively. Cheap — also run inline by
    ``RAFT_TPU_TUNING=measure``.
``ivf_scan``
    end-to-end IVF-Flat search with the fused Pallas list-scan kernel vs
    the XLA bucketized scan (key: cap, k, approx).
``ivf_scan_extract``
    the kernel's in-kernel extraction arms raced head-to-head (exact
    k-pass sweep vs lane-binned vs R-deep binned vs the unextracted
    fold, charged with its deferred merge) by forcing each via
    ``fused_list_scan_topk(extract=...)``; TPU-only by default (the
    kernel's compile target).
``fused_topk_tile``
    brute-force backends end-to-end: XLA lax.scan tiling vs the fused
    Pallas distance+partial-top-k kernel per (variant, row-tile) —
    winners are brute_force impl strings, so tile geometry is adopted
    from measurement with no code change.
``pq_scan``
    end-to-end IVF-PQ search per cache kind — i8 decoded residuals
    (1 MXU pass), packed-i4 raw residuals (1 pass, in-kernel nibble
    decode), pq4 transposed codes (16-pass one-hot contraction), and
    the rabitq sign-bit rung TIMED THROUGH ITS RERANK PIPELINE
    (``search_refined``, codes rerank). The race is matched-recall:
    arms that cannot clear the finest classic rung's recall − 0.01 are
    filtered out before any timing (the ``binned_loss_fits``
    eligibility pattern). The recall-band survivors compete for
    ``cache_dtype="auto"``'s sub-i8-budget slot (``_cache_kind_for``
    keeps the finest rung whenever it fits); i8's time is captured for
    the record.

``graph_join``
    nn-descent local-join backends raced at one join-block shape: the
    XLA einsum + keep-min merge vs the fused Pallas kernel per node
    tile (``pallas:8`` … ``pallas:32``, ops/graph_join.py) — the
    winner string carries the tile, so a live-chip capture adopts
    node-tile geometry with no code change (ISSUE 15).
``beam_step_tile``
    the fused CAGRA beam-step kernel's query-tile (lane) geometry
    raced over ``tuning.BEAM_STEP_TILES`` on real packed inline rows;
    TPU-only by default (the kernel's compile target), winner strings
    ``pallas:<g>`` consumed by ``cagra._resolve_beam_tile``.
``serve_service``
    end-to-end ``ivf_flat.search`` medians per (bucket, probe-rung)
    shape — not a dispatch race but a TIMING table: the serve layer's
    deadline machinery (batcher slack test, shed/downshift estimates)
    reads these through ``serve.adaptive.service_estimate_ms`` instead
    of guessing (ISSUE 14, docs/serving.md §13).

Index-building ops (ivf_scan, pq_scan, serve_service) are only
captured by ``scripts/capture_dispatch_tables.py``; measuring them at
dispatch time would build an index inside a search call.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

_DEF_REPS = 5


def _median_ms(fn, reps: int = _DEF_REPS) -> float:
    """Median wall-clock ms of ``fn()`` after one warmup (compile) call.
    ``fn`` must return jax arrays; completion is forced per rep."""
    import jax

    jax.block_until_ready(fn())
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(ts))


def _rand(shape, dtype, seed=0):
    import jax
    import jax.numpy as jnp

    x = jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)
    return jax.block_until_ready(x.astype(dtype))


# ---------------------------------------------------------------------------
# select_k / merge_topk: top_k vs tournament
# ---------------------------------------------------------------------------


def select_candidates(key: Dict) -> List[str]:
    """Eligible select_k implementations at ``key`` (mirrors the
    constraints in matrix/select_k.py): the tournament is float-only
    and needs k <= n; the hierarchical rung (every dtype) needs at
    least 4 local tiles' worth of data to be a tree at all."""
    cands = ["top_k"]
    dtype = str(key.get("dtype", "float32"))
    if dtype.startswith(("float", "bfloat")):
        cands.append("tournament")
    n, k = int(key.get("n", 0)), int(key.get("k", 1))
    K = 1 << (max(k, 1) - 1).bit_length()
    if n >= 4 * K:
        cands.append("hierarchical")
    return cands


def bench_select(key: Dict, candidates: Optional[List[str]] = None,
                 reps: int = _DEF_REPS) -> Dict[str, float]:
    """Time the select_k implementations at ``key``
    ({n, k, batch, dtype}); returns {candidate: median_ms}."""
    import jax.numpy as jnp

    from raft_tpu.matrix.select_k import (
        _hierarchical_topk,
        _select_k,
        _tournament_topk,
    )

    n = int(key["n"])
    k = int(key["k"])
    batch = int(key.get("batch", 64))
    dtype = jnp.dtype(key.get("dtype", "float32"))
    if candidates is None:
        candidates = select_candidates(key)
    x = _rand((batch, n), dtype)
    times: Dict[str, float] = {}
    if "top_k" in candidates:
        times["top_k"] = _median_ms(lambda: _select_k(x, k, True), reps)
    if "tournament" in candidates:
        times["tournament"] = _median_ms(
            lambda: _tournament_topk(x, k, True), reps
        )
    if "hierarchical" in candidates:
        times["hierarchical"] = _median_ms(
            lambda: _hierarchical_topk(x, k, True), reps
        )
    return times


# ---------------------------------------------------------------------------
# ivf_scan: fused Pallas kernel vs XLA bucketized scan
# ---------------------------------------------------------------------------

# shared small-but-representative search workload for the end-to-end ops
_SCAN_N = 20_000
_SCAN_D = 64
_SCAN_M = 512


def _scan_dataset(n=_SCAN_N, d=_SCAN_D, m=_SCAN_M):
    rng = np.random.default_rng(7)
    data = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((m, d)).astype(np.float32)
    return data, queries


def bench_ivf_scan(key: Dict, candidates: List[str],
                   reps: int = _DEF_REPS):
    """Time end-to-end IVF-Flat search per scan impl at ``key``
    ({k, approx, ...}). Candidates: "xla" | "pallas" |
    "pallas_interpret" (CPU-debug kernel — orders of magnitude slower
    than compiled, only meaningful relative to itself). Returns
    (times, key) with the key enriched by the built index's list
    capacity — the field ``_resolve_scan_impl`` looks up by."""
    from raft_tpu.neighbors import ivf_flat

    key = dict(key)
    k = int(key.get("k", 10))
    n_lists = int(key.get("n_lists", 64))
    n_probes = int(key.get("n_probes", 8))
    approx = bool(key.get("approx", True))
    data, queries = _scan_dataset(n=int(key.get("n", _SCAN_N)))
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=4), data
    )
    key["cap"] = int(index.storage.shape[1])
    times: Dict[str, float] = {}
    for impl in candidates:
        sp = ivf_flat.SearchParams(
            n_probes=n_probes, scan_impl=impl,
            local_recall_target=0.95 if approx else 1.0,
        )
        try:
            times[impl] = _median_ms(
                lambda sp=sp: ivf_flat.search(sp, index, queries, k), reps
            )
        except Exception:  # noqa: BLE001 - impl unavailable on backend
            continue
    return times, key


def bench_scan_extract(key: Dict, candidates: Optional[List[str]] = None,
                       reps: int = _DEF_REPS,
                       interpret: bool = False) -> Dict[str, float]:
    """Time the fused kernel's in-kernel extraction variants directly
    (exact k-pass sweep vs lane-binned vs R-deep binned) by forcing each
    arm through ``fused_list_scan_topk(extract=...)`` on a synthetic
    list-block workload. ``interpret`` runs the kernel in interpret mode
    (CPU debug — numbers only meaningful relative to each other)."""
    import jax.numpy as jnp

    from raft_tpu.ops import ivf_scan

    k = int(key.get("k", 10))
    cap = int(key.get("cap", 512))
    G = int(key.get("g", 64))
    C = int(key.get("n_lists", 8))
    d = int(key.get("d", 64))
    nb = int(key.get("nb", 16))
    if candidates is None:
        from raft_tpu.ops.ivf_scan import binned_loss_fits

        # race only arms a DEFAULT-target serve call can actually pick:
        # the table key carries no recall dimension, so a winner that
        # is ineligible at serve time would be skipped wholesale by
        # DispatchTable.lookup (it never consults the runner-up) and
        # the chip time racing it wasted (review fix, r6)
        candidates = ["exact"]
        if cap % 128 == 0 and cap > 128:
            if k <= 64 and binned_loss_fits(k):
                candidates.append("binned")
            if k <= 256:
                candidates.append("binned_deep")
                candidates.append("fold")
    storage = _rand((C, cap, d), jnp.float32, seed=1)
    qv = _rand((nb, G, d), jnp.bfloat16, seed=2)
    import jax

    indices = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32)[None],
                               (C, cap))
    sizes = jnp.full((C,), cap, jnp.int32)
    buckets = (jnp.arange(nb, dtype=jnp.int32) % C)
    qaux = jnp.sum(qv.astype(jnp.float32) ** 2, axis=2)
    norms = jnp.sum(storage.astype(jnp.float32) ** 2, axis=2)
    jax.block_until_ready((indices, qaux, norms))
    times: Dict[str, float] = {}
    n_probes = int(key.get("n_probes", 8))

    def run(arm):
        import jax.numpy as jnp

        from raft_tpu.neighbors.common import merge_topk

        out_d, out_i = ivf_scan.fused_list_scan_topk(
            storage, indices, sizes, buckets, qv, qaux, norms,
            None, k=k, metric_kind=ivf_scan.L2,
            approx=arm != "exact", interpret=interpret,
            # the race measures TIME; recall-fit filtering happens at
            # dispatch (choose() intersects table winners with the
            # caller's eligible set), so keep every arm forceable here
            recall_target=0.0,
            extract=arm,
        )
        # charge EVERY arm its downstream cross-probe merge at the real
        # pool width (n_probes x candidate-width): fold's whole trade is
        # a wider merge for zero extraction passes, so the race is only
        # end-to-end honest when both sides pay their merge
        kc = int(out_d.shape[2])
        pool_d = jnp.tile(out_d.reshape(-1, kc), (1, n_probes))
        pool_i = jnp.tile(out_i.reshape(-1, kc), (1, n_probes))
        return merge_topk(pool_d, pool_i, k, True)

    for arm in candidates:
        try:
            times[arm] = _median_ms(lambda arm=arm: run(arm), reps)
        except Exception:  # noqa: BLE001 - arm unavailable on backend
            continue
    return times


def bench_fused_topk(key: Dict, candidates: Optional[List[str]] = None,
                     reps: int = _DEF_REPS,
                     interpret: bool = False) -> Dict[str, float]:
    """Race the brute-force scan backends at ``key`` ({m, n, d, k}):
    the XLA lax.scan tiling ("scan") vs the fused Pallas
    distance+partial-top-k kernel per (variant, row-tile) — candidate
    names are brute_force's impl strings ("fused_exact:1024",
    "fused_fold:2048", ...), so the captured winner IS the dispatch
    answer and a live-chip capture adopts new tile geometry with no
    code change. ``interpret`` appends ":interpret" to the fused
    candidates (CPU debug-only numbers)."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    m = int(key.get("m", 512))
    n = int(key.get("n", _SCAN_N))
    d = int(key.get("d", _SCAN_D))
    k = int(key.get("k", 10))
    if candidates is None:
        from raft_tpu.tuning import fused_topk_candidate_impls

        # race the exact same enumeration brute_force dispatches over
        # (microbench charges fold with its deferred merge either way)
        candidates = ["scan"] + fused_topk_candidate_impls(k, approx_ok=True)
    data, queries = _scan_dataset(n=n, d=d, m=m)
    index = brute_force.build(data, "sqeuclidean")
    q = jnp.asarray(queries)
    times: Dict[str, float] = {}
    for impl in candidates:
        arm = impl
        if interpret and impl.startswith("fused"):
            arm = impl + ":interpret"
        try:
            times[impl] = _median_ms(
                lambda arm=arm: brute_force.search(index, q, k, impl=arm),
                reps)
        except Exception:  # noqa: BLE001 - impl unavailable on backend
            continue
    return times


def bench_graph_join(key: Dict, candidates: Optional[List[str]] = None,
                     reps: int = _DEF_REPS,
                     interpret: bool = False) -> Dict[str, float]:
    """Race the nn-descent local-join backends at ``key``
    ({rows, K, S, d}): the XLA einsum + keep-min merge ("xla") vs the
    fused Pallas kernel per node tile ("pallas:8" ... "pallas:32",
    ops/graph_join.py) — candidate names are nn_descent's join impl
    strings, so the captured winner IS the dispatch answer and a
    live-chip capture adopts node-tile geometry with no code change.
    The workload is one join block at the real shape (current lists +
    sampled candidates + the reverse slab), gathers included — both
    arms pay the candidate-vector gather, so the race isolates the
    score+merge transients the kernel removes. ``interpret`` runs the
    kernel in interpret mode (CPU debug-only numbers)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.nn_descent import _join_block, _make_rev

    rows = int(key.get("rows", 4096))
    K = int(key.get("K", 64))
    S = int(key.get("S", 128))
    d = int(key.get("d", 64))
    n = 2 * rows            # join block over half the node range
    if candidates is None:
        from raft_tpu.tuning import GRAPH_JOIN_TILES

        candidates = ["xla"] + [f"pallas:{t}" for t in GRAPH_JOIN_TILES]
    rng = np.random.default_rng(23)
    data = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    norms = jnp.sum(data * data, axis=1)
    graph_i = jnp.asarray(
        rng.integers(0, n, (n, K)).astype(np.int32))
    graph_d = jnp.asarray(
        rng.standard_normal((n, K)).astype(np.float32) ** 2)
    rev_i = jax.block_until_ready(_make_rev(graph_i))
    pool = jnp.concatenate([graph_i, rev_i], axis=1)
    cols = jnp.asarray(rng.integers(0, 2 * K * K, S).astype(np.int32))
    start0 = jnp.int32(0)
    times: Dict[str, float] = {}
    for impl in candidates:
        kind, _, tile = impl.partition(":")
        if kind.startswith("pallas") and interpret:
            kind = "pallas_interpret"
        try:
            times[impl] = _median_ms(
                lambda kind=kind, tile=tile: _join_block(
                    data, norms, graph_d, graph_i, pool, rev_i, cols,
                    start0, rows=rows, ip=False, impl=kind,
                    tile_b=int(tile) if tile else 0), reps)
        except Exception:  # noqa: BLE001 - impl unavailable on backend
            continue
    return times


def bench_beam_step(key: Dict, candidates: Optional[List[str]] = None,
                    reps: int = _DEF_REPS,
                    interpret: bool = False) -> Dict[str, float]:
    """Race the fused beam-step kernel's query-tile geometry at ``key``
    ({m, itopk, width, deg, d}) — op key ``beam_step_tile``, candidate
    names ``pallas:<g>`` over ``tuning.BEAM_STEP_TILES`` (the lane tile
    cagra._resolve_beam_tile dispatches): one packed-scoring
    beam_merge_step call per tile on real inline rows, so the captured
    winner adopts tile geometry with no code change."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.beam_step import beam_merge_step, beam_step_vmem_bytes

    m = int(key.get("m", 1024))
    L = int(key.get("itopk", 64))
    width = int(key.get("width", 4))
    deg = int(key.get("deg", 32))
    d = int(key.get("d", 64))
    n = 20_000
    if candidates is None:
        from raft_tpu.tuning import BEAM_STEP_TILES

        candidates = [
            f"pallas:{g}" for g in BEAM_STEP_TILES
            if beam_step_vmem_bytes(g, L, width, deg, d) <= 8 << 20
        ]
    rng = np.random.default_rng(29)
    x = rng.standard_normal((n, d)).astype(np.float32)
    graph = rng.integers(0, n, (n, deg)).astype(np.int32)
    idx = cagra.from_graph(x, graph, "sqeuclidean")
    if idx.nbr_pack is None:
        return {}
    q = rng.standard_normal((m, d)).astype(np.float32)
    qs = jnp.asarray(q * 2.0 * idx.code_scale, jnp.bfloat16)
    qperm = jnp.transpose(qs.reshape(m, d // 4, 4), (0, 2, 1))
    qrep = jnp.tile(qperm, (1, 1, deg))
    parents = jnp.asarray(rng.integers(0, n, (width, m)).astype(np.int32))
    pack = idx.nbr_pack[jnp.maximum(parents.T, 0)]
    bd = jnp.asarray(np.sort(
        rng.standard_normal((L, m)).astype(np.float32) ** 2, axis=0))
    bi = jnp.asarray(rng.integers(0, n, (L, m)).astype(np.int32))
    be = jnp.zeros((L, m), jnp.int32)
    jax.block_until_ready((qrep, pack, bd))
    times: Dict[str, float] = {}
    for impl in candidates:
        try:
            g = int(impl.split(":", 1)[1])
        except (IndexError, ValueError):
            continue
        try:
            times[impl] = _median_ms(
                lambda g=g: beam_merge_step(
                    bd, bi, be, qrep=qrep, pack=pack, parents=parents,
                    deg=deg, d=d, width=width, g=g,
                    interpret=interpret), reps)
        except Exception:  # noqa: BLE001 - tile unavailable on backend
            continue
    return times


def _pq_oracle_ids(data, queries, k: int):
    """Exact L2 top-k ids for the shared pq_scan workload (the recall
    judge for the matched-recall race below)."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(data)
    q = jnp.asarray(queries)
    d2 = (jnp.sum(q * q, 1)[:, None] + jnp.sum(x * x, 1)[None, :]
          - 2.0 * q @ x.T)
    _, ids = jax.lax.top_k(-d2, k)
    return np.asarray(ids)


def _pq_recall(ids, want) -> float:
    """Set-intersection recall@k — THE one implementation
    (bench.harness.compute_recall), so the dispatch race's recall gate
    can never drift from bench's reported recall."""
    from raft_tpu.bench.harness import compute_recall

    return float(compute_recall(np.asarray(ids), np.asarray(want)))


def rabitq_matched_refine_ratio(recalls: Dict[int, float],
                                target: float) -> Optional[int]:
    """Smallest refine_ratio whose measured pipeline recall clears
    ``target`` — or None when no ratio does (the arm is then filtered
    out of the race entirely). The loss-aware-eligibility pattern from
    ``ivf_scan.binned_loss_fits``: an arm that cannot hit the caller's
    recall band must be excluded BEFORE the race, because the table key
    carries no recall dimension and ``DispatchTable.lookup`` never
    consults the runner-up."""
    for rr in sorted(recalls):
        if recalls[rr] >= target:
            return rr
    return None


# refine ratios the rabitq arm may race at (the acceptance band caps
# the pipeline at <= 4; larger ratios would change the op's semantics)
_RABITQ_RATIOS = (2, 4)


def bench_pq_scan(key: Dict, candidates: List[str],
                  reps: int = _DEF_REPS):
    """Time end-to-end IVF-PQ search per cache kind at ``key``. The
    build uses pq_bits=4 so the classic kinds (i8/i4/pq4) are feasible
    on one quantizer config; search runs with lut_dtype="auto" (cache
    scan — the path the choice governs). Returns (times, key) with the
    key enriched by the built geometry (cap/rot/pq_bits — the fields
    ``_cache_kind_for`` looks up by).

    The race is MATCHED-RECALL (ISSUE 11): each arm's recall vs the
    exact oracle is measured first; the target is the finest SUB-i8
    classic rung's recall minus 0.01 (the acceptance band — the entry
    decides the sub-i8 auto slot, so i8 must not set the bar), and an
    arm that cannot hit it is filtered out BEFORE any timing — the
    ``binned_loss_fits`` eligibility pattern, because a table winner is
    never re-filtered by recall at dispatch time. The "rabitq" arm is
    timed through its WHOLE pipeline (``search_refined`` at the
    smallest refine_ratio <= 4 that clears the target; codes rerank),
    so its time is end-to-end honest against the single-stage kinds.
    Sub-target recalls are recorded in the key for the table record."""
    from raft_tpu.neighbors import ivf_pq

    key = dict(key)
    k = int(key.get("k", 10))
    n_lists = int(key.get("n_lists", 64))
    n_probes = int(key.get("n_probes", 8))
    pq_dim = int(key.get("pq_dim", 32))
    data, queries = _scan_dataset(n=int(key.get("n", _SCAN_N)))
    want = _pq_oracle_ids(data, queries, k)
    built: Dict[str, tuple] = {}      # kind -> (index, search thunk)
    recalls: Dict[str, float] = {}
    for kind in ("i8", "i4", "pq4", "rabitq"):
        if kind not in candidates:
            continue
        params = ivf_pq.IndexParams(
            n_lists=n_lists, pq_bits=4, pq_dim=pq_dim, kmeans_n_iters=4,
            cache_decoded=True, cache_dtype=kind,
        )
        try:
            index = ivf_pq.build(params, data)
            if index.cache_kind != kind:
                continue  # budget-gated out: not a competitor here
            key.setdefault("cap", int(index.indices.shape[1]))
            key.setdefault("rot", int(index.rot_dim))
            key.setdefault("pq_bits", 4)
            sp = ivf_pq.SearchParams(n_probes=n_probes)
            if kind == "rabitq":
                rr_rec = {}
                for rr in _RABITQ_RATIOS:
                    _, ids = ivf_pq.search_refined(sp, index, queries, k,
                                                   refine_ratio=rr)
                    rr_rec[rr] = _pq_recall(ids, want)
                built[kind] = (index, sp, rr_rec)
            else:
                _, ids = ivf_pq.search(sp, index, queries, k)
                recalls[kind] = _pq_recall(ids, want)
                built[kind] = (index, sp, None)
        except Exception:  # noqa: BLE001 - kind unavailable on backend
            continue
    # matched-recall target: the finest SUB-i8 classic rung present,
    # minus the acceptance band's 0.01. NOT i8's recall — the table
    # entry decides the sub-i8 "auto" slot (dispatch only consults it
    # when i8 misses the budget, with sub-i8 candidates), so a target
    # set by i8 would filter every actual competitor and leave a
    # winner=i8 entry the lookup can never use (review fix, r10).
    # i8 is still timed below, for the record.
    classic = [recalls[kk] for kk in ("i4", "pq4") if kk in recalls]
    target = (max(classic) - 0.01) if classic else 0.0
    key["recall_target"] = round(target, 4)
    times: Dict[str, float] = {}
    for kind, (index, sp, rr_rec) in built.items():
        if kind == "rabitq":
            rr = rabitq_matched_refine_ratio(rr_rec, target)
            key["rabitq_recall"] = round(max(rr_rec.values()), 4)
            if rr is None:
                continue              # can't hit the band: not raced
            key["rabitq_refine_ratio"] = int(rr)
            times[kind] = _median_ms(
                lambda sp=sp, ix=index, rr=rr: ivf_pq.search_refined(
                    sp, ix, queries, k, refine_ratio=rr),
                reps,
            )
        else:
            if recalls.get(kind, 0.0) < target:
                continue              # below the band: not raced
            times[kind] = _median_ms(
                lambda sp=sp, ix=index: ivf_pq.search(sp, ix, queries, k),
                reps,
            )
    return times, key


# ---------------------------------------------------------------------------
# inline measurement (RAFT_TPU_TUNING=measure) + capture grids
# ---------------------------------------------------------------------------


def measure_op(op: str, key: Dict,
               candidates: List[str]) -> Dict[str, float]:
    """Measure one (op, key) synchronously — only the cheap selection
    ops; the index-building ops raise (capture those with
    scripts/capture_dispatch_tables.py)."""
    if op in ("select_k", "merge_topk"):
        return bench_select(key, candidates, reps=3)
    raise ValueError(
        f"op {op!r} cannot be measured inline; run "
        "scripts/capture_dispatch_tables.py"
    )


def select_grid(quick: bool = True) -> List[Dict]:
    """(n, k, batch) grid for the select_k op — spans the projected
    crossover region (k ~ 256, n >= 8K)."""
    ns = [8_192, 65_536] if quick else [8_192, 65_536, 262_144]
    ks = [64, 256, 1024] if quick else [64, 256, 1024, 4096]
    batches = [64] if quick else [16, 64, 256]
    grid = []
    for n in ns:
        for k in ks:
            if k * 4 > n:
                continue
            for b in batches:
                grid.append({"n": n, "k": k, "batch": b,
                             "dtype": "float32"})
    return grid


def merge_grid(quick: bool = True) -> List[Dict]:
    """(c, k, batch) grid for merge_topk — candidate pools are
    n_probes x kl wide and batch is the query count, so the regime is
    wider-batch / narrower-n than select_k's."""
    grid = []
    shapes = ([(1280, 10), (8192, 64), (16384, 512)] if quick else
              [(1280, 10), (2560, 32), (8192, 64), (8192, 512),
               (16384, 512), (32768, 1024)])
    for c, k in shapes:
        for b in ([256] if quick else [64, 256, 1024]):
            grid.append({"n": c, "k": k, "batch": b, "dtype": "float32"})
    return grid


def scan_grid(quick: bool = True) -> List[Dict]:
    del quick
    # the k=130 exact row covers the known pallas weak spot (the k-pass
    # unrolled extraction measured ~7x slower than XLA at k=130, r4
    # v5e) so the
    # table's interpolation radius cannot route mid-k exact searches
    # onto an unmeasured arm
    return [{"n": _SCAN_N, "k": 10, "approx": True, "n_lists": 64,
             "n_probes": 8},
            {"n": _SCAN_N, "k": 64, "approx": False, "n_lists": 64,
             "n_probes": 8},
            {"n": _SCAN_N, "k": 130, "approx": False, "n_lists": 64,
             "n_probes": 8}]


def pq_grid(quick: bool = True) -> List[Dict]:
    del quick
    return [{"n": _SCAN_N, "k": 10, "pq_dim": 32, "n_lists": 64,
             "n_probes": 8}]


def extract_grid(quick: bool = True) -> List[Dict]:
    ks = [10, 64, 130] if quick else [10, 32, 64, 130, 256]
    return [{"cap": 512, "k": k, "g": 64, "n_lists": 8, "d": 64,
             "nb": 16} for k in ks]


def graph_join_grid(quick: bool = True) -> List[Dict]:
    """(rows, K, S, d) grid for the graph_join race — the nn-descent
    block shapes CAGRA builds dispatch at (K = intermediate degree,
    S = n_candidates), plus the small-K regime where XLA's batched
    einsum can win back."""
    if quick:
        return [{"rows": 4096, "K": 64, "S": 128, "d": 64},
                {"rows": 4096, "K": 96, "S": 128, "d": 128}]
    return [{"rows": r, "K": K, "S": S, "d": d}
            for r in (4096, 16384)
            for (K, S) in ((32, 64), (64, 128), (96, 128))
            for d in (64, 128)]


def beam_step_grid(quick: bool = True) -> List[Dict]:
    """(m, itopk, width, deg, d) grid for the beam_step_tile race —
    the serve bucket ladder's batch range at the CAGRA search shapes."""
    if quick:
        return [{"m": 1024, "itopk": 64, "width": 4, "deg": 32, "d": 64}]
    return [{"m": m, "itopk": L, "width": 4, "deg": 32, "d": d}
            for m in (256, 1024, 10240)
            for L in (64, 128)
            for d in (64, 128)]


def fused_topk_grid(quick: bool = True) -> List[Dict]:
    """(m, n, d, k) grid for the brute-force backend race — the
    north-star bruteforce_sift10k shape's neighborhood plus the large-k
    regime where the exact arm ages out."""
    if quick:
        return [{"m": 512, "n": 20_000, "d": 64, "k": 10},
                {"m": 512, "n": 20_000, "d": 64, "k": 100}]
    return [{"m": m, "n": n, "d": d, "k": k}
            for n in (20_000, 100_000)
            for (m, d) in ((512, 64), (2048, 128))
            for k in (10, 100, 256)]


def serve_grid(quick: bool = True) -> List[Dict]:
    """(bucket, rung) grid for the serve_service capture — the bucket
    ladder the micro-batcher dispatches at crossed with the adaptive
    probe-rung ladder (docs/serving.md §13). The medians feed the
    batcher's deadline slack test and the engine's shed/downshift
    estimates through ``serve.adaptive.service_estimate_ms``."""
    buckets = [8, 32, 128] if quick else [1, 8, 32, 128, 256]
    rungs = [1, 4, 16, 64] if quick else [1, 2, 4, 8, 16, 32, 64]
    return [{"bucket": b, "rung": r} for b in buckets for r in rungs]


def bench_serve_service(keys: List[Dict], reps: int = _DEF_REPS,
                        n: int = 20_000, dim: int = 64,
                        n_lists: int = 64):
    """Median end-to-end ``ivf_flat.search`` service time per
    (bucket, rung) shape over ONE shared index — the per-rung
    service-time table the serve deadline machinery reads instead of a
    hardcoded guess. Yields (key, {"search": median_ms})."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(17)
    x = rng.standard_normal((n, dim)).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=5), x)
    for key in keys:
        bucket = int(key["bucket"])
        rung = int(min(key["rung"], n_lists))
        q = jnp.asarray(rng.standard_normal(
            (bucket, dim)).astype(np.float32))
        sp = ivf_flat.SearchParams(n_probes=rung, compute_dtype="f32",
                                   local_recall_target=1.0)

        def run(q=q, sp=sp):
            return ivf_flat.search(sp, index, q, 10)

        yield dict(key, rung=rung), {"search": _median_ms(run, reps)}


def bench_pipeline_depth(reps: int = 3, n_items: int = 24,
                         work_ms: float = 2.0) -> Dict[str, float]:
    """Race the graft-flow prefetch depths
    (:data:`raft_tpu.core.pipeline.PIPELINE_DEPTH_CANDIDATES`) on a
    balanced synthetic read/compute stream — equal sleep on the
    producer (the host-tier read) and the consumer (the scoring loop),
    the regime where overlap pays the most. The winner lands in the
    table's ``pipeline_depth`` budget, which every streaming path reads
    through :func:`raft_tpu.core.pipeline.resolve_depth` when the
    caller leaves the depth defaulted."""
    from raft_tpu.core import pipeline as gf

    def run(depth: int) -> float:
        def source():
            for i in range(n_items):
                time.sleep(work_ms / 1e3)
                yield i

        t0 = time.perf_counter()
        with gf.Prefetcher(source, depth=depth,
                           path="capture.pipeline") as pf:
            for _ in pf:
                time.sleep(work_ms / 1e3)
        return (time.perf_counter() - t0) * 1e3

    return {str(depth): min(run(depth) for _ in range(max(reps, 1)))
            for depth in gf.PIPELINE_DEPTH_CANDIDATES}


def default_budgets() -> Dict[str, int]:
    """Measured-environment byte budgets. The CAGRA inline budget tracks
    the device HBM actually present (packed table + dataset + transients
    must co-reside: cap at ~40% of the per-device byte limit), falling
    back to the analytic default when the backend doesn't report one."""
    from raft_tpu.neighbors.cagra import _INLINE_BUDGET

    budget = _INLINE_BUDGET
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            budget = int(limit * 0.4)
    except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow memory-stats probe; backends without stats fall back to the analytic budget
        pass
    return {"cagra_inline_bytes": int(budget)}


def capture(backend: Optional[str] = None, quick: bool = True,
            include_interpret: bool = False, reps: int = _DEF_REPS,
            ops: Optional[List[str]] = None, verbose: bool = True):
    """Run the full grid and return a populated DispatchTable."""
    import jax

    from raft_tpu import tuning
    from raft_tpu.tuning.table import TABLE_VERSION, DispatchTable

    backend = backend or tuning.backend_name()
    on_tpu = backend == "tpu"
    t = DispatchTable({
        "version": TABLE_VERSION,
        "backend": backend,
        "captured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "device": str(jax.devices()[0]),
        "ops": {},
        "budgets": {},
    })

    def log(msg):
        if verbose:
            print(msg, flush=True)

    want = set(ops) if ops else {"select_k", "merge_topk", "ivf_scan",
                                 "pq_scan", "ivf_scan_extract",
                                 "fused_topk_tile", "serve_service",
                                 "graph_join", "beam_step_tile",
                                 "pipeline_depth"}
    if "select_k" in want:
        for key in select_grid(quick):
            times = bench_select(key, reps=reps)
            log(f"select_k {key} -> {t.record('select_k', key, times)} "
                f"{times}")
    if "merge_topk" in want:
        for key in merge_grid(quick):
            times = bench_select(key, select_candidates(key), reps=reps)
            log(f"merge_topk {key} -> "
                f"{t.record('merge_topk', key, times)} {times}")
    scan_cands = ["xla"] + (["pallas"] if on_tpu else
                            ["pallas_interpret"] if include_interpret
                            else [])
    if "ivf_scan" in want:
        for key in scan_grid(quick):
            times, key = bench_ivf_scan(key, scan_cands, reps=reps)
            if times:
                log(f"ivf_scan {key} -> "
                    f"{t.record('ivf_scan', key, times)} {times}")
    if "pq_scan" in want:
        for key in pq_grid(quick):
            times, key = bench_pq_scan(key, ["i8", "i4", "pq4", "rabitq"],
                                       reps=reps)
            if times:
                log(f"pq_scan {key} -> "
                    f"{t.record('pq_scan', key, times)} {times}")
    # in-kernel extraction arms: the kernel only compiles on TPU, so the
    # CPU capture records this op solely under --interpret (debug-only
    # relative numbers); a CPU table without it falls back analytically,
    # which is correct — the choice never fires off-TPU
    if "ivf_scan_extract" in want and (on_tpu or include_interpret):
        for key in extract_grid(quick):
            times = bench_scan_extract(key, reps=reps,
                                       interpret=not on_tpu)
            if times:
                log(f"ivf_scan_extract {key} -> "
                    f"{t.record('ivf_scan_extract', key, times)} {times}")
    # brute-force backend race (scan vs fused kernel per variant/tile):
    # same TPU-only rule — fused candidates need the compile target, the
    # CPU capture times only the scan arm unless --interpret
    if "fused_topk_tile" in want:
        for key in fused_topk_grid(quick):
            cands = (None if on_tpu or include_interpret else ["scan"])
            times = bench_fused_topk(key, cands, reps=reps,
                                     interpret=not on_tpu)
            if times:
                log(f"fused_topk_tile {key} -> "
                    f"{t.record('fused_topk_tile', key, times)} {times}")
    # nn-descent local-join backends: the xla arm races everywhere; the
    # fused-kernel tiles need the compile target (or --interpret for
    # CPU debug numbers) — same rule as the other kernel ops
    if "graph_join" in want:
        for key in graph_join_grid(quick):
            cands = (None if on_tpu or include_interpret
                     else ["xla"])
            times = bench_graph_join(key, cands, reps=reps,
                                     interpret=not on_tpu)
            if times:
                log(f"graph_join {key} -> "
                    f"{t.record('graph_join', key, times)} {times}")
    # beam query-tile geometry: kernel-only op, TPU (or --interpret)
    if "beam_step_tile" in want and (on_tpu or include_interpret):
        for key in beam_step_grid(quick):
            times = bench_beam_step(key, reps=reps, interpret=not on_tpu)
            if times:
                log(f"beam_step_tile {key} -> "
                    f"{t.record('beam_step_tile', key, times)} {times}")
    if "serve_service" in want:
        # single-candidate op: the entry's TIMES are the product (the
        # serve deadline machinery reads the per-(bucket, rung) median
        # through adaptive.service_estimate_ms), the winner is moot
        medians = []
        for key, times in bench_serve_service(serve_grid(quick),
                                              reps=reps):
            log(f"serve_service {key} -> {times}")
            t.record("serve_service", key, times)
            medians.append(times["search"])
        # the deadline headroom budget scales with THIS host's service
        # times (a p95-based shed gate needs slack to absorb the
        # service distribution's own tail; the median-of-medians is a
        # robust proxy that shrinks to ~nothing on a real chip)
        t.set_budget("serve_deadline_headroom_ms",
                     max(5, int(round(float(np.median(medians))))))
    if "pipeline_depth" in want:
        # graft-flow depth race (host-side timing, backend-independent):
        # the measured winner becomes the default prefetch depth for
        # every streaming path on this backend
        times = bench_pipeline_depth(reps=min(reps, 3))
        winner = t.record("pipeline_depth", {"shape": "balanced"}, times)
        log(f"pipeline_depth balanced -> {winner} {times}")
        t.set_budget("pipeline_depth", int(winner))
    for name, val in default_budgets().items():
        t.set_budget(name, val)
    return t
