"""Matrix layer (SURVEY.md §2.4): utilities + the select_k top-k engine."""

from raft_tpu.matrix.select_k import select_k, select_k_threshold
from raft_tpu.matrix.ops import (
    argmax,
    argmin,
    col_wise_sort,
    eye,
    gather,
    gather_if,
    init,
    linewise_op,
    norm,
    reverse,
    scatter,
    slice_matrix,
    triangular_lower,
    triangular_upper,
)

__all__ = [
    "select_k",
    "select_k_threshold",
    "argmax",
    "argmin",
    "col_wise_sort",
    "eye",
    "gather",
    "gather_if",
    "init",
    "linewise_op",
    "norm",
    "reverse",
    "scatter",
    "slice_matrix",
    "triangular_lower",
    "triangular_upper",
]
