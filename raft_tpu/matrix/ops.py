"""Matrix utilities.

Analog of the reference's ``cpp/include/raft/matrix`` toolbox (SURVEY.md
§2.4): gather/scatter/slice/argmax/argmin, columnwise sort, linewise ops,
norms, init, reverse, triangular. On TPU these are thin jit-compatible
wrappers over XLA ops — the value is the stable API surface for consumers.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def gather(matrix, row_indices) -> jax.Array:
    """Select rows (reference matrix/gather.cuh)."""
    return jnp.take(jnp.asarray(matrix), jnp.asarray(row_indices), axis=0)


def gather_if(matrix, row_indices, mask, fill_value=0):
    m = jnp.asarray(matrix)
    out = gather(m, row_indices)
    return jnp.where(jnp.asarray(mask)[:, None], out, fill_value)


def scatter(matrix, row_indices, rows) -> jax.Array:
    """Write rows at row_indices (reference matrix/scatter.cuh)."""
    return jnp.asarray(matrix).at[jnp.asarray(row_indices)].set(jnp.asarray(rows))


def slice_matrix(matrix, row_start: int, row_end: int, col_start: int = 0, col_end: Optional[int] = None):
    """Static sub-block (reference matrix/slice.cuh)."""
    m = jnp.asarray(matrix)
    col_end = m.shape[1] if col_end is None else col_end
    return m[row_start:row_end, col_start:col_end]


def argmax(matrix) -> jax.Array:
    """Per-row argmax (reference matrix/argmax.cuh)."""
    return jnp.argmax(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def argmin(matrix) -> jax.Array:
    return jnp.argmin(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def col_wise_sort(matrix, ascending: bool = True):
    """Sort each row's values (reference matrix/col_wise_sort.cuh sorts keys
    per row returning sorted keys + source indices)."""
    m = jnp.asarray(matrix)
    order = jnp.argsort(m if ascending else -m, axis=1)
    return jnp.take_along_axis(m, order, axis=1), order.astype(jnp.int32)


def linewise_op(matrix, vec, along_rows: bool, op) -> jax.Array:
    """Broadcast a vector op along rows or columns
    (reference matrix/linewise_op.cuh / linalg matrix_vector_op)."""
    m = jnp.asarray(matrix)
    v = jnp.asarray(vec)
    return op(m, v[None, :] if along_rows else v[:, None])


def norm(matrix, norm_type: str = "l2", axis: int = 1) -> jax.Array:
    m = jnp.asarray(matrix)
    if norm_type in ("l2", "l2sqrt"):
        out = jnp.sum(m * m, axis=axis)
        return jnp.sqrt(out) if norm_type == "l2sqrt" else out
    if norm_type == "l1":
        return jnp.sum(jnp.abs(m), axis=axis)
    if norm_type == "linf":
        return jnp.max(jnp.abs(m), axis=axis)
    raise ValueError(norm_type)


def init(shape, value, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)


def reverse(matrix, axis: int = 0) -> jax.Array:
    return jnp.flip(jnp.asarray(matrix), axis=axis)


def eye(n: int, dtype=jnp.float32) -> jax.Array:
    return jnp.eye(n, dtype=dtype)


def triangular_upper(matrix) -> jax.Array:
    return jnp.triu(jnp.asarray(matrix))


def triangular_lower(matrix) -> jax.Array:
    return jnp.tril(jnp.asarray(matrix))
