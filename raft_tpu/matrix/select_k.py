"""select_k — the k-selection engine.

TPU-native analog of the reference's ``raft::matrix::select_k``
(cpp/include/raft/matrix/select_k.cuh:81) whose CUDA backends are a radix
11-bit histogram select and warp-level bitonic priority queues chosen by a
learned heuristic (matrix/detail/select_k-inl.cuh:51-79). The dispatch
here has three arms: XLA's ``lax.top_k`` (hardware sort unit —
near-optimal for small k), the exact tournament network
``_tournament_topk`` for large k at n >> k — the compacting radix-select
analog, built on the reshape-bitonic networks with no gathers — and the
hierarchical ``_hierarchical_topk`` (per-tile local top-K through the
hardware sort unit, then a keep-smallest-K pair-merge tree; the in-VMEM
reduction shape RAFT's warpsort runs per-warp before its cross-warp
merge, matrix/detail/select_k-inl.cuh dispatch + select_warpsort.cuh).
Like the reference, the arm is chosen from MEASUREMENTS:
``dispatch_select_impl`` consults the per-backend dispatch table
(``raft_tpu.tuning``) and falls back to the analytic crossover
projection only where the table has no entry. The entry point also (a) maps
select-min onto top_k by negation and (b) carries pass-through source
indices (the reference's ``in_idx``). A two-pass histogram-threshold
variant is kept as ``select_k_threshold`` for callers wanting that
structure; the tournament supersedes it for dispatch (the histogram
variant never compacts, so it cannot beat the hardware top_k).

Design sheet for the hierarchical rung (tile sizing, merge-tree shape,
tie/NaN contracts) and the roofline the selection work is measured
against: docs/kernels.md.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def select_k(
    in_val,
    k: int,
    in_idx=None,
    select_min: bool = True,
    sorted: bool = True,  # noqa: A002 - matches reference arg name
    impl: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) per row.

    Parameters mirror the reference API (matrix/select_k.cuh:81):

    in_val : [batch, n] values.
    in_idx : optional [batch, n] source indices carried with the values
        (defaults to 0..n-1 per row).
    select_min : True → smallest-k (the reference's SelectMinK).
    impl : "auto" (measured dispatch, below) | "top_k" | "tournament"
        | "hierarchical".

    Returns (out_val [batch, k], out_idx [batch, k]).
    """
    in_val = jnp.asarray(in_val)
    squeeze = in_val.ndim == 1
    if squeeze:
        in_val = in_val[None, :]
    batch, n = in_val.shape
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for row length {n}")
    if impl not in ("auto", "top_k", "tournament", "hierarchical"):
        raise ValueError(
            "impl must be 'auto' | 'top_k' | 'tournament' | "
            f"'hierarchical', got {impl!r}")
    if impl == "tournament" and not jnp.issubdtype(in_val.dtype,
                                                  jnp.floating):
        # the tournament's merge space is f32 — forcing it onto integers
        # would reintroduce the >2^24 ordering collapse the integer
        # top_k path exists to avoid (the hierarchical rung carries
        # integer keys in the integer domain and IS eligible)
        raise ValueError(
            f"impl='tournament' is float-only, got {in_val.dtype}")
    if impl == "auto":
        impl = dispatch_select_impl(batch, n, int(k), in_val.dtype)
    from raft_tpu import obs

    # trace-time span: select_k usually runs under an outer jit, so this
    # attributes COMPILE time per impl; steady-state dispatch is silent
    with obs.span("select_k", impl=impl, n=n, k=int(k), batch=batch):
        if impl == "tournament":
            vals, idxs = _tournament_topk(in_val, int(k), bool(select_min))
        elif impl == "hierarchical":
            vals, idxs = _hierarchical_topk(in_val, int(k),
                                            bool(select_min))
        else:
            vals, idxs = _select_k(in_val, int(k), bool(select_min))
    if in_idx is not None:
        in_idx = jnp.asarray(in_idx)
        if squeeze and in_idx.ndim == 1:
            in_idx = in_idx[None, :]
        # tournament pad slots carry position -1: without the mask the
        # gather would wrap to in_idx[..., -1] and return a real id
        mapped = jnp.take_along_axis(in_idx, jnp.maximum(idxs, 0), axis=1)
        idxs = jnp.where(idxs < 0, jnp.asarray(-1, mapped.dtype), mapped)
    if squeeze:
        return vals[0], idxs[0]
    return vals, idxs


def dispatch_select_impl(batch: int, n: int, k: int, dtype,
                         op: str = "select_k",
                         fallback: Optional[str] = None) -> str:
    """The measured selection dispatch (the reference's learned
    heuristic, select_k-inl.cuh:51-79): consult the per-backend dispatch
    table (``raft_tpu/tuning/tables/<backend>.json``, captured by
    scripts/capture_dispatch_tables.py; see docs/dispatch_tuning.md)
    through ``tuning.choose``. The analytic fallback — used on a table
    miss or with RAFT_TPU_TUNING=off — keeps the asymptotic-cost
    projection: lax.top_k's full-row sort is near-optimal for small k,
    but its O(n log^2 n) compare-exchange cost loses to the tournament
    network (sorted 2K blocks + log rounds of keep-smallest-2K pair
    merges, each round HALVING the data — the compaction the reference
    buys with multi-pass radix select, select_radix.cuh:231,546) once
    k > 256 and n >= 8K. The tournament is float-only (its pad/merge
    space is f32); the hierarchical rung (per-tile hardware top-K +
    keep-smallest-K merge tree, docs/kernels.md §hierarchical) is
    eligible at every dtype — integer keys stay in the integer domain —
    and is the analytic answer for large-k integer selects the
    tournament cannot take.

    ``op`` lets callers with their own shape regime (merge_topk's
    wide-batch candidate pools) look up a dedicated table section with
    the same candidate constraints; ``fallback`` overrides the analytic
    projection on a miss (merge_topk passes "auto" to defer to this
    op's own dispatch at the inner select)."""
    from raft_tpu import tuning

    floating = jnp.issubdtype(dtype, jnp.floating)
    candidates = ["top_k"] + (["tournament"] if floating else [])
    K = 1 << (int(k) - 1).bit_length()
    if n >= 4 * K:
        # below 4 tiles of 2K the "tree" degenerates to one local top_k
        # plus overhead — never a candidate there
        candidates.append("hierarchical")
    if fallback is None:
        fallback = ("tournament" if k > 256 and n >= 8 * K and floating
                    else "hierarchical"
                    if k > 256 and n >= 8 * K and "hierarchical" in candidates
                    else "top_k")
    return tuning.choose(
        op,
        {"n": int(n), "k": int(k), "batch": int(batch),
         "dtype": jnp.dtype(dtype).name},
        candidates, fallback,
    )


@functools.partial(jax.jit, static_argnums=(1, 2))
def _select_k(in_val, k: int, select_min: bool):
    if select_min:
        # top_k selects max; negate.
        if jnp.issubdtype(in_val.dtype, jnp.floating):
            vals, idxs = jax.lax.top_k(-in_val, k)
            return -vals, idxs.astype(jnp.int32)
        # Integers: bitwise NOT is the order-reversing map that stays in
        # the integer domain — exact at every value (monotone decreasing
        # for signed AND unsigned, no INT_MIN negation overflow, none of
        # the f32 cast's precision loss above 2^24).
        work = in_val.astype(jnp.int32) if in_val.dtype == jnp.bool_ else in_val
        vals, idxs = jax.lax.top_k(~work, k)
        return (~vals).astype(in_val.dtype), idxs.astype(jnp.int32)
    vals, idxs = jax.lax.top_k(in_val, k)
    return vals, idxs.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _tournament_topk(in_val, k: int, select_min: bool):
    """Exact large-k selection as a compacting tournament — the TPU
    answer to the reference's multi-pass radix select
    (matrix/detail/select_radix.cuh:231,546: histogram the threshold
    bin, COMPACT survivors, sort only ~k). TPUs have no cheap scatter
    compaction, so the compaction here is structural instead: sort 2K
    blocks (K = k rounded to a power of two) with the reshape-bitonic
    network, then log2(B) pair-merge rounds where each round keeps the
    2K smallest of two sorted blocks (elementwise min/max against the
    reversed partner + a log(2K)-substage bitonic merge) and HALVES the
    live data — the survivors-only shrink the radix compaction buys,
    with no gathers anywhere. Total compare-exchange work is
    ~n(log^2(2K)/2 + 2 log(2K)) vs the full sort's n log^2(n)/2.

    Output contract matches the top_k arm: values are returned in the
    input dtype, and in-data non-finite entries keep their real column
    index (exactly like lax.top_k). NaN inputs are NOT supported (NaN
    poisons the merge comparisons and surfaces first instead of last;
    the library's sentinel-masking convention is ±inf, which behaves) —
    the NaN-tolerant arms are top_k and hierarchical. The one divergence: STRUCTURAL pad
    slots (from rounding n up to the power-of-two block grid) carry
    index -1 — they can only reach the output when a row has fewer than
    k finite entries, where they tie with the row's own +/-inf entries
    and -1 is the honest no-candidate answer (the library-wide pad
    convention)."""
    from raft_tpu.matrix.bitonic import merge_bitonic, sort_by_key

    m, n = in_val.shape
    K = 1 << (int(k) - 1).bit_length()
    L = 2 * K
    nb = -(-n // L)
    B = 1 << (int(nb) - 1).bit_length()
    work = in_val if select_min else -in_val
    work = work.astype(jnp.float32)
    pad = B * L - n
    big = jnp.inf
    ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (m, n))
    if pad:
        work = jnp.pad(work, ((0, 0), (0, pad)), constant_values=big)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1)

    kb = work.reshape(m * B, L)
    ib = ids.reshape(m * B, L)
    kb, (ib,) = sort_by_key(kb, ib)                  # ascending blocks
    kb = kb.reshape(m, B, L)
    ib = ib.reshape(m, B, L)
    while B > 1:
        B //= 2
        u = kb[:, 0::2]
        v = jnp.flip(kb[:, 1::2], axis=-1)           # descending partner
        iu = ib[:, 0::2]
        iv = jnp.flip(ib[:, 1::2], axis=-1)
        take_u = u <= v
        lo = jnp.where(take_u, u, v)                 # bitonic: 2K smallest
        li = jnp.where(take_u, iu, iv)
        lo, (li,) = merge_bitonic(
            lo.reshape(m * B, L), li.reshape(m * B, L)
        )
        kb = lo.reshape(m, B, L)
        ib = li.reshape(m, B, L)
    vals = kb[:, 0, :k]
    idxs = ib[:, 0, :k]
    if not select_min:
        vals = -vals
    return vals.astype(in_val.dtype), idxs


@functools.partial(jax.jit, static_argnums=(1, 2))
def _hierarchical_topk(in_val, k: int, select_min: bool):
    """Hierarchical in-fast-memory selection: per-tile local top-K
    through the hardware sort unit, then a keep-smallest-K pair-merge
    tree — the third dispatch rung (RAFT's warpsort shape: each warp
    reduces its slice in registers, cross-warp merge finishes,
    select_warpsort.cuh:100; here a tile is the "warp" and the merge is
    the reshape-bitonic keep-smallest-K round).

    Differs from the tournament on both ends: the LOCAL stage is
    ``lax.top_k`` over an L-wide tile (L >> 2K — the hardware sort unit
    compacts L -> K in one pass where the tournament pays a full
    bitonic sort of every 2K block), and the MERGE tree works K-wide
    blocks (half the tournament's 2K merge width). Costs one
    take_along_axis gather per payload at the local stage — a
    [m*B, K]-from-[m*B, L] row gather, which is exactly the trade the
    dispatch table measures against the gather-free tournament.

    Dtype-complete: integer keys stay in the integer domain (bitwise-NOT
    order reversal — exact above 2^24 where an f32 cast collapses,
    including INT_MIN), and the ORIGINAL values ride the merge as a
    payload so no inverse mapping is ever applied to the output. NaNs
    are quarantined to the worst KEY CLASS (+inf in min-space): selected
    after every finite entry, tied with genuine worst-infinity entries
    (column order breaks the tie), reported as NaN. Structural pad slots
    carry index -1 — the library-wide no-candidate convention (same as
    the tournament).
    """
    from raft_tpu.matrix.bitonic import merge_bitonic

    m, n = in_val.shape
    K = 1 << (int(k) - 1).bit_length()
    # tile length: power of two, >= 2K so every tile can source a full
    # output block, ~1K lanes so the local stage stays VMEM-resident
    L = max(2 * K, 1024)
    nt = -(-n // L)
    B = 1 << (int(nt) - 1).bit_length()
    floating = jnp.issubdtype(in_val.dtype, jnp.floating)
    ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (m, n))
    if floating:
        keys = in_val.astype(jnp.float32)
        keys = keys if select_min else -keys
        keys = jnp.where(jnp.isnan(keys), jnp.inf, keys)
        pad_key = jnp.inf
        pad_val = jnp.asarray(
            jnp.inf if select_min else -jnp.inf, in_val.dtype)
        orig = in_val
    else:
        work = (in_val.astype(jnp.int32) if in_val.dtype == jnp.bool_
                else in_val)
        keys = work if select_min else ~work
        # typed scalar: a bare python UINT_MAX overflows the weak int32
        # promotion inside jnp.pad
        pad_key = jnp.asarray(jnp.iinfo(keys.dtype).max, keys.dtype)
        info = (None if in_val.dtype == jnp.bool_
                else jnp.iinfo(in_val.dtype))
        pad_val = jnp.asarray(
            True if info is None and select_min
            else False if info is None
            else info.max if select_min else info.min, in_val.dtype)
        orig = in_val
    pad = B * L - n
    if pad:
        keys = jnp.pad(keys, ((0, 0), (0, pad)), constant_values=pad_key)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
        orig = jnp.pad(orig, ((0, 0), (0, pad)),
                       constant_values=pad_val)
    kb = keys.reshape(m * B, L)
    # local top-K in min-key space: top_k selects LARGEST, so reverse
    # the order inside the key domain (float negation is exact on the
    # sanitized keys; integer bitwise-NOT is the exact reversing map)
    if floating:
        neg, pos = jax.lax.top_k(-kb, K)
        kb = -neg
    else:
        inv, pos = jax.lax.top_k(~kb, K)
        kb = ~inv
    pos = pos.astype(jnp.int32)
    ib = jnp.take_along_axis(ids.reshape(m * B, L), pos, axis=1)
    vb = jnp.take_along_axis(orig.reshape(m * B, L), pos, axis=1)
    kb = kb.reshape(m, B, K)
    ib = ib.reshape(m, B, K)
    vb = vb.reshape(m, B, K)
    while B > 1:
        B //= 2
        u = kb[:, 0::2]
        v = jnp.flip(kb[:, 1::2], axis=-1)           # descending partner
        take_u = u <= v                              # ties keep the
        lo = jnp.where(take_u, u, v)                 # earlier block (stable)
        li = jnp.where(take_u, ib[:, 0::2],
                       jnp.flip(ib[:, 1::2], axis=-1))
        lv = jnp.where(take_u, vb[:, 0::2],
                       jnp.flip(vb[:, 1::2], axis=-1))
        lo, (li, lv) = merge_bitonic(
            lo.reshape(m * B, K), li.reshape(m * B, K),
            lv.reshape(m * B, K),
        )
        kb = lo.reshape(m, B, K)
        ib = li.reshape(m, B, K)
        vb = lv.reshape(m, B, K)
    return vb[:, 0, :k], ib[:, 0, :k]


# ---------------------------------------------------------------------------
# kernel contracts (graft-kern; docs/static_analysis.md §engine-4).
# select_k has no pallas_call — these rungs are the kernel-SHAPED
# selection networks (in-VMEM tile reductions + merge trees), so they
# register for the DYNAMIC adversarial sweep only: every dtype they
# claim, k==n, k==1, single-row, sublane-boundary ±1 row counts, and
# the >2^24 integer domain, against the stable-sort oracle.
# ---------------------------------------------------------------------------

from raft_tpu.analysis.contracts import kernel_contract  # noqa: E402


def _sel_case_ok(case: dict) -> bool:
    return 0 < case.get("k", 1) <= case.get("n", 1)


kernel_contract(
    "select_k_hierarchical",
    module=__name__,
    entry="select_k",
    driver="raft_tpu.analysis.contract_drivers:drive_select_k",
    tail_rows="padded",          # structural pads carry index -1
    k_range=(1, 1024),
    dtypes=("float32", "bfloat16", "int32", "uint32", "bool"),
    exactness="bitwise",
    base={"batch": 8, "n": 1000, "impl": "hierarchical"},
    rows_key="n", batch_key="batch",
    case_filter=_sel_case_ok,
    extra_cases=(
        {"impl": "hierarchical", "batch": 8, "n": 1000, "k": 100,
         "dtype": "float32", "nan": True},
    ),
    notes="NaNs quarantined to the worst key class; integer keys stay "
          "in the integer domain (bitwise-NOT reversal, exact > 2^24).",
)

kernel_contract(
    "select_k_tournament",
    module=__name__,
    entry="select_k",
    driver="raft_tpu.analysis.contract_drivers:drive_select_k",
    tail_rows="padded",
    k_range=(1, 1024),
    dtypes=("float32",),         # float-only by contract (docstring)
    exactness="bitwise",
    base={"batch": 8, "n": 1000, "impl": "tournament"},
    rows_key="n", batch_key="batch",
    case_filter=_sel_case_ok,
    notes="NaN inputs unsupported by design (±inf is the library "
          "sentinel convention); the NaN-tolerant arms are top_k and "
          "hierarchical.",
)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def select_k_threshold(in_val, k: int, select_min: bool = True, n_bins: int = 4096):
    """Two-pass histogram threshold select for very large k.

    The TPU analog of the reference's multi-pass radix select
    (matrix/detail/select_radix.cuh:231,546): pass 1 histograms values into
    ``n_bins`` buckets to find the k-th threshold bucket; pass 2 emits
    everything strictly better than the threshold plus enough
    threshold-equal items to fill k, via a masked sort of candidates only.
    Returns (out_val, out_idx) like select_k. Rows are processed fully
    vectorized; candidate compaction uses one top_k over a masked copy, so
    the win is numerical (no full-row sort) for n >> k.
    """
    in_val = jnp.asarray(in_val)
    batch, n = in_val.shape
    work = in_val if select_min else -in_val
    lo = work.min(axis=1, keepdims=True)
    hi = work.max(axis=1, keepdims=True)
    span = jnp.where(hi > lo, hi - lo, 1.0)
    bins = jnp.clip(((work - lo) / span * n_bins).astype(jnp.int32), 0, n_bins - 1)
    hist = jax.vmap(lambda b: jnp.bincount(b, length=n_bins))(bins)
    csum = jnp.cumsum(hist, axis=1)
    # threshold bin: first bin where cumulative count >= k
    thr_bin = jnp.argmax(csum >= k, axis=1)
    keep = bins <= thr_bin[:, None]
    masked = jnp.where(keep, work, jnp.inf)
    vals, idxs = jax.lax.top_k(-masked, k)
    vals = -vals
    if not select_min:
        vals = -vals
    return vals, idxs.astype(jnp.int32)
