"""select_k — the k-selection engine.

TPU-native analog of the reference's ``raft::matrix::select_k``
(cpp/include/raft/matrix/select_k.cuh:81) whose CUDA backends are a radix
11-bit histogram select and warp-level bitonic priority queues chosen by a
learned heuristic (matrix/detail/select_k-inl.cuh:51-79). On TPU, XLA's
``lax.top_k`` lowers to the hardware sort unit and is already near-optimal
for the k ranges the reference covers; the "dispatch" concept survives as a
single entry point that (a) maps select-min onto top_k by negation and (b)
carries pass-through source indices (the reference's ``in_idx``). A
two-pass histogram-threshold variant (the radix-select analog) is exposed
as ``select_k_threshold``; it is not auto-dispatched because without
candidate compaction it cannot beat the hardware top_k (see note in
``select_k``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def select_k(
    in_val,
    k: int,
    in_idx=None,
    select_min: bool = True,
    sorted: bool = True,  # noqa: A002 - matches reference arg name
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) per row.

    Parameters mirror the reference API (matrix/select_k.cuh:81):

    in_val : [batch, n] values.
    in_idx : optional [batch, n] source indices carried with the values
        (defaults to 0..n-1 per row).
    select_min : True → smallest-k (the reference's SelectMinK).

    Returns (out_val [batch, k], out_idx [batch, k]).
    """
    in_val = jnp.asarray(in_val)
    squeeze = in_val.ndim == 1
    if squeeze:
        in_val = in_val[None, :]
    batch, n = in_val.shape
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for row length {n}")
    # Dispatch note (the reference's learned heuristic,
    # select_k-inl.cuh:51-79): on TPU a single lax.top_k lowers to the
    # hardware sort unit for every (k, n) the reference covers, and the
    # histogram-threshold path as implemented still ends in a full-row
    # top_k over the masked copy — so dispatching to it only adds passes.
    # It stays available as select_k_threshold for callers that want the
    # two-pass structure; revisit if a compacting implementation lands.
    vals, idxs = _select_k(in_val, int(k), bool(select_min))
    if in_idx is not None:
        in_idx = jnp.asarray(in_idx)
        if squeeze and in_idx.ndim == 1:
            in_idx = in_idx[None, :]
        idxs = jnp.take_along_axis(in_idx, idxs, axis=1)
    if squeeze:
        return vals[0], idxs[0]
    return vals, idxs


@functools.partial(jax.jit, static_argnums=(1, 2))
def _select_k(in_val, k: int, select_min: bool):
    if select_min:
        # top_k selects max; negate. Use where-safe negation for ints.
        if jnp.issubdtype(in_val.dtype, jnp.floating):
            vals, idxs = jax.lax.top_k(-in_val, k)
            return -vals, idxs.astype(jnp.int32)
        vals, idxs = jax.lax.top_k(-in_val.astype(jnp.float32), k)
        return jnp.take_along_axis(in_val, idxs, axis=1), idxs.astype(jnp.int32)
    vals, idxs = jax.lax.top_k(in_val, k)
    return vals, idxs.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def select_k_threshold(in_val, k: int, select_min: bool = True, n_bins: int = 4096):
    """Two-pass histogram threshold select for very large k.

    The TPU analog of the reference's multi-pass radix select
    (matrix/detail/select_radix.cuh:231,546): pass 1 histograms values into
    ``n_bins`` buckets to find the k-th threshold bucket; pass 2 emits
    everything strictly better than the threshold plus enough
    threshold-equal items to fill k, via a masked sort of candidates only.
    Returns (out_val, out_idx) like select_k. Rows are processed fully
    vectorized; candidate compaction uses one top_k over a masked copy, so
    the win is numerical (no full-row sort) for n >> k.
    """
    in_val = jnp.asarray(in_val)
    batch, n = in_val.shape
    work = in_val if select_min else -in_val
    lo = work.min(axis=1, keepdims=True)
    hi = work.max(axis=1, keepdims=True)
    span = jnp.where(hi > lo, hi - lo, 1.0)
    bins = jnp.clip(((work - lo) / span * n_bins).astype(jnp.int32), 0, n_bins - 1)
    hist = jax.vmap(lambda b: jnp.bincount(b, length=n_bins))(bins)
    csum = jnp.cumsum(hist, axis=1)
    # threshold bin: first bin where cumulative count >= k
    thr_bin = jnp.argmax(csum >= k, axis=1)
    keep = bins <= thr_bin[:, None]
    masked = jnp.where(keep, work, jnp.inf)
    vals, idxs = jax.lax.top_k(-masked, k)
    vals = -vals
    if not select_min:
        vals = -vals
    return vals, idxs.astype(jnp.int32)
