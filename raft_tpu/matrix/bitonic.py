"""Bitonic sorting networks as reshape + elementwise ops (no gathers).

TPU-native analog of the reference's warp bitonic sort
(cpp/include/raft/util/bitonic_sort.cuh; CAGRA's itopk merge
detail/cagra/bitonic.hpp): the CUDA warp-shuffle compare-exchange becomes
a static [.., L/(2j), 2, j] reshape pair-up — every substage is pure
elementwise min/max/select on the VPU, so sorting a row costs zero
dynamic gathers (lax.sort / argsort + take_along_axis lower to serial
per-row gathers on TPU and measure ~5-10x slower at beam-search shapes,
r3 v5e).

Rows sort along the LAST axis, ascending by key, payloads carried by the
same compare-exchange predicate. Length must be a power of two — callers
pad with +inf keys.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


def _substage(keys, payloads, j: int, asc_mask):
    """One compare-exchange substage: partner i <-> i^j via reshape."""
    shape = keys.shape
    L = shape[-1]
    lead = shape[:-1]
    r = lead + (L // (2 * j), 2, j)

    def pair(x):
        x = x.reshape(r)
        return x[..., 0, :], x[..., 1, :]

    k0, k1 = pair(keys)
    swap = jnp.where(asc_mask, k0 > k1, k0 < k1)        # [.., L/2j, j] bool

    def exchange(x0, x1):
        lo = jnp.where(swap, x1, x0)
        hi = jnp.where(swap, x0, x1)
        return jnp.stack([lo, hi], axis=-2).reshape(shape)

    keys = exchange(k0, k1)
    payloads = tuple(exchange(*pair(p)) for p in payloads)
    return keys, payloads


@functools.lru_cache(maxsize=None)
def _asc_masks(L: int):
    """Static ascending-direction masks per (k, j) substage.

    Direction of the compare at index i in stage k is ascending iff bit
    log2(k) of i is 0 (both partners agree: they differ only in bit
    log2(j) < log2(k)).
    """
    idx = np.arange(L)
    masks = {}
    k = 2
    while k <= L:
        asc = (idx & k) == 0
        j = k // 2
        while j >= 1:
            masks[(k, j)] = asc.reshape(L // (2 * j), 2, j)[:, 0, :]
            j //= 2
        k *= 2
    return masks


def sort_by_key(keys, *payloads, descending: bool = False):
    """Sort rows of ``keys`` (last axis, power-of-two length) carrying
    ``payloads`` through the same permutation. Returns (keys, payloads)."""
    L = keys.shape[-1]
    if L & (L - 1):
        raise ValueError(f"bitonic length must be a power of two, got {L}")
    masks = _asc_masks(L)
    k = 2
    while k <= L:
        j = k // 2
        while j >= 1:
            # descending = flip every comparison direction (key negation
            # would overflow INT_MIN and conflate +0.0/-0.0)
            asc = jnp.asarray(
                ~masks[(k, j)] if descending else masks[(k, j)]
            )
            keys, payloads = _substage(keys, payloads, j, asc)
            j //= 2
        k *= 2
    return keys, payloads


def merge_bitonic(keys, *payloads):
    """Final-stage network on rows that are already BITONIC (ascending
    then descending): log2(L) all-ascending substages produce fully
    ascending rows. The primitive under ``merge_sorted`` and the
    tournament top-k's pair-merge (select_k._tournament_topk)."""
    L = keys.shape[-1]
    if L & (L - 1):
        raise ValueError(f"bitonic length must be a power of two, got {L}")
    j = L // 2
    while j >= 1:
        asc = jnp.asarray(np.ones((L // (2 * j), j), dtype=bool))
        keys, payloads = _substage(keys, payloads, j, asc)
        j //= 2
    return keys, payloads


def merge_sorted(keys, *payloads):
    """Bitonic *merge* of a row whose two halves are each sorted
    ascending: flip the upper half to form a bitonic sequence, then run
    the final-stage network — log2(L) substages instead of a full sort.
    Used for sorted-buffer + sorted-candidates merges."""
    L = keys.shape[-1]
    if L & (L - 1):
        raise ValueError(f"bitonic length must be a power of two, got {L}")
    half = L // 2
    flip = lambda x: jnp.concatenate(
        [x[..., :half], jnp.flip(x[..., half:], axis=-1)], axis=-1
    )
    keys = flip(keys)
    payloads = tuple(flip(p) for p in payloads)
    j = L // 2
    while j >= 1:
        asc = jnp.asarray(
            np.ones((L // (2 * j), j), dtype=bool)
        )
        keys, payloads = _substage(keys, payloads, j, asc)
        j //= 2
    return keys, payloads
