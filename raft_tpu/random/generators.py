"""Dataset generators.

Analogs of the reference's random generators (SURVEY.md §2.5):
make_blobs.cuh, make_regression.cuh, multi_variable_gaussian.cuh,
rmat_rectangular_generator.cuh (pylibraft-exposed), permute.cuh,
sample_without_replacement.cuh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng import RngState, _as_key


def make_blobs(
    n_samples: int,
    n_features: int,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers=None,
    shuffle: bool = True,
    seed: int | RngState | jax.Array = 0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Isotropic Gaussian blobs (reference random/make_blobs.cuh).

    Returns (X [n_samples, n_features], labels [n_samples]).
    """
    key = _as_key(seed)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k1, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1],
        )
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(k2, (n_samples,), 0, n_clusters)
    noise = cluster_std * jax.random.normal(k3, (n_samples, n_features), dtype=dtype)
    x = centers[labels] + noise
    if shuffle:
        perm = jax.random.permutation(k4, n_samples)
        x, labels = x[perm], labels[perm]
    return x, labels.astype(jnp.int32)


def make_regression(
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    seed: int | RngState | jax.Array = 0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model regression data (reference random/make_regression.cuh).
    Returns (X, y, coef)."""
    key = _as_key(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    n_informative = n_informative if n_informative is not None else n_features
    x = jax.random.normal(k1, (n_samples, n_features), dtype=dtype)
    coef = jnp.zeros((n_features, n_targets), dtype)
    coef = coef.at[:n_informative].set(
        100.0 * jax.random.uniform(k2, (n_informative, n_targets), dtype=dtype)
    )
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(k3, y.shape, dtype=dtype)
    return x, y.squeeze(), coef.squeeze()


def multi_variable_gaussian(mean, cov, n_samples: int, seed=0, dtype=jnp.float32) -> jax.Array:
    """Samples from N(mean, cov) (reference random/multi_variable_gaussian.cuh)."""
    key = _as_key(seed)
    mean = jnp.asarray(mean, dtype)
    cov = jnp.asarray(cov, dtype)
    return jax.random.multivariate_normal(key, mean, cov, (n_samples,), dtype=dtype)


def permute(x, seed=0) -> Tuple[jax.Array, jax.Array]:
    """Random row permutation (reference random/permute.cuh).
    Returns (permuted_rows, permutation)."""
    x = jnp.asarray(x)
    key = _as_key(seed)
    perm = jax.random.permutation(key, x.shape[0])
    return x[perm], perm.astype(jnp.int32)


def sample_without_replacement(n_population: int, n_samples: int, weights=None, seed=0) -> jax.Array:
    """Weighted sampling w/o replacement via Gumbel top-k — the same
    one-pass trick as the reference's per-item keyed selection
    (random/sample_without_replacement.cuh)."""
    key = _as_key(seed)
    if weights is None:
        return jax.random.permutation(key, n_population)[:n_samples].astype(jnp.int32)
    logw = jnp.log(jnp.maximum(jnp.asarray(weights, jnp.float32), 1e-30))
    g = jax.random.gumbel(key, (n_population,))
    _, idx = jax.lax.top_k(logw + g, n_samples)
    return idx.astype(jnp.int32)


def rmat_rectangular_generator(
    r_scale: int,
    c_scale: int,
    n_edges: int,
    theta=None,
    seed=0,
) -> Tuple[jax.Array, jax.Array]:
    """R-MAT graph generator (reference
    random/rmat_rectangular_generator.cuh; pylibraft
    random/rmat_rectangular_generator.pyx).

    theta: [max(r_scale,c_scale), 4] per-level quadrant probabilities
    (a,b,c,d) or a flat [4] reused per level (defaults to the classic
    0.57/0.19/0.19/0.05). Returns (src [n_edges], dst [n_edges]).

    Each of the scale levels doubles the row/col space; per edge and level a
    quadrant is drawn and its bit appended — expressed as a vectorized scan
    over levels (no per-edge loops).
    """
    key = _as_key(seed)
    max_scale = max(r_scale, c_scale)
    if theta is None:
        theta = jnp.tile(jnp.asarray([0.57, 0.19, 0.19, 0.05], jnp.float32), (max_scale, 1))
    else:
        theta = jnp.asarray(theta, jnp.float32)
        if theta.ndim == 1:
            theta = jnp.tile(theta[None, :], (max_scale, 1))
    probs = theta / theta.sum(axis=1, keepdims=True)

    u = jax.random.uniform(key, (max_scale, n_edges))
    cum = jnp.cumsum(probs, axis=1)
    quad = (u[:, :, None] > cum[:, None, :]).sum(axis=2)  # [levels, edges] in 0..3
    row_bit = (quad >= 2).astype(jnp.int64)  # c,d quadrants go down
    col_bit = (quad % 2).astype(jnp.int64)   # b,d quadrants go right

    levels = jnp.arange(max_scale)
    r_active = (levels < r_scale)[:, None]
    c_active = (levels < c_scale)[:, None]
    r_weights = jnp.where(r_active, 1 << jnp.minimum(r_scale - 1 - levels, 62), 0)
    c_weights = jnp.where(c_active, 1 << jnp.minimum(c_scale - 1 - levels, 62), 0)
    src = (row_bit * r_weights).sum(axis=0)
    dst = (col_bit * c_weights).sum(axis=0)
    return src.astype(jnp.int64), dst.astype(jnp.int64)
