"""Random layer (SURVEY.md §2.5): RNG state + dataset generators."""

from raft_tpu.random.rng import RngState, uniform, normal, randint, bernoulli
from raft_tpu.random.generators import (
    make_blobs,
    make_regression,
    multi_variable_gaussian,
    permute,
    rmat_rectangular_generator,
    sample_without_replacement,
)

__all__ = [
    "RngState",
    "uniform",
    "normal",
    "randint",
    "bernoulli",
    "make_blobs",
    "make_regression",
    "multi_variable_gaussian",
    "permute",
    "rmat_rectangular_generator",
    "sample_without_replacement",
]
