"""RNG state.

Analog of the reference's ``RngState`` with Philox/PCG generators
(random/rng_state.hpp:28-38, rng_device.cuh). JAX's counter-based threefry
serves the same role (reproducible, parallel-safe); `RngState` wraps a key
with the reference's seed/advance semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class RngState:
    """Mutable key holder mirroring raft::random::RngState(seed)."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.key = jax.random.PRNGKey(self.seed)

    def advance(self, n: int = 1) -> None:
        for _ in range(n):
            self.key, _ = jax.random.split(self.key)

    def next_key(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub


def _as_key(state) -> jax.Array:
    if isinstance(state, RngState):
        return state.next_key()
    if isinstance(state, int):
        return jax.random.PRNGKey(state)
    return state  # assume PRNGKey


def uniform(state, shape, low=0.0, high=1.0, dtype=jnp.float32) -> jax.Array:
    return jax.random.uniform(_as_key(state), shape, dtype=dtype, minval=low, maxval=high)


def normal(state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32) -> jax.Array:
    return mu + sigma * jax.random.normal(_as_key(state), shape, dtype=dtype)


def randint(state, shape, low, high, dtype=jnp.int32) -> jax.Array:
    return jax.random.randint(_as_key(state), shape, low, high, dtype=dtype)


def bernoulli(state, shape, p=0.5) -> jax.Array:
    return jax.random.bernoulli(_as_key(state), p, shape)
