"""Error taxonomy, classification, and the retry/backoff executor.

A production jax_graft deployment dies today on the first transient
fault: XLA surfaces everything as one exception type whose *message*
carries the gRPC-style status (``RESOURCE_EXHAUSTED``, ``UNAVAILABLE``,
``DEADLINE_EXCEEDED`` ...), so callers either swallow everything (the
GL008 anti-pattern) or die on everything. This module is the single
place that reads those messages: :func:`classify` maps any exception to
one of five kinds, and :func:`run` retries the retryable ones with
exponential backoff under a wall-clock deadline — the cooperative analog
of the reference's ``interruptible.hpp`` + the retry loops every
long-running RAFT consumer (raft-dask, the ANN bench harness) writes by
hand.

Kinds:

* ``transient``    — UNAVAILABLE / ABORTED / connection resets; retry.
* ``oom``          — RESOURCE_EXHAUSTED / allocator failures; do NOT
                     retry at the same size — the degradation ladder
                     (:mod:`raft_tpu.resilience.degrade`) halves the
                     chunk and re-dispatches.
* ``dead_backend`` — the hung-backend class ``core/exit_guard.py`` only
                     papers over at process exit (rc=124 dead-axon);
                     retryable once :func:`backend_alive` confirms the
                     device answers again.
* ``interrupted``  — cooperative cancellation
                     (:class:`raft_tpu.core.interruptible.Interruptible`);
                     never retried, always propagated.
* ``fatal``        — everything else (shape errors, ValueError, bugs);
                     never retried.
"""

from __future__ import annotations

import os
import re
import subprocess
import threading
import time
from typing import Callable, Iterable, Optional, Tuple

# classification kinds ------------------------------------------------------

TRANSIENT = "transient"
OOM = "oom"
DEAD_BACKEND = "dead_backend"
INTERRUPTED = "interrupted"
FATAL = "fatal"

KINDS = (TRANSIENT, OOM, DEAD_BACKEND, INTERRUPTED, FATAL)


class ResilienceError(RuntimeError):
    """Base for errors raised by the resilience layer itself."""


class TransientError(ResilienceError):
    """A failure the caller knows to be transient (e.g. a measurement
    stage whose tail says UNAVAILABLE); :func:`classify` maps it to
    ``transient`` without message sniffing."""


class DeadBackendError(ResilienceError):
    """The backend stopped answering and did not come back within the
    retry budget (the rc=124 dead-axon class, surfaced as an exception
    instead of a hang)."""


class DeadlineExceededError(ResilienceError):
    """:func:`run`'s wall-clock deadline expired before an attempt
    succeeded. Carries the last underlying failure as ``__cause__``."""


class ShardDropoutError(ResilienceError):
    """A sharded search lost one or more shards and the caller did not
    opt into partial results (``partial_ok=False``)."""


# message patterns ----------------------------------------------------------
# XLA/PJRT surface status codes inside the exception text; these are the
# spellings observed from jaxlib's XlaRuntimeError and the axon tunnel.

_OOM_RE = re.compile(
    r"RESOURCE[ _]?EXHAUSTED|out of memory|OOM|allocat\w* .*fail|"
    r"exceeds the memory", re.IGNORECASE,
)
_TRANSIENT_RE = re.compile(
    r"UNAVAILABLE|ABORTED|CANCELLED|DEADLINE[ _]?EXCEEDED|UNKNOWN: |"
    r"connection (reset|refused|closed)|socket closed|broken pipe|"
    r"temporarily unavailable|try again", re.IGNORECASE,
)
_DEAD_RE = re.compile(
    r"dead[ -]?backend|backend .*(unreachable|died|lost)|"
    r"device or resource busy|heartbeat|FAILED[ _]?PRECONDITION: .*donat",
    re.IGNORECASE,
)


def classify(exc: BaseException) -> str:
    """Map an exception to one of :data:`KINDS`.

    Injected faults (:mod:`raft_tpu.resilience.faultinject`) carry their
    kind explicitly; cooperative interruption and the resilience layer's
    own typed errors short-circuit; anything else is classified from its
    message text, defaulting to ``fatal`` (never silently retry an
    unknown failure).

    Every classification is reported to graft-scope
    (:func:`raft_tpu.obs.on_error`): ``errors_total{kind}`` counts it,
    the flight recorder logs it, and — in flight mode — a fatal or
    dead_backend verdict auto-dumps the ring as the post-mortem
    artifact. No-op with ``RAFT_TPU_OBS=off``.
    """
    kind = _classify(exc)
    from raft_tpu import obs

    obs.on_error(kind, exc)
    return kind


def _classify(exc: BaseException) -> str:
    kind = getattr(exc, "fault_kind", None)
    if kind in KINDS:
        return kind
    from raft_tpu.core.interruptible import InterruptedException

    if isinstance(exc, InterruptedException):
        return INTERRUPTED
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return INTERRUPTED
    if isinstance(exc, TransientError):
        return TRANSIENT
    if isinstance(exc, DeadBackendError):
        return DEAD_BACKEND
    if isinstance(exc, MemoryError):
        return OOM
    if isinstance(exc, subprocess.TimeoutExpired):
        # the wedged-stage class: the child never answered
        return DEAD_BACKEND
    return classify_text(str(exc))


def classify_text(text: str) -> str:
    """Classify raw failure text (a subprocess tail, a log line) with the
    same message patterns :func:`classify` applies to exceptions — the
    measurement scripts use this on stage output to decide whether a
    non-zero rc is worth one retry."""
    if _OOM_RE.search(text):
        return OOM
    if _DEAD_RE.search(text):
        return DEAD_BACKEND
    if _TRANSIENT_RE.search(text):
        return TRANSIENT
    return FATAL


# liveness ------------------------------------------------------------------


def backend_alive(timeout_s: float = 30.0) -> bool:
    """In-process device liveness check — the reusable promotion of the
    dead-axon probe that ``core/exit_guard.py`` / ``bench/harness.py``
    only apply at process boundaries.

    Dispatches a trivial device op on a daemon worker thread and waits
    up to ``timeout_s``: the known outage mode *hangs* inside the
    runtime holding the GIL-released device lock, so a plain call could
    never return False. A hung probe leaks its daemon thread — by
    construction there is no way to preempt the runtime call.
    """
    done = threading.Event()
    ok: list = []

    def _probe():
        try:
            import jax

            x = jax.device_put(1)
            jax.block_until_ready(x)
            ok.append(True)
        except Exception:  # graft-lint: allow-unclassified-swallow liveness probe: ANY failure means not-alive, classification is the caller's job  # noqa: BLE001
            pass
        finally:
            done.set()

    t = threading.Thread(target=_probe, daemon=True, name="raft-tpu-liveness")
    t.start()
    done.wait(timeout_s)
    return bool(ok)


# the retry executor --------------------------------------------------------

_DEFAULT_RETRY: Tuple[str, ...] = (TRANSIENT, DEAD_BACKEND)

# full-jitter backoff (ISSUE 18): N replicas retrying against one
# recovering worker with bare exponential backoff fire in lockstep —
# every wave lands together, and a rebalancing fleet amplifies the
# storm (the re-replication traffic rides the same transport). Each
# sleep is drawn uniformly from [0, backoff_s * mult**attempt] (the
# AWS "full jitter" schedule), from a process-local seeded RNG so
# drills and tests are deterministic: seed via RAFT_TPU_JITTER_SEED or
# seed_jitter().

_jitter_lock = threading.Lock()


def _fresh_jitter_rng(seed: Optional[int] = None):
    import random

    if seed is None:
        env = os.environ.get("RAFT_TPU_JITTER_SEED", "").strip()
        seed = int(env) if env else None
    return random.Random(seed)


_jitter_rng = _fresh_jitter_rng()


def seed_jitter(seed: Optional[int]) -> None:
    """Re-seed the backoff-jitter RNG (tests / deterministic drills);
    ``None`` restores the env-or-entropy default."""
    global _jitter_rng
    with _jitter_lock:
        _jitter_rng = _fresh_jitter_rng(seed)


def backoff_jitter_s(attempt: int, backoff_s: float,
                     mult: float = 2.0, jitter: bool = True) -> float:
    """The sleep before retry ``attempt`` (0-based): full jitter over
    the exponential cap ``backoff_s * mult**attempt``, or the bare cap
    with ``jitter=False`` (callers that need the worst-case bound for
    deadline math use the cap; the drawn value is always <= it)."""
    cap = backoff_s * (mult ** attempt)
    if not jitter or cap <= 0:
        return cap
    with _jitter_lock:
        return _jitter_rng.uniform(0.0, cap)


def run(
    fn: Callable,
    *args,
    deadline_s: Optional[float] = None,
    retries: int = 3,
    backoff_s: float = 0.5,
    backoff_mult: float = 2.0,
    jitter: bool = True,
    retry_on: Iterable[str] = _DEFAULT_RETRY,
    probe_timeout_s: float = 30.0,
    on_retry: Optional[Callable[[int, str, BaseException], None]] = None,
    token=None,
    **kwargs,
):
    """Run ``fn(*args, **kwargs)`` with classified retry under a deadline.

    * Exceptions are :func:`classify`\\ d; only kinds in ``retry_on``
      (default transient + dead_backend) are retried, up to ``retries``
      times with full-jitter exponential backoff: each sleep is drawn
      uniformly from ``[0, backoff_s * backoff_mult**i]``
      (:func:`backoff_jitter_s` — seeded via ``RAFT_TPU_JITTER_SEED``
      or :func:`seed_jitter`; ``jitter=False`` restores the bare
      exponential schedule). The DEADLINE check uses the un-jittered
      cap, so whether a final retry is attempted does not depend on
      the RNG draw.
    * ``deadline_s`` is a wall-clock budget over ALL attempts: when a
      retry (including its backoff sleep) cannot start inside it,
      :class:`DeadlineExceededError` is raised with the last failure as
      ``__cause__``. The deadline cannot preempt a *running* attempt —
      pair it with a subprocess/thread timeout for hard preemption (the
      measurement scripts use subprocess timeouts as the hard bound).
    * A ``dead_backend`` failure is only retried after
      :func:`backend_alive` confirms the device answers again; a probe
      failure converts the retry into :class:`DeadBackendError`. The
      probe runs on :func:`backend_alive`'s bounded daemon thread and
      its wait is CLAMPED to the remaining ``deadline_s`` — a hanging
      probe (the dead-axon init-hang mode) counts against the deadline
      instead of stalling the retry loop ``probe_timeout_s`` past it,
      and a probe that times out is classified ``dead_backend``.
    * ``token`` (an :class:`~raft_tpu.core.interruptible.Interruptible`)
      is checked before every attempt so ``cancel()`` from another
      thread stops the retry loop too.
    """
    retry_on = tuple(retry_on)
    start = time.monotonic()
    attempt = 0
    while True:
        if token is not None:
            token.check()
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — classified, not swallowed
            kind = classify(e)
            if kind not in retry_on or attempt >= retries:
                raise
            # deadline/probe math uses the un-jittered CAP so the
            # retry-vs-give-up decision is deterministic; the actual
            # sleep is the jittered draw (always <= cap)
            cap = backoff_s * (backoff_mult ** attempt)
            sleep = backoff_jitter_s(attempt, backoff_s, backoff_mult,
                                     jitter)
            if deadline_s is not None and \
                    time.monotonic() - start + cap >= deadline_s:
                raise DeadlineExceededError(
                    f"deadline {deadline_s}s exhausted after "
                    f"{attempt + 1} attempt(s); last failure: {kind}"
                ) from e
            if kind == DEAD_BACKEND:
                # clamp the liveness probe to the remaining deadline:
                # backend_alive's bounded daemon-thread join means a
                # hung probe returns at the budget, but an unclamped
                # probe_timeout_s (default 30s) could still stall the
                # loop far past a tighter deadline_s
                probe_budget = probe_timeout_s
                if deadline_s is not None:
                    probe_budget = min(
                        probe_budget,
                        deadline_s - (time.monotonic() - start) - cap,
                    )
                if probe_budget <= 0 or not backend_alive(probe_budget):
                    raise DeadBackendError(
                        f"backend did not come back within "
                        f"{max(probe_budget, 0.0):.3g}s probe budget "
                        f"after: {e}"
                    ) from e
            from raft_tpu import obs

            obs.counter("retries", kind=kind)
            obs.event("retry", attempt=attempt, error_kind=kind,
                      error=str(e)[:200], backoff_s=sleep)
            if on_retry is not None:
                on_retry(attempt, kind, e)
            time.sleep(sleep)
            attempt += 1
