"""Fault-tolerant execution layer (docs/resilience.md).

Four pillars, threaded through the batch, streaming, sharded, and bench
paths:

1. **Classified retry** — :func:`classify` maps any JAX/XLA exception
   to transient / oom / dead_backend / interrupted / fatal; :func:`run`
   retries the retryable kinds with exponential backoff under a
   wall-clock deadline, probing :func:`backend_alive` before trusting a
   dead backend again.
2. **OOM degradation ladder** (:mod:`raft_tpu.resilience.degrade`) —
   RESOURCE_EXHAUSTED halves the chunk and re-dispatches; the surviving
   size is recorded via :func:`raft_tpu.tuning.record_budget` so later
   calls start safe.
3. **Checkpointed streaming**
   (:mod:`raft_tpu.resilience.checkpoint`) — ``build_streamed`` /
   ``search_file`` persist a per-chunk manifest + state blob and resume
   bitwise-identically.
4. **Fault injection** (:mod:`raft_tpu.resilience.faultinject`) — a
   deterministic harness (env ``RAFT_TPU_FAULTS``) that drives all of
   the above on CPU in tier-1.
"""

from raft_tpu.resilience.errors import (
    DEAD_BACKEND,
    FATAL,
    INTERRUPTED,
    KINDS,
    OOM,
    TRANSIENT,
    DeadBackendError,
    DeadlineExceededError,
    ResilienceError,
    ShardDropoutError,
    TransientError,
    backend_alive,
    backoff_jitter_s,
    classify,
    classify_text,
    run,
    seed_jitter,
)
from raft_tpu.resilience.checkpoint import (
    CheckpointMismatchError,
    StreamCheckpoint,
)
from raft_tpu.resilience import degrade, faultinject

__all__ = [
    "DEAD_BACKEND", "FATAL", "INTERRUPTED", "KINDS", "OOM", "TRANSIENT",
    "CheckpointMismatchError", "DeadBackendError", "DeadlineExceededError",
    "ResilienceError", "ShardDropoutError", "StreamCheckpoint",
    "TransientError", "backend_alive", "backoff_jitter_s", "classify",
    "classify_text", "degrade", "faultinject", "run", "seed_jitter",
]
