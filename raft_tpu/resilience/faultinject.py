"""Deterministic fault injection for the resilience layer.

Grammar (env ``RAFT_TPU_FAULTS``, comma-separated)::

    oom@chunk:3            synthetic RESOURCE_EXHAUSTED at chunk index 3
                           (of ANY stage — the first loop to reach it)
    oom@chunk:3*2          ... firing twice (two ladder rungs)
    transient@chunk:0      synthetic UNAVAILABLE at chunk 0
    dead@stage:search      hung-backend failure anywhere in stage "search"
    oom@stage:build.pass2  OOM at the first check inside that stage
    dead@stage:build.pass2#3   ... at that stage's chunk 3 specifically
    shard@rank:2           shard 2's local result is invalidated (queried
                           by the sharded searches, never raised)
    dead@proc:2            fabric worker process 2 dies (hard exit, no
                           response) at its next data-plane RPC
    slow@proc:1*3          worker 1 stalls its next 3 data-plane RPCs
                           (the late-answer / hedging failure mode)
    slow@stage:serve.dispatch*4   the named stage's next 4 checks STALL
                           (sleep SLOW_STAGE_SLEEP_S, default 0.25s;
                           env RAFT_TPU_FAULTS_SLOW_MS overrides) —
                           the SLO deadline-pressure failure mode
                           (docs/serving.md §13): work is late, not
                           failed
    drop@rpc:search        the next "search" RPC's response is dropped —
                           the router sees only a timeout
    flap@proc:1*3          worker 1 FLAPS: it dies, and after the
                           control plane respawns it, dies again —
                           three deaths total, then stays up. The
                           budget is charged one death per incarnation
                           PARENT-side (:func:`respawned_spec`), which
                           is what distinguishes it from ``dead@proc``:
                           a dead machine stays dead under respawn, a
                           flapping one eventually holds (ISSUE 18 —
                           the autoscaler-thrash drill)
    dead@proc:0#after:20   delayed death: worker 0 survives its first
                           20 data-plane RPCs, then dies — scripted
                           late-failure schedules (the chaos-curve
                           loadgen) without runtime re-injection. The
                           ``#after:N`` arming delay composes with any
                           proc kind and with ``*count``
                           (``flap@proc:1#after:10*2``: dies after
                           every 10 survived RPCs, twice)

The ``proc``/``rpc`` scopes are consumed by the multi-host serving
fabric's workers (:mod:`raft_tpu.comms.procgroup` via
:func:`proc_action` / :func:`rpc_dropped`, docs/serving.md §10) rather
than raised: process death and response loss are not exceptions at the
fault site, they are *absences* the router must diagnose from timeouts.

Instrumented loops call :func:`check` at every chunk boundary (the
point where a real device failure would surface); matching specs raise
synthetic exceptions whose *messages* carry the same status text the
real failures do, so :func:`raft_tpu.resilience.errors.classify` treats
injected and real faults identically — the whole ladder/retry/resume
machinery is exercised on CPU in tier-1. Each spec fires ``count``
times (default once) then stays quiet, which is exactly how a transient
fault behaves under retry and how an OOM behaves after the ladder
halves the chunk.

Programmatic use (tests)::

    with faultinject.inject("oom@chunk:2"):
        search_stream(...)

The env var is read once per :func:`plan` call when no programmatic
plan is installed; :func:`clear` resets everything.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import threading
from typing import FrozenSet, List, Optional

from raft_tpu.resilience import errors

ENV_VAR = "RAFT_TPU_FAULTS"

_KINDS = ("oom", "dead", "transient", "shard", "slow", "drop", "flap")
_SCOPES = ("chunk", "stage", "rank", "proc", "rpc")

# kind/scope compatibility for the process-level grammar: "slow"
# stalls a worker process's RPCs or a named stage's checkpoints, "drop"
# only targets an RPC response, and a process can only die, stall, or
# flap (an OOM inside a worker surfaces as a normal classified
# exception via dead/oom@stage instead); "flap" only makes sense where
# a control plane can respawn the victim, i.e. at proc scope
_SCOPE_KINDS = {"proc": ("dead", "slow", "flap"), "rpc": ("drop",)}
_KIND_SCOPES = {"slow": ("proc", "stage"), "drop": ("rpc",),
                "flap": ("proc",)}

# how long one fired slow@stage spec stalls its checkpoint (seconds);
# RAFT_TPU_FAULTS_SLOW_MS overrides for tests that need a tighter or
# looser squeeze
SLOW_STAGE_SLEEP_S = 0.25


def _slow_stage_sleep_s() -> float:
    ms = os.environ.get("RAFT_TPU_FAULTS_SLOW_MS", "").strip()
    try:
        return float(ms) / 1e3 if ms else SLOW_STAGE_SLEEP_S
    except ValueError:
        return SLOW_STAGE_SLEEP_S

_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z]+)@(?P<scope>[a-z]+):(?P<arg>[^*]+?)(?:\*(?P<count>\d+))?$"
)


class InjectedFault(RuntimeError):
    """Base class for synthetic faults; ``fault_kind`` short-circuits
    :func:`raft_tpu.resilience.errors.classify`."""

    fault_kind = errors.FATAL


class InjectedOOM(InjectedFault):
    fault_kind = errors.OOM


class InjectedDeadBackend(InjectedFault):
    fault_kind = errors.DEAD_BACKEND


class InjectedTransient(InjectedFault):
    fault_kind = errors.TRANSIENT


_EXC = {
    "oom": (InjectedOOM, "RESOURCE_EXHAUSTED: injected fault"),
    "dead": (InjectedDeadBackend, "injected dead-backend fault"),
    "transient": (InjectedTransient, "UNAVAILABLE: injected fault"),
}


@dataclasses.dataclass
class FaultSpec:
    kind: str        # oom | dead | transient | shard | slow | drop | flap
    scope: str       # chunk | stage | rank | proc | rpc
    arg: str         # chunk index / stage name / rank
    remaining: int = 1
    # arming delay (proc scope): the spec stays quiet for its victim's
    # first `delay` data-plane RPCs, then fires — the scripted
    # late-death schedule of the chaos-curve drills
    delay: int = 0

    def render(self) -> str:
        after = f"#after:{self.delay}" if self.delay else ""
        return f"{self.kind}@{self.scope}:{self.arg}{after}*{self.remaining}"


def parse(spec: str) -> List[FaultSpec]:
    """Parse a comma-separated fault spec string (see module docstring)."""
    out: List[FaultSpec] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = _SPEC_RE.match(part)
        if not m:
            raise ValueError(
                f"bad fault spec {part!r}: want kind@scope:arg[*count], "
                f"e.g. oom@chunk:3 or dead@stage:search"
            )
        kind, scope = m.group("kind"), m.group("scope")
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (want {_KINDS})")
        if scope not in _SCOPES:
            raise ValueError(f"unknown fault scope {scope!r} (want {_SCOPES})")
        if scope in _SCOPE_KINDS and kind not in _SCOPE_KINDS[scope]:
            raise ValueError(
                f"fault kind {kind!r} not valid at scope {scope!r} "
                f"(want one of {_SCOPE_KINDS[scope]})"
            )
        if kind in _KIND_SCOPES and scope not in _KIND_SCOPES[kind]:
            raise ValueError(
                f"fault kind {kind!r} needs scope "
                f"{_KIND_SCOPES[kind]}, got {scope!r}"
            )
        arg = m.group("arg").strip()
        delay = 0
        if scope == "proc" and "#" in arg:
            # delayed proc spec: R#after:N — arms after N survived
            # data-plane RPCs
            arg, _, after = arg.partition("#")
            if not after.startswith("after:"):
                raise ValueError(
                    f"bad proc delay in {part!r}: want "
                    f"kind@proc:R#after:N")
            delay = int(after[len("after:"):])
            if delay < 0:
                raise ValueError(f"negative delay in {part!r}")
        if scope in ("chunk", "rank", "proc"):
            int(arg)                     # validate now, fail loudly
        if scope == "stage" and "#" in arg:
            int(arg.rpartition("#")[2])   # stage#chunk form
        out.append(FaultSpec(
            kind, scope, arg,
            int(m.group("count") or 1),
            delay,
        ))
    return out


# ---------------------------------------------------------------------------
# the installed plan
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plan: Optional[List[FaultSpec]] = None      # programmatic plan
_env_cache: Optional[tuple] = None           # (env string, parsed plan)


def install(spec: Optional[str]) -> None:
    """Install a programmatic plan (overrides the env var); ``None``
    restores env control."""
    global _plan
    with _lock:
        _plan = parse(spec) if spec is not None else None


def clear() -> None:
    """Drop the programmatic plan AND the env cache (tests)."""
    global _plan, _env_cache
    with _lock:
        _plan = None
        _env_cache = None


@contextlib.contextmanager
def inject(spec: str):
    """Scoped programmatic injection: ``with inject("oom@chunk:2"): ...``"""
    install(spec)
    try:
        yield
    finally:
        install(None)


def _plan_locked() -> List[FaultSpec]:
    """The live plan; caller holds ``_lock``. The consuming checkpoints
    (:func:`check`/:func:`proc_action`/:func:`rpc_dropped`) resolve the
    plan under the SAME lock hold that decrements ``remaining`` — the
    old fetch-then-relock let an ``install()``/``clear()`` swap the plan
    in between, so a one-shot spec could be consumed off a detached
    list (firing after a clear, or twice across the swap)."""
    global _env_cache
    if _plan is not None:
        return _plan
    env = os.environ.get(ENV_VAR, "")
    if _env_cache is None or _env_cache[0] != env:
        _env_cache = (env, parse(env) if env else [])
    return _env_cache[1]


def plan() -> List[FaultSpec]:
    """The live plan: the programmatic one if installed, else the parsed
    env var (cached against the env string so spec state persists across
    calls within one process)."""
    with _lock:
        return _plan_locked()


def active() -> bool:
    return bool(plan())


# ---------------------------------------------------------------------------
# injection points
# ---------------------------------------------------------------------------


def check(stage: str, chunk: Optional[int] = None,
          stage_only: bool = False) -> None:
    """A fault point: raise the first matching live spec's synthetic
    error. Call this where a real device failure would surface (chunk
    boundaries of the streaming/build loops, stage entries of the
    measurement battery). Spec matching + one-shot consumption happen
    in ONE critical section (plan resolution included); the obs
    bookkeeping and the raise run outside it.

    ``stage_only=True`` marks a fetch-stage fault point (graft-flow's
    ``stream.read`` / ``tiered.fetch`` producers): only specs that name
    the stage explicitly (``slow@stage:stream.read``, ordinals
    included) match there — ``oom@chunk:N`` specs stay reserved for the
    consuming dispatch, so chunk faults keep attributing to the
    iteration that scores the chunk, never to a background read."""
    fired: Optional[FaultSpec] = None
    with _lock:
        for s in _plan_locked():
            if s.kind == "shard" or s.scope in ("proc", "rpc") \
                    or s.remaining <= 0:
                # shard/proc/rpc specs are queried (dead_ranks,
                # proc_action, rpc_dropped), never raised here
                continue
            if s.scope == "chunk":
                hit = (not stage_only) and chunk is not None \
                    and int(s.arg) == chunk
            elif "#" in s.arg:           # stage-scoped ordinal
                name, _, idx = s.arg.rpartition("#")
                hit = stage == name and chunk is not None \
                    and chunk == int(idx)
            else:
                hit = s.arg == stage
            if hit:
                s.remaining -= 1
                fired = s
                break
    if fired is None:
        return
    from raft_tpu import obs

    obs.counter("faults_injected", kind=fired.kind, stage=stage)
    obs.event("fault_injected",
              spec=f"{fired.kind}@{fired.scope}:{fired.arg}",
              stage=stage, chunk=chunk)
    if fired.kind == "slow":
        # a stall, not a failure: the checkpoint is late — exactly the
        # shape deadline-driven serving must shed/downshift around
        import time

        time.sleep(_slow_stage_sleep_s())
        return
    cls, msg = _EXC[fired.kind]
    raise cls(f"{msg} ({fired.kind}@{fired.scope}:{fired.arg} at "
              f"stage={stage!r} chunk={chunk})")


def dead_ranks() -> FrozenSet[int]:
    """Ranks whose shard-local result should be invalidated
    (``shard@rank:R`` specs). Queried — never consumed — by the sharded
    searches, which mask the shard out of the merge when
    ``partial_ok=True``."""
    return frozenset(
        int(s.arg) for s in plan() if s.kind == "shard" and s.scope == "rank"
    )


def has_shard_faults() -> bool:
    return bool(dead_ranks())


def proc_action(rank: int) -> Optional[str]:
    """Consume the first live process-scoped spec matching worker
    ``rank`` and name the action it demands:

    * ``"die"``  — a ``dead@proc:R`` or ``flap@proc:R*K`` spec: the
      worker must hard-exit with no response (the SIGKILL /
      machine-loss mode; flap's death budget is additionally charged
      parent-side per incarnation — :func:`respawned_spec`);
    * ``"slow"`` — a ``slow@proc:R*K`` spec: the worker must stall this
      response past the router's hedge threshold (the late-answer mode).

    Returns ``None`` when nothing matches. A spec with an ``#after:N``
    arming delay stays quiet — decrementing its delay — for its
    victim's first N matching calls. Called by the fabric workers
    (:mod:`raft_tpu.comms.procgroup`) at their data-plane fault points —
    the place a real machine failure would surface."""
    fired: Optional[FaultSpec] = None
    with _lock:
        for s in _plan_locked():
            if s.scope != "proc" or s.remaining <= 0:
                continue
            if int(s.arg) != int(rank):
                continue
            if s.delay > 0:
                # not armed yet: this RPC survives, the countdown
                # advances; keep scanning — an armed later spec may
                # still claim the call
                s.delay -= 1
                continue
            s.remaining -= 1
            fired = s
            break
    if fired is None:
        return None
    action = "die" if fired.kind in ("dead", "flap") else "slow"
    from raft_tpu import obs

    obs.counter("faults_injected", kind=fired.kind,
                stage=f"proc:{rank}")
    obs.event("fault_injected",
              spec=f"{fired.kind}@{fired.scope}:{fired.arg}",
              rank=int(rank), action=action)
    return action


def respawned_spec(spec: Optional[str], rank: int,
                   deaths: int) -> Optional[str]:
    """The fault plan a RESPAWNED incarnation of worker ``rank`` should
    install, given the group's spawn-time plan and how many of this
    rank's incarnations have died so far (``deaths``).

    Each child process holds its own copy of the plan, so a budget that
    must span incarnations has to be charged where the respawn decision
    is made — the parent. The rewrite encodes the kind semantics:

    * ``flap@proc:rank*K`` — charged one death per prior incarnation;
      dropped once the budget is spent (the worker finally holds). Its
      ``#after:N`` delay is kept, so a flapping worker serves N RPCs
      between deaths.
    * ``dead@proc:rank`` — inherited verbatim but with any ``#after:N``
      delay DROPPED: the delay models when the first death lands; once
      the machine is dead it stays dead, and every respawned
      incarnation dies at its first data-plane RPC. This permanence is
      what distinguishes ``dead`` from ``flap`` under a self-healing
      control plane (its restart budget, not the fault plan, ends the
      futile respawn loop).
    * everything else (other ranks' specs, slow/stage/chunk specs) is
      inherited verbatim.

    Returns ``None`` when nothing survives the rewrite."""
    if not spec:
        return None
    out: List[str] = []
    for s in parse(spec):
        if s.scope == "proc" and int(s.arg) == int(rank):
            if s.kind == "flap":
                left = s.remaining - int(deaths)
                if left <= 0:
                    continue
                s.remaining = left
            elif s.kind == "dead":
                s.delay = 0
        out.append(s.render())
    return ",".join(out) if out else None


def rpc_dropped(method: str) -> bool:
    """Consume a ``drop@rpc:METHOD`` spec: True means this RPC's
    response must be dropped on the floor — the caller sees only a
    timeout, exactly like a response lost on the wire."""
    fired: Optional[FaultSpec] = None
    with _lock:
        for s in _plan_locked():
            if s.scope != "rpc" or s.remaining <= 0:
                continue
            if s.arg != method:
                continue
            s.remaining -= 1
            fired = s
            break
    if fired is None:
        return False
    from raft_tpu import obs

    obs.counter("faults_injected", kind=fired.kind,
                stage=f"rpc:{method}")
    obs.event("fault_injected",
              spec=f"{fired.kind}@{fired.scope}:{fired.arg}",
              method=method)
    return True
