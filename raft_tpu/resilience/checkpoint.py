"""Checkpointed streaming: per-chunk manifest + partial-state blob.

Long streamed jobs (``ivf_pq.build_streamed`` at DEEP-100M scale runs
hours; ``search_file`` over a big-ann query file) lose everything to a
mid-stream interruption today. A :class:`StreamCheckpoint` directory
makes them resumable:

* ``manifest.json`` — the per-chunk JSON manifest: phase, chunk/step
  counter, rows done, optional rng state, a config fingerprint, and the
  name of the state blob.
* ``state.bin`` — the partial-state arrays in the repo's versioned
  index-file container (:func:`raft_tpu.core.serialize.write_index_file`
  — length-prefixed ``.npy`` blocks, so a checkpoint round-trip is
  bitwise exact and ``resume=`` reproduces the uninterrupted output
  bit-for-bit).

Writes are atomic (temp file + ``os.replace``), blob first and manifest
last, so a crash mid-save leaves the previous checkpoint intact: the
manifest never names a blob that was not fully written.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from raft_tpu.core import serialize

_MANIFEST = "manifest.json"
_KIND = "resilience_checkpoint"
_VERSION = 1


class CheckpointMismatchError(RuntimeError):
    """The checkpoint's config fingerprint does not match the resuming
    job — resuming would silently corrupt the output."""


class StreamCheckpoint:
    """One resumable streamed job == one checkpoint directory."""

    def __init__(self, directory: str):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)

    # -- paths -------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def _blob_name(self, step: int) -> str:
        return f"state-{int(step)}.bin"

    def exists(self) -> bool:
        return os.path.exists(self.manifest_path)

    # -- save / load -------------------------------------------------------

    def save(
        self,
        phase: str,
        step: int,
        meta: Dict[str, Any],
        arrays: Dict[str, Any],
        fingerprint: Optional[Dict[str, Any]] = None,
        rng_state: Any = None,
    ) -> None:
        """Atomically persist one chunk boundary's full state.

        ``meta`` is JSON-scalar progress state (offsets, counters,
        picked cache kind ...); ``arrays`` is the partial-state tensors
        (host or device — moved to host here); ``fingerprint`` is the
        immutable job config a resume must match exactly.
        """
        host_arrays = {k: np.asarray(v) for k, v in arrays.items()
                       if v is not None}
        blob = self._blob_name(step)
        tmp_blob = os.path.join(self.dir, blob + ".tmp")
        serialize.write_index_file(
            tmp_blob, _KIND, _VERSION,
            {"phase": phase, "step": int(step)}, host_arrays,
        )
        os.replace(tmp_blob, os.path.join(self.dir, blob))
        manifest = {
            "version": _VERSION,
            "phase": phase,
            "step": int(step),
            "meta": meta,
            "fingerprint": fingerprint or {},
            "rng_state": rng_state,
            "blob": blob,
            "arrays": sorted(host_arrays),
        }
        tmp_man = self.manifest_path + ".tmp"
        with open(tmp_man, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp_man, self.manifest_path)
        from raft_tpu import obs

        obs.counter("checkpoint_saves", phase=phase)
        obs.event("checkpoint_save", phase=phase, step=int(step))
        # older blobs are garbage once the manifest points past them
        for name in os.listdir(self.dir):
            if name.startswith("state-") and name.endswith(".bin") \
                    and name != blob:
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass  # a stale blob is harmless, never fail a save

    def peek(
        self, fingerprint: Optional[Dict[str, Any]] = None
    ) -> Optional[Tuple[str, int, Dict[str, Any]]]:
        """Read progress state — ``(phase, step, meta)`` — from the
        manifest alone, without deserializing the (possibly multi-GB)
        state blob. Same fingerprint validation as :meth:`load`; returns
        ``None`` for a missing or torn checkpoint."""
        if not self.exists():
            return None
        with open(self.manifest_path) as f:
            manifest = json.load(f)
        if fingerprint is not None and manifest.get("fingerprint") and \
                manifest["fingerprint"] != _jsonify(fingerprint):
            raise CheckpointMismatchError(
                f"checkpoint at {self.dir} was written by a different job "
                f"config: {manifest['fingerprint']} != {_jsonify(fingerprint)}"
            )
        if not os.path.exists(os.path.join(self.dir, manifest["blob"])):
            return None     # torn save
        return manifest["phase"], int(manifest["step"]), manifest["meta"]

    def load(
        self, fingerprint: Optional[Dict[str, Any]] = None
    ) -> Optional[Tuple[str, int, Dict[str, Any], Dict[str, np.ndarray]]]:
        """Load the latest checkpoint: ``(phase, step, meta, arrays)``,
        or ``None`` when the directory holds no (complete) checkpoint.
        When ``fingerprint`` is given it must equal the saved one."""
        if not self.exists():
            return None
        with open(self.manifest_path) as f:
            manifest = json.load(f)
        if fingerprint is not None and manifest.get("fingerprint") and \
                manifest["fingerprint"] != _jsonify(fingerprint):
            raise CheckpointMismatchError(
                f"checkpoint at {self.dir} was written by a different job "
                f"config: {manifest['fingerprint']} != {_jsonify(fingerprint)}"
            )
        blob = os.path.join(self.dir, manifest["blob"])
        if not os.path.exists(blob):
            return None     # torn save; the job restarts from scratch
        _, blob_meta, arrays = serialize.read_index_file(blob, _KIND)
        if blob_meta.get("step") != manifest["step"]:
            return None     # blob/manifest disagree; treat as absent
        from raft_tpu import obs

        obs.counter("checkpoint_resumes", phase=manifest["phase"])
        obs.event("checkpoint_resume", phase=manifest["phase"],
                  step=int(manifest["step"]))
        return (manifest["phase"], int(manifest["step"]),
                manifest["meta"], arrays)

    def clear(self) -> None:
        for name in os.listdir(self.dir):
            if name == _MANIFEST or (name.startswith("state-")
                                     and name.endswith(".bin")):
                os.remove(os.path.join(self.dir, name))


def _jsonify(d: Dict[str, Any]) -> Dict[str, Any]:
    """Round-trip through JSON so fingerprint comparison sees the same
    scalar types the manifest stored (tuples -> lists, ints -> ints)."""
    return json.loads(json.dumps(d))
