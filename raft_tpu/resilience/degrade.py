"""The OOM degradation ladder: halve-and-redispatch instead of dying.

A ``RESOURCE_EXHAUSTED`` during a chunked dispatch does not invalidate
the work — it only proves the chunk was too big for the HBM headroom
left by the resident index. The ladder catches OOM-classified failures,
halves the chunk, and re-dispatches the halves; every surviving size is
recorded as an in-process :func:`raft_tpu.tuning.record_budget` entry
so *later* calls in the same process start at the size that survived
instead of re-climbing the ladder (the measured-dispatch analog of the
reference's memory-pool fallback allocators).

Row-independent dispatches only: a search over rows ``[a:b]`` must equal
the concatenation of searches over ``[a:m]`` and ``[m:b]`` (true for
every per-query search path here; NOT true for the donated build
scatters, which therefore checkpoint instead of degrading —
docs/resilience.md).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax

from raft_tpu.resilience import errors


def run_halving(
    fn: Callable,
    batch,
    *,
    min_rows: int = 1,
    budget_name: Optional[str] = None,
) -> Tuple[object, int]:
    """Run ``fn(batch)`` with OOM-halving over ``batch``'s leading axis.

    Returns ``(result, surviving_rows)`` where ``surviving_rows`` is the
    largest row count that dispatched successfully (== ``len(batch)``
    when no fault struck). Results of split dispatches are concatenated
    leaf-wise along axis 0, so they are bitwise what the unsplit
    dispatch would have produced for any row-independent ``fn``. Non-OOM
    failures and OOMs at ``min_rows`` propagate.
    """
    rows = int(batch.shape[0])
    try:
        out = fn(batch)
        # force completion INSIDE the ladder: XLA dispatch is async, so
        # without a sync the OOM surfaces later at some consumer outside
        # any recovery scope
        jax.block_until_ready(out)
        return out, rows
    except Exception as e:  # noqa: BLE001 — classified below, not swallowed
        if errors.classify(e) != errors.OOM or rows <= min_rows:
            raise
    half = rows // 2
    from raft_tpu import obs

    obs.counter("oom_ladder_downshifts", path="halving")
    obs.event("oom_downshift", path="halving", rows=rows, half=half)
    r1, s1 = run_halving(fn, batch[:half], min_rows=min_rows,
                         budget_name=None)
    r2, s2 = run_halving(fn, batch[half:], min_rows=min_rows,
                         budget_name=None)
    survived = min(s1, s2)
    if budget_name is not None:
        from raft_tpu import tuning

        tuning.record_budget(budget_name, survived)
    out = jax.tree_util.tree_map(
        lambda a, b: jax.numpy.concatenate([a, b], axis=0), r1, r2
    )
    return out, survived


def run_shrinking_blocks(
    fn: Callable,
    total_rows: int,
    block_rows: int,
    *,
    min_rows: int = 1,
    budget_name: Optional[str] = None,
    stage: str = "block",
):
    """Cover ``[0, total_rows)`` with ``fn(start, rows)`` dispatches,
    halving the block size on OOM (the surviving size sticks for the
    remaining blocks). Yields the per-block results in order.

    The host-blocked-loop shape of CAGRA's transient-buffer chunking
    (``_detour_counts``): each block is synced before the next dispatch
    so an OOM is caught at ITS block, not at some later consumer.
    """
    start = 0
    block = max(int(block_rows), min_rows)
    limit = block                 # transient per-position cap (tail OOMs)
    bi = 0
    while start < total_rows:
        rows = min(limit, block, total_rows - start)
        from raft_tpu.resilience import faultinject

        try:
            faultinject.check(stage=stage, chunk=bi)
            out = fn(start, rows)
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — classified, not swallowed
            if errors.classify(e) != errors.OOM or rows <= min_rows:
                raise
            half = max(rows // 2, min_rows)
            limit = half
            from raft_tpu import obs

            obs.counter("oom_ladder_downshifts", path="blocks", stage=stage)
            obs.event("oom_downshift", path="blocks", stage=stage,
                      rows=rows, half=half)
            if rows >= block:
                # a FULL block failed: the learned size shrinks for good
                # (a short tail failing must not poison the process-wide
                # budget with its half-of-a-few-rows size)
                block = half
                if budget_name is not None:
                    from raft_tpu import tuning

                    tuning.record_budget(budget_name, half)
            continue
        yield out
        start += rows
        bi += 1
        limit = block             # reset the transient cap after success
