// Native IO runtime for raft_tpu — the TPU-host analog of the reference's
// native data-loading path (cpp/bench/ann/src/common/dataset.hpp mmap+read
// loaders and the batch_load_iterator host side,
// cpp/include/raft/spatial/knn/detail/ann_utils.cuh:397).
//
// Python drives the device; this layer keeps the *disk* side off the
// interpreter: positioned block reads and a double-buffered reader thread
// that prefetches ahead of consumption, so streaming index builds overlap
// file IO with TPU work instead of stalling on synchronous memmap page
// faults. Exposed through ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -shared -fPIC -pthread (see native/__init__.py).

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// Positioned read: returns bytes read, or -1 on error.
long rt_read_block(const char* path, long offset, long nbytes, void* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, offset, SEEK_SET) != 0) {
    std::fclose(f);
    return -1;
  }
  size_t got = std::fread(out, 1, (size_t)nbytes, f);
  std::fclose(f);
  return (long)got;
}

struct Prefetcher {
  FILE* f = nullptr;
  long block_bytes = 0;
  long remaining = 0;
  int depth = 2;
  bool eof = false;
  bool error = false;
  bool stop = false;
  std::deque<std::vector<uint8_t>> ready;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits: a block is ready
  std::condition_variable cv_space;   // reader waits: ring has space
  std::thread worker;

  void run() {
    for (;;) {
      std::vector<uint8_t> buf;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return stop || (long)ready.size() < depth; });
        if (stop || remaining <= 0) break;
      }
      long want = block_bytes < remaining ? block_bytes : remaining;
      buf.resize((size_t)want);
      size_t got = std::fread(buf.data(), 1, (size_t)want, f);
      std::unique_lock<std::mutex> lk(mu);
      if ((long)got != want) error = true;
      buf.resize(got);
      remaining -= (long)got;
      if (remaining <= 0 || got == 0) eof = true;
      if (got > 0) ready.emplace_back(std::move(buf));
      cv_ready.notify_one();
      if (eof || error) break;
    }
    std::unique_lock<std::mutex> lk(mu);
    eof = true;
    cv_ready.notify_all();
  }
};

// Open a streaming window [offset, offset+total_bytes) read in
// block_bytes chunks with `depth` blocks of read-ahead.
void* rt_prefetch_open(const char* path, long offset, long block_bytes,
                       long total_bytes, int depth) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  if (std::fseek(f, offset, SEEK_SET) != 0) {
    std::fclose(f);
    return nullptr;
  }
  auto* p = new Prefetcher();
  p->f = f;
  p->block_bytes = block_bytes;
  p->remaining = total_bytes;
  p->depth = depth > 1 ? depth : 1;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Copy the next block into out (capacity out_cap). Returns bytes copied,
// 0 at end of stream, -1 on error.
long rt_prefetch_next(void* handle, void* out, long out_cap) {
  auto* p = (Prefetcher*)handle;
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_ready.wait(lk, [&] { return !p->ready.empty() || p->eof || p->error; });
  if (p->ready.empty()) return p->error ? -1 : 0;
  std::vector<uint8_t> buf = std::move(p->ready.front());
  p->ready.pop_front();
  p->cv_space.notify_one();
  lk.unlock();
  long n = (long)buf.size();
  if (n > out_cap) return -1;
  std::memcpy(out, buf.data(), (size_t)n);
  return n;
}

void rt_prefetch_close(void* handle) {
  auto* p = (Prefetcher*)handle;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_space.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  if (p->f) std::fclose(p->f);
  delete p;
}

}  // extern "C"
