"""ctypes bindings for the native IO runtime (raft_tpu_native.cpp).

Compiled on first use with g++ (-O3 -shared -fPIC -pthread) into this
directory, keyed on source mtime; every entry point has a numpy fallback
so the package works without a toolchain. pybind11 is deliberately not
used (not in the image) — the C ABI + ctypes is the whole interface.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "raft_tpu_native.cpp")
_SO = os.path.join(_DIR, "libraft_tpu_native.so")
_lock = threading.Lock()
_lib = None
_lib_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
             _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if stale/absent) the native library, or None."""
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        try:
            stale = (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_SO)
            lib.rt_read_block.restype = ctypes.c_long
            lib.rt_read_block.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_void_p
            ]
            lib.rt_prefetch_open.restype = ctypes.c_void_p
            lib.rt_prefetch_open.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
                ctypes.c_int,
            ]
            lib.rt_prefetch_next.restype = ctypes.c_long
            lib.rt_prefetch_next.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long
            ]
            lib.rt_prefetch_close.restype = None
            lib.rt_prefetch_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


def read_block(path: str, offset: int, nbytes: int) -> np.ndarray:
    """Positioned binary read → uint8 array (native; numpy fallback)."""
    lib = get_lib()
    out = np.empty(nbytes, np.uint8)
    if lib is not None:
        got = lib.rt_read_block(
            path.encode(), offset, nbytes,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        if got < 0:
            raise IOError(f"native read failed: {path}")
        return out[:got]
    with open(path, "rb") as fp:
        fp.seek(offset)
        data = fp.read(nbytes)
    out[: len(data)] = np.frombuffer(data, np.uint8)
    return out[: len(data)]


class FilePrefetcher:
    """Double-buffered streaming reads of [offset, offset+total_bytes) in
    ``block_bytes`` chunks — a reader thread keeps ``depth`` blocks ahead
    of the consumer (the host half of the reference's
    batch_load_iterator pipeline, ann_utils.cuh:397).
    """

    def __init__(self, path: str, offset: int, block_bytes: int,
                 total_bytes: int, depth: int = 2):
        self.path = path
        self.offset = int(offset)
        self.block_bytes = int(block_bytes)
        self.total_bytes = int(total_bytes)
        self.depth = int(depth)
        self._lib = get_lib()

    def __iter__(self) -> Iterator[np.ndarray]:
        if self._lib is None:
            # numpy fallback: plain sequential reads (same truncation
            # contract as the native path: short file -> IOError)
            pos, end = self.offset, self.offset + self.total_bytes
            with open(self.path, "rb") as fp:
                fp.seek(pos)
                while pos < end:
                    want = min(self.block_bytes, end - pos)
                    data = fp.read(want)
                    if len(data) < want:
                        raise IOError(
                            f"short read at {pos}: {self.path} is smaller "
                            "than the requested stream window"
                        )
                    pos += len(data)
                    yield np.frombuffer(data, np.uint8)
            return
        handle = self._lib.rt_prefetch_open(
            self.path.encode(), self.offset, self.block_bytes,
            self.total_bytes, self.depth,
        )
        if not handle:
            raise IOError(f"prefetch_open failed: {self.path}")
        try:
            while True:
                # fresh buffer per block: the consumer keeps it, so no
                # second copy on top of the prefetcher's memcpy
                buf = np.empty(self.block_bytes, np.uint8)
                got = self._lib.rt_prefetch_next(
                    handle, buf.ctypes.data_as(ctypes.c_void_p),
                    self.block_bytes,
                )
                if got < 0:
                    raise IOError(f"prefetch read failed: {self.path}")
                if got == 0:
                    return
                yield buf[:got]
        finally:
            self._lib.rt_prefetch_close(handle)
