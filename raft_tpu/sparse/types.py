"""Sparse matrix containers — COO and CSR.

TPU-native analog of the reference's owning/view sparse structures
(cpp/include/raft/core/{coo_matrix,csr_matrix,sparse_types}.hpp and the
legacy ``raft::sparse::COO`` in sparse/coo.hpp).

Design: both containers are immutable pytree dataclasses with a *fixed*
``nnz`` — XLA requires static shapes, so structural mutation (dedup,
filtering) either returns a same-length container plus a validity mask, or
compresses on the host at an API boundary. ``shape`` is static aux data so
jitted functions specialize per matrix geometry, matching how the reference
templates on index/value types rather than carrying runtime descriptors.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class COO:
    """Coordinate-format sparse matrix (reference sparse/coo.hpp COO).

    rows/cols: int32 [nnz]; vals: [nnz]; shape: static (m, n).
    """

    rows: jax.Array
    cols: jax.Array
    vals: jax.Array
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals), self.shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        return cls(*children, shape=shape)

    def to_dense(self) -> jax.Array:
        return coo_to_dense(self)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CSR:
    """Compressed-sparse-row matrix (reference core/csr_matrix.hpp).

    indptr: int32 [m+1]; indices: int32 [nnz]; vals: [nnz]; shape (m, n).
    """

    indptr: jax.Array
    indices: jax.Array
    vals: jax.Array
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    def tree_flatten(self):
        return (self.indptr, self.indices, self.vals), self.shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        return cls(*children, shape=shape)

    def to_dense(self) -> jax.Array:
        return coo_to_dense(csr_to_coo(self))


# ---------------------------------------------------------------------------
# conversions (reference sparse/convert/{coo,csr,dense}.cuh)
# ---------------------------------------------------------------------------


def coo_sort(coo: COO) -> COO:
    """Row-major (row, col) lexicographic sort (sparse/op/sort.cuh coo_sort)."""
    order = jnp.lexsort((coo.cols, coo.rows))
    return COO(coo.rows[order], coo.cols[order], coo.vals[order], coo.shape)


def coo_to_csr(coo: COO, assume_sorted: bool = False) -> CSR:
    """COO → CSR (sparse/convert/csr.cuh sorted_coo_to_csr)."""
    if not assume_sorted:
        coo = coo_sort(coo)
    m = coo.shape[0]
    indptr = jnp.searchsorted(
        coo.rows, jnp.arange(m + 1, dtype=coo.rows.dtype)
    ).astype(jnp.int32)
    return CSR(indptr, coo.cols, coo.vals, coo.shape)


def csr_to_coo(csr: CSR) -> COO:
    """CSR → COO (sparse/convert/coo.cuh csr_to_coo)."""
    nnz = csr.indices.shape[0]
    counts = jnp.diff(csr.indptr)
    rows = jnp.repeat(
        jnp.arange(csr.shape[0], dtype=jnp.int32), counts,
        total_repeat_length=nnz,
    )
    return COO(rows, csr.indices, csr.vals, csr.shape)


def dense_to_coo(x) -> COO:
    """Dense → COO. Host-side (nnz is data-dependent; XLA needs it static)."""
    x = np.asarray(x)
    rows, cols = np.nonzero(x)
    return COO(
        jnp.asarray(rows, jnp.int32),
        jnp.asarray(cols, jnp.int32),
        jnp.asarray(x[rows, cols]),
        tuple(x.shape),
    )


def dense_to_csr(x) -> CSR:
    return coo_to_csr(dense_to_coo(x), assume_sorted=True)


def coo_to_dense(coo: COO) -> jax.Array:
    """COO → dense scatter (sparse/convert/dense.cuh csr_to_dense)."""
    out = jnp.zeros(coo.shape, coo.vals.dtype)
    return out.at[coo.rows, coo.cols].add(coo.vals)


def from_scipy(sp) -> CSR:
    """Interop: scipy.sparse matrix → CSR."""
    sp = sp.tocsr()
    return CSR(
        jnp.asarray(sp.indptr, jnp.int32),
        jnp.asarray(sp.indices, jnp.int32),
        jnp.asarray(sp.data),
        tuple(sp.shape),
    )


def to_scipy(mat):
    """Interop: COO/CSR → scipy.sparse.csr_matrix (host copy)."""
    import scipy.sparse as sps

    if isinstance(mat, COO):
        return sps.coo_matrix(
            (np.asarray(mat.vals), (np.asarray(mat.rows), np.asarray(mat.cols))),
            shape=mat.shape,
        ).tocsr()
    return sps.csr_matrix(
        (np.asarray(mat.vals), np.asarray(mat.indices), np.asarray(mat.indptr)),
        shape=mat.shape,
    )
