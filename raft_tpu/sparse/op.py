"""COO/CSR structural ops (reference sparse/op/{sort,filter,reduce,slice,
row_op}.cuh).

Fixed-shape policy: ops that shrink nnz (dedup, zero-removal) come in two
flavors — a jittable masked form that keeps nnz and returns a validity
mask, and a host ``compress=True`` form that materializes the short result
at the API boundary (the reference's equivalent of a device→host nnz
readback before reallocating, e.g. sparse/op/detail/filter.cuh:coo_remove_scalar).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.types import COO, CSR, coo_sort, coo_to_csr, csr_to_coo


def degree(coo: COO) -> jax.Array:
    """Per-row nonzero count (sparse/linalg/degree.cuh coo_degree)."""
    return jnp.zeros(coo.shape[0], jnp.int32).at[coo.rows].add(1)


def coo_remove_scalar(coo: COO, scalar: float = 0.0) -> COO:
    """Drop entries equal to ``scalar`` (sparse/op/filter.cuh
    coo_remove_scalar). Host-compressing: output nnz is data-dependent."""
    keep = np.asarray(coo.vals != scalar)
    return COO(
        jnp.asarray(np.asarray(coo.rows)[keep]),
        jnp.asarray(np.asarray(coo.cols)[keep]),
        jnp.asarray(np.asarray(coo.vals)[keep]),
        coo.shape,
    )


def sum_duplicates(coo: COO, compress: bool = True):
    """Merge duplicate (row, col) entries by summing values
    (the reference's max_duplicates/sum pattern in sparse/op/reduce.cuh).

    compress=True: host-compressed COO with unique coordinates.
    compress=False: jittable — returns (coo_sorted_summed, valid_mask) at
    the original nnz; invalid slots carry zero values.
    """
    coo = coo_sort(coo)
    nnz = coo.rows.shape[0]
    if nnz == 0:
        return coo if compress else (coo, jnp.zeros((0,), bool))
    same = (coo.rows[1:] == coo.rows[:-1]) & (coo.cols[1:] == coo.cols[:-1])
    first = jnp.concatenate([jnp.ones((1,), bool), ~same])
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    summed = jnp.zeros((nnz,), coo.vals.dtype).at[gid].add(coo.vals)
    # each group's sum lands on the group's first slot; the rest zero out
    # (typed zero: a weak 0.0 would silently promote integer vals)
    vals = jnp.where(first, summed[gid], jnp.zeros((), coo.vals.dtype))
    out = COO(coo.rows, coo.cols, vals, coo.shape)
    if not compress:
        return out, first
    keep = np.asarray(first)
    return COO(
        jnp.asarray(np.asarray(out.rows)[keep]),
        jnp.asarray(np.asarray(out.cols)[keep]),
        jnp.asarray(np.asarray(out.vals)[keep]),
        coo.shape,
    )


def symmetrize(coo: COO, mode: str = "max") -> COO:
    """Graph symmetrization A ← sym(A) (sparse/linalg/symmetrize.cuh).

    mode: "max" keeps max(|a_ij|, |a_ji|) — the KNN-graph symmetrization
    used for single-linkage connectivity; "sum" computes A + Aᵀ;
    "mean" (A + Aᵀ)/2. Host-compressing.
    """
    both = COO(
        jnp.concatenate([coo.rows, coo.cols]),
        jnp.concatenate([coo.cols, coo.rows]),
        jnp.concatenate([coo.vals, coo.vals]),
        coo.shape,
    )
    if mode == "sum":
        return sum_duplicates(both)
    # recover per-key duplicate counts to undo the sum
    s = coo_sort(both)
    same = (s.rows[1:] == s.rows[:-1]) & (s.cols[1:] == s.cols[:-1])
    first = jnp.concatenate([jnp.ones((1,), bool), ~same])
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    nnz2 = s.rows.shape[0]
    cnt = jnp.zeros((nnz2,), jnp.float32).at[gid].add(1.0)
    if mode == "mean":
        summed = jnp.zeros((nnz2,), s.vals.dtype).at[gid].add(s.vals)
        vals = jnp.where(first, summed[gid] / cnt[gid], 0.0)
    elif mode == "max":
        big = jnp.full((nnz2,), -jnp.inf, jnp.float32)
        mx = big.at[gid].max(s.vals.astype(jnp.float32))
        vals = jnp.where(first, mx[gid].astype(s.vals.dtype), 0.0)
    else:
        raise ValueError(mode)
    keep = np.asarray(first)
    return COO(
        jnp.asarray(np.asarray(s.rows)[keep]),
        jnp.asarray(np.asarray(s.cols)[keep]),
        jnp.asarray(np.asarray(vals)[keep]),
        coo.shape,
    )


def row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """Contiguous row range view (sparse/op/slice.cuh csr_row_slice).
    Host-compressing (slice nnz is data-dependent)."""
    indptr = np.asarray(csr.indptr)
    lo, hi = int(indptr[start]), int(indptr[stop])
    return CSR(
        jnp.asarray(indptr[start : stop + 1] - lo, jnp.int32),
        csr.indices[lo:hi],
        csr.vals[lo:hi],
        (stop - start, csr.shape[1]),
    )


def row_op(csr: CSR, fn) -> CSR:
    """Apply ``fn(vals, rows) -> vals`` over entries with their row ids
    (sparse/op/row_op.cuh csr_row_op analog)."""
    coo = csr_to_coo(csr)
    return CSR(csr.indptr, csr.indices, fn(coo.vals, coo.rows), csr.shape)
