"""Sparse solvers: minimum spanning tree + connected components + Lanczos
(reference sparse/solver/{mst,mst_solver}.cuh and
sparse/neighbors/cross_component_nn.cuh).

MST is Borůvka's algorithm, which is the natural TPU formulation: every
round each component picks its lightest outgoing edge with two
segment-min passes (weight, then edge-id among ties), merges via
pointer-jumping — all fixed-shape, all vectorized across components, at
most ⌈log₂ n⌉ rounds. The reference's GPU MST (detail/mst_solver_inl.cuh)
is Borůvka too, built on per-supervertex atomic min-reduction; the
segment-min is the collective analog of that atomic.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.linalg.lanczos import lanczos_eigsh  # re-export (sparse/solver/lanczos.cuh)
from raft_tpu.sparse.types import COO, CSR, csr_to_coo

__all__ = ["mst", "connected_components", "lanczos_eigsh", "connect_components"]


def _pointer_jump(parent):
    """Collapse a parent forest to its roots (log-step path doubling)."""
    def cond_fn(state):
        p, changed = state
        return changed

    def while_body(state):
        p, _ = state
        p2 = p[p]
        return p2, jnp.any(p2 != p)

    p, _ = jax.lax.while_loop(cond_fn, while_body, (parent, jnp.bool_(True)))
    return p


@functools.partial(jax.jit, static_argnums=(3,))
def _boruvka(rows, cols, w, n: int):
    """Fixed-shape Borůvka. Edges must contain both directions of every
    undirected edge. Returns (mst_edge_mask [E] bool, colors [n] i32)."""
    E = rows.shape[0]
    inf = jnp.float32(jnp.inf)
    colors0 = jnp.arange(n, dtype=jnp.int32)
    mask0 = jnp.zeros((E,), bool)

    def cond_fn(state):
        _, _, again, it = state
        return again & (it < n)

    def body(state):
        colors, mask, _, it = state
        cr = colors[rows]
        cc = colors[cols]
        cross = cr != cc
        w_eff = jnp.where(cross, w, inf)
        # pass 1: lightest outgoing weight per component
        minw = jnp.full((n,), inf).at[cr].min(w_eff)
        # passes 2-3: tie-break among the lightest by the *symmetric* key
        # (w, min(u,v), max(u,v)) — both directions of an undirected edge
        # share it, so merge cycles longer than 2 cannot form (the
        # reference's alteration step, detail/mst_solver_inl.cuh
        # min_edge_per_supervertex, alters weights for the same reason)
        is_w = cross & (w_eff <= minw[cr])
        lo = jnp.minimum(rows, cols)
        hi = jnp.maximum(rows, cols)
        minlo = jnp.full((n,), n, jnp.int32).at[cr].min(
            jnp.where(is_w, lo, n)
        )
        is_wl = is_w & (lo == minlo[cr])
        minhi = jnp.full((n,), n, jnp.int32).at[cr].min(
            jnp.where(is_wl, hi, n)
        )
        is_whl = is_wl & (hi == minhi[cr])
        eid = jnp.where(is_whl, jnp.arange(E, dtype=jnp.int32), E)
        pick = jnp.full((n,), E, jnp.int32).at[cr].min(eid)  # [n] edge ids
        valid = pick < E
        # mark picked edges in the MST (pad slot E absorbs invalid picks)
        mask = (
            jnp.zeros((E + 1,), bool)
            .at[jnp.where(valid, pick, E)]
            .set(True)[:E]
            | mask
        )
        # build the merge forest: component c -> color of its pick's far end
        parent = jnp.where(valid, colors[cols[jnp.clip(pick, 0, E - 1)]],
                           jnp.arange(n, dtype=jnp.int32))
        # break 2-cycles (a<->b both picked each other): keep the smaller id
        two_cycle = parent[parent] == jnp.arange(n, dtype=jnp.int32)
        parent = jnp.where(
            two_cycle & (parent > jnp.arange(n, dtype=jnp.int32)),
            jnp.arange(n, dtype=jnp.int32),
            parent,
        )
        roots = _pointer_jump(parent)
        new_colors = roots[colors]
        return new_colors, mask, jnp.any(valid), it + 1

    colors, mask, _, _ = jax.lax.while_loop(
        cond_fn, body, (colors0, mask0, jnp.bool_(True), jnp.int32(0))
    )
    return mask, colors


def mst(
    coo: COO, symmetrize_input: bool = True
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, jax.Array]:
    """Minimum spanning forest of a weighted undirected graph
    (reference sparse/solver/mst.cuh mst: colors + MST edge list out).

    Parameters: ``coo`` — edge list; if ``symmetrize_input``, the mirror
    of every edge is appended (Borůvka needs both directions).

    Returns ``(src, dst, weight, colors)``: host-compressed MST edge
    arrays (n-1 edges per connected component tree) and the final
    per-vertex component color (connected components for free).
    """
    n = coo.shape[0]
    rows, cols, vals = coo.rows, coo.cols, coo.vals.astype(jnp.float32)
    if symmetrize_input:
        rows, cols, vals = (
            jnp.concatenate([rows, cols]),
            jnp.concatenate([cols, rows]),
            jnp.concatenate([vals, vals]),
        )
    mask, colors = _boruvka(rows, cols, vals, n)
    keep = np.asarray(mask)
    src = np.asarray(rows)[keep]
    dst = np.asarray(cols)[keep]
    w = np.asarray(vals)[keep]
    # canonicalize + dedupe edges picked from both directions
    lo, hi = np.minimum(src, dst), np.maximum(src, dst)
    _, uniq = np.unique(lo.astype(np.int64) * n + hi, return_index=True)
    return src[uniq], dst[uniq], w[uniq], colors


def connected_components(coo: COO) -> Tuple[int, jax.Array]:
    """Weakly connected components via label propagation + pointer jumping
    (the reference reaches this through MST colors / cuGraph).

    Returns (n_components, labels [n] with labels in [0, n_components)).
    """
    n = coo.shape[0]
    # run Borůvka on unit weights: final colors are the components
    _, colors = _boruvka(
        jnp.concatenate([coo.rows, coo.cols]),
        jnp.concatenate([coo.cols, coo.rows]),
        jnp.ones((2 * coo.rows.shape[0],), jnp.float32),
        n,
    )
    c = np.asarray(colors)
    uniq, labels = np.unique(c, return_inverse=True)
    return int(uniq.size), jnp.asarray(labels.astype(np.int32))


def connect_components(
    x, colors, metric="sqeuclidean"
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Minimum cross-component connecting edges
    (reference sparse/neighbors/cross_component_nn.cuh: for each vertex
    find its nearest neighbor in a *different* component, then keep each
    component's lightest such edge — the FixConnectivitiesRedOp pattern
    that repairs a disconnected KNN graph before single-linkage).

    Returns host arrays (src, dst, weight) of candidate bridging edges
    (at most one per component).
    """
    from raft_tpu.distance.pairwise import pairwise_distance

    x = jnp.asarray(x)
    n = x.shape[0]
    colors = jnp.asarray(colors)
    # tiled cross-component 1-NN: mask same-component pairs to +inf
    block = max(1, min(n, (64 << 20) // max(4 * n, 1)))
    best_d = []
    best_j = []
    for r0 in range(0, n, block):
        r1 = min(r0 + block, n)
        d = pairwise_distance(x[r0:r1], x, metric)
        same = colors[r0:r1, None] == colors[None, :]
        d = jnp.where(same, jnp.inf, d)
        best_d.append(jnp.min(d, axis=1))
        best_j.append(jnp.argmin(d, axis=1))
    bd = jnp.concatenate(best_d)
    bj = jnp.concatenate(best_j)
    # lightest outgoing edge per component (segment-min, like Borůvka pass)
    cr = colors
    minw = jnp.full((n,), jnp.inf).at[cr].min(bd)
    is_min = bd <= minw[cr]
    vid = jnp.where(is_min, jnp.arange(n), n)
    pick = jnp.full((n,), n, jnp.int32).at[cr].min(vid.astype(jnp.int32))
    valid = np.asarray(pick < n) & np.isfinite(np.asarray(minw))
    pick_h = np.asarray(pick)[valid]
    return (
        pick_h,
        np.asarray(bj)[pick_h],
        np.asarray(bd)[pick_h],
    )
