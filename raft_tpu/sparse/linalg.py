"""Sparse linear algebra (reference sparse/linalg/{spmm,transpose,norm,
degree,add,symmetrize,spectral}.cuh).

TPU formulation: SpMV/SpMM are gather + segment-sum — XLA lowers the
segment-sum to a sorted-scatter-add which is bandwidth-bound, exactly the
roofline a cuSPARSE SpMV sits on. For the MXU-heavy consumers (spectral
embedding) the Lanczos operator only needs matvecs, so this is the whole
story; there is deliberately no sparse-GEMM — at RAFT's densities a
block-densified dense GEMM beats any TPU SpGEMM formulation
(see sparse/distance.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR, coo_sort, coo_to_csr, csr_to_coo
from raft_tpu.sparse import op as sparse_op


def spmv(csr: CSR, v) -> jax.Array:
    """y = A @ v for CSR A [m, n], dense v [n]."""
    coo = csr_to_coo(csr)
    prod = csr.vals * v[csr.indices]
    return jax.ops.segment_sum(prod, coo.rows, num_segments=csr.shape[0])


def spmm(csr: CSR, b) -> jax.Array:
    """C = A @ B for CSR A [m, k], dense B [k, n] (sparse/linalg/spmm.cuh).

    O(nnz · n) gather + segment-sum; rows of B are gathered per nonzero.
    """
    coo = csr_to_coo(csr)
    contrib = csr.vals[:, None] * b[csr.indices]  # [nnz, n]
    return jax.ops.segment_sum(contrib, coo.rows, num_segments=csr.shape[0])


def gemv_t(csr: CSR, v) -> jax.Array:
    """y = Aᵀ @ v without materializing the transpose."""
    coo = csr_to_coo(csr)
    return jax.ops.segment_sum(
        csr.vals * v[coo.rows], csr.indices, num_segments=csr.shape[1]
    )


def transpose(csr: CSR) -> CSR:
    """CSR transpose via COO swap + re-sort (sparse/linalg/transpose.cuh)."""
    coo = csr_to_coo(csr)
    m, n = csr.shape
    return coo_to_csr(COO(coo.cols, coo.rows, coo.vals, (n, m)))


def row_norm(csr: CSR, norm: str = "l2") -> jax.Array:
    """Per-row norms (sparse/linalg/norm.cuh rowNormCsr): l1 | l2 | linf."""
    coo = csr_to_coo(csr)
    m = csr.shape[0]
    if norm == "l1":
        return jax.ops.segment_sum(jnp.abs(csr.vals), coo.rows, num_segments=m)
    if norm == "l2":
        return jax.ops.segment_sum(csr.vals * csr.vals, coo.rows, num_segments=m)
    if norm == "linf":
        return jax.ops.segment_max(jnp.abs(csr.vals), coo.rows, num_segments=m)
    raise ValueError(norm)


def add(a: CSR, b: CSR) -> CSR:
    """C = A + B (sparse/linalg/add.cuh csr_add). Host-compressing."""
    assert a.shape == b.shape
    ca, cb = csr_to_coo(a), csr_to_coo(b)
    both = COO(
        jnp.concatenate([ca.rows, cb.rows]),
        jnp.concatenate([ca.cols, cb.cols]),
        jnp.concatenate([ca.vals, cb.vals]),
        a.shape,
    )
    return coo_to_csr(sparse_op.sum_duplicates(both), assume_sorted=True)


def degree(csr: CSR) -> jax.Array:
    """Weighted vertex degree d_i = Σ_j a_ij."""
    return row_norm(csr, "l1")


def laplacian(adj: CSR, normalized: bool = False) -> Tuple[CSR, jax.Array]:
    """Graph Laplacian L = D - A (or normalized I - D^-1/2 A D^-1/2) from a
    symmetric adjacency (the operator behind the reference's
    spectral/matrix_wrappers.hpp laplacian_matrix_t).

    Returns (L as CSR, degree vector). The diagonal is appended as explicit
    entries, so L is directly usable by spmv/Lanczos.
    """
    coo = csr_to_coo(adj)
    m = adj.shape[0]
    d = jax.ops.segment_sum(coo.vals, coo.rows, num_segments=m)
    if normalized:
        dinv = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.maximum(d, 1e-30)), 0.0)
        offdiag = -coo.vals * dinv[coo.rows] * dinv[coo.cols]
        diag = jnp.where(d > 0, 1.0, 0.0)
    else:
        offdiag = -coo.vals
        diag = d
    rows = jnp.concatenate([coo.rows, jnp.arange(m, dtype=jnp.int32)])
    cols = jnp.concatenate([coo.cols, jnp.arange(m, dtype=jnp.int32)])
    vals = jnp.concatenate([offdiag, diag])
    lap = coo_to_csr(
        sparse_op.sum_duplicates(COO(rows, cols, vals, adj.shape)),
        assume_sorted=True,
    )
    return lap, d
