"""Sparse subsystem — COO/CSR types, ops, linalg, distances, neighbors,
MST/CC solvers (reference cpp/include/raft/sparse/, SURVEY.md §2.7)."""

from raft_tpu.sparse.types import (
    COO,
    CSR,
    coo_sort,
    coo_to_csr,
    coo_to_dense,
    csr_to_coo,
    dense_to_coo,
    dense_to_csr,
    from_scipy,
    to_scipy,
)
from raft_tpu.sparse import distance, linalg, neighbors, op, solver
from raft_tpu.sparse.solver import connected_components, mst

__all__ = [
    "COO", "CSR",
    "coo_sort", "coo_to_csr", "coo_to_dense", "csr_to_coo",
    "dense_to_coo", "dense_to_csr", "from_scipy", "to_scipy",
    "distance", "linalg", "neighbors", "op", "solver",
    "connected_components", "mst",
]
