"""Sparse pairwise distances (reference sparse/distance/distance.cuh:76-127,
detail/{l2,ip,bin,lp}_distance.cuh + coo_spmv strategies).

TPU-first design decision: the reference's sparse engine is a family of
load-balanced COO-SpMV strategies because on a GPU the win is skipping
zero multiplies. On TPU the MXU makes dense FLOPs nearly free while
irregular gathers are expensive, so sparsity pays in *memory*, not FLOPs.
The engine therefore densifies VMEM-sized row blocks (a contiguous CSR row
range is one dynamic-slice + scatter) and rides the existing dense
pairwise engine (distance/pairwise.py) per block pair — GEMM + epilogue
for expanded metrics, tiled broadcast-reduce for the rest. Same numerics,
same metric table, one code path to test.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.pairwise import _pairwise
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.sparse.types import CSR
from raft_tpu.utils.math import cdiv
from raft_tpu.utils.precision import dist_dot


@functools.partial(jax.jit, static_argnums=(3, 4))
def _densify_rows(indices, vals, row_lens, block_rows: int, n_cols: int):
    """Scatter one padded row-block into dense [block_rows, n_cols].

    indices/vals are the block's entries padded to a static length with
    index == n_cols (dropped by the scatter); row_lens [block_rows] gives
    per-row entry counts so entries map to their rows.
    """
    L = indices.shape[0]
    row_of = jnp.searchsorted(
        jnp.cumsum(row_lens), jnp.arange(L, dtype=jnp.int32), side="right"
    ).astype(jnp.int32)
    dense = jnp.zeros((block_rows, n_cols + 1), vals.dtype)
    dense = dense.at[row_of, jnp.clip(indices, 0, n_cols)].add(
        jnp.where(indices < n_cols, vals, 0.0)
    )
    return dense[:, :n_cols]


def densify_block(csr: CSR, r0: int, r1: int, c0: int = 0,
                  c1: Optional[int] = None) -> jax.Array:
    """Densify rows [r0, r1) x columns [c0, c1) of a CSR matrix.
    Host-orchestrated: the block's nnz span comes from indptr on the
    host, the scatter runs jitted. The entry slice is padded to the next
    power of two (padding scatters into the dropped guard column) so
    block nnz variation doesn't recompile ``_densify_rows`` per block.
    Entries outside the column range scatter into the guard column —
    the column blocking that keeps vocab-sized dims off HBM."""
    indptr = np.asarray(csr.indptr)
    lo, hi = int(indptr[r0]), int(indptr[r1])
    block_rows = r1 - r0
    row_lens = csr.indptr[r0 + 1 : r1 + 1] - csr.indptr[r0:r1]
    L = hi - lo
    nnz, n_cols = csr.indices.shape[0], csr.shape[1]
    if c1 is None:
        c1 = n_cols
    width = c1 - c0
    if nnz == 0 or L == 0:
        return jnp.zeros((block_rows, width), csr.vals.dtype)
    Lpad = max(1 << (L - 1).bit_length(), 8)
    span = lo + np.arange(Lpad)
    take = jnp.asarray(np.minimum(span, max(nnz - 1, 0)), jnp.int32)
    valid = jnp.asarray(span < hi)
    idx = csr.indices[take]
    in_range = valid & (idx >= c0) & (idx < c1)
    indices = jnp.where(in_range, idx - c0, width)
    vals = jnp.where(in_range, csr.vals[take], 0)
    return _densify_rows(indices, vals, row_lens, block_rows, width)


def check_sparse_metric(metric) -> DistanceType:
    """Resolve + validate a metric for sparse inputs (the sparse engine's
    supported set is the dense table minus Haversine/Precomputed,
    mirroring the reference's sparse dispatch at
    sparse/distance/distance.cuh:76-127)."""
    metric = resolve_metric(metric)
    if metric in (DistanceType.Haversine, DistanceType.Precomputed):
        raise ValueError(f"{metric} not supported for sparse inputs")
    return metric


def pairwise_distance(
    x: CSR,
    y: CSR,
    metric="euclidean",
    metric_arg: float = 2.0,
    block_rows: Optional[int] = None,
    col_block: Optional[int] = None,
) -> jax.Array:
    """Full [m, n] distance matrix between sparse row sets.

    Mirrors the reference's sparse pairwiseDistance entry
    (sparse/distance/distance.cuh:76). Supports every dense metric except
    Haversine/Precomputed (the reference's sparse set is the same minus
    haversine). Blocks of ``block_rows`` query rows are densified and fed
    to the dense engine against the densified index.
    """
    metric = check_sparse_metric(metric)
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"feature dims differ: {x.shape} vs {y.shape}")
    m, n = x.shape[0], y.shape[0]
    D = x.shape[1]
    # vocab-sized feature dims: full-row densification collapses, switch
    # to the column-blocked engine (combine rules per metric)
    if col_block is None and D > 16384:
        col_block = 8192
    if col_block is not None and col_block < D:
        if metric not in (_COLBLOCK_DOT | _COLBLOCK_ADD | _COLBLOCK_MAX):
            raise ValueError(
                f"{metric} has no column-chunk combine rule; supported "
                "high-dim metrics: L2*/IP/Cosine/L1/Canberra/Linf"
            )
        br = block_rows or max(
            64, min(max(m, n), (64 << 20) // max(4 * col_block, 1)))
        return _pairwise_colblocked(x, y, metric, float(metric_arg),
                                    br, int(col_block))
    if block_rows is None:
        # ~64 MiB of densified block per side
        block_rows = max(64, min(m, (64 << 20) // max(4 * x.shape[1], 1)))
    out = []
    # densify per block *pair* so peak dense memory is two blocks (+ the
    # [m, n] output the API contract requires); y is re-densified per x
    # block only when it doesn't fit a single block
    single_y = densify_block(y, 0, n) if n <= block_rows else None
    for r0 in range(0, m, block_rows):
        r1 = min(r0 + block_rows, m)
        xb = densify_block(x, r0, r1)
        row = []
        for c0 in range(0, n, block_rows):
            c1 = min(c0 + block_rows, n)
            yb = single_y if single_y is not None else densify_block(y, c0, c1)
            row.append(
                _pairwise(xb, yb, int(metric), float(metric_arg), None, None)
            )
        out.append(row[0] if len(row) == 1 else jnp.concatenate(row, axis=1))
    return jnp.concatenate(out, axis=0)


# metrics the column-blocked (high-dim) engine supports, by combine rule
_COLBLOCK_DOT = frozenset({
    DistanceType.InnerProduct, DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded, DistanceType.L2Unexpanded,
    DistanceType.CosineExpanded,
})
_COLBLOCK_ADD = frozenset({DistanceType.L1, DistanceType.Canberra})
_COLBLOCK_MAX = frozenset({DistanceType.Linf})


def _pairwise_colblocked(x: CSR, y: CSR, metric: DistanceType,
                         metric_arg: float, block_rows: int,
                         col_block: int) -> jax.Array:
    """High-dimensional sparse pairwise distances: densify [rows, cols]
    TILES (bounded by block_rows x col_block regardless of the feature
    dim) and combine partial results across column chunks — the TPU
    answer to the reference's COO-SpMV strategies for vocab-sized dims
    (sparse/distance/detail/coo_spmv.cuh). Expanded metrics accumulate
    MXU dot blocks + per-chunk norms; additive metrics (L1, Canberra)
    sum chunk distances; Linf maxes them. Column chunks iterate OUTER of
    y blocks so each x tile densifies once per (row-block, col-chunk)."""
    m, n = x.shape[0], y.shape[0]
    D = x.shape[1]
    dot_like = metric in _COLBLOCK_DOT
    combine_max = metric in _COLBLOCK_MAX
    ip = metric == DistanceType.InnerProduct
    out = []
    ycuts = list(range(0, n, block_rows))
    for r0 in range(0, m, block_rows):
        r1 = min(r0 + block_rows, m)
        accs = [None] * len(ycuts)
        yn2s = [None] * len(ycuts)
        xn2 = None
        for d0 in range(0, D, col_block):
            d1 = min(d0 + col_block, D)
            xb = densify_block(x, r0, r1, d0, d1).astype(jnp.float32)
            if dot_like and not ip:
                px = jnp.sum(xb * xb, axis=1)
                xn2 = px if xn2 is None else xn2 + px
            for bi, c0 in enumerate(ycuts):
                c1 = min(c0 + block_rows, n)
                yb = densify_block(y, c0, c1, d0, d1).astype(jnp.float32)
                if dot_like:
                    part = dist_dot(xb, yb.T)
                    accs[bi] = part if accs[bi] is None else accs[bi] + part
                    if not ip:
                        py = jnp.sum(yb * yb, axis=1)
                        yn2s[bi] = (py if yn2s[bi] is None
                                    else yn2s[bi] + py)
                else:
                    part = _pairwise(xb, yb, int(metric),
                                     float(metric_arg), None, None)
                    if accs[bi] is None:
                        accs[bi] = part
                    elif combine_max:
                        accs[bi] = jnp.maximum(accs[bi], part)
                    else:
                        accs[bi] = accs[bi] + part
        rows = []
        for bi in range(len(ycuts)):
            acc, yn2 = accs[bi], yn2s[bi]
            if not dot_like or ip:
                blk = acc
            elif metric == DistanceType.CosineExpanded:
                denom = jnp.sqrt(
                    jnp.maximum(xn2[:, None] * yn2[None, :], 1e-30))
                blk = 1.0 - acc / denom
            else:
                blk = jnp.maximum(
                    xn2[:, None] + yn2[None, :] - 2.0 * acc, 0.0)
                if metric == DistanceType.L2SqrtExpanded:
                    blk = jnp.sqrt(blk)
            rows.append(blk)
        out.append(rows[0] if len(rows) == 1
                   else jnp.concatenate(rows, axis=1))
    return jnp.concatenate(out, axis=0)
