"""Sparse pairwise distances (reference sparse/distance/distance.cuh:76-127,
detail/{l2,ip,bin,lp}_distance.cuh + coo_spmv strategies).

TPU-first design decision: the reference's sparse engine is a family of
load-balanced COO-SpMV strategies because on a GPU the win is skipping
zero multiplies. On TPU the MXU makes dense FLOPs nearly free while
irregular gathers are expensive, so sparsity pays in *memory*, not FLOPs.
The engine therefore densifies VMEM-sized row blocks (a contiguous CSR row
range is one dynamic-slice + scatter) and rides the existing dense
pairwise engine (distance/pairwise.py) per block pair — GEMM + epilogue
for expanded metrics, tiled broadcast-reduce for the rest. Same numerics,
same metric table, one code path to test.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.pairwise import _pairwise
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.sparse.types import CSR
from raft_tpu.utils.math import cdiv


@functools.partial(jax.jit, static_argnums=(3, 4))
def _densify_rows(indices, vals, row_lens, block_rows: int, n_cols: int):
    """Scatter one padded row-block into dense [block_rows, n_cols].

    indices/vals are the block's entries padded to a static length with
    index == n_cols (dropped by the scatter); row_lens [block_rows] gives
    per-row entry counts so entries map to their rows.
    """
    L = indices.shape[0]
    row_of = jnp.searchsorted(
        jnp.cumsum(row_lens), jnp.arange(L, dtype=jnp.int32), side="right"
    ).astype(jnp.int32)
    dense = jnp.zeros((block_rows, n_cols + 1), vals.dtype)
    dense = dense.at[row_of, jnp.clip(indices, 0, n_cols)].add(
        jnp.where(indices < n_cols, vals, 0.0)
    )
    return dense[:, :n_cols]


def densify_block(csr: CSR, r0: int, r1: int) -> jax.Array:
    """Densify rows [r0, r1) of a CSR matrix. Host-orchestrated: the block's
    nnz span comes from indptr on the host, the scatter runs jitted. The
    entry slice is padded to the next power of two (padding scatters into
    the dropped guard column) so block nnz variation doesn't recompile
    ``_densify_rows`` per block."""
    indptr = np.asarray(csr.indptr)
    lo, hi = int(indptr[r0]), int(indptr[r1])
    block_rows = r1 - r0
    row_lens = csr.indptr[r0 + 1 : r1 + 1] - csr.indptr[r0:r1]
    L = hi - lo
    nnz, n_cols = csr.indices.shape[0], csr.shape[1]
    if nnz == 0 or L == 0:
        return jnp.zeros((block_rows, n_cols), csr.vals.dtype)
    Lpad = max(1 << (L - 1).bit_length(), 8)
    span = lo + np.arange(Lpad)
    take = jnp.asarray(np.minimum(span, max(nnz - 1, 0)), jnp.int32)
    valid = jnp.asarray(span < hi)
    indices = jnp.where(valid, csr.indices[take], n_cols)
    vals = jnp.where(valid, csr.vals[take], 0)
    return _densify_rows(indices, vals, row_lens, block_rows, n_cols)


def check_sparse_metric(metric) -> DistanceType:
    """Resolve + validate a metric for sparse inputs (the sparse engine's
    supported set is the dense table minus Haversine/Precomputed,
    mirroring the reference's sparse dispatch at
    sparse/distance/distance.cuh:76-127)."""
    metric = resolve_metric(metric)
    if metric in (DistanceType.Haversine, DistanceType.Precomputed):
        raise ValueError(f"{metric} not supported for sparse inputs")
    return metric


def pairwise_distance(
    x: CSR,
    y: CSR,
    metric="euclidean",
    metric_arg: float = 2.0,
    block_rows: Optional[int] = None,
) -> jax.Array:
    """Full [m, n] distance matrix between sparse row sets.

    Mirrors the reference's sparse pairwiseDistance entry
    (sparse/distance/distance.cuh:76). Supports every dense metric except
    Haversine/Precomputed (the reference's sparse set is the same minus
    haversine). Blocks of ``block_rows`` query rows are densified and fed
    to the dense engine against the densified index.
    """
    metric = check_sparse_metric(metric)
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"feature dims differ: {x.shape} vs {y.shape}")
    m, n = x.shape[0], y.shape[0]
    if block_rows is None:
        # ~64 MiB of densified block per side
        block_rows = max(64, min(m, (64 << 20) // max(4 * x.shape[1], 1)))
    out = []
    # densify per block *pair* so peak dense memory is two blocks (+ the
    # [m, n] output the API contract requires); y is re-densified per x
    # block only when it doesn't fit a single block
    single_y = densify_block(y, 0, n) if n <= block_rows else None
    for r0 in range(0, m, block_rows):
        r1 = min(r0 + block_rows, m)
        xb = densify_block(x, r0, r1)
        row = []
        for c0 in range(0, n, block_rows):
            c1 = min(c0 + block_rows, n)
            yb = single_y if single_y is not None else densify_block(y, c0, c1)
            row.append(
                _pairwise(xb, yb, int(metric), float(metric_arg), None, None)
            )
        out.append(row[0] if len(row) == 1 else jnp.concatenate(row, axis=1))
    return jnp.concatenate(out, axis=0)
