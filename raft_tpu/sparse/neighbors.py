"""Sparse neighbors: sparse brute-force KNN and KNN-graph construction
(reference sparse/neighbors/{brute_force,knn,knn_graph,
cross_component_nn}.cuh).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.matrix.select_k import select_k
from raft_tpu.sparse import distance as sparse_distance
from raft_tpu.sparse.types import COO, CSR


def brute_force_knn(
    x: CSR, y: CSR, k: int, metric="euclidean", metric_arg: float = 2.0,
    block_rows: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN between sparse query rows ``x`` and sparse index rows ``y``
    (reference sparse/neighbors/detail/knn.cuh brute_force_knn: tiled sparse
    pairwise + select_k per tile — the same structure here, with the tile
    distances coming from the densified-block engine).

    Returns (distances [m, k], indices [m, k]).
    """
    metric = sparse_distance.check_sparse_metric(metric)
    minim = is_min_close(metric)
    m, n = x.shape[0], y.shape[0]
    out_d, out_i = [], []
    # index side streams in blocks with a running top-k merge, so peak
    # dense memory is one block per side regardless of index size
    # (knn_merge_parts is the reference's detail/knn_merge_parts.cuh)
    from raft_tpu.neighbors.common import knn_merge_parts

    single_y = (
        sparse_distance.densify_block(y, 0, n) if n <= block_rows else None
    )
    for r0 in range(0, m, block_rows):
        r1 = min(r0 + block_rows, m)
        xb = sparse_distance.densify_block(x, r0, r1)
        part_d, part_i, offsets = [], [], []
        for c0 in range(0, n, block_rows):
            c1 = min(c0 + block_rows, n)
            yb = (
                single_y if single_y is not None
                else sparse_distance.densify_block(y, c0, c1)
            )
            d = sparse_distance._pairwise(
                xb, yb, int(metric), float(metric_arg), None, None
            )
            dd, ii = select_k(d, min(k, c1 - c0), select_min=minim)
            if dd.shape[1] < k:  # tiny tail block: pad to k for stacking
                pad = k - dd.shape[1]
                fill = jnp.inf if minim else -jnp.inf
                dd = jnp.pad(dd, ((0, 0), (0, pad)), constant_values=fill)
                ii = jnp.pad(ii, ((0, 0), (0, pad)), constant_values=-1)
            part_d.append(dd)
            part_i.append(ii)
            offsets.append(c0)
        if len(part_d) == 1:
            out_d.append(part_d[0])
            out_i.append(part_i[0])
        else:
            md, mi = knn_merge_parts(
                jnp.stack(part_d), jnp.stack(part_i), k,
                select_min=minim, translations=jnp.asarray(offsets),
            )
            # pad slots carry +-inf sentinels; keep their ids at -1
            # (translations shifted the -1 pads to look like real ids)
            sentinel = jnp.inf if minim else -jnp.inf
            mi = jnp.where(md == sentinel, -1, mi)
            out_d.append(md)
            out_i.append(mi)
    return jnp.concatenate(out_d, axis=0), jnp.concatenate(out_i, axis=0)


@functools.partial(jax.jit, static_argnums=(3, 5, 6, 7, 8))
def _score_block_dense_q(qs, yb, filter_bits, filter_nbits, col0, kb,
                         metric_val, minim, oor):
    from raft_tpu.neighbors.common import filter_keep

    d = sparse_distance._pairwise(qs, yb, metric_val, 2.0, None, None)
    sentinel = jnp.inf if minim else -jnp.inf
    cols = col0 + jnp.arange(yb.shape[0], dtype=jnp.int32)
    if filter_bits is not None:
        keep = filter_keep(filter_bits, filter_nbits,
                           jnp.broadcast_to(cols[None, :], d.shape),
                           out_of_range=oor)
        d = jnp.where(keep, d, sentinel)
    dd, ii = select_k(d, kb, select_min=minim)
    # global doc ids; sentinel slots (padding / filtered-out) stay -1
    ids = jnp.where(dd == sentinel, -1, col0 + ii.astype(jnp.int32))
    return dd, ids


def brute_force_knn_dense_queries(
    queries, docs: CSR, k: int, metric="inner_product",
    prefilter=None, block_rows: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN of DENSE query rows against a sparse CSR document
    matrix — the hybrid plan's lexical leg (ROADMAP 6(a)): the query
    batch is small and dense (the vocab slice of a hybrid query), the
    documents stay sparse at rest and densify one row block at a time.
    ``prefilter`` composes exactly like the dense scans (filter_keep
    over GLOBAL doc ids, so serve's tombstone masks work unchanged);
    dropped and padding slots return id -1 at the sentinel distance.

    Returns (distances [m, k], indices [m, k]), best-first.
    """
    from raft_tpu.neighbors.common import as_filter, knn_merge_parts

    metric = sparse_distance.check_sparse_metric(metric)
    minim = is_min_close(metric)
    queries = jnp.asarray(queries)
    n = docs.shape[0]
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for doc count {n}")
    filt = as_filter(prefilter)
    bits = getattr(filt, "bitset", None)
    oor = getattr(filt, "out_of_range", "drop")
    part_d, part_i = [], []
    for c0 in range(0, n, block_rows):
        c1 = min(c0 + block_rows, n)
        yb = sparse_distance.densify_block(docs, c0, c1)
        dd, ii = _score_block_dense_q(
            queries, yb,
            None if bits is None else bits.bits,
            None if bits is None else int(bits.n_bits),
            jnp.int32(c0), min(k, c1 - c0), int(metric), bool(minim), oor)
        if dd.shape[1] < k:  # tiny tail block: pad to k for stacking
            pad = k - dd.shape[1]
            fill = jnp.inf if minim else -jnp.inf
            dd = jnp.pad(dd, ((0, 0), (0, pad)), constant_values=fill)
            ii = jnp.pad(ii, ((0, 0), (0, pad)), constant_values=-1)
        part_d.append(dd)
        part_i.append(ii)
    if len(part_d) == 1:
        return part_d[0], part_i[0]
    # ids are already global (offset applied in-kernel): no translations
    md, mi = knn_merge_parts(jnp.stack(part_d), jnp.stack(part_i), k,
                             select_min=minim)
    sentinel = jnp.inf if minim else -jnp.inf
    return md, jnp.where(md == sentinel, -1, mi)


def knn_graph(
    x, k: int, metric="sqeuclidean", include_self: bool = False
) -> COO:
    """Symmetric KNN connectivity graph from dense rows (reference
    sparse/neighbors/knn_graph.cuh knn_graph — the single-linkage
    connectivity builder).

    Each row contributes its k nearest neighbors as weighted edges; the
    graph is returned un-symmetrized COO (callers symmetrize with
    sparse.op.symmetrize, as the reference's connectivities detail does).
    """
    from raft_tpu.neighbors import brute_force

    x = jnp.asarray(x)
    n = x.shape[0]
    kk = k if include_self else k + 1
    dist, idx = brute_force.knn(x, x, kk, metric=metric)
    if not include_self:
        # drop each row's self column (first hit at distance 0; guard the
        # degenerate duplicate-point case by masking where idx == row)
        rows = jnp.arange(n)[:, None]
        self_mask = idx == rows
        # ensure exactly one drop per row: prefer the self column, else col 0
        has_self = self_mask.any(axis=1)
        drop = jnp.where(has_self, jnp.argmax(self_mask, axis=1), 0)
        keep = jnp.arange(kk)[None, :] != drop[:, None]
        order = jnp.argsort(~keep, axis=1, stable=True)[:, : kk - 1]
        dist = jnp.take_along_axis(dist, order, axis=1)
        idx = jnp.take_along_axis(idx, order, axis=1)
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), idx.shape[1])
    return COO(
        rows, idx.reshape(-1).astype(jnp.int32),
        dist.reshape(-1).astype(jnp.float32), (n, n),
    )
