"""Linear assignment problem solver
(reference solver/linear_assignment.cuh ``LinearAssignmentProblem`` —
the Date–Nagi GPU Hungarian implementation).

TPU-first re-design: the Hungarian algorithm's augmenting-path search is
a serial frontier walk, which maps terribly to SPMD hardware; the
*auction algorithm* (Bertsekas) is its market dual and vectorizes
completely — every unassigned row bids in parallel (one [n, n] max +
top-2 pass on the MXU/VPU), objects resolve bids with a segment-max, and
ε-scaling phases drive the bid increments down until the assignment is
provably within n·ε of optimal (exact for integer costs once ε < 1/n).
Each phase is a single ``lax.while_loop`` — no host round-trips inside a
phase.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=(3,))
def _auction_phase(benefit, price, assign, n: int, eps):
    """Run parallel (Jacobi) auction rounds at one ε until all assigned.

    benefit [n, n]; price [n]; assign [n] person→object (-1 unassigned).
    """
    NEG = jnp.float32(-jnp.finfo(jnp.float32).max / 4)

    def cond(state):
        assign, price, it = state
        return jnp.any(assign < 0) & (it < 50 * n + 1000)

    def body(state):
        assign, price, it = state
        unass = assign < 0
        vals = benefit - price[None, :]                      # [n, n]
        top2, idx2 = jax.lax.top_k(vals, 2)
        j = idx2[:, 0]
        bid_amt = price[j] + (top2[:, 0] - top2[:, 1]) + eps  # [n]
        bid_amt = jnp.where(unass, bid_amt, NEG)
        # object side: winner = argmax bid (tie → lowest person id)
        best_bid = jnp.full((n,), NEG).at[j].max(bid_amt)
        is_best = unass & (bid_amt >= best_bid[j]) & (best_bid[j] > NEG)
        pid = jnp.where(is_best, jnp.arange(n, dtype=jnp.int32), n)
        winner = jnp.full((n,), n, jnp.int32).at[j].min(pid)  # [n] per object
        won_obj = winner < n                                  # objects w/ bid
        # evict previous owners of rebid objects
        prev_owner_lost = won_obj[jnp.where(assign >= 0, assign, 0)] & (
            assign >= 0
        ) & (winner[jnp.where(assign >= 0, assign, 0)]
             != jnp.arange(n, dtype=jnp.int32))
        assign = jnp.where(prev_owner_lost, -1, assign)
        # award objects to winners
        obj_of_winner = jnp.full((n,), -1, jnp.int32).at[
            jnp.where(won_obj, winner, n)
        ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
        assign = jnp.where(obj_of_winner >= 0, obj_of_winner, assign)
        price = jnp.where(won_obj, best_bid, price)
        return assign, price, it + 1

    assign, price, _ = jax.lax.while_loop(cond, body, (assign, price, 0))
    return assign, price


def solve(cost, maximize: bool = False, eps_scale: float = 4.0,
          final_eps: float | None = None) -> Tuple[jax.Array, jax.Array]:
    """Solve the square LAP. Returns (row_assignment [n], total_cost).

    ``row_assignment[i]`` is the column assigned to row i (the reference's
    ``getRowAssignmentVector``). ε-scaling runs from max|cost|/2 down by
    ``eps_scale`` per phase to ``final_eps`` (default 1/(n+1), the
    integer-exactness threshold).
    """
    cost = jnp.asarray(cost, jnp.float32)
    n = cost.shape[0]
    if cost.shape != (n, n):
        raise ValueError(f"square cost matrix required, got {cost.shape}")
    if n == 1:
        return jnp.zeros((1,), jnp.int32), cost[0, 0]
    benefit = cost if maximize else -cost
    # graft-lint: allow-host-sync auction epsilon schedule needs a concrete scale once per solve
    scale = float(jnp.max(jnp.abs(benefit)))
    eps = max(scale / 2.0, 1e-6)
    final = final_eps if final_eps is not None else 1.0 / (n + 1)
    price = jnp.zeros((n,), jnp.float32)
    assign = jnp.full((n,), -1, jnp.int32)
    while True:
        assign_new, price = _auction_phase(
            benefit, price, jnp.full((n,), -1, jnp.int32), n,
            jnp.float32(eps),
        )
        assign = assign_new
        if eps <= final:
            break
        eps = max(eps / eps_scale, final)
    total = jnp.sum(cost[jnp.arange(n), assign])
    return assign, total


class LinearAssignmentProblem:
    """Object API mirroring the reference class
    (solver/linear_assignment.cuh:44): ``solve`` + row/col assignment and
    dual accessors."""

    def __init__(self, size: int, batchsize: int = 1, epsilon: float = 1e-6):
        self.size = size
        self.batchsize = batchsize
        self.epsilon = epsilon
        self._row = None
        self._obj = None

    def solve(self, cost) -> None:
        cost = jnp.asarray(cost, jnp.float32)
        if cost.ndim == 2:
            cost = cost[None]
        rows, objs = [], []
        for b in range(cost.shape[0]):
            r, o = solve(cost[b])
            rows.append(r)
            objs.append(o)
        self._row = jnp.stack(rows)
        self._obj = jnp.stack(objs)

    def getRowAssignmentVector(self, b: int = 0):
        return self._row[b]

    def getColAssignmentVector(self, b: int = 0):
        r = self._row[b]
        n = r.shape[0]
        return jnp.zeros((n,), jnp.int32).at[r].set(
            jnp.arange(n, dtype=jnp.int32)
        )

    def getPrimalObjectiveValue(self, b: int = 0):
        return self._obj[b]
