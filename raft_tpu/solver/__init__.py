"""Solvers — linear assignment (reference cpp/include/raft/solver/
linear_assignment.cuh; legacy alias raft/lap/)."""

from raft_tpu.solver.linear_assignment import LinearAssignmentProblem, solve

__all__ = ["LinearAssignmentProblem", "solve"]
