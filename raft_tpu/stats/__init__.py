"""Stats layer: summary statistics + model-evaluation metrics.

Reference: cpp/include/raft/stats/ (SURVEY.md §2.10).
"""

from raft_tpu.stats.moments import (
    cluster_dispersion,
    cov,
    histogram,
    mean,
    mean_center,
    meanvar,
    minmax,
    stddev,
    weighted_mean,
)
from raft_tpu.stats.metrics import (
    accuracy,
    adjusted_rand_index,
    completeness_score,
    entropy,
    homogeneity_score,
    information_criterion,
    mutual_info_score,
    neighborhood_recall,
    r2_score,
    rand_index,
    regression_metrics,
    silhouette_score,
    trustworthiness_score,
    v_measure,
)

__all__ = [
    "mean", "stddev", "cov", "minmax", "meanvar", "histogram",
    "weighted_mean", "mean_center", "cluster_dispersion",
    "accuracy", "r2_score", "regression_metrics",
    "adjusted_rand_index", "rand_index", "silhouette_score", "v_measure",
    "mutual_info_score", "entropy", "homogeneity_score",
    "completeness_score", "information_criterion",
    "neighborhood_recall", "trustworthiness_score",
]
