"""Summary statistics (reference raft/stats/{mean,stddev,cov,minmax,meanvar,
histogram,weighted_mean}.cuh). All are thin jit-compatible reductions — the
reference needs custom CUDA kernels for these; XLA fuses them for free."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def mean(data, along_rows: bool = True) -> jax.Array:
    """Column means (reference stats/mean.cuh). ``along_rows=True`` averages
    over rows (the reference's rowMajor sample-major convention)."""
    return jnp.mean(jnp.asarray(data), axis=0 if along_rows else 1)


def stddev(data, mu=None, sample: bool = False) -> jax.Array:
    """Column standard deviations (reference stats/stddev.cuh)."""
    data = jnp.asarray(data)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    var = jnp.mean((data - mu[None, :]) ** 2, axis=0)
    if sample:
        n = data.shape[0]
        var = var * n / jnp.maximum(n - 1, 1)
    return jnp.sqrt(var)


def meanvar(data, sample: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Fused mean+variance (reference stats/meanvar.cuh)."""
    data = jnp.asarray(data)
    mu = jnp.mean(data, axis=0)
    var = jnp.mean((data - mu[None, :]) ** 2, axis=0)
    if sample:
        n = data.shape[0]
        var = var * n / jnp.maximum(n - 1, 1)
    return mu, var


def mean_center(data, mu=None) -> jax.Array:
    """Subtract column means (reference stats/mean_center.cuh)."""
    data = jnp.asarray(data)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    return data - mu[None, :]


def cov(data, mu=None, sample: bool = True) -> jax.Array:
    """Covariance matrix (reference stats/cov.cuh): centered gram / (n-1)."""
    data = jnp.asarray(data).astype(jnp.float32)
    n = data.shape[0]
    if mu is None:
        mu = jnp.mean(data, axis=0)
    c = data - mu[None, :]
    denom = jnp.maximum(n - 1, 1) if sample else n
    return jnp.dot(
        c.T, c, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ) / denom


def minmax(data) -> Tuple[jax.Array, jax.Array]:
    """Column-wise (min, max) (reference stats/minmax.cuh)."""
    data = jnp.asarray(data)
    return jnp.min(data, axis=0), jnp.max(data, axis=0)


def weighted_mean(data, weights, along_rows: bool = True) -> jax.Array:
    """Weighted mean (reference stats/weighted_mean.cuh)."""
    data = jnp.asarray(data).astype(jnp.float32)
    w = jnp.asarray(weights).astype(jnp.float32)
    axis = 0 if along_rows else 1
    ws = w[:, None] if axis == 0 else w[None, :]
    return (data * ws).sum(axis) / jnp.maximum(w.sum(), 1e-30)


def histogram(data, n_bins: int, lo=None, hi=None) -> Tuple[jax.Array, jax.Array]:
    """Per-column histogram (reference stats/histogram.cuh).

    Returns (counts [n_bins, n_cols], edges [n_bins+1])."""
    data = jnp.asarray(data)
    if data.ndim == 1:
        data = data[:, None]
    lo = jnp.min(data) if lo is None else lo
    hi = jnp.max(data) if hi is None else hi
    edges = jnp.linspace(lo, hi, n_bins + 1)
    scaled = (data - lo) / jnp.maximum(hi - lo, 1e-30) * n_bins
    bins = jnp.clip(scaled.astype(jnp.int32), 0, n_bins - 1)
    one_hot = bins[:, :, None] == jnp.arange(n_bins)[None, None, :]
    return one_hot.sum(axis=0).T.astype(jnp.int32), edges


def cluster_dispersion(
    centroids, cluster_sizes, n_points: Optional[int] = None
) -> jax.Array:
    """Between-cluster dispersion (reference stats/dispersion.cuh:84):
    sqrt(sum_i sizes_i * ||c_i - mu||^2) with mu the size-weighted centroid
    mean over n_points."""
    centroids = jnp.asarray(centroids, jnp.float32)
    sizes = jnp.asarray(cluster_sizes, jnp.float32)
    n = jnp.float32(n_points) if n_points is not None else sizes.sum()
    mu = (sizes[:, None] * centroids).sum(axis=0) / jnp.maximum(n, 1.0)
    diff = centroids - mu[None, :]
    return jnp.sqrt(jnp.sum(sizes * jnp.sum(diff * diff, axis=1)))
