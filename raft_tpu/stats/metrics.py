"""Model-evaluation metrics (reference raft/stats/: accuracy, r2_score,
regression_metrics, adjusted_rand_index, mutual_info, entropy,
homogeneity/completeness/v_measure, silhouette_score,
information_criterion, trustworthiness, and the ANN-evaluation
``neighborhood_recall`` — stats/neighborhood_recall.cuh:86,171)."""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.utils.precision import dist_dot


# ---------------------------------------------------------------------------
# regression / classification
# ---------------------------------------------------------------------------


def accuracy(predictions, labels) -> jax.Array:
    """Fraction of exact matches (reference stats/accuracy.cuh)."""
    predictions = jnp.asarray(predictions)
    labels = jnp.asarray(labels)
    return jnp.mean((predictions == labels).astype(jnp.float32))


def r2_score(y, y_hat) -> jax.Array:
    """Coefficient of determination (reference stats/r2_score.cuh)."""
    y = jnp.asarray(y).astype(jnp.float32)
    y_hat = jnp.asarray(y_hat).astype(jnp.float32)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30)


def regression_metrics(predictions, ref) -> dict:
    """MAE / MSE / median-AE (reference stats/regression_metrics.cuh)."""
    p = jnp.asarray(predictions).astype(jnp.float32)
    r = jnp.asarray(ref).astype(jnp.float32)
    abs_diff = jnp.abs(p - r)
    return {
        "mean_abs_error": jnp.mean(abs_diff),
        "mean_squared_error": jnp.mean((p - r) ** 2),
        "median_abs_error": jnp.median(abs_diff),
    }


# ---------------------------------------------------------------------------
# clustering metrics
# ---------------------------------------------------------------------------


def _contingency(a, b, n_classes_a: int, n_classes_b: int) -> jax.Array:
    a = jnp.asarray(a).astype(jnp.int32)
    b = jnp.asarray(b).astype(jnp.int32)
    oh_a = (a[:, None] == jnp.arange(n_classes_a)[None, :]).astype(jnp.float32)
    oh_b = (b[:, None] == jnp.arange(n_classes_b)[None, :]).astype(jnp.float32)
    return dist_dot(oh_a.T, oh_b)  # [Ca, Cb]


def _n_classes(x) -> int:
    import numpy as np

    return int(np.asarray(x).max()) + 1


def rand_index(a, b) -> jax.Array:
    """Unadjusted Rand index (reference stats/rand_index.cuh)."""
    ca, cb = _n_classes(a), _n_classes(b)
    m = _contingency(a, b, ca, cb)
    n = jnp.asarray(a).shape[0]
    sum_comb = jnp.sum(m * (m - 1) / 2)
    sum_a = jnp.sum(m.sum(1) * (m.sum(1) - 1) / 2)
    sum_b = jnp.sum(m.sum(0) * (m.sum(0) - 1) / 2)
    total = n * (n - 1) / 2
    return (total + 2 * sum_comb - sum_a - sum_b) / total


def adjusted_rand_index(a, b) -> jax.Array:
    """ARI (reference stats/adjusted_rand_index.cuh)."""
    ca, cb = _n_classes(a), _n_classes(b)
    m = _contingency(a, b, ca, cb)
    n = jnp.asarray(a).shape[0]
    sum_comb = jnp.sum(m * (m - 1) / 2)
    sum_a = jnp.sum(m.sum(1) * (m.sum(1) - 1) / 2)
    sum_b = jnp.sum(m.sum(0) * (m.sum(0) - 1) / 2)
    total = n * (n - 1) / 2
    expected = sum_a * sum_b / jnp.maximum(total, 1e-30)
    max_index = (sum_a + sum_b) / 2
    return (sum_comb - expected) / jnp.maximum(max_index - expected, 1e-30)


def entropy(labels, n_classes: Optional[int] = None) -> jax.Array:
    """Shannon entropy of a labeling (reference stats/entropy.cuh)."""
    labels = jnp.asarray(labels)
    c = n_classes if n_classes is not None else _n_classes(labels)
    counts = jnp.bincount(labels.astype(jnp.int32), length=c).astype(jnp.float32)
    p = counts / jnp.maximum(counts.sum(), 1e-30)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def mutual_info_score(a, b) -> jax.Array:
    """Mutual information (reference stats/mutual_info_score.cuh)."""
    ca, cb = _n_classes(a), _n_classes(b)
    m = _contingency(a, b, ca, cb)
    n = jnp.maximum(m.sum(), 1e-30)
    pij = m / n
    pi = pij.sum(1, keepdims=True)
    pj = pij.sum(0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-30)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(ratio), 0.0))


def homogeneity_score(truth, pred) -> jax.Array:
    """(reference stats/homogeneity_score.cuh)."""
    mi = mutual_info_score(truth, pred)
    h = entropy(truth)
    return jnp.where(h > 0, mi / h, 1.0)


def completeness_score(truth, pred) -> jax.Array:
    """(reference stats/completeness_score.cuh)."""
    return homogeneity_score(pred, truth)


def v_measure(truth, pred, beta: float = 1.0) -> jax.Array:
    """(reference stats/v_measure.cuh)."""
    h = homogeneity_score(truth, pred)
    c = completeness_score(truth, pred)
    return (1 + beta) * h * c / jnp.maximum(beta * h + c, 1e-30)


def silhouette_score(x, labels, n_classes: Optional[int] = None) -> jax.Array:
    """Mean silhouette coefficient (reference stats/silhouette_score.cuh).

    Computed from the full pairwise-distance matrix — suitable for the same
    sample sizes the reference's batched variant targets."""
    x = jnp.asarray(x).astype(jnp.float32)
    labels = jnp.asarray(labels).astype(jnp.int32)
    c = n_classes if n_classes is not None else _n_classes(labels)
    n = x.shape[0]
    xn = jnp.sum(x * x, axis=1)
    d = jnp.sqrt(jnp.maximum(
        xn[:, None] + xn[None, :] - 2.0 * dist_dot(x, x.T), 0.0))
    one_hot = (labels[:, None] == jnp.arange(c)[None, :]).astype(jnp.float32)
    # mean distance of sample i to every cluster: [n, c]
    sums = dist_dot(d, one_hot)
    counts = one_hot.sum(0)[None, :]
    own = one_hot.astype(bool)
    # a(i): mean dist to own cluster, excluding self
    own_count = jnp.take_along_axis(
        jnp.broadcast_to(counts, (n, c)), labels[:, None], 1)[:, 0]
    a = jnp.take_along_axis(sums, labels[:, None], 1)[:, 0] / jnp.maximum(
        own_count - 1, 1)
    # b(i): min over other *non-empty* clusters of mean dist (an empty
    # class id would otherwise contribute a spurious 0)
    means = sums / jnp.maximum(counts, 1)
    means = jnp.where(own | (counts == 0), jnp.inf, means)
    b = jnp.min(means, axis=1)
    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
    # singleton clusters contribute 0
    s = jnp.where(own_count > 1, s, 0.0)
    return jnp.mean(s)


def information_criterion(
    log_likelihood, n_params: int, n_samples: int, kind: str = "aic"
):
    """AIC / AICc / BIC (reference stats/information_criterion.cuh)."""
    ll = jnp.asarray(log_likelihood)
    if kind == "aic":
        return -2.0 * ll + 2.0 * n_params
    if kind == "aicc":
        corr = 2.0 * n_params * (n_params + 1) / max(n_samples - n_params - 1, 1)
        return -2.0 * ll + 2.0 * n_params + corr
    if kind == "bic":
        return -2.0 * ll + n_params * math.log(max(n_samples, 1))
    raise ValueError(f"unknown criterion {kind!r}")


# ---------------------------------------------------------------------------
# neighborhood metrics (ANN evaluation)
# ---------------------------------------------------------------------------


def neighborhood_recall(
    indices, ref_indices, distances=None, ref_distances=None, eps: float = 1e-3
) -> jax.Array:
    """Recall of ANN results vs ground truth with distance-tie tolerance
    (reference stats/neighborhood_recall.cuh:86). [m, k] each."""
    indices = jnp.asarray(indices)
    ref_indices = jnp.asarray(ref_indices)
    match = (indices[:, :, None] == ref_indices[:, None, :]).any(-1)
    if distances is not None and ref_distances is not None:
        distances = jnp.asarray(distances)
        ref_distances = jnp.asarray(ref_distances)
        # a miss whose distance ties the reference counts as a hit
        tie = (
            jnp.abs(distances[:, :, None] - ref_distances[:, None, :]) <= eps
        ).any(-1)
        match = match | tie
    return jnp.mean(match.astype(jnp.float32))


def trustworthiness_score(x, x_embedded, n_neighbors: int = 5) -> jax.Array:
    """Embedding trustworthiness (reference stats/trustworthiness_score.cuh).

    T(k) = 1 - 2/(n k (2n - 3k - 1)) * sum_i sum_{j in kNN_emb(i) \\ kNN_x(i)}
    (rank_x(i, j) - k)."""
    x = jnp.asarray(x).astype(jnp.float32)
    e = jnp.asarray(x_embedded).astype(jnp.float32)
    n = x.shape[0]
    k = n_neighbors

    def sqdist(a):
        an = jnp.sum(a * a, axis=1)
        d = an[:, None] + an[None, :] - 2.0 * dist_dot(a, a.T)
        return d.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)

    dx = sqdist(x)
    de = sqdist(e)
    # rank of each point in x-space per row (0 = nearest)
    order_x = jnp.argsort(dx, axis=1)
    ranks_x = jnp.zeros((n, n), jnp.int32)
    ranks_x = jax.vmap(
        lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32))
    )(ranks_x, order_x)
    # k nearest in embedding space
    knn_e = jnp.argsort(de, axis=1)[:, :k]
    r = jnp.take_along_axis(ranks_x, knn_e, axis=1)  # [n, k]
    penalty = jnp.sum(jnp.maximum(r - k + 1, 0).astype(jnp.float32))
    denom = n * k * (2.0 * n - 3.0 * k - 1.0)
    return 1.0 - 2.0 / denom * penalty
