"""Comms session registry — the raft-dask session-management analog.

The reference keeps a per-worker registry of initialized comms sessions
(``Comms.init`` broadcasts an NCCL uniqueId, each dask worker stores
``{sessionId: {nccl, ucx, handle, ...}}`` and consumers fetch the
worker-local handle by sessionId:
python/raft-dask/raft_dask/common/comms.py:173 ``Comms.init``,
:248 ``local_handle``, :269 ``get_raft_comm_state``).

On TPU the roles map as: one JAX *process* is one worker; the process
group is established once by ``raft_tpu.bootstrap.init_multihost``
(jax.distributed — the runtime owns rank discovery, so there is no
uniqueId exchange); a *session* is then a named (mesh, axis) binding
with its injected-comms handle. Multiple sessions can coexist per
process (e.g. a global mesh session and a sub-mesh session), matching
the multiple-dask-session model.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

from jax.sharding import Mesh

# per-process session registry: the `_raft_comm_state` worker attribute
# of the reference (one process == one worker here)
_comm_state: dict = {}


class CommsSession:
    """Session-scoped comms initializer (reference ``Comms``,
    raft_dask/common/comms.py:84).

    >>> s = CommsSession(mesh)        # or CommsSession() for all devices
    >>> s.init()
    >>> h = local_handle(s.sessionId) # DeviceResources with comms bound
    >>> ... shard_map(lambda x: h.comms.allreduce(x), ...)
    >>> s.destroy()
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 axis_name: str = "shard", seed: int = 0,
                 verbose: bool = False):
        self.sessionId = uuid.uuid4().hex
        self._mesh = mesh
        self.axis_name = axis_name
        self.seed = seed
        self.verbose = verbose
        self.initialized = False

    def init(self) -> "CommsSession":
        """Create this session's worker-local state: the bound mesh, the
        ``Comms`` facade, and a handle with the comms injected (the
        reference's ``_func_init_all`` on every worker)."""
        from raft_tpu.comms.comms import Comms, default_mesh
        from raft_tpu.core.resources import DeviceResources

        if self.initialized:
            import warnings

            warnings.warn("Comms have already been initialized.")
            return self
        mesh = (self._mesh if self._mesh is not None
                else default_mesh(axis_name=self.axis_name))
        comms = Comms(mesh, self.axis_name)
        handle = DeviceResources(seed=self.seed, mesh=mesh)
        handle.set_comms(comms)
        state = get_comm_state(self.sessionId)
        state.update({"mesh": mesh, "comms": comms, "handle": handle})
        self.initialized = True
        if self.verbose:
            print(f"comms session {self.sessionId} initialized "
                  f"({mesh.shape[self.axis_name]} devices)")
        return self

    def destroy(self) -> None:
        """Drop the session's registry state (``_func_destroy_all``)."""
        _comm_state.pop(self.sessionId, None)
        self.initialized = False

    def __enter__(self) -> "CommsSession":
        return self.init()

    def __exit__(self, *exc) -> None:
        self.destroy()


def get_comm_state(sessionId: Optional[str]) -> dict:
    """Worker-local state dict for a session, created (timestamp-only)
    if absent; with ``sessionId=None`` returns all sessions — mirroring
    ``get_raft_comm_state`` (raft_dask/common/comms.py:269)."""
    if sessionId is None:
        return _comm_state
    if sessionId not in _comm_state:
        _comm_state[sessionId] = {"ts": time.time()}
    return _comm_state[sessionId]


def session_handle(sessionId: str):
    """The worker-local handle for an initialized session, or None —
    the raft-dask ``local_handle(sessionId)`` (comms.py:248). Named
    ``session_handle`` because ``raft_tpu.comms.local_handle`` already
    provides the sessionless mesh->handle shortcut."""
    state = get_comm_state(sessionId)
    return state.get("handle")
