"""Distributed communication layer (SURVEY.md §2.12).

The reference exposes a virtual ``comms_iface`` (allreduce/bcast/allgather/
reducescatter/p2p/comm_split/barrier — core/comms.hpp:123-230) implemented
over NCCL+UCX (comms/std_comms.hpp) or MPI (comms/mpi_comms.hpp), injected
into the handle. The TPU-native equivalent keeps the facade but implements
every collective with ``jax.lax`` primitives over a mesh axis inside
``shard_map`` — XLA lowers them onto ICI rings (and DCN across slices), so
there is no NCCL/UCX analog to manage and no streams to sync.

Use: build a ``Comms`` from a mesh axis; inside ``shard_map``-decorated
functions call its methods (they are thin names over jax.lax collectives);
``comm_split`` maps to operating on a sub-axis of the mesh.
"""

from raft_tpu.comms.comms import Comms, default_mesh, local_handle
from raft_tpu.comms.ops import (
    allgather,
    allreduce,
    alltoall,
    barrier,
    bcast,
    device_multicast_sendrecv,
    device_sendrecv,
    gather,
    reduce,
    reducescatter,
)
from raft_tpu.comms.session import (
    CommsSession,
    get_comm_state,
    session_handle,
)
from raft_tpu.comms.procgroup import (
    LocalGroup,
    ProcGroup,
    WorkerRuntime,
)
from raft_tpu.comms.sharded import (
    sharded_cagra_build,
    sharded_cagra_search,
    sharded_ivf_build,
    sharded_ivf_pq_build,
    sharded_ivf_pq_search,
    sharded_ivf_row_search,
    sharded_ivf_search,
    sharded_knn,
    sharded_pairwise_distance,
)

__all__ = [
    "Comms",
    "LocalGroup",
    "ProcGroup",
    "WorkerRuntime",
    "default_mesh",
    "local_handle",
    "allreduce",
    "allgather",
    "alltoall",
    "barrier",
    "bcast",
    "reduce",
    "gather",
    "reducescatter",
    "device_sendrecv",
    "device_multicast_sendrecv",
    "sharded_cagra_build",
    "sharded_cagra_search",
    "sharded_ivf_build",
    "CommsSession",
    "get_comm_state",
    "session_handle",
    "sharded_ivf_pq_build",
    "sharded_ivf_pq_search",
    "sharded_ivf_row_search",
    "sharded_ivf_search",
    "sharded_knn",
    "sharded_pairwise_distance",
]
