"""Collective operations over a mesh axis.

Each function mirrors one virtual of the reference's ``comms_iface``
(core/comms.hpp:123-230) and must be called inside ``shard_map`` (or pmap)
with the named axis bound. XLA lowers these to ICI/DCN collectives — the
NCCL ring the reference manages by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.comms.compat import axis_size


def allreduce(x, axis_name: str, op: str = "sum"):
    """comms_iface::allreduce (core/comms.hpp)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError(f"unsupported reduce op {op!r}")


def reduce(x, axis_name: str, root: int = 0, op: str = "sum"):
    """comms_iface::reduce — result valid on root, zeros elsewhere."""
    full = allreduce(x, axis_name, op)
    rank = lax.axis_index(axis_name)
    return jnp.where(rank == root, full, jnp.zeros_like(full))

def bcast(x, axis_name: str, root: int = 0):
    """comms_iface::bcast — every rank gets root's value."""
    rank = lax.axis_index(axis_name)
    masked = jnp.where(rank == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = False):
    """comms_iface::allgather(v)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def gather(x, axis_name: str, root: int = 0, axis: int = 0):
    """comms_iface::gather — gathered result on root (others get zeros)."""
    full = lax.all_gather(x, axis_name, axis=axis, tiled=True)
    rank = lax.axis_index(axis_name)
    return jnp.where(rank == root, full, jnp.zeros_like(full))


def reducescatter(x, axis_name: str, scatter_axis: int = 0):
    """comms_iface::reducescatter."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def alltoall(x, axis_name: str, split_axis: int = 0, concat_axis: int = 0):
    """Dense all-to-all (no direct reference virtual; std_comms implements
    p2p equivalents). Used by IVF multi-shard query routing."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def device_sendrecv(x, axis_name: str, shift: int = 1):
    """comms_iface::device_sendrecv — ring permute by ``shift``
    (ppermute rides ICI neighbor links)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def device_multicast_sendrecv(x, axis_name: str, shifts):
    """comms_iface::device_multicast_sendrecv — sum of several ring shifts."""
    out = jnp.zeros_like(x)
    for s in shifts:
        out = out + device_sendrecv(x, axis_name, s)
    return out


def barrier(axis_name: str):
    """comms_iface::barrier — a collective no-op that forces rendezvous."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)
