"""jax version compatibility for ``shard_map``.

The sharded layer targets the modern API (``jax.shard_map`` with the
``check_vma`` knob, jax >= 0.7); older runtimes ship it as
``jax.experimental.shard_map.shard_map`` with the same knob spelled
``check_rep``. One wrapper keeps every call site on the modern spelling
so the comms layer (and its tests) import on both."""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.7

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis (``lax.axis_size`` on modern
    jax; reconstructed from the axis env on older runtimes, where
    ``core.axis_frame`` hands back the size directly)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as jc

    frame = jc.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size
