"""Comms facade + bootstrap.

``Comms`` is the analog of the reference's value-facade ``comms_t``
(core/comms.hpp:252) bound to a mesh axis, injected into the handle the way
std_comms is injected via the COMMUNICATOR slot
(core/resource/comms.hpp). The raft-dask bootstrap
(python/raft-dask/raft_dask/common/comms.py:173 ``Comms.init`` + NCCL
unique-id broadcast) collapses to: construct a Mesh (single-host) or call
``jax.distributed.initialize`` (multi-host) — the TPU runtime owns rank
discovery, so there is no unique-id exchange to implement.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.comms import ops as _ops
from raft_tpu.core.resources import DeviceResources


def default_mesh(n_devices: Optional[int] = None, axis_name: str = "shard") -> Mesh:
    """Build a 1-D mesh over the first n devices (all by default)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


class Comms:
    """Communicator bound to one mesh axis (reference comms_t).

    rank/size are static per-callsite inside shard_map; the collective
    methods simply forward to raft_tpu.comms.ops with the bound axis.
    ``comm_split`` returns a Comms on another axis of the same mesh —
    the reference's sub-communicator concept (core/comms.hpp comm_split;
    SUB_COMMUNICATOR slot).
    """

    def __init__(self, mesh: Mesh, axis_name: str = "shard"):
        if axis_name not in mesh.axis_names:
            raise ValueError(f"axis {axis_name!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis_name]

    def rank(self):
        """Callable only inside shard_map (like comms_t::get_rank on-device)."""
        return jax.lax.axis_index(self.axis_name)

    def comm_split(self, axis_name: str) -> "Comms":
        return Comms(self.mesh, axis_name)

    # -- collectives (inside shard_map) ------------------------------------
    def allreduce(self, x, op: str = "sum"):
        return _ops.allreduce(x, self.axis_name, op)

    def bcast(self, x, root: int = 0):
        return _ops.bcast(x, self.axis_name, root)

    def reduce(self, x, root: int = 0, op: str = "sum"):
        return _ops.reduce(x, self.axis_name, root, op)

    def allgather(self, x, axis: int = 0, tiled: bool = False):
        return _ops.allgather(x, self.axis_name, axis, tiled)

    def gather(self, x, root: int = 0, axis: int = 0):
        return _ops.gather(x, self.axis_name, root, axis)

    def reducescatter(self, x, scatter_axis: int = 0):
        return _ops.reducescatter(x, self.axis_name, scatter_axis)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        return _ops.alltoall(x, self.axis_name, split_axis, concat_axis)

    def device_sendrecv(self, x, shift: int = 1):
        return _ops.device_sendrecv(x, self.axis_name, shift)

    def barrier(self):
        return _ops.barrier(self.axis_name)


def local_handle(
    mesh: Optional[Mesh] = None,
    axis_name: str = "shard",
    seed: int = 0,
) -> DeviceResources:
    """Handle with an injected communicator — the raft-dask
    ``local_handle(sessionId)`` analog (raft_dask/common/comms.py:248)."""
    mesh = mesh if mesh is not None else default_mesh(axis_name=axis_name)
    h = DeviceResources(seed=seed, mesh=mesh)
    h.set_comms(Comms(mesh, axis_name))
    return h


# Multi-host bootstrap lives in raft_tpu.bootstrap (import-light):
# jax.distributed.initialize must run before anything touches the XLA
# backend, and importing THIS package already does — so a bootstrap
# entry point here could never succeed and is deliberately not provided.
