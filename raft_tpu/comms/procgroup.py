"""Process-boundary worker groups for the multi-host serving fabric.

The reference's cluster tier is raft-dask: one OS process per GPU, an
index shard per worker, queries broadcast and per-worker top-ks merged
(PAPER.md; raft_dask/common/comms.py). This module is the TPU-repo
analog of that *process* layer — everything above one process boundary
and below the router (:mod:`raft_tpu.serve.fabric`):

* :class:`WorkerRuntime` — the worker-side state machine. It owns
  per-generation shard indexes (built with the repo's own
  ``brute_force``/``ivf_flat`` paths, warmed at prepare time) and
  answers a small RPC vocabulary: ``search`` / ``ping`` (data plane)
  and ``prepare`` / ``publish`` / ``abort`` / ``retire`` (the two-phase
  hot-swap control plane, docs/serving.md §10).
* :class:`ProcGroup` — N real ``multiprocessing`` (spawn) children,
  one :class:`WorkerRuntime` each, request/response queues per worker
  and a parent-side receiver thread matching responses to futures.
  This is the tier the SIGKILL / machine-loss failure modes live in.
* :class:`LocalGroup` — the in-process twin: the SAME runtime on
  daemon threads. Every router behavior (hedging, circuit breaking,
  two-phase swap, coverage) is exercised without process-spawn cost —
  the fabric counterpart of the CPU-mesh
  ``--xla_force_host_platform_device_count`` strategy the sharded
  tests use.

Failure semantics are *absences*, not exceptions: a dead worker never
answers (the router diagnoses the timeout), a dropped RPC loses only
its response, a slow worker answers late enough to trigger hedging.
The deterministic fault points come from
:func:`raft_tpu.resilience.faultinject.proc_action` /
:func:`~raft_tpu.resilience.faultinject.rpc_dropped`
(``dead@proc:R``, ``slow@proc:R*K``, ``drop@rpc:METHOD``).
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue as _pyqueue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.analysis import lockwatch
from raft_tpu.obs import trace as obs_trace
from raft_tpu.resilience import errors as _rerrors
from raft_tpu.resilience import faultinject

# sentinel statuses a worker's handle() can return instead of a reply
DIE = "__die__"       # hard-exit, no response (dead@proc)
DROP = "__drop__"     # swallow the response (drop@rpc)

# methods that count as the data plane: dead@proc / slow@proc faults
# fire here (a worker that died takes its control plane with it anyway,
# but arming death on control RPCs would kill workers during their own
# bootstrap prepare/publish — nondeterministic and not the failure mode
# under test)
DATA_PLANE = ("search", "ping")

_NO_GEN = "no_gen"


class RemoteWorkerError(RuntimeError):
    """A failure serialized back from a worker process. ``fault_kind``
    carries the worker-side :func:`raft_tpu.resilience.classify`
    verdict so the router's classification agrees with the worker's."""

    def __init__(self, msg: str, kind: Optional[str] = None):
        super().__init__(msg)
        if kind in _rerrors.KINDS:
            self.fault_kind = kind


def is_no_gen(exc: BaseException) -> bool:
    """True when a worker rejected an RPC because it does not hold the
    requested generation — a *stale* worker (missed a publish while
    partitioned), not a broken one; the router re-syncs instead of
    circuit-breaking."""
    return _NO_GEN in str(exc)


def _remote_error(payload: dict) -> RemoteWorkerError:
    return RemoteWorkerError(
        str(payload.get("error", "worker error")),
        kind=payload.get("kind"),
    )


# ---------------------------------------------------------------------------
# shard index construction/search — shared by workers and the tests'
# surviving-shard oracle (bitwise identity demands one code path)
# ---------------------------------------------------------------------------


def build_shard_entry(vectors: np.ndarray, offset: int,
                      algo: str = "brute_force") -> tuple:
    """Build one shard's index over ``vectors`` whose global row ids
    start at ``offset``. Returns an opaque entry for
    :func:`search_shard_entry`."""
    vectors = np.ascontiguousarray(vectors, dtype=np.float32)
    if algo == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat

        params = ivf_flat.IndexParams(
            n_lists=max(1, min(16, vectors.shape[0] // 8)))
        idx = ivf_flat.build(params, vectors)
        # exhaustive probing: the fabric's correctness contract is that
        # a covered shard's answer is exact for that shard
        sp = ivf_flat.SearchParams(n_probes=idx.n_lists,
                                   compute_dtype="f32",
                                   local_recall_target=1.0)
        return ("ivf_flat", idx, sp, int(offset), int(vectors.shape[0]))
    from raft_tpu.neighbors import brute_force

    idx = brute_force.build(vectors)
    return ("brute_force", idx, None, int(offset), int(vectors.shape[0]))


def search_shard_entry(entry: tuple, q: np.ndarray,
                       k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Search one shard entry at ``k``, returning host ``(d, i)`` with
    GLOBAL row ids, column-padded to exactly ``k`` with the
    worst-possible sentinel (a shard smaller than ``k`` can only
    contribute its real rows)."""
    algo, idx, sp, offset, rows = entry
    kq = int(min(k, rows))
    if algo == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat

        # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path: the fabric worker runs one per-shard scan; the router owns the multi-stage tail
        d, i = ivf_flat.search(sp, idx, q, kq)
    else:
        from raft_tpu.neighbors import brute_force

        # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path: exact per-shard scan, no pipeline to plan
        d, i = brute_force.search(idx, q, kq)
    d = np.asarray(d).astype(np.float32, copy=False)
    i = np.asarray(i).astype(np.int32, copy=False)
    i = np.where(i >= 0, i + np.int32(offset), np.int32(-1))
    if kq < k:
        pad = k - kq
        d = np.concatenate(
            [d, np.full((d.shape[0], pad), np.inf, np.float32)], axis=1)
        i = np.concatenate(
            [i, np.full((i.shape[0], pad), -1, np.int32)], axis=1)
    return d, i


# ---------------------------------------------------------------------------
# the worker-side state machine
# ---------------------------------------------------------------------------


class WorkerRuntime:
    """One fabric worker's state: per-generation shard indexes and the
    RPC vocabulary. Transport-agnostic — :class:`ProcGroup` runs one
    per child process, :class:`LocalGroup` one per daemon thread."""

    def __init__(self, rank: int, algo: str = "brute_force",
                 slow_s: float = 0.15, shared_registry: bool = False):
        self.rank = int(rank)
        self.algo = algo
        self.slow_s = float(slow_s)
        # True for LocalGroup's in-process twin: every worker thread
        # shares the ROUTER's metrics registry, so collect_metrics must
        # not hand the same registry back once per worker (the fleet
        # sum would multiply (n_workers+1)x)
        self.shared_registry = bool(shared_registry)
        self.current_gen = 0
        # gen_id -> {shard_id: entry}; staged holds prepared-not-published
        self.gens: Dict[int, Dict[int, tuple]] = {}
        self.staged: Dict[int, Dict[int, tuple]] = {}

    def handle(self, method: str, payload: Optional[dict]):
        """Dispatch one RPC. Returns ``("ok", reply)`` / ``("err",
        {"error", "kind"})``, or the :data:`DIE` / :data:`DROP`
        sentinels when an injected process fault demands an absence
        instead of an answer."""
        if method in DATA_PLANE:
            action = faultinject.proc_action(self.rank)
            if action == "die":
                return DIE, None
            if action == "slow":
                time.sleep(self.slow_s)
        if faultinject.rpc_dropped(method):
            return DROP, None
        try:
            faultinject.check(stage=f"fabric.{method}")
            fn = getattr(self, "_do_" + method, None)
            if fn is None:
                raise ValueError(f"unknown fabric RPC {method!r}")
            obs.counter("fabric.worker_rpcs_total", method=method)
            return "ok", fn(payload or {})
        except BaseException as e:  # noqa: BLE001 — classified here, re-classified by the router from the serialized kind
            kind = _rerrors.classify(e)
            return "err", {"error": f"{type(e).__name__}: {e}",
                           "kind": kind}

    # -- data plane ---------------------------------------------------------

    def _do_ping(self, payload: dict) -> dict:
        return {"rank": self.rank, "gen": self.current_gen,
                "gens": sorted(self.gens)}

    def _do_search(self, payload: dict) -> dict:
        gen = int(payload["gen"])
        shards = self.gens.get(gen)
        if shards is None:
            raise KeyError(
                f"{_NO_GEN}: worker {self.rank} does not hold "
                f"generation {gen} (has {sorted(self.gens)})")
        sid = int(payload["shard"])
        entry = shards.get(sid)
        if entry is None:
            raise KeyError(
                f"{_NO_GEN}: worker {self.rank} holds generation {gen} "
                f"but not shard {sid}")
        q = np.asarray(payload["q"])
        k = int(payload["k"])
        if not obs.enabled():
            d, i = search_shard_entry(entry, q, k)
            return {"gen": gen, "shard": sid, "d": d, "i": i}
        # graft-trace adoption (ISSUE 13): the RPC's trace context
        # becomes this thread's ambient context, so the spans the
        # search itself opens (brute_force/ivf_flat entry spans) carry
        # the SAME trace id the router minted — and a compact span
        # summary piggybacks on the reply, which is how the router
        # assembles the per-query waterfall without a second round
        # trip. No extra span is opened here: the entry span inside
        # search_shard_entry already names this work, and the serving
        # hot path pays for every per-RPC obs call in the loadgen A/B
        # overhead budget (FABRIC_r13.json). search_shard_entry
        # returns host numpy (it np.asarray's the device result), so
        # the measured ms is device-COMPLETE scan time, not dispatch
        # wall-clock.
        ctx = obs_trace.adopt(payload.get(obs_trace.WIRE_FIELD))
        with obs_trace.activate(ctx):
            t0 = time.perf_counter()
            d, i = search_shard_entry(entry, q, k)
            scan_ms = (time.perf_counter() - t0) * 1e3
        return {"gen": gen, "shard": sid, "d": d, "i": i,
                "spans": [{"name": "worker_scan", "worker": self.rank,
                           "shard": sid, "ms": round(scan_ms, 4),
                           "device_complete": True}]}

    def _do_collect_metrics(self, payload: dict) -> dict:
        """Fleet federation (ISSUE 13): hand the router this worker's
        whole metrics registry as a snapshot-shaped map. The router
        merges every worker's map under a ``worker`` label into one
        Prometheus exposition / JSON snapshot
        (:mod:`raft_tpu.obs.federation`). A shared-registry runtime
        (LocalGroup threads) answers with an EMPTY map and says so —
        its series already reach the router as its own registry, and
        returning them per worker would multiply every fleet sum."""
        if self.shared_registry:
            return {"rank": self.rank, "mode": obs.mode(),
                    "shared_registry": True, "metrics": {}}
        metrics = (obs.snapshot(runtime_gauges=False)["metrics"]
                   if obs.enabled() else {})
        return {"rank": self.rank, "mode": obs.mode(), "metrics": metrics}

    # -- two-phase swap control plane ---------------------------------------

    def _do_prepare(self, payload: dict) -> dict:
        gen = int(payload["gen"])
        built: Dict[int, tuple] = {}
        for sid, (vec, offset) in payload["shards"].items():
            vec = np.asarray(vec, dtype=np.float32)
            entry = build_shard_entry(vec, int(offset), self.algo)
            # warm: trace the search once now so publish -> first query
            # adds no compile on the serving path
            search_shard_entry(
                entry, np.zeros((1, vec.shape[1]), np.float32),
                int(min(4, vec.shape[0])))
            built[int(sid)] = entry
        self.staged[gen] = built
        return {"gen": gen, "shards": sorted(built)}

    def _do_publish(self, payload: dict) -> dict:
        gen = int(payload["gen"])
        if gen in self.gens:
            self.current_gen = max(self.current_gen, gen)
            return {"gen": gen}               # idempotent re-publish
        staged = self.staged.pop(gen, None)
        if staged is None:
            raise KeyError(
                f"{_NO_GEN}: worker {self.rank} has no staged "
                f"generation {gen} to publish")
        self.gens[gen] = staged
        # max, not assignment: a router resync of an OLDER generation
        # racing a newer publish must not regress the current pointer
        self.current_gen = max(self.current_gen, gen)
        return {"gen": gen}

    def _do_abort(self, payload: dict) -> dict:
        gen = int(payload["gen"])
        self.staged.pop(gen, None)
        return {"gen": gen}

    def _do_retire(self, payload: dict) -> dict:
        gen = int(payload["gen"])
        if gen != self.current_gen:
            self.gens.pop(gen, None)
        return {"gen": gen}

    def _do_set_faults(self, payload: dict) -> dict:
        faultinject.install(payload.get("spec") or None)
        return {"ok": True}


# ---------------------------------------------------------------------------
# multiprocessing transport
# ---------------------------------------------------------------------------


def _proc_worker_main(rank: int, req_q, resp_q, algo: str, slow_s: float,
                      fault_spec: Optional[str],
                      platform: Optional[str],
                      obs_mode: Optional[str] = None) -> None:
    """Child-process entry: run one :class:`WorkerRuntime` over the
    request queue until a ``stop``. A ``dead@proc`` fault hard-exits
    (``os._exit``) with no response — the honest SIGKILL analog."""
    if platform:
        # belt-and-braces: the parent already swapped the env before
        # spawn, but backend selection must never fall through to a
        # hung TPU plugin inside a fabric worker
        os.environ.setdefault("JAX_PLATFORMS", platform)
    if obs_mode is not None:
        # inherit the PARENT's resolved obs mode, not just the env: a
        # parent that called obs.set_mode("on") (tests, loadgen) would
        # otherwise spawn blind workers and the federation / worker-span
        # half of every trace would silently be empty
        obs.set_mode(obs_mode)
    if fault_spec:
        faultinject.install(fault_spec)
    rt = WorkerRuntime(rank, algo=algo, slow_s=slow_s)
    while True:
        msg = req_q.get()
        if msg is None:
            return
        req_id, method, payload = msg
        if method == "stop":
            return
        status, out = rt.handle(method, payload)
        if status is DIE:
            os._exit(17)
        if status is DROP:
            continue
        resp_q.put((req_id, status == "ok", out))


# one lock for the spawn-time environment swap (XLA_FLAGS /
# JAX_PLATFORMS are process-global; concurrent spawns must not
# interleave their save/restore)
_SPAWN_ENV_LOCK = lockwatch.make_lock("comms.spawn_env")


class _ProcWorker:
    __slots__ = ("rank", "proc", "req_q", "resp_q", "pending", "lock",
                 "stopping", "receiver", "dead_reason")

    def __init__(self, rank, proc, req_q, resp_q):
        self.rank = rank
        self.proc = proc
        self.req_q = req_q
        self.resp_q = resp_q
        self.pending: Dict[int, Future] = {}
        # graft-race sanitizer node "comms.procworker"
        self.lock = lockwatch.make_lock("comms.procworker")
        self.stopping = False
        # set (under `lock`) the moment the worker is declared dead and
        # its pending futures are drained: `call` checks it under the
        # SAME lock hold that registers the future, closing the window
        # where a registration racing the drain was never resolved
        self.dead_reason: Optional[str] = None
        self.receiver: Optional[threading.Thread] = None


class ProcGroup:
    """N fabric workers as real OS processes (``multiprocessing`` spawn
    context — fork after JAX initialization is unsafe).

    Parent-side API (shared with :class:`LocalGroup`):

    * :meth:`call` — fire an RPC, get a :class:`Future` (resolves with
      the reply payload, or raises the classified failure);
    * :meth:`alive` / :meth:`kill` / :meth:`restart` — process
      lifecycle (``kill`` is SIGKILL: the machine-loss drill);
    * :meth:`add_worker` / :meth:`retire` — dynamic admission and
      retirement (ISSUE 18): ranks are append-only and stable; a
      retired rank's slot stays (dead) so in-flight routing indexed by
      rank never dangles. Membership mutation is single-actor by
      contract — the control plane (graft-helm) or the owning test,
      never concurrent mutators;
    * :meth:`close` — stop everything.

    Children inherit the parent environment minus the
    ``--xla_force_host_platform_device_count`` test flag (a worker
    needs one device, not eight virtual ones) and with
    ``JAX_PLATFORMS`` pinned to ``platform`` (default ``cpu`` — a
    fabric worker must never block on a hung TPU plugin probe).
    """

    def __init__(self, n_workers: int, algo: str = "brute_force",
                 slow_s: float = 0.15, fault_spec: Optional[str] = None,
                 platform: Optional[str] = "cpu"):
        self.n_workers = int(n_workers)
        self.algo = algo
        self.slow_s = float(slow_s)
        self.fault_spec = fault_spec
        self.platform = platform
        self._ctx = mp.get_context("spawn")
        self._req_ids = itertools.count(1)
        # incarnation deaths per rank — the parent-side flap budget
        # (faultinject.respawned_spec): each child holds its own copy
        # of the fault plan, so the cross-incarnation charge lives here
        self._deaths: Dict[int, int] = {}
        self._workers: List[_ProcWorker] = [
            self._spawn(r, fault_spec) for r in range(self.n_workers)
        ]

    # -- lifecycle ----------------------------------------------------------

    def ranks(self) -> List[int]:
        """All member ranks ever admitted (retired/dead slots included —
        liveness is :meth:`alive`'s question)."""
        return list(range(len(self._workers)))

    def _spawn(self, rank: int, fault_spec: Optional[str]) -> _ProcWorker:
        req_q = self._ctx.Queue()
        resp_q = self._ctx.Queue()
        proc = self._ctx.Process(
            target=_proc_worker_main,
            args=(rank, req_q, resp_q, self.algo, self.slow_s,
                  fault_spec, self.platform, obs.mode()),
            daemon=True,
            name=f"raft-tpu-fabric-w{rank}",
        )
        with _SPAWN_ENV_LOCK:
            saved = {k: os.environ.get(k)
                     for k in ("XLA_FLAGS", "JAX_PLATFORMS")}
            flags = " ".join(
                tok for tok in (saved["XLA_FLAGS"] or "").split()
                if "xla_force_host_platform_device_count" not in tok)
            if flags:
                os.environ["XLA_FLAGS"] = flags
            else:
                os.environ.pop("XLA_FLAGS", None)
            if self.platform:
                os.environ["JAX_PLATFORMS"] = self.platform
            try:
                proc.start()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
        w = _ProcWorker(rank, proc, req_q, resp_q)
        w.receiver = threading.Thread(
            target=self._recv_loop, args=(w,), daemon=True,
            name=f"raft-tpu-fabric-recv-{rank}")
        w.receiver.start()
        return w

    def _recv_loop(self, w: _ProcWorker) -> None:
        while not w.stopping:
            try:
                msg = w.resp_q.get(timeout=0.1)
            except _pyqueue.Empty:
                if not w.proc.is_alive():
                    # drain what the child flushed before dying, then
                    # fail everything still outstanding
                    while True:
                        try:
                            self._resolve(w, w.resp_q.get_nowait())
                        except _pyqueue.Empty:
                            break
                    self._fail_pending(
                        w, f"fabric worker {w.rank} process died")
                    return
                continue
            except (OSError, EOFError, ValueError):
                # queue torn down under us (close/kill)
                self._fail_pending(
                    w, f"fabric worker {w.rank} channel closed")
                return
            self._resolve(w, msg)

    def _resolve(self, w: _ProcWorker, msg) -> None:
        req_id, ok, payload = msg
        with w.lock:
            fut = w.pending.pop(req_id, None)
        if fut is None or fut.done():
            return                      # hedge loser / timed-out caller
        if ok:
            fut.set_result(payload)
        else:
            fut.set_exception(_remote_error(payload))

    def _fail_pending(self, w: _ProcWorker, msg: str) -> None:
        with w.lock:
            w.dead_reason = msg
            pending = list(w.pending.values())
            w.pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(_rerrors.DeadBackendError(msg))

    # -- the RPC surface ----------------------------------------------------

    def call(self, rank: int, method: str,
             payload: Optional[dict] = None) -> Future:
        w = self._workers[rank]
        fut: Future = Future()
        req_id = next(self._req_ids)
        fut._raft_req_id = req_id
        # register-or-reject ATOMICALLY against _fail_pending: the old
        # unlocked aliveness check let a kill/close land between the
        # check and the registration — the drain saw an empty pending
        # map, the future was registered after it, and nobody ever
        # resolved it (the caller hung to its timeout)
        with w.lock:
            dead = w.dead_reason
            if dead is None and (w.stopping or not w.proc.is_alive()):
                dead = f"fabric worker {rank} process is not alive"
            if dead is None:
                w.pending[req_id] = fut
        if dead is not None:
            fut.set_exception(_rerrors.DeadBackendError(dead))
            return fut
        try:
            w.req_q.put((req_id, method, payload))
        except BaseException as e:  # noqa: BLE001 — classified: a torn queue is the dead-worker signal
            _rerrors.classify(e)
            with w.lock:
                w.pending.pop(req_id, None)
            if not fut.done():
                fut.set_exception(_rerrors.DeadBackendError(
                    f"fabric worker {rank} request channel broken: {e}"))
        return fut

    def forget(self, rank: int, fut: Future) -> None:
        """Abandon one outstanding call: drop its pending entry so a
        response that never arrives (dropped RPC, hung-but-alive
        worker) cannot pin the Future + payload until process death. A
        late response for a forgotten id is discarded by
        :meth:`_resolve`."""
        req_id = getattr(fut, "_raft_req_id", None)
        if req_id is None:
            return
        w = self._workers[rank]
        with w.lock:
            w.pending.pop(req_id, None)

    def alive(self, rank: int) -> bool:
        w = self._workers[rank]
        return not w.stopping and w.proc.is_alive()

    def kill(self, rank: int) -> None:
        """SIGKILL the worker — the machine-loss drill. Outstanding
        futures fail with :class:`DeadBackendError`."""
        w = self._workers[rank]
        w.proc.kill()
        w.proc.join(timeout=10.0)
        self._fail_pending(w, f"fabric worker {rank} killed")

    def restart(self, rank: int,
                fault_spec: Optional[str] = None,
                inherit_faults: bool = False) -> None:
        """Respawn ``rank`` as a fresh process with NO index state (the
        router must re-sync it). The fresh incarnation installs no
        fault plan unless one is given explicitly — or
        ``inherit_faults=True``, which installs the spawn-time plan
        rewritten by :func:`faultinject.respawned_spec` (flap budgets
        charged one death per prior incarnation, dead specs kept
        permanent): the control plane's respawn path, where the drills
        need the schedule to survive the respawn it provoked."""
        old = self._workers[rank]
        old.stopping = True
        if old.proc.is_alive():
            old.proc.kill()
        old.proc.join(timeout=10.0)
        self._fail_pending(old, f"fabric worker {rank} restarted")
        self._deaths[rank] = self._deaths.get(rank, 0) + 1
        if fault_spec is None and inherit_faults:
            fault_spec = faultinject.respawned_spec(
                self.fault_spec, rank, self._deaths[rank])
        self._workers[rank] = self._spawn(rank, fault_spec)

    def add_worker(self, fault_spec: Optional[str] = None) -> int:
        """Admit one new worker (autoscale-up): spawn it under the next
        rank and return that rank. The newcomer owns no shards until a
        generation that places some on it is published
        (``Fabric.rebalance``)."""
        rank = len(self._workers)
        self._workers.append(self._spawn(rank, fault_spec))
        self.n_workers = len(self._workers)
        return rank

    def retire(self, rank: int, timeout_s: float = 10.0) -> None:
        """Retire one worker for good (autoscale-down): graceful stop,
        SIGKILL past the timeout. The rank slot stays, dead — ranks are
        stable for the life of the group."""
        w = self._workers[rank]
        w.stopping = True
        try:
            w.req_q.put((0, "stop", None))
        except BaseException as e:  # noqa: BLE001 — classified: retiring an already-dead queue
            _rerrors.classify(e)
        w.proc.join(timeout=timeout_s)
        if w.proc.is_alive():
            w.proc.kill()
            w.proc.join(timeout=5.0)
        self._fail_pending(w, f"fabric worker {rank} retired")

    def close(self, timeout_s: float = 10.0) -> None:
        for w in self._workers:
            w.stopping = True
            try:
                w.req_q.put((0, "stop", None))
            except BaseException as e:  # noqa: BLE001 — classified: shutdown of an already-dead queue
                _rerrors.classify(e)
        deadline = time.monotonic() + timeout_s
        for w in self._workers:
            w.proc.join(timeout=max(deadline - time.monotonic(), 0.1))
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(timeout=5.0)
            self._fail_pending(w, f"fabric worker {w.rank} closed")


# ---------------------------------------------------------------------------
# in-process transport
# ---------------------------------------------------------------------------


class _LocalWorker:
    __slots__ = ("rank", "runtime", "q", "pending", "lock", "dead",
                 "thread")

    def __init__(self, rank, runtime):
        self.rank = rank
        self.runtime = runtime
        self.q: "_pyqueue.Queue" = _pyqueue.Queue()
        self.pending: Dict[int, Future] = {}
        # graft-race sanitizer node "comms.localworker"; `dead` is
        # written under it (see _fail_pending) so `call` can
        # register-or-reject atomically against a concurrent kill
        self.lock = lockwatch.make_lock("comms.localworker")
        self.dead = False
        self.thread: Optional[threading.Thread] = None


class LocalGroup:
    """The in-process twin of :class:`ProcGroup`: the same
    :class:`WorkerRuntime` per worker, on daemon threads. Identical
    parent-side semantics — a "died" worker stops answering forever
    (:meth:`alive` goes False, outstanding futures fail) rather than
    raising, so every router failure path is exercised without spawn
    cost. Fault plans are the AMBIENT :mod:`faultinject` plan (one
    process, one plan), matching each runtime by its rank."""

    def __init__(self, n_workers: int, algo: str = "brute_force",
                 slow_s: float = 0.05, fault_spec: Optional[str] = None,
                 platform: Optional[str] = None):
        del platform                    # one process, one platform
        if fault_spec:
            faultinject.install(fault_spec)
        self.n_workers = int(n_workers)
        self.algo = algo
        self.slow_s = float(slow_s)
        self._req_ids = itertools.count(1)
        self._workers: List[_LocalWorker] = [
            self._spawn(r) for r in range(self.n_workers)
        ]

    def ranks(self) -> List[int]:
        return list(range(len(self._workers)))

    def _spawn(self, rank: int) -> _LocalWorker:
        w = _LocalWorker(rank, WorkerRuntime(rank, algo=self.algo,
                                             slow_s=self.slow_s,
                                             shared_registry=True))
        w.thread = threading.Thread(
            target=self._loop, args=(w,), daemon=True,
            name=f"raft-tpu-fabric-local-w{rank}")
        w.thread.start()
        return w

    def _loop(self, w: _LocalWorker) -> None:
        while True:
            msg = w.q.get()
            if msg is None:
                return
            req_id, method, payload = msg
            with w.lock:
                dead = w.dead           # guarded read: kill/close write
                #                         it under the same lock
            if dead:
                continue                # the dead answer nothing, ever
            status, out = w.runtime.handle(method, payload)
            if status is DIE:
                self._fail_pending(
                    w, f"fabric worker {w.rank} died (injected)")
                continue
            if status is DROP:
                with w.lock:
                    w.pending.pop(req_id, None)
                continue
            with w.lock:
                fut = w.pending.pop(req_id, None)
            if fut is None or fut.done():
                continue
            if status == "ok":
                fut.set_result(out)
            else:
                fut.set_exception(_remote_error(out))

    def _fail_pending(self, w: _LocalWorker, msg: str) -> None:
        """Declare ``w`` dead and drain its futures — `dead` flips under
        the SAME lock hold that empties ``pending``, so `call`'s
        register-or-reject can never interleave between the two."""
        with w.lock:
            w.dead = True
            pending = list(w.pending.values())
            w.pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(_rerrors.DeadBackendError(msg))

    def call(self, rank: int, method: str,
             payload: Optional[dict] = None) -> Future:
        w = self._workers[rank]
        fut: Future = Future()
        req_id = next(self._req_ids)
        fut._raft_req_id = req_id
        # atomic register-or-reject (see _ProcWorker.dead_reason): the
        # old unlocked `if w.dead` check raced kill() — a future
        # registered after the drain was never resolved and its caller
        # hung to the RPC deadline
        with w.lock:
            dead = w.dead
            if not dead:
                w.pending[req_id] = fut
        if dead:
            fut.set_exception(_rerrors.DeadBackendError(
                f"fabric worker {rank} is not alive"))
            return fut
        w.q.put((req_id, method, payload))
        return fut

    def forget(self, rank: int, fut: Future) -> None:
        req_id = getattr(fut, "_raft_req_id", None)
        if req_id is None:
            return
        w = self._workers[rank]
        with w.lock:
            w.pending.pop(req_id, None)

    def alive(self, rank: int) -> bool:
        return not self._workers[rank].dead

    def kill(self, rank: int) -> None:
        # _fail_pending flips `dead` and drains atomically
        self._fail_pending(self._workers[rank],
                           f"fabric worker {rank} killed")

    def restart(self, rank: int,
                fault_spec: Optional[str] = None,
                inherit_faults: bool = False) -> None:
        # inherit_faults is a no-op here by design: one process, one
        # AMBIENT plan — a respawned local runtime sees the same specs,
        # with flap budgets already decremented by the deaths they
        # caused (the cross-incarnation charge ProcGroup has to
        # replicate parent-side)
        del inherit_faults
        old = self._workers[rank]
        self._fail_pending(old, f"fabric worker {rank} restarted")
        old.q.put(None)                 # let the old thread exit
        if fault_spec:
            faultinject.install(fault_spec)
        self._workers[rank] = self._spawn(rank)

    def add_worker(self, fault_spec: Optional[str] = None) -> int:
        if fault_spec:
            faultinject.install(fault_spec)
        rank = len(self._workers)
        self._workers.append(self._spawn(rank))
        self.n_workers = len(self._workers)
        return rank

    def retire(self, rank: int, timeout_s: float = 10.0) -> None:
        del timeout_s
        w = self._workers[rank]
        self._fail_pending(w, f"fabric worker {rank} retired")
        w.q.put(None)

    def close(self, timeout_s: float = 10.0) -> None:
        for w in self._workers:
            self._fail_pending(w, f"fabric worker {w.rank} closed")
            w.q.put(None)
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout=timeout_s)
