"""Sharded (multi-chip) algorithms over a device mesh.

The reference's multi-GPU model (SURVEY.md §2.18): each rank holds an index
shard; queries are replicated; per-shard top-k results are merged. Consumers
wire it with raft-dask + NCCL. Here the whole pattern is one ``shard_map``:
the dataset is sharded over the mesh axis, each device runs the local
search, and the shard top-ks are all-gathered and merged on-device over ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors.common import merge_topk


def sharded_knn(
    queries,
    dataset,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN with the dataset row-sharded over ``mesh[axis_name]``.

    Dataset rows must be divisible by the axis size (pad upstream). Queries
    are replicated; each shard computes a local top-k with *global* ids
    (rank offset added), then shard results are all-gathered and merged —
    the knn_merge_parts-over-NCCL pattern
    (detail/knn_merge_parts.cuh + raft-dask) as a single XLA program.
    """
    metric = resolve_metric(metric)
    queries = jnp.asarray(queries)
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        raise ValueError(f"dataset rows {n} not divisible by mesh axis {nshards}")
    shard_rows = n // nshards
    select_min = is_min_close(metric)

    def local(q, db_shard):
        rank = jax.lax.axis_index(axis_name)
        d, i = brute_force._search(
            q, db_shard, None, None, None, int(k), int(metric), float(metric_arg),
            int(min(shard_rows, 8192)),
        )
        i = i + (rank * shard_rows).astype(i.dtype)
        # gather all shards' candidates onto every device, merge locally
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)  # [m, S*k]
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        return merge_topk(gd, gi, k, select_min)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis_name, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)(queries, dataset)


def sharded_ivf_search(
    search_params,
    index,
    queries,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
) -> Tuple[jax.Array, jax.Array]:
    """Approximate KNN with the IVF index's *lists* sharded over the mesh.

    The reference's large-index multi-GPU model: each rank owns an index
    shard and runs the same search; per-rank top-ks are merged
    (raft-dask + detail/knn_merge_parts.cuh:140). Here each device holds
    ``n_lists / n_shards`` lists (centers, storage blocks, norms all
    sharded on the list axis), probes ``n_probes / n_shards`` of them, and
    the per-shard top-ks are all-gathered + merged over ICI.

    Stored ids are global dataset row ids, so no rank offset is needed.
    """
    from raft_tpu.neighbors import ivf_flat

    queries = jnp.asarray(queries)
    C = index.n_lists
    nshards = mesh.shape[axis_name]
    if C % nshards != 0:
        raise ValueError(f"n_lists {C} not divisible by mesh axis {nshards}")
    local_lists = C // nshards
    n_probes = max(1, min(int(search_params.n_probes) // nshards, local_lists))
    cap = index.storage.shape[1]
    if k > n_probes * cap:
        raise ValueError(
            f"k={k} exceeds the per-shard candidate pool "
            f"(n_probes/shard={n_probes} x cap={cap}); raise n_probes to at "
            f"least {nshards * -(-k // max(cap, 1))} for a {nshards}-way mesh"
        )
    select_min = is_min_close(index.metric)
    metric = int(index.metric)
    group = ivf_flat.adaptive_query_group(
        int(queries.shape[0]), n_probes, index.n_lists,
        int(search_params.query_group),
    )
    bucket_batch = int(search_params.bucket_batch)

    has_norms = index.data_norms is not None

    def local(q, centers, storage, indices, list_sizes, *rest):
        norms = rest[0] if has_norms else None
        d, i = ivf_flat._ivf_search(
            q, centers, storage, indices, list_sizes,
            int(k), n_probes, metric, group, bucket_batch, 0,
            str(search_params.compute_dtype),
            float(search_params.local_recall_target),
            norms, None,
        )
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)  # [m, S*k]
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        return merge_topk(gd, gi, k, select_min)

    args = [queries, index.centers, index.storage, index.indices, index.list_sizes]
    in_specs = [P(), P(axis_name, None), P(axis_name, None, None),
                P(axis_name, None), P(axis_name)]
    if has_norms:
        args.append(index.data_norms)
        in_specs.append(P(axis_name, None))

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)(*args)


def sharded_pairwise_distance(
    x,
    y,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
) -> jax.Array:
    """Pairwise distance with x row-sharded over the mesh: each device
    computes its row block against replicated y; the result stays sharded
    (the caller sees one logical [m, n] array)."""
    from raft_tpu.distance.pairwise import _pairwise

    metric = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    nshards = mesh.shape[axis_name]
    if x.shape[0] % nshards != 0:
        raise ValueError(f"x rows {x.shape[0]} not divisible by mesh axis {nshards}")

    def local(xs, yr):
        return _pairwise(xs, yr, int(metric), float(metric_arg), None, None)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(axis_name, None),
        check_vma=False,
    )
    return jax.jit(fn)(x, y)
