"""Sharded (multi-chip) algorithms over a device mesh.

The reference's multi-GPU model (SURVEY.md §2.18): each rank holds an index
shard; queries are replicated; per-shard top-k results are merged. Consumers
wire it with raft-dask + NCCL. Here the whole pattern is one ``shard_map``:
the dataset is sharded over the mesh axis, each device runs the local
search, and the shard top-ks are all-gathered and merged on-device over ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors.common import merge_topk


def sharded_knn(
    queries,
    dataset,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN with the dataset row-sharded over ``mesh[axis_name]``.

    Dataset rows must be divisible by the axis size (pad upstream). Queries
    are replicated; each shard computes a local top-k with *global* ids
    (rank offset added), then shard results are all-gathered and merged —
    the knn_merge_parts-over-NCCL pattern
    (detail/knn_merge_parts.cuh + raft-dask) as a single XLA program.
    """
    metric = resolve_metric(metric)
    queries = jnp.asarray(queries)
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        raise ValueError(f"dataset rows {n} not divisible by mesh axis {nshards}")
    shard_rows = n // nshards
    select_min = is_min_close(metric)

    def local(q, db_shard):
        rank = jax.lax.axis_index(axis_name)
        d, i = brute_force._search(
            q, db_shard, None, None, None, int(k), int(metric), float(metric_arg),
            int(min(shard_rows, 8192)),
        )
        i = i + (rank * shard_rows).astype(i.dtype)
        # gather all shards' candidates onto every device, merge locally
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)  # [m, S*k]
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        return merge_topk(gd, gi, k, select_min)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis_name, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)(queries, dataset)


def sharded_pairwise_distance(
    x,
    y,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
) -> jax.Array:
    """Pairwise distance with x row-sharded over the mesh: each device
    computes its row block against replicated y; the result stays sharded
    (the caller sees one logical [m, n] array)."""
    from raft_tpu.distance.pairwise import _pairwise

    metric = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    nshards = mesh.shape[axis_name]
    if x.shape[0] % nshards != 0:
        raise ValueError(f"x rows {x.shape[0]} not divisible by mesh axis {nshards}")

    def local(xs, yr):
        return _pairwise(xs, yr, int(metric), float(metric_arg), None, None)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(axis_name, None),
        check_vma=False,
    )
    return jax.jit(fn)(x, y)
