"""Sharded (multi-chip) algorithms over a device mesh.

The reference's multi-GPU model (SURVEY.md §2.18): each rank holds an index
shard; queries are replicated; per-shard top-k results are merged. Consumers
wire it with raft-dask + NCCL. Here the whole pattern is one ``shard_map``:
the dataset is sharded over the mesh axis, each device runs the local
search, and the shard top-ks are all-gathered and merged on-device over ICI.

Graceful shard degradation (docs/resilience.md): the searches accept
``partial_ok=True`` — a shard whose local result is invalid (NaN, or a
rank named by an injected ``shard@rank:R`` fault) is masked to the
worst-possible sentinel before ``merge_topk``, and the call returns the
merged results plus a replicated coverage fraction instead of raising
(the reference's ``knn_merge_parts`` multi-rank model tolerates exactly
this per-rank variation). Detection runs when ``partial_ok=True`` OR a
shard fault is injected; in the latter case ``partial_ok=False`` raises
:class:`raft_tpu.resilience.ShardDropoutError` on any dropout. Without
either, the plain path is compiled unchanged (no validity scan, no
coverage collective) — a real NaN shard then propagates exactly as it
did pre-resilience; callers that want NaN *detection* opt in with
``partial_ok=True`` and check ``coverage < 1``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from raft_tpu.comms.compat import shard_map

from raft_tpu import obs
from raft_tpu import plan as plan_mod
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors.common import merge_topk
from raft_tpu.resilience import ShardDropoutError, faultinject


def _dead_rank_array() -> jax.Array:
    """Injected-dead ranks as a replicated input array (NOT baked into
    the trace, so jit caches stay valid across changing fault plans)."""
    bad = sorted(faultinject.dead_ranks())
    return jnp.asarray(bad if bad else [-1], jnp.int32)


def _mask_invalid(d, i, rank, bad_ranks, select_min):
    """Shard-local validity, PER QUERY ROW: a row is dropped when its
    shard's rank is fault-injected dead (all rows) or its local top-k
    carries NaN (the real-fault signature: a wedged collective / corrupt
    block scores NaN). Row-granular on purpose — queries are replicated,
    so one NaN *query* poisons the same row on every shard, and a
    whole-shard verdict would sentinel all S shards over one bad input
    row. Invalid rows score the worst-possible sentinel with ids -1, so
    the cross-shard merge ranks every surviving candidate ahead of
    them."""
    dead = jnp.any(rank == bad_ranks)
    row_ok = jnp.logical_not(dead | jnp.any(jnp.isnan(d), axis=1))  # [m]
    sent = jnp.asarray(jnp.inf if select_min else -jnp.inf, d.dtype)
    d = jnp.where(row_ok[:, None], d, sent)
    i = jnp.where(row_ok[:, None], i, jnp.asarray(-1, i.dtype))
    return d, i, row_ok


def _coverage(valid, axis_name) -> jax.Array:
    """Replicated surviving fraction over shards x query rows: a fully
    dead shard of S costs 1/S; a single poisoned query row (invalid on
    every shard, since queries are replicated) costs 1/m."""
    flags = jax.lax.all_gather(valid.astype(jnp.float32), axis_name)
    return jnp.mean(flags)


def _record_full_coverage(what: str) -> None:
    """The healthy-path twin of :func:`_finish_partial`'s gauge: the
    plain (no validity scan) path serves full coverage by construction,
    and recording ``shard_coverage{what} = 1`` there lets a dashboard
    distinguish "healthy S/S shards" from "metric never emitted" —
    previously the series only ever carried degraded values."""
    if obs.enabled():
        obs.gauge("shard_coverage", 1.0, what=what)


def _finish_partial(out, partial_ok: bool, what: str):
    """Host-side tail of a partial-capable search: hand back (d, i,
    coverage) under ``partial_ok``, else raise on any dropout.

    With obs enabled the replicated coverage fraction is recorded as the
    ``shard_coverage{what}`` gauge (plus ``shard_dropouts_total`` when it
    dips below 1) — note the gauge read forces a host sync of the
    coverage scalar, which the bare ``partial_ok=True`` path otherwise
    defers to the caller."""
    d, i, cov = out
    if obs.enabled():
        c = float(np.asarray(cov))
        obs.gauge("shard_coverage", c, what=what)
        if c < 1.0:
            obs.counter("shard_dropouts_total", what=what)
            obs.event("shard_dropout", what=what, coverage=c)
    if partial_ok:
        return d, i, cov
    # fault-detection path without the partial opt-in: refuse to return
    # silently-degraded results
    if float(np.asarray(cov)) < 1.0:
        raise ShardDropoutError(
            f"{what}: shard coverage {float(np.asarray(cov)):.3f} < 1 "
            "(a shard's local result was invalid); pass partial_ok=True "
            "to accept partial results plus a coverage fraction"
        )
    return d, i


def sharded_knn(
    queries,
    dataset,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    partial_ok: bool = False,
) -> Tuple[jax.Array, ...]:
    """Exact KNN with the dataset row-sharded over ``mesh[axis_name]``.

    Dataset rows need NOT divide the axis size: non-divisible ``n`` is
    auto-padded with sentinel rows whose distances mask to
    worst-possible and whose ids mask to -1 inside the local search, so
    they can only surface when ``k`` exceeds the real row count
    ("pad upstream" was a robustness foot-gun). Queries are replicated;
    each shard computes a local top-k with *global* ids (rank offset
    added), then shard results are all-gathered and merged — the
    knn_merge_parts-over-NCCL pattern
    (detail/knn_merge_parts.cuh + raft-dask) as a single XLA program.

    ``partial_ok=True`` returns ``(dists, ids, coverage)`` with invalid
    shards (NaN local results, injected dead ranks) masked out of the
    merge — see the module docstring.
    """
    metric = resolve_metric(metric)
    queries = jnp.asarray(queries)
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        padded = -(-n // nshards) * nshards
        dataset = jnp.concatenate(
            [dataset,
             jnp.zeros((padded - n,) + dataset.shape[1:], dataset.dtype)],
            axis=0,
        )
    n_pad = dataset.shape[0] - n
    shard_rows = dataset.shape[0] // nshards
    select_min = is_min_close(metric)
    partial = partial_ok or faultinject.has_shard_faults()
    # zero-filled pad rows DO score (a query near the origin ranks them
    # well under L2), so the local top-k is widened by the pad count —
    # at most n_pad real candidates can be displaced before the mask
    # turns every pad row into the worst-possible sentinel
    k_local = int(min(k + n_pad, shard_rows)) if n_pad else int(k)

    def local(q, db_shard, *rest):
        rank = jax.lax.axis_index(axis_name)
        d, i = brute_force._search(
            q, db_shard, None, None, None, k_local, int(metric),
            float(metric_arg), int(min(shard_rows, 8192)),
        )
        i = i + (rank * shard_rows).astype(i.dtype)
        if n_pad:
            pad = i >= n
            d = jnp.where(pad, jnp.asarray(
                jnp.inf if select_min else -jnp.inf, d.dtype), d)
            i = jnp.where(pad, jnp.asarray(-1, i.dtype), i)
        if partial:
            d, i, valid = _mask_invalid(d, i, rank, rest[0], select_min)
        # gather all shards' candidates onto every device, merge locally
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)  # [m, S*k]
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        md, mi = merge_topk(gd, gi, k, select_min)
        if partial:
            return md, mi, _coverage(valid, axis_name)
        return md, mi

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis_name, None)) + ((P(),) if partial else ()),
        out_specs=(P(), P()) + ((P(),) if partial else ()),
        check_vma=False,
    )
    args = (queries, dataset) + ((_dead_rank_array(),) if partial else ())
    with obs.entry_span("search", "sharded_knn",
                        queries=int(queries.shape[0]), k=int(k),
                        shards=int(nshards)):
        out = jax.jit(fn)(*args)
    if partial:
        return _finish_partial(out, partial_ok, "sharded_knn")
    _record_full_coverage("sharded_knn")
    return out


def sharded_ivf_search(
    search_params,
    index,
    queries,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
    partial_ok: bool = False,
) -> Tuple[jax.Array, ...]:
    """Approximate KNN with the IVF index's *lists* sharded over the mesh.

    The reference's large-index multi-GPU model: each rank owns an index
    shard and runs the same search; per-rank top-ks are merged
    (raft-dask + detail/knn_merge_parts.cuh:140). Here each device holds
    ``n_lists / n_shards`` lists (centers, storage blocks, norms all
    sharded on the list axis), probes ``n_probes / n_shards`` of them, and
    the per-shard top-ks are all-gathered + merged over ICI.

    Stored ids are global dataset row ids, so no rank offset is needed.

    ``partial_ok=True`` returns ``(dists, ids, coverage)`` with invalid
    shards masked out of the merge (module docstring).
    """
    from raft_tpu.neighbors import ivf_flat

    queries = jnp.asarray(queries)
    C = index.n_lists
    nshards = mesh.shape[axis_name]
    if C % nshards != 0:
        raise ValueError(f"n_lists {C} not divisible by mesh axis {nshards}")
    local_lists = C // nshards
    n_probes = max(1, min(int(search_params.n_probes) // nshards, local_lists))
    cap = index.storage.shape[1]
    if k > n_probes * cap:
        raise ValueError(
            f"k={k} exceeds the per-shard candidate pool "
            f"(n_probes/shard={n_probes} x cap={cap}); raise n_probes to at "
            f"least {nshards * -(-k // max(cap, 1))} for a {nshards}-way mesh"
        )
    select_min = is_min_close(index.metric)
    metric = int(index.metric)
    group = ivf_flat.adaptive_query_group(
        int(queries.shape[0]), n_probes, index.n_lists,
        int(search_params.query_group),
    )
    bucket_batch = int(search_params.bucket_batch)

    has_norms = index.data_norms is not None
    partial = partial_ok or faultinject.has_shard_faults()

    def local(q, centers, storage, indices, list_sizes, *rest):
        rest = list(rest)
        norms = rest.pop(0) if has_norms else None
        bad = rest.pop(0) if partial else None
        rank = jax.lax.axis_index(axis_name)
        # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path: one collective per-shard scan + merge, no multi-stage tail
        d, i = ivf_flat._ivf_search(
            q, centers, storage, indices, list_sizes,
            int(k), n_probes, metric, group, bucket_batch, 0,
            str(search_params.compute_dtype),
            float(search_params.local_recall_target),
            float(search_params.merge_recall_target),
            norms, None,
        )
        if partial:
            d, i, valid = _mask_invalid(d, i, rank, bad, select_min)
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)  # [m, S*k]
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        md, mi = merge_topk(gd, gi, k, select_min)
        if partial:
            return md, mi, _coverage(valid, axis_name)
        return md, mi

    args = [queries, index.centers, index.storage, index.indices, index.list_sizes]
    in_specs = [P(), P(axis_name, None), P(axis_name, None, None),
                P(axis_name, None), P(axis_name)]
    if has_norms:
        args.append(index.data_norms)
        in_specs.append(P(axis_name, None))
    if partial:
        args.append(_dead_rank_array())
        in_specs.append(P())

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()) + ((P(),) if partial else ()),
        check_vma=False,
    )
    with obs.entry_span("search", "sharded_ivf",
                        queries=int(queries.shape[0]), k=int(k),
                        shards=int(nshards)):
        out = jax.jit(fn)(*args)
    if partial:
        return _finish_partial(out, partial_ok, "sharded_ivf_search")
    _record_full_coverage("sharded_ivf_search")
    return out


def sharded_ivf_pq_search(
    search_params,
    index,
    queries,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
    refine_ratio: int = 1,
    partial_ok: bool = False,
    rerank_source=None,
) -> Tuple[jax.Array, ...]:
    """Approximate KNN with the IVF-PQ index's *lists* sharded over the
    mesh — the DEEP-1B-scale model (the reference fits DEEP-1B in 24 GiB
    per GPU via PQ and shards across GPUs via comms,
    docs/source/using_raft_comms.rst): each device owns
    ``n_lists / n_shards`` lists (centers, packed codes, norms, int8
    cache all sharded on the list axis), probes its share, and the
    per-shard top-ks are all-gathered + merged over ICI.

    PER_CLUSTER codebooks shard with their lists; PER_SUBSPACE codebooks
    and the rotation are replicated. Stored ids are global dataset row
    ids, so no rank offset is needed.

    ``refine_ratio > 1`` adds a PER-SHARD exact re-rank from the residual
    cache before the cross-shard merge (the reference's refine_ratio
    pattern, bench/ann raft_ivf_pq_wrapper.h, with the dataset read
    replaced by on-chip cache decode — detail/refine_host-inl.hpp's role
    at a scale where the f32 dataset cannot be resident): each shard
    searches ``k * refine_ratio`` candidates over slot-substituted
    indices, decodes those slots from ITS OWN cache shard at f32, ranks
    exactly, and only the refined top-k rides the all-gather. Requires
    the index to carry a residual cache.

    ``rerank_source`` (the tiered-memory shape, docs/serving.md §12)
    reranks from HOST-resident originals INSTEAD of the per-shard
    cache: a :class:`raft_tpu.neighbors.tiered.RerankSource` (or host
    numpy/memmap array — wrapped per call). The shards then merge
    their FIRST-stage top-``k*refine_ratio`` candidates, and the host
    source fetches only the merged shortlist's unique rows for the
    exact final ranking — no residual cache required, and
    ``partial_ok`` composes (an uncovered shard's ``-1`` rows stay
    invalid through the rerank; coverage passes through unchanged).

    ``partial_ok=True`` returns ``(dists, ids, coverage)`` with invalid
    shards masked out of the merge (module docstring).
    """
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.ivf_flat import adaptive_query_group

    queries = jnp.asarray(queries)
    C = index.n_lists
    nshards = mesh.shape[axis_name]
    if C % nshards != 0:
        raise ValueError(f"n_lists {C} not divisible by mesh axis {nshards}")
    local_lists = C // nshards
    n_probes = max(1, min(int(search_params.n_probes) // nshards, local_lists))
    if index.codes.ndim != 3:
        raise ValueError(
            "flat-codes (100M-scale streamed) indexes are single-device "
            "only for now: sharding needs per-device [C, cap, nw] blocks"
        )
    cap = index.indices.shape[1]
    if k > n_probes * cap:
        raise ValueError(
            f"k={k} exceeds the per-shard candidate pool "
            f"(n_probes/shard={n_probes} x cap={cap}); raise n_probes to at "
            f"least {nshards * -(-k // max(cap, 1))} for a {nshards}-way mesh"
        )
    select_min = is_min_close(index.metric)
    metric = int(index.metric)
    group = adaptive_query_group(
        int(queries.shape[0]), n_probes, index.n_lists,
        int(search_params.query_group),
    )
    bucket_batch = int(search_params.bucket_batch)
    per_cluster = int(index.codebook_kind) == ivf_pq.codebook_gen.PER_CLUSTER
    has_cache = index.recon_cache is not None
    has_fac = index.cache_kind == "rabitq"
    lut = ivf_pq._norm_dtype_knob(search_params.lut_dtype)
    if lut == "i8" and index.cache_kind not in ("i8", "i4"):
        # mirror ivf_pq.search(): a pq4 code cache is not the i8 LUT path
        raise ValueError("lut_dtype='i8' needs the decoded-residual cache")
    if lut == "auto" and not has_cache:
        lut = "f32"
    internal = ivf_pq._norm_dtype_knob(search_params.internal_distance_dtype)

    refine_ratio = int(refine_ratio)
    src = None
    if rerank_source is not None:
        from raft_tpu.neighbors import tiered

        src = tiered.as_source(rerank_source)
    cache_refine = (refine_ratio > 1 and src is None
                    and index.cache_kind in ("i4", "i8"))
    # rabitq shards as first-stage subplan + ROUTER-side rerank: the
    # 1-bit scan returns GLOBAL slots (shard offset applied in-trace),
    # the merged slot shortlist re-scores at full PQ fidelity from the
    # full index's packed codes once, host-side of the collective
    codes_refine = (refine_ratio > 1 and src is None and has_fac)
    if refine_ratio > 1 and src is None and not (cache_refine
                                                 or codes_refine):
        raise ValueError(
            "refine_ratio > 1 needs the decoded-RESIDUAL cache (i8/i4; "
            "build with cache_decoded=True within the cache budget) or "
            "a host rerank_source= (neighbors.tiered) — a pq4 code "
            "cache carries no fidelity beyond the scan itself"
        )
    if codes_refine and int(index.codes.shape[-1]) == 0:
        raise ValueError(
            "sharded rabitq refine re-scores the merged shortlist from "
            "the packed PQ codes — build with keep_codes=True, or pass "
            "a host rerank_source= (neighbors.tiered)"
        )
    k_search = k * refine_ratio
    if k_search > n_probes * cap:
        raise ValueError(
            f"k*refine_ratio={k_search} exceeds the per-shard candidate "
            f"pool (n_probes/shard={n_probes} x cap={cap})"
        )
    # with a router-side rerank tail (host source or rabitq codes) the
    # shards merge their FIRST-stage shortlists at full k_search width;
    # the exact rerank happens once on the merged candidates
    k_merge = k_search if (src is not None or codes_refine) else k

    has_scales = has_cache and index.cache_scales is not None
    partial = partial_ok or faultinject.has_shard_faults()

    # the pipeline as DATA (raft_tpu.plan): the pre-merge subplan runs
    # per worker inside shard_map, the rerank tail (if any) once on the
    # router — split_at_merge cuts at the collective
    tail_kind = ("tiered" if src is not None
                 else "codes" if codes_refine else None)
    p = plan_mod.sharded_ivf_pq_plan(
        int(k), int(k_search), int(k_merge),
        local_rerank=cache_refine, tail=tail_kind)
    head_plan, tail_plan = plan_mod.split_at_merge(p)
    head_cp = plan_mod.compile(
        head_plan, index, k=int(k), search_params=search_params,
        refine_ratio=refine_ratio,
        n_probes=n_probes, metric=metric, group=group,
        bucket_batch=bucket_batch,
        codebook_kind=int(index.codebook_kind),
        compute_dtype=str(search_params.compute_dtype),
        local_recall_target=float(search_params.local_recall_target),
        merge_recall_target=float(search_params.merge_recall_target),
        lut=lut, internal=internal,
        pq_dim=int(index.pq_dim), pq_bits=int(index.pq_bits),
        recon_scale=float(index.recon_scale),
        axis_name=axis_name, select_min=select_min)
    tail_cp = (None if tail_plan is None
               else plan_mod.compile(tail_plan, index, k=int(k),
                                     source=src))

    local_slots = local_lists * cap

    def local(q, centers, centers_rot, rotation, pq_centers, codes,
              indices, list_sizes, rec_norms, *rest):
        rest = list(rest)
        cache = rest.pop(0) if has_cache else None
        scales = rest.pop(0) if has_scales else None
        qnorms = rest.pop(0) if (has_scales or has_fac) else None
        fac = rest.pop(0) if has_fac else None
        bad = rest.pop(0) if partial else None
        rank = jax.lax.axis_index(axis_name)
        if cache_refine:
            # per-shard rerank decodes from ITS OWN cache: LOCAL slots
            search_ids = ivf_pq._slot_indices(indices)
        elif codes_refine:
            # router rerank decodes from the FULL index: local slots
            # lift to global flat slots by the shard's block offset
            s = ivf_pq._slot_indices(indices)
            search_ids = jnp.where(s >= 0, s + rank * local_slots, -1)
        else:
            search_ids = indices
        arrays = (q, centers, centers_rot, rotation, pq_centers, codes,
                  search_ids, list_sizes, rec_norms, None, cache,
                  jnp.float32(index.recon_scale), scales, qnorms, fac)
        extra = {"indices": indices, "cache": cache, "scales": scales}
        if partial:
            cov = {}

            def pre_merge(d, i):
                d, i, valid = _mask_invalid(d, i, rank, bad, select_min)
                cov["valid"] = valid
                return d, i

            extra["pre_merge"] = pre_merge
        md, mi = head_cp(q, arrays=arrays, extra=extra)
        if partial:
            return md, mi, _coverage(cov["valid"], axis_name)
        return md, mi

    args = [queries, index.centers, index.centers_rot, index.rotation,
            index.pq_centers, index.codes, index.indices, index.list_sizes,
            index.rec_norms]
    in_specs = [
        P(),                          # queries replicated
        P(axis_name, None),           # centers
        P(axis_name, None),           # centers_rot
        P(),                          # rotation replicated
        P(axis_name, None, None) if per_cluster else P(),
        P(axis_name, None, None),     # packed codes
        P(axis_name, None),           # indices
        P(axis_name),                 # list_sizes
        P(axis_name, None),           # rec_norms
    ]
    if has_cache:
        args.append(index.recon_cache)
        in_specs.append(P(axis_name, None, None))
    if has_scales:
        args.append(index.cache_scales)        # [C, rot] per-list scales
        in_specs.append(P(axis_name, None))
    if has_scales or has_fac:
        qn = (index.cache_qnorms if index.cache_qnorms is not None
              else index.rec_norms)
        args.append(qn)
        in_specs.append(P(axis_name, None))
    if has_fac:
        args.append(index.cache_fac)           # [C, cap] discriminator
        in_specs.append(P(axis_name, None))
    if partial:
        args.append(_dead_rank_array())
        in_specs.append(P())

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()) + ((P(),) if partial else ()),
        check_vma=False,
    )
    with obs.entry_span("search", "sharded_ivf_pq",
                        queries=int(queries.shape[0]), k=int(k),
                        shards=int(nshards), refine_ratio=refine_ratio):
        out = jax.jit(fn)(*args)
        if tail_cp is not None:
            # router-side rerank over the MERGED shortlist (tiered
            # fetch of unique rows, or rabitq slot decode from the
            # packed codes); uncovered shards' -1 rows stay invalid
            # and sink at the exact ranking
            md, mi = out[0], out[1]
            rd, ri = tail_cp(queries, extra={"candidates": (md, mi)})
            out = (rd, ri) + tuple(out[2:])
    if partial:
        return _finish_partial(out, partial_ok, "sharded_ivf_pq_search")
    _record_full_coverage("sharded_ivf_pq_search")
    return out


def sharded_ivf_pq_build(
    params,
    dataset,
    mesh: Mesh,
    axis_name: str = "shard",
):
    """Sharded IVF-PQ build: quantizers (coarse centers, rotation, PQ
    codebooks) are trained ONCE on a subsample, then each device encodes
    ITS row shard under ``shard_map`` — the FLOP-heavy stage (coarse
    assignment + per-subspace argmin) scales linearly over the mesh, the
    reference's multi-GPU build split (raft-dask builds per-worker parts
    against shared quantizers). The per-shard codes are all-gathered and
    packed into the global list layout; at real DEEP-1B scale the gather
    becomes a list-owner reduce-scatter instead (each device keeps only
    its C/S lists — see ``sharded_ivf_pq_search``'s in_specs), which this
    single-host rehearsal cannot exercise.

    Returns a regular ``ivf_pq.Index`` with GLOBAL row ids; pass it to
    ``sharded_ivf_pq_search`` to search list-sharded over the mesh.
    """
    from raft_tpu.neighbors import ivf_pq

    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        raise ValueError(f"dataset rows {n} not divisible by mesh axis {nshards}")

    frac = float(params.kmeans_trainset_fraction)
    if 0 < frac < 1.0 and int(n * frac) >= int(params.n_lists):
        trainset = dataset[:: max(int(1.0 / frac), 1)]
    else:
        trainset = dataset
    quant = ivf_pq._quantizer_index(params, trainset, dim)

    def local_encode(part):
        labels, packed = ivf_pq.encode(quant, part)
        return labels, packed

    fn = shard_map(
        local_encode,
        mesh=mesh,
        in_specs=(P(axis_name, None),),
        out_specs=(P(axis_name), P(axis_name, None)),
        check_vma=False,
    )
    labels, packed = jax.jit(fn)(dataset)

    import numpy as np
    from raft_tpu.neighbors.ivf_flat import _aligned_cap, _pack_lists

    ids = jnp.arange(n, dtype=jnp.int32)
    counts = np.bincount(np.asarray(labels), minlength=quant.n_lists)
    cap = _aligned_cap(int(counts.max()))
    codes_packed, indices, list_sizes = _pack_lists(
        packed, labels, ids, quant.n_lists, cap
    )
    rec_norms = ivf_pq._rec_norms(
        codes_packed, quant.pq_centers, int(params.codebook_kind),
        quant.pq_dim, int(params.pq_bits),
    )
    import dataclasses as _dc

    return ivf_pq._attach_cache(_dc.replace(
        quant,
        codes=codes_packed,
        indices=indices,
        list_sizes=list_sizes,
        rec_norms=rec_norms,
    ))


def sharded_cagra_build(
    params,
    dataset,
    mesh: Mesh,
    axis_name: str = "shard",
):
    """Row-sharded CAGRA: each shard builds an independent graph over its
    dataset partition — the raft-dask per-worker-index model (each Dask
    worker builds/owns an ANN index over its partition; queries broadcast,
    results merged). Returns a ``cagra.Index`` whose arrays carry a
    leading shard axis ([S, rows, ...]) with LOCAL graph ids.

    The per-shard builds run sequentially on the default device (the
    build pipeline is host-orchestrated); the stacked result is laid out
    for ``sharded_cagra_search``'s shard_map.
    """
    from raft_tpu.neighbors import cagra

    import dataclasses

    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        raise ValueError(f"dataset rows {n} not divisible by mesh axis {nshards}")
    rows = n // nshards
    # per-shard inline packing happens below with a GLOBAL dequant scale
    # (per-shard scales would diverge and the stacked Index carries one).
    # Eligibility is budgeted on the PER-SHARD residency (max_rows=rows):
    # search-time HBM holds one shard's table under shard_map, so an
    # S-way mesh keeps the fused beam kernel at S times the single-chip
    # scale (the build still materializes the stacked pack host-side —
    # transient, not the search-time bound)
    want_inline = bool(params.inline_codes)
    params = dataclasses.replace(params, inline_codes=False)
    subs = []
    for s in range(nshards):
        subs.append(cagra.build(params, dataset[s * rows:(s + 1) * rows]))
    graphs = jnp.stack([s.graph for s in subs])          # [S, rows, deg]
    datasets = jnp.stack([s.dataset for s in subs])      # [S, rows, d]
    norms = (jnp.stack([s.data_norms for s in subs])
             if subs[0].data_norms is not None else None)
    out = cagra.Index(dataset=datasets, graph=graphs,
                      metric=subs[0].metric, data_norms=norms)
    d = dataset.shape[1]
    deg = graphs.shape[2]
    need_norms = out.metric != DistanceType.InnerProduct
    if want_inline and cagra._inline_eligible(n, d, deg, need_norms,
                                              max_rows=rows):
        scale = cagra._code_scale(dataset)
        packs, codes = [], []
        for s in subs:
            p_, c_, _ = cagra._pack_tables(
                s.dataset, s.graph, need_norms, scale=scale)
            packs.append(p_)
            codes.append(c_)
        out = dataclasses.replace(
            out, nbr_pack=jnp.stack(packs),              # [S, rows, W]
            flat_codes=jnp.stack(codes),                 # [S, rows, d] i8
            code_scale=float(scale),
        )
    return out


def sharded_cagra_search(
    search_params,
    index,
    queries,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
) -> Tuple[jax.Array, jax.Array]:
    """Beam search over a row-sharded CAGRA index (from
    ``sharded_cagra_build``): queries are replicated, every device runs
    the beam search on its own sub-graph, local ids get the shard's row
    offset, and the per-shard top-ks are all-gathered + merged over ICI
    (the knn_merge_parts-over-comms pattern,
    detail/knn_merge_parts.cuh:140).

    When the index carries the stacked inline layout (sharded_cagra_build
    with inline_codes=True), each shard runs the FUSED Pallas beam kernel
    on its own sub-graph — the same kernel as single-chip search, with
    the per-shard packed table and int8 codes threaded through shard_map
    (local itopk per shard, merged over ICI; the reference's multi-GPU
    CAGRA similarly runs its single-CTA kernel per GPU and merges).
    ``scan_impl`` resolution matches single-device search: "auto" picks
    the kernel on TPU, the exact scattered-gather path elsewhere;
    "pallas_interpret" forces the kernel in interpret mode (CPU-mesh
    parity tests / dryrun)."""
    from raft_tpu.neighbors import cagra

    queries = jnp.asarray(queries)
    nshards = mesh.shape[axis_name]
    S, rows, _ = index.dataset.shape
    if S != nshards:
        raise ValueError(f"index has {S} shards, mesh axis has {nshards}")
    select_min = is_min_close(index.metric)
    itopk, width, iters, n_seeds = cagra.search_plan(search_params, k)
    has_norms = index.data_norms is not None
    dtype = str(getattr(search_params, "compute_dtype", "auto"))
    requested = str(getattr(search_params, "scan_impl", "auto"))
    # same resolver + validation as single-device cagra.search
    impl = cagra._resolve_beam_impl(requested, index, dtype)
    fused = impl.startswith("pallas")
    if fused and index.nbr_pack is None:
        raise ValueError(
            "scan_impl=%r needs the stacked inline layout (build with "
            "sharded_cagra_build inline_codes=True)" % impl)
    if fused and dtype != "auto":
        raise ValueError(
            "scan_impl=%r scores int8 traversal distances; compute_dtype "
            "must stay 'auto' (got %r)" % (impl, dtype))

    def local(q, ds, graph, *rest):
        rank = jax.lax.axis_index(axis_name)
        rest = list(rest)
        norms = rest.pop(0)[0] if has_norms else None
        if fused:
            pack = rest.pop(0)[0]                        # [rows, W]
            codes = rest.pop(0)[0]                       # [rows, d] i8
            # graft-lint: allow-hand-wired-pipeline cagra's beam loop compiles as one scan node (ROADMAP 8(b)); the sharded variant calls the kernel arm directly
            d, i = cagra._beam_search_pallas(
                q, ds[0], graph[0], norms, pack, codes,
                jnp.float32(index.code_scale), int(k), itopk, width,
                iters, int(index.metric), n_seeds,
                impl == "pallas_interpret",
            )
        else:
            # graft-lint: allow-hand-wired-pipeline cagra's beam loop compiles as one scan node (ROADMAP 8(b)); the sharded variant calls the kernel arm directly
            d, i = cagra._beam_search(
                q, ds[0], graph[0], norms, int(k), itopk, width, iters,
                int(index.metric), "f32" if dtype == "auto" else dtype,
                n_seeds,
            )
        i = jnp.where(i >= 0, i + (rank * rows).astype(i.dtype), -1)
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        return merge_topk(gd, gi, k, select_min)

    args = [queries, index.dataset, index.graph]
    in_specs = [P(), P(axis_name, None, None), P(axis_name, None, None)]
    if has_norms:
        args.append(index.data_norms)
        in_specs.append(P(axis_name, None))
    if fused:
        args.append(index.nbr_pack)
        in_specs.append(P(axis_name, None, None))
        args.append(index.flat_codes)
        in_specs.append(P(axis_name, None, None))

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    with obs.entry_span("search", "sharded_cagra",
                        queries=int(queries.shape[0]), k=int(k),
                        shards=int(nshards)):
        return jax.jit(fn)(*args)


def sharded_ivf_build(
    params,
    dataset,
    mesh: Mesh,
    axis_name: str = "shard",
):
    """Sharded IVF-Flat build: coarse centers are trained ONCE on a
    subsample (the reference trains on a fraction anyway,
    kmeans_trainset_fraction), then every shard packs ITS dataset rows
    into the shared list structure — the per-shard extend +
    shared-centers pattern of the reference's multi-GPU builds. Returns
    an ``ivf_flat.Index`` whose list arrays carry a leading shard axis
    ([S, n_lists, cap, ...]) with GLOBAL row ids, consumable by
    ``sharded_ivf_row_search``."""
    from raft_tpu.neighbors import ivf_flat

    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    nshards = mesh.shape[axis_name]
    if n % nshards != 0:
        raise ValueError(f"dataset rows {n} not divisible by mesh axis {nshards}")
    rows = n // nshards
    subs = []
    for s in range(nshards):
        part = dataset[s * rows:(s + 1) * rows]
        ids = jnp.arange(s * rows, (s + 1) * rows, dtype=jnp.int32)
        if s == 0:
            sub = ivf_flat.build(params, part, row_ids=ids)
            empty = ivf_flat.Index(
                centers=sub.centers,
                storage=jnp.zeros((sub.n_lists, 0) + sub.storage.shape[2:],
                                  sub.storage.dtype),
                indices=jnp.zeros((sub.n_lists, 0), jnp.int32),
                list_sizes=jnp.zeros((sub.n_lists,), jnp.int32),
                metric=sub.metric, metric_arg=sub.metric_arg,
                data_norms=(jnp.zeros((sub.n_lists, 0), jnp.float32)
                            if sub.data_norms is not None else None),
            )
        else:
            # every later shard packs its rows against shard-0's centers
            # (shared coarse quantizer -> identical bucketing everywhere)
            sub = ivf_flat.extend(empty, part, ids)
        subs.append(sub)
    cap = max(s.storage.shape[1] for s in subs)

    def padcap(a, fill):
        return jnp.pad(a, [(0, 0), (0, cap - a.shape[1])] +
                       [(0, 0)] * (a.ndim - 2), constant_values=fill)

    storage = jnp.stack([padcap(s.storage, 0) for s in subs])
    indices = jnp.stack([padcap(s.indices, -1) for s in subs])
    sizes = jnp.stack([s.list_sizes for s in subs])
    centers = jnp.stack([s.centers for s in subs])
    norms = (jnp.stack([padcap(s.data_norms, 0) for s in subs])
             if subs[0].data_norms is not None else None)
    from raft_tpu.neighbors.ivf_flat import Index as FlatIndex

    return FlatIndex(centers=centers, storage=storage, indices=indices,
                     list_sizes=sizes, metric=subs[0].metric,
                     data_norms=norms)


def sharded_ivf_row_search(
    search_params,
    index,
    queries,
    k: int,
    mesh: Mesh,
    axis_name: str = "shard",
) -> Tuple[jax.Array, jax.Array]:
    """Search a row-sharded IVF-Flat index (from ``sharded_ivf_build``):
    every device probes its own full list structure (which holds only its
    dataset partition's rows) with the FULL n_probes, then shard top-ks
    are all-gathered + merged."""
    from raft_tpu.neighbors import ivf_flat

    queries = jnp.asarray(queries)
    nshards = mesh.shape[axis_name]
    S = index.centers.shape[0]
    if S != nshards:
        raise ValueError(f"index has {S} shards, mesh axis has {nshards}")
    C = index.centers.shape[1]
    n_probes = int(min(search_params.n_probes, C))
    select_min = is_min_close(index.metric)
    metric = int(index.metric)
    group = ivf_flat.adaptive_query_group(
        int(queries.shape[0]), n_probes, C, int(search_params.query_group),
    )
    has_norms = index.data_norms is not None

    def local(q, centers, storage, indices, list_sizes, *rest):
        norms = rest[0][0] if has_norms else None
        # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path: one collective per-shard scan + merge, no multi-stage tail
        d, i = ivf_flat._ivf_search(
            q, centers[0], storage[0], indices[0], list_sizes[0],
            int(k), n_probes, metric, group,
            int(search_params.bucket_batch), 0,
            str(search_params.compute_dtype),
            float(search_params.local_recall_target),
            float(search_params.merge_recall_target),
            norms, None,
        )
        gd = jax.lax.all_gather(d, axis_name, axis=1, tiled=True)
        gi = jax.lax.all_gather(i, axis_name, axis=1, tiled=True)
        return merge_topk(gd, gi, k, select_min)

    args = [queries, index.centers, index.storage, index.indices,
            index.list_sizes]
    in_specs = [P(), P(axis_name, None, None), P(axis_name, None, None, None),
                P(axis_name, None, None), P(axis_name, None)]
    if has_norms:
        args.append(index.data_norms)
        in_specs.append(P(axis_name, None, None))

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    with obs.entry_span("search", "sharded_ivf_row",
                        queries=int(queries.shape[0]), k=int(k),
                        shards=int(nshards)):
        return jax.jit(fn)(*args)


def sharded_pairwise_distance(
    x,
    y,
    mesh: Mesh,
    axis_name: str = "shard",
    metric="sqeuclidean",
    metric_arg: float = 2.0,
) -> jax.Array:
    """Pairwise distance with x row-sharded over the mesh: each device
    computes its row block against replicated y; the result stays sharded
    (the caller sees one logical [m, n] array)."""
    from raft_tpu.distance.pairwise import _pairwise

    metric = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    nshards = mesh.shape[axis_name]
    if x.shape[0] % nshards != 0:
        raise ValueError(f"x rows {x.shape[0]} not divisible by mesh axis {nshards}")

    def local(xs, yr):
        return _pairwise(xs, yr, int(metric), float(metric_arg), None, None)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(axis_name, None),
        check_vma=False,
    )
    return jax.jit(fn)(x, y)
