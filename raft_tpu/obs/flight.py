"""Flight recorder: a bounded ring of recent span/metric/error events.

In ``RAFT_TPU_OBS=flight`` mode every completed root span, metric
update, and classified error lands in a fixed-size ring buffer
(:data:`DEFAULT_CAPACITY` events, oldest evicted first). The ring is
dumpable as JSONL on demand (:func:`dump`) and dumps ITSELF — once per
process — when :func:`on_error` sees a classified ``fatal`` or
``dead_backend`` failure, so a wedged TPU job leaves a post-mortem
artifact under ``RAFT_TPU_OBS_DIR`` the same way ``core/exit_guard``
leaves an honest exit code.

Dump grammar: one JSON object per line, every line carrying ``t``
(unix seconds) and ``kind``:

* ``{"kind": "span", "thread": ..., "tree": {nested span dict}}``
* ``{"kind": "metric", "name": ..., "value": ..., "labels": {...}}``
* ``{"kind": "error", "error_kind": "oom"|..., "type": ..., "message": ...}``
* ``{"kind": "event", "event": ..., ...}`` — library breadcrumbs
  (retries, ladder downshifts, injected faults, checkpoint saves)
* ``{"kind": "waterfall", "trace_id": ..., "stages": [...], ...}`` — a
  completed graft-trace waterfall (:mod:`raft_tpu.obs.trace`); dumps
  from different processes stitch by ``trace_id``
  (``scripts/obs_report.py stitch``)
* a final ``{"kind": "snapshot", "metrics": {...}}`` line — the full
  registry at dump time.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import List, Optional

from raft_tpu.obs import config
from raft_tpu.obs import metrics

DEFAULT_CAPACITY = 4096

_lock = threading.Lock()
_events: "collections.deque" = collections.deque(maxlen=DEFAULT_CAPACITY)
_auto_dumped = False
_last_dump_path: Optional[str] = None
# monotonic per-process dump sequence: two dumps in the same wall-clock
# second used to compute the same flight-<pid>-<unix>.jsonl path and the
# second silently OVERWROTE the first (ISSUE 13 satellite) — the
# counter makes every default path distinct for the process lifetime
_dump_seq = itertools.count(1)


def record(kind: str, **fields) -> None:
    """Append one event to the ring (no-op outside flight mode)."""
    if not config.FLIGHT:
        return
    evt = {"t": time.time(), "kind": kind}
    evt.update(fields)
    with _lock:
        _events.append(evt)


def event(name: str, **fields) -> None:
    """A library breadcrumb (``kind="event"``): retries, ladder
    downshifts, fault injections, checkpoint saves..."""
    record("event", event=name, **fields)


def events() -> List[dict]:
    """The current ring contents, oldest first."""
    with _lock:
        return list(_events)


def clear() -> None:
    global _auto_dumped, _last_dump_path
    with _lock:
        _events.clear()
        _auto_dumped = False
        _last_dump_path = None


def last_dump_path() -> Optional[str]:
    return _last_dump_path


def dump(path: Optional[str] = None, reason: str = "manual") -> str:
    """Write the ring + a final metrics-snapshot line as JSONL.

    ``path`` defaults to ``RAFT_TPU_OBS_DIR`` (or cwd) /
    ``flight-<pid>-<unix>-<seq>.jsonl`` — ``seq`` is a monotonic
    per-process counter, so two dumps landing in the same second get
    distinct paths instead of the later overwriting the earlier.
    Returns the path written.
    """
    global _last_dump_path
    if path is None:
        d = config.obs_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flight-{os.getpid()}-{int(time.time())}"
               f"-{next(_dump_seq):03d}.jsonl")
    with _lock:
        evts = list(_events)
    with open(path, "w") as fp:
        for evt in evts:
            fp.write(json.dumps(evt, default=str) + "\n")
        fp.write(json.dumps({
            "t": time.time(), "kind": "snapshot", "reason": reason,
            "metrics": metrics.snapshot(runtime_gauges=False)["metrics"],
        }, default=str) + "\n")
    with _lock:
        # guarded like clear()'s write: last_dump_path() from another
        # thread (the exit guard, tests) must not read a torn update
        _last_dump_path = path
    metrics.counter("flight_dumps", reason=reason)
    return path


# fatal/dead_backend spellings duplicated from resilience.errors — obs
# must stay import-leaf (resilience imports obs, never the reverse)
_AUTO_DUMP_KINDS = ("fatal", "dead_backend")

# one failure traverses NESTED recovery layers (stream.py: run_halving
# wraps resilience.run, both classify the same exception), so repeat
# classifications of the same live exception object must count once.
# The seen-marker lives ON the exception (builtin exceptions accept
# attributes but not weakrefs, and an id()-keyed cache could suppress a
# new failure at a recycled address); the rare attribute-less exception
# type just counts every time.
_COUNTED_ATTR = "_raft_tpu_obs_counted"


def _already_counted(exc: BaseException) -> bool:
    if getattr(exc, _COUNTED_ATTR, False):
        return True
    try:
        setattr(exc, _COUNTED_ATTR, True)
    except (AttributeError, TypeError):
        pass                     # immutable exception: count every time
    return False


def on_error(kind: str, exc: Optional[BaseException] = None,
             where: Optional[str] = None) -> None:
    """The resilience layer's error hook: counts
    ``errors_total{kind}`` (once per distinct exception object, however
    many nested recovery layers classify it), records an error event,
    and — in flight mode, once per process — auto-dumps the ring when
    ``kind`` is ``fatal`` or ``dead_backend``. Never raises: a broken
    disk must not mask the error being recorded."""
    global _auto_dumped
    if not config.ENABLED:
        return
    try:
        if exc is not None and _already_counted(exc):
            return
        metrics.counter("errors_total", kind=kind)
        record("error", error_kind=kind, where=where,
               type=type(exc).__name__ if exc is not None else None,
               message=(str(exc)[:500] if exc is not None else None))
        if kind in _AUTO_DUMP_KINDS and config.FLIGHT:
            with _lock:
                if _auto_dumped:
                    return
                _auto_dumped = True
            dump(reason=f"auto:{kind}")
    except Exception:  # noqa: BLE001
        pass
