"""Fleet metrics federation: merge per-worker snapshots into one view.

The router is the natural scrape point for the fabric, but through r12
each worker process owned a private metrics registry the router never
saw. Federation (ISSUE 13) makes the fleet one surface: the
``collect_metrics`` worker RPC returns each worker's
``obs.snapshot()['metrics']`` map, and :func:`merge_metric_maps` folds
them — every point gains a ``worker`` label naming its source, so
identical series from different workers stay distinct instances
(Prometheus-style federation: label, never sum, across instances).

Outputs: one JSON snapshot (:func:`federated_snapshot`) and one
Prometheus text exposition (:func:`render_prometheus` — the
snapshot-shaped twin of :func:`raft_tpu.obs.metrics.export_prometheus`,
which reads the live registry instead).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from raft_tpu.obs import metrics as _metrics

WORKER_LABEL = "worker"


def merge_metric_maps(parts: Dict[str, dict]) -> dict:
    """Merge ``{source_label: metrics_map}`` (each a
    ``snapshot()['metrics']`` dict) into one metrics map whose every
    point carries ``worker=<source_label>``.

    A name registered with conflicting kinds across sources keeps the
    first kind seen and records the clash under ``_conflicts`` instead
    of silently mixing exposition types."""
    out: dict = {}
    conflicts: List[str] = []
    for src in sorted(parts):
        mmap = parts[src] or {}
        for name in sorted(mmap):
            m = mmap[name]
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {"kind": m.get("kind"), "points": []}
            elif dst["kind"] != m.get("kind"):
                conflicts.append(
                    f"{name}: {src} says {m.get('kind')!r}, "
                    f"kept {dst['kind']!r}")
                continue
            for p in m.get("points", []):
                q = dict(p)
                q["labels"] = dict(p.get("labels", {}))
                q["labels"][WORKER_LABEL] = str(src)
                dst["points"].append(q)
    if conflicts:
        out["_conflicts"] = {"kind": "meta", "points": conflicts}
    return out


def federated_snapshot(parts: Dict[str, dict],
                       workers: Optional[List] = None) -> dict:
    """A snapshot-shaped federated view: ``{"mode": "federated",
    "time_unix": ..., "workers": [...], "metrics": {...}}``. ``workers``
    names the live sources (defaults to the keys of ``parts``)."""
    return {
        "mode": "federated",
        "time_unix": time.time(),
        "workers": sorted(str(w) for w in (
            workers if workers is not None else parts)),
        "metrics": merge_metric_maps(parts),
    }


def render_prometheus(metrics_map: dict) -> str:
    """Render a snapshot-shaped metrics map (``snapshot()['metrics']``
    or :func:`merge_metric_maps` output) as Prometheus text exposition
    0.0.4 — delegates to :func:`raft_tpu.obs.metrics.render_metrics_map`
    (ONE rendering path shared with the live exporter, so
    naming/escaping rules cannot diverge; federation meta entries like
    ``_conflicts`` are skipped there)."""
    return _metrics.render_metrics_map(metrics_map)
