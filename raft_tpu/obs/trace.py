"""graft-trace: cross-process trace context + per-query waterfalls.

PR 4's spans are per-process trees; PR 6 made the serving path
multi-process, so one query crossing ``Server.submit -> batcher ->
fabric router -> worker RPC -> shard scan -> merge`` used to leave
disconnected fragments with no shared identity. This module is the
shared identity (ISSUE 13):

* **trace context** — a ``(trace_id, parent_span_id)`` pair minted at
  the serving entry (:func:`start_trace`), carried across every
  transport ``call`` as the structured :data:`WIRE_FIELD` payload field
  (:func:`traced_payload` injects it; graft-lint rule GL019 keeps
  call sites honest), and adopted worker-side (:func:`adopt` +
  :func:`activate`) so the worker's spans carry the same trace id;
* **waterfall assembly** — the router appends per-stage timings
  (:func:`stage`: ``queue_wait`` / ``linger`` / ``rpc`` /
  ``worker_scan`` / ``merge`` / ``rerank``, hedge attempts and retries
  as sibling stages with a ``status``) into a bounded per-trace record,
  completed by :func:`finish` into a ring readable with
  :func:`trace_report` — and, in flight mode, recorded as a
  ``waterfall`` event so cross-process dumps stitch by trace id
  (``scripts/obs_report.py``).

Off-mode contract (the PR-4 allocation guard extends here): every
public function returns after one module-attribute read
(:data:`raft_tpu.obs.config.ENABLED`) — no ids are minted, no ring is
touched, :func:`traced_payload` hands its payload back unmodified.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from raft_tpu.obs import config

# the structured RPC-payload field carrying the context across the
# process boundary. The field rides the payload INTO the worker's
# handler untouched; each traced handler (procgroup._do_search today)
# adopts + activates it itself — a new traced RPC must do the same, or
# its worker-side spans carry no trace id
WIRE_FIELD = "trace"

# bounded assembly state: open waterfalls a failure orphaned are
# evicted oldest-first past MAX_OPEN; completed waterfalls ride a ring
# sized like the flight recorder's event ring so a chaos loadgen's
# whole answer stream stays reportable
MAX_OPEN = 1024
MAX_DONE = 4096
# stages kept per waterfall before truncation (a retry storm must not
# grow an unbounded record); the drop count is kept on the waterfall
MAX_STAGES = 128

_lock = threading.Lock()
_open: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
_done: "collections.deque" = collections.deque(maxlen=MAX_DONE)
# lifetime completion count: _done_total - len(_done) = how many
# completed waterfalls the bounded ring has evicted — consumers that
# present per-run totals (the loadgen columns) must not pretend the
# ring is the run (no silent caps)
_done_total = 0
_ids = itertools.count(1)
_pid_salt: Optional[str] = None

_tls = threading.local()


class TraceContext:
    """One query's identity: ``trace_id`` names the whole path,
    ``parent_span_id`` the entry span children attach under."""

    __slots__ = ("trace_id", "parent_span_id")

    def __init__(self, trace_id: str, parent_span_id: str):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id!r}, {self.parent_span_id!r})"


def _mint_id() -> str:
    # pid + random salt + monotonic counter: unique across the fabric's
    # processes with no coordination (two workers minting concurrently
    # can never collide on the salt+pid prefix)
    global _pid_salt
    if _pid_salt is None:
        _pid_salt = f"{os.getpid():x}.{os.urandom(3).hex()}"
    return f"{_pid_salt}.{next(_ids):x}"


# ---------------------------------------------------------------------------
# context minting / wire format / ambient adoption
# ---------------------------------------------------------------------------


def start_trace(entry: str, **attrs) -> Optional[TraceContext]:
    """Mint a trace context at a serving entry point and open its
    waterfall. Returns ``None`` when obs is off."""
    if not config.ENABLED:
        return None
    tid = _mint_id()
    ctx = TraceContext(tid, _mint_id())
    wf = {
        "trace_id": tid,
        "entry": entry,
        "t_unix": time.time(),
        "_t0": time.perf_counter(),
        "attrs": dict(attrs),
        "stages": [],
        "dropped_stages": 0,
    }
    with _lock:
        _open[tid] = wf
        while len(_open) > MAX_OPEN:
            _open.popitem(last=False)      # orphaned by a failure: evict
    return ctx


def to_wire(ctx: Optional[TraceContext]) -> Optional[dict]:
    """The structured RPC field for ``ctx`` (None passes through)."""
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id,
            "parent_span_id": ctx.parent_span_id}


def adopt(wire) -> Optional[TraceContext]:
    """Rebuild a context from a :data:`WIRE_FIELD` payload field (the
    worker side of the propagation). Tolerates None/garbage — a
    malformed field must degrade to an untraced call, never fail it."""
    if not config.ENABLED or not isinstance(wire, dict):
        return None
    tid = wire.get("trace_id")
    if not isinstance(tid, str):
        return None
    psid = wire.get("parent_span_id")
    return TraceContext(tid, psid if isinstance(psid, str) else tid)


def traced_payload(payload: Optional[dict],
                   ctx: Optional[TraceContext] = None) -> Optional[dict]:
    """Inject the trace context (``ctx`` or the thread's ambient one)
    into an RPC payload under :data:`WIRE_FIELD`. The GL019-enforced
    helper: every data-plane transport ``call`` site threads its payload
    through here. Off mode (or no context) returns ``payload``
    unchanged — one module-attribute read."""
    if not config.ENABLED:
        return payload
    if ctx is None:
        ctx = current()
    if ctx is None:
        return payload
    out = dict(payload) if payload else {}
    out[WIRE_FIELD] = to_wire(ctx)
    return out


def current() -> Optional[TraceContext]:
    """The thread's ambient trace context, or None."""
    if not config.ENABLED:
        return None
    return getattr(_tls, "ctx", None)


def current_id() -> Optional[str]:
    """The ambient trace id (the span layer stamps it on every span)."""
    ctx = getattr(_tls, "ctx", None)
    return ctx.trace_id if ctx is not None else None


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make ``ctx`` the thread's ambient context for the body (the
    worker-side adoption: spans opened inside carry its trace id)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


# ---------------------------------------------------------------------------
# waterfall assembly
# ---------------------------------------------------------------------------


def _trace_id(ctx_or_id) -> Optional[str]:
    if isinstance(ctx_or_id, TraceContext):
        return ctx_or_id.trace_id
    if isinstance(ctx_or_id, str):
        return ctx_or_id
    return None


def stage(ctx_or_id, name: str, ms: Optional[float] = None,
          t_start: Optional[float] = None, status: str = "ok",
          **attrs) -> None:
    """Append one stage to an open waterfall. ``ms`` is the stage's
    duration; ``t_start`` (a ``time.perf_counter()`` value) positions it
    on the waterfall's time axis as ``t_off_ms``. ``status`` marks
    hedge winners/losers, failures, and retries (``"ok"`` |
    ``"hedge_win"`` | ``"hedge_loser"`` | ``"failed"`` | ``"timeout"``
    | ``"retry"`` | ...)."""
    if not config.ENABLED:
        return
    tid = _trace_id(ctx_or_id)
    if tid is None:
        return
    entry: Dict[str, object] = {"stage": name, "status": status}
    if ms is not None:
        entry["ms"] = round(float(ms), 4)
    for k, v in attrs.items():
        if v is not None:
            entry[k] = v
    with _lock:
        wf = _open.get(tid)
        if wf is None:
            return                        # evicted / already finished
        if t_start is not None:
            entry["t_off_ms"] = round(
                (float(t_start) - wf["_t0"]) * 1e3, 4)
        if len(wf["stages"]) < MAX_STAGES:
            wf["stages"].append(entry)
        else:
            wf["dropped_stages"] += 1


def finish(ctx_or_id, status: str = "ok", **attrs) -> Optional[dict]:
    """Complete a waterfall: stamp total ``ms`` + ``status``, move it to
    the done ring, record it to the flight ring (``kind="waterfall"``)
    and the ``trace.waterfalls_total{status}`` counter. Returns the
    completed record (shared with the ring — treat as read-only)."""
    global _done_total
    if not config.ENABLED:
        return None
    tid = _trace_id(ctx_or_id)
    if tid is None:
        return None
    with _lock:
        wf = _open.pop(tid, None)
        if wf is None:
            return None
        wf["ms"] = round((time.perf_counter() - wf.pop("_t0")) * 1e3, 4)
        wf["status"] = status
        if attrs:
            wf["attrs"].update(attrs)
        if not wf["dropped_stages"]:
            del wf["dropped_stages"]
        _done.append(wf)
        _done_total += 1
    from raft_tpu.obs import metrics

    metrics.counter("trace.waterfalls_total", status=status)
    if config.FLIGHT:
        from raft_tpu.obs import flight

        flight.record("waterfall", **wf)
    return wf


def trace_report(trace_id: Optional[str] = None,
                 limit: Optional[int] = None) -> List[dict]:
    """Completed waterfalls, oldest first (``obs.trace_report()``).

    ``trace_id`` filters to one trace; ``limit`` keeps the newest N.
    Records are shared with the ring — treat them as read-only."""
    with _lock:
        items = list(_done)
    if trace_id is not None:
        items = [w for w in items if w["trace_id"] == trace_id]
    if limit is not None:
        items = items[-int(limit):]
    return items


def _percentile(sorted_ms: List[float], p: float) -> Optional[float]:
    if not sorted_ms:
        return None
    # nearest-rank on the sorted sample — dependency-free (this module
    # must stay importable without numpy)
    idx = min(len(sorted_ms) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_ms) - 1)))))
    return round(sorted_ms[idx], 4)


def stage_stats(waterfalls: List[dict]) -> dict:
    """Per-stage latency attribution over a set of waterfalls: for each
    stage name, ``{count, p50_ms, p99_ms, hedge_wins, hedge_losers,
    failed, retries}`` — the columns ``serve_loadgen --fabric`` emits
    and ``obs_report.py`` renders. Failed/timeout/retry stages carry no
    ``ms`` toward the percentiles of successful work."""
    per: Dict[str, dict] = {}
    for wf in waterfalls:
        for s in wf.get("stages", ()):
            d = per.setdefault(str(s.get("stage")), {
                "count": 0, "_ms": [], "hedge_wins": 0,
                "hedge_losers": 0, "failed": 0, "retries": 0,
            })
            d["count"] += 1
            status = s.get("status", "ok")
            if status == "hedge_win":
                d["hedge_wins"] += 1
            elif status == "hedge_loser":
                d["hedge_losers"] += 1
            elif status in ("failed", "timeout"):
                d["failed"] += 1
            elif status == "retry":
                d["retries"] += 1
            if s.get("ms") is not None and status in ("ok", "hedge_win"):
                d["_ms"].append(float(s["ms"]))
    out: Dict[str, dict] = {}
    for name in sorted(per):
        d = per[name]
        ms = sorted(d.pop("_ms"))
        d["p50_ms"] = _percentile(ms, 50)
        d["p99_ms"] = _percentile(ms, 99)
        out[name] = d
    return out


def ring_stats() -> dict:
    """Honesty accounting for the bounded done ring: ``completed_total``
    waterfalls finished since the last :func:`reset`, ``retained`` still
    readable, ``evicted`` aged out of the ring. Consumers presenting
    per-run aggregates (the loadgen waterfall columns) surface
    ``evicted`` so a truncated window never reads as the whole run."""
    with _lock:
        retained = len(_done)
        return {"completed_total": _done_total, "retained": retained,
                "evicted": _done_total - retained}


def waterfall_complete(wf: dict) -> bool:
    """ONE definition of a complete end-to-end fabric waterfall — the
    chaos acceptance (tests/test_fabric.py) and the loadgen's
    ``complete_fraction`` column consume this same predicate, so the
    shipped artifact and the test cannot silently diverge: the query
    was ANSWERED (ok/degraded), a ``merge`` stage closed it, and every
    shard it reports covered contributed a device-complete
    ``worker_scan`` stage from the worker that served it."""
    if wf.get("status") not in ("ok", "degraded"):
        return False
    stages = wf.get("stages", ())
    if not any(s.get("stage") == "merge" for s in stages):
        return False
    covered = set(wf.get("attrs", {}).get("covered_shards", ()))
    scanned = {s.get("shard") for s in stages
               if s.get("stage") == "worker_scan"
               and s.get("device_complete")}
    return covered <= scanned


def reset() -> None:
    """Drop open and completed waterfalls (tests / between runs)."""
    global _done_total
    with _lock:
        _open.clear()
        _done.clear()
        _done_total = 0
