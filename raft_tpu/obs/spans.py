"""Structured spans: host wall-clock trees that still show up in XLA traces.

``span(name, **attrs)`` is a context manager capturing host wall-clock
(and, via :meth:`Span.sync`, device-complete time) into a PER-THREAD
span tree; every span also opens a ``jax.profiler.TraceAnnotation`` so
the same names line up in an XLA/TPU profiler capture — this absorbs
the old ``core/trace.py`` NVTX-analog ranges (which now delegate here).

Trace-time semantics: a span opened inside jit-traced Python executes
host-side AT TRACE TIME only — it measures trace/compile attribution
(the compile-vs-dispatch split TPU-KNN's analysis method needs) and is
absent from steady-state cached dispatches. Spans at the public entry
points (outside jit) measure real per-call wall-clock.

Off mode (:data:`raft_tpu.obs.config.ENABLED` False) returns a shared
no-op singleton: one module-attribute read, no allocation that outlives
the call.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

from raft_tpu.obs import config
from raft_tpu.obs import metrics
from raft_tpu.obs import trace as _trace

# children kept per span before truncation (a 100k-chunk streamed search
# must not grow an unbounded tree); the drop count is recorded
MAX_CHILDREN = 64
# completed per-thread root trees kept for inspection
MAX_COMPLETED = 256


class _NullSpan:
    """Shared off-mode singleton: a reusable, reentrant no-op span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def sync(self, value=None):
        return value


_NULL_SPAN = _NullSpan()

_tls = threading.local()
_lock = threading.Lock()
_completed: "collections.deque" = collections.deque(maxlen=MAX_COMPLETED)


def _stack(create: bool = True):
    s = getattr(_tls, "stack", None)
    if s is None and create:
        s = _tls.stack = []
    return s


class Span:
    """One timed, attributed node in the per-thread span tree."""

    __slots__ = ("name", "attrs", "t0", "ms", "device_ms", "children",
                 "dropped_children", "_ta")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.ms: Optional[float] = None
        self.device_ms: Optional[float] = None
        self.children = []
        self.dropped_children = 0
        self._ta = None

    # -- context protocol --------------------------------------------------

    def __enter__(self):
        try:
            import jax

            # scalar attrs ride into the profiler trace as annotation
            # metadata — core/trace.py's annotate(name, **kwargs) keeps
            # emitting the same TraceAnnotation payload through obs
            meta = {k: v for k, v in self.attrs.items()
                    if isinstance(v, (str, int, float, bool))}
            self._ta = jax.profiler.TraceAnnotation(self.name, **meta)
            self._ta.__enter__()
        except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow profiler annotation is best-effort; span timing must survive a profiler-less runtime
            self._ta = None
        # graft-trace adoption (ISSUE 13): a span opened under an
        # activated cross-process context carries the shared trace id,
        # so one trace id names worker-side spans, router spans, and
        # flight-dumped trees alike — the stitch key obs_report uses
        tid = _trace.current_id()
        if tid is not None and "trace_id" not in self.attrs:
            self.attrs["trace_id"] = tid
        _stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.ms = (time.perf_counter() - self.t0) * 1e3
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        if self._ta is not None:
            try:
                self._ta.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001 — profiler teardown is best-effort (see __enter__)
                pass
        stack = _stack(create=False)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:          # unbalanced exit: heal
            stack.remove(self)
        metrics.observe("span_ms", self.ms, name=self.name)
        self._finish(stack)
        return False

    def _finish(self, stack) -> None:
        if stack:
            parent = stack[-1]
            if len(parent.children) < MAX_CHILDREN:
                parent.children.append(self)
            else:
                parent.dropped_children += 1
        else:
            entry = (threading.current_thread().name, self.to_dict())
            with _lock:
                _completed.append(entry)
            if config.FLIGHT:
                from raft_tpu.obs import flight

                flight.record("span", thread=entry[0], tree=entry[1])

    # -- user API ----------------------------------------------------------

    def set(self, **attrs) -> "Span":
        """Attach attributes discovered mid-span (chosen impl, rows...)."""
        self.attrs.update(attrs)
        return self

    def sync(self, value=None):
        """Optional device-sync timestamp: block until ``value`` (any
        pytree of jax arrays) is ready and record the elapsed time as
        ``device_ms`` — the device-complete latency, distinct from the
        dispatch wall-clock ``ms``. Returns ``value``."""
        if value is not None:
            try:
                import jax

                jax.block_until_ready(value)
            except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow non-array values legitimately pass through unsynced
                pass
        self.device_ms = (time.perf_counter() - self.t0) * 1e3
        return value

    def to_dict(self) -> dict:
        d = {"name": self.name, "ms": self.ms}
        if self.device_ms is not None:
            d["device_ms"] = self.device_ms
        if self.attrs:
            d["attrs"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        if self.dropped_children:
            d["dropped_children"] = self.dropped_children
        return d


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


class _EntrySpan(Span):
    """A span at a public search/build entry point: on exit it also
    feeds the standard entry metrics (``queries_total`` /
    ``builds_total``, ``<op>_latency_ms{algo}``)."""

    __slots__ = ("op", "algo", "queries")

    def __init__(self, op: str, algo: str, queries: Optional[int],
                 attrs: dict):
        super().__init__(f"{algo}.{op}", attrs)
        self.op = op
        self.algo = algo
        self.queries = queries

    def __exit__(self, exc_type, exc, tb):
        out = super().__exit__(exc_type, exc, tb)
        if exc_type is None:
            if self.op == "search":
                if self.queries:
                    metrics.counter("queries_total", self.queries,
                                    algo=self.algo)
                metrics.observe("search_latency_ms", self.ms, algo=self.algo)
            elif self.op == "build":
                metrics.counter("builds_total", algo=self.algo)
                metrics.observe("build_latency_ms", self.ms, algo=self.algo)
        return out


def span(name: str, **attrs):
    """Open a structured span (see module docstring). Off mode returns
    the shared no-op singleton."""
    if not config.ENABLED:
        return _NULL_SPAN
    return Span(name, attrs)


def entry_span(op: str, algo: str, queries: Optional[int] = None, **attrs):
    """A :func:`span` for a public ``search``/``build`` entry point that
    also emits the standard entry metrics on clean exit:
    ``queries_total{algo}`` (+= ``queries``) and
    ``search_latency_ms{algo}`` for ``op="search"``;
    ``builds_total{algo}`` and ``build_latency_ms{algo}`` for
    ``op="build"``. The latency is the HOST wall-clock of the entry
    (trace + dispatch; device compute overlaps asynchronously) — call
    ``.sync(result)`` before exit for device-complete timing."""
    if not config.ENABLED:
        return _NULL_SPAN
    if queries is not None:
        attrs.setdefault("queries", int(queries))
        queries = int(queries)
    return _EntrySpan(op, algo, queries, attrs)


def current() -> Optional[Span]:
    """The innermost live span on THIS thread, or None."""
    s = _stack(create=False)
    return s[-1] if s else None


def recent(limit: int = MAX_COMPLETED):
    """Most recent completed per-thread root span trees, newest last:
    ``[(thread_name, tree_dict), ...]``."""
    with _lock:
        items = list(_completed)
    return items[-limit:]


def reset() -> None:
    """Drop completed trees (tests). Live per-thread stacks are left
    alone — a reset mid-span must not orphan the exit."""
    with _lock:
        _completed.clear()
