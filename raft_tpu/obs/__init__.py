"""graft-scope: structured spans, metrics registry, flight recorder.

The observability layer (ISSUE 4): the reference RAFT ships NVTX ranges
and an spdlog sink; a production TPU deployment needs per-stage
wall-clock attribution (TPU-KNN, arXiv:2206.14286 — compile vs dispatch
vs device compute), per-stage counters (FusionANNS-style scan/rerank/
merge breakdowns, arXiv:2409.16576), and a post-mortem trail when a job
wedges. Three parts, all zero-dependency:

* **spans** (:mod:`raft_tpu.obs.spans`) — ``obs.span(name, **attrs)``
  context managers building a per-thread tree of host wall-clock (and
  optional device-sync) timings, each also emitting a
  ``jax.profiler.TraceAnnotation`` so XLA profiler captures line up;
* **metrics** (:mod:`raft_tpu.obs.metrics`) — counters / gauges /
  fixed-bucket histograms (``obs.counter("tuning.dispatch", op=...)``),
  exportable as a JSON snapshot (:func:`snapshot`) or Prometheus text
  (:func:`export_prometheus`);
* **flight recorder** (:mod:`raft_tpu.obs.flight`) — a bounded ring of
  recent span/metric/error events, dumped as JSONL on demand or
  automatically on a classified fatal/dead_backend failure.

Knobs: ``RAFT_TPU_OBS=off|on|flight`` (default off; the off path is a
single module-attribute read per call site), ``RAFT_TPU_OBS_DIR`` (dump
directory). Full metric catalog: docs/observability.md.
"""

from raft_tpu.obs.config import (
    ENV_VAR,
    DIR_VAR,
    MODES,
    mode,
    obs_dir,
    reload,
    set_mode,
)
from raft_tpu.obs import config as _config
from raft_tpu.obs import federation
from raft_tpu.obs import flight as _flight
from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import spans as _spans
from raft_tpu.obs import trace
from raft_tpu.obs.trace import (
    TraceContext,
    start_trace,
    trace_report,
    traced_payload,
)
from raft_tpu.obs.metrics import (
    DEFAULT_MS_BUCKETS,
    UNIT_BUCKETS,
    capture_runtime_gauges,
    counter,
    export_prometheus,
    gauge,
    observe,
    snapshot,
)
from raft_tpu.obs.spans import Span, current, entry_span, recent, span
from raft_tpu.obs.flight import (
    dump as flight_dump,
    event,
    events as flight_events,
    last_dump_path,
    on_error,
)


def enabled() -> bool:
    """True when spans/metrics are live (mode ``on`` or ``flight``)."""
    return _config.ENABLED


def write_snapshot(path: str) -> str:
    """Write :func:`snapshot` as JSON to ``path`` (the ``--obs-snapshot``
    sidecar writer used by the bench harness). Returns ``path``."""
    import json

    with open(path, "w") as fp:
        json.dump(snapshot(), fp, indent=1, default=str)
        fp.write("\n")
    return path


def reset() -> None:
    """Drop all metrics, completed span trees, flight events, and
    trace waterfalls (tests / between bench cases). The mode is
    untouched."""
    _metrics.reset()
    _spans.reset()
    _flight.clear()
    trace.reset()


__all__ = [
    "DEFAULT_MS_BUCKETS", "DIR_VAR", "ENV_VAR", "MODES", "Span",
    "UNIT_BUCKETS",
    "TraceContext", "capture_runtime_gauges", "counter", "current",
    "enabled", "entry_span", "event", "export_prometheus", "federation",
    "flight_dump", "flight_events", "gauge", "last_dump_path", "mode",
    "obs_dir", "observe", "on_error", "recent", "reload", "reset",
    "set_mode", "snapshot", "span", "start_trace", "trace",
    "trace_report", "traced_payload", "write_snapshot",
]
