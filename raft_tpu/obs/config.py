"""Observability mode resolution (env ``RAFT_TPU_OBS``).

Three modes:

* ``off``    — (default) every obs call site returns after ONE module
               attribute read (:data:`ENABLED`); no registry, no spans,
               no events, no allocation that outlives the call.
* ``on``     — spans + metrics are live; exporters
               (:func:`raft_tpu.obs.snapshot`,
               :func:`raft_tpu.obs.export_prometheus`) have data.
* ``flight`` — ``on`` plus the flight recorder: span/metric/error
               events land in a bounded ring buffer
               (:mod:`raft_tpu.obs.flight`) and a classified
               fatal/dead_backend failure auto-dumps it as JSONL under
               ``RAFT_TPU_OBS_DIR`` — the post-mortem artifact.

The mode is resolved ONCE at import (plus on :func:`set_mode` /
:func:`reload`) into the module-level booleans :data:`ENABLED` and
:data:`FLIGHT` so the disabled hot path costs a single dict lookup
(a module attribute read), not an ``os.environ`` hit per call.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_VAR = "RAFT_TPU_OBS"
DIR_VAR = "RAFT_TPU_OBS_DIR"

MODES = ("off", "on", "flight")

# hot-path flags — read these, never os.environ, at call sites
ENABLED: bool = False
FLIGHT: bool = False

_mode: str = "off"
_override: Optional[str] = None


def _refresh() -> None:
    global ENABLED, FLIGHT, _mode
    if _override is not None:
        m = _override
    else:
        m = os.environ.get(ENV_VAR, "off").strip().lower()
        if m not in MODES:
            m = "off"
    _mode = m
    ENABLED = m != "off"
    FLIGHT = m == "flight"


def mode() -> str:
    """The active obs mode: ``off`` | ``on`` | ``flight``."""
    return _mode


def set_mode(m: Optional[str]) -> None:
    """Override the env knob in-process (``None`` restores env control)."""
    global _override
    if m is not None and m not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {m!r}")
    _override = m
    _refresh()


def reload() -> None:
    """Re-read ``RAFT_TPU_OBS`` (after an env change mid-process)."""
    _refresh()


def obs_dir() -> str:
    """Dump directory for flight-recorder artifacts
    (``RAFT_TPU_OBS_DIR``, default: the working directory)."""
    return os.environ.get(DIR_VAR, "").strip() or "."


_refresh()
