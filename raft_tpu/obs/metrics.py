"""Metrics registry: counters, gauges, fixed-bucket histograms.

The process-wide registry behind ``obs.counter/gauge/observe``. Metrics
are named like ``queries_total`` / ``tuning.dispatch`` and carry flat
string labels; a (name, sorted-labels) pair is one time series. Export
as a JSON-able snapshot (:func:`snapshot`) or Prometheus text
exposition format (:func:`export_prometheus`).

Design constraints (ISSUE 4):

* zero dependencies — dict + lock, no client library;
* the ``RAFT_TPU_OBS=off`` path is a single module-attribute read per
  call site (:data:`raft_tpu.obs.config.ENABLED`), touching neither the
  registry nor the lock;
* fixed buckets — histograms never rebucket, so concurrent observers
  only ever add into preallocated slots.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from raft_tpu.obs import config

# value <= edge lands in that bucket (Prometheus ``le`` semantics);
# the implicit +Inf bucket is always last. Spans ms-scale dispatch up
# to minute-scale builds.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000,
)

# the unit-interval preset (ISSUE 19): [0,1]-valued metrics — recall,
# coverage, fill/hit ratios — collapse into DEFAULT_MS_BUCKETS' first
# bucket (every value <= 0.5). These edges spend their resolution where
# quality metrics live: coarse below 0.5, fine toward 1.0 (a recall
# drop from 0.99 to 0.95 must move mass across an edge, not vanish
# inside one).
UNIT_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
    0.875, 0.9, 0.925, 0.95, 0.975, 0.99, 1.0,
)

_COUNTER = "counter"
_GAUGE = "gauge"
_HISTOGRAM = "histogram"

_LabelKey = Tuple[Tuple[str, str], ...]


class _Metric:
    __slots__ = ("name", "kind", "buckets", "points")

    def __init__(self, name: str, kind: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.buckets = buckets
        # counter/gauge: labelkey -> float
        # histogram:     labelkey -> [per-bucket counts (+Inf last), sum, n]
        self.points: Dict[_LabelKey, object] = {}


_lock = threading.RLock()
_registry: Dict[str, _Metric] = {}
# GL007 hook state: last-seen jit cache sizes per tracked function
_compile_last: Dict[str, int] = {}


def _label_key(labels: Dict[str, object]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _get_metric(name: str, kind: str,
                buckets: Optional[Tuple[float, ...]]) -> _Metric:
    m = _registry.get(name)
    if m is None:
        m = _Metric(name, kind, buckets)
        _registry[name] = m
    elif m.kind != kind:
        raise ValueError(
            f"metric {name!r} already registered as {m.kind}, not {kind}")
    return m


def _flight_event(name: str, value: float, labels: Dict[str, object]) -> None:
    if not config.FLIGHT:
        return
    from raft_tpu.obs import flight

    flight.record("metric", name=name, value=value,
                  labels={str(k): str(v) for k, v in labels.items()})


def counter(name: str, value: float = 1.0, /, **labels) -> None:
    """Add ``value`` (default 1) to counter ``name`` at ``labels``."""
    if not config.ENABLED:
        return
    with _lock:
        m = _get_metric(name, _COUNTER, None)
        key = _label_key(labels)
        m.points[key] = float(m.points.get(key, 0.0)) + float(value)
    _flight_event(name, float(value), labels)


def gauge(name: str, value: float, /, **labels) -> None:
    """Set gauge ``name`` at ``labels`` to ``value``."""
    if not config.ENABLED:
        return
    with _lock:
        m = _get_metric(name, _GAUGE, None)
        m.points[_label_key(labels)] = float(value)
    _flight_event(name, float(value), labels)


def observe(name: str, value: float, /,
            buckets: Optional[Tuple[float, ...]] = None, **labels) -> None:
    """Record ``value`` into histogram ``name`` at ``labels``.

    ``buckets`` (ascending upper edges, +Inf implicit) is fixed at the
    histogram's FIRST observation; later calls inherit it.
    """
    if not config.ENABLED:
        return
    value = float(value)
    with _lock:
        m = _get_metric(name, _HISTOGRAM,
                        tuple(buckets) if buckets else DEFAULT_MS_BUCKETS)
        key = _label_key(labels)
        point = m.points.get(key)
        if point is None:
            point = [[0] * (len(m.buckets) + 1), 0.0, 0]
            m.points[key] = point
        counts, _, _ = point
        for i, edge in enumerate(m.buckets):
            if value <= edge:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        point[1] += value
        point[2] += 1
    _flight_event(name, value, labels)


def reset() -> None:
    """Drop every registered series (tests / between bench cases)."""
    with _lock:
        _registry.clear()
        _compile_last.clear()


# ---------------------------------------------------------------------------
# runtime gauges: device memory + the GL007 recompile hook
# ---------------------------------------------------------------------------

# the jitted hot-path functions whose trace-cache growth means
# steady-state recompilation (the GL007 class; the jaxpr auditor sweeps
# the same caches) — (module, attribute) pairs resolved lazily
_TRACKED_JITS = (
    ("raft_tpu.matrix.select_k", "_select_k"),
    ("raft_tpu.matrix.select_k", "_tournament_topk"),
)


def capture_runtime_gauges() -> None:
    """Record point-in-time runtime gauges:

    * ``device_memory_bytes{device,stat}`` from each local device's
      ``memory_stats()`` (absent on CPU — skipped silently);
    * ``jit_cache_entries{fn}`` for the tracked hot-path jits, plus a
      ``recompiles{fn}`` counter incremented by any growth since the
      previous capture (the in-process GL007 trace-counting hook:
      steady-state serving must keep this counter flat).

    Called automatically by :func:`snapshot`; safe no-op when obs is off
    or the runtime refuses to answer.
    """
    if not config.ENABLED:
        return
    try:
        import jax

        for d in jax.local_devices():
            ms = d.memory_stats()
            if not ms:
                continue
            for stat, v in ms.items():
                if isinstance(v, (int, float)):
                    gauge("device_memory_bytes", float(v),
                          device=d.id, stat=stat)
    except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow metrics capture must never fail the caller; a mute backend just yields no gauges
        pass
    import importlib

    for mod_name, fn_name in _TRACKED_JITS:
        try:
            fn = getattr(importlib.import_module(mod_name), fn_name, None)
        except ImportError:
            continue
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:
            continue
        try:
            n = int(size_of())
        except Exception:  # noqa: BLE001 — private jax API probe; absence of the gauge is the degraded answer
            continue
        label = f"{mod_name.rsplit('.', 1)[-1]}.{fn_name}"
        gauge("jit_cache_entries", float(n), fn=label)
        with _lock:
            prev = _compile_last.get(label)
            _compile_last[label] = n
        if prev is not None and n > prev:
            counter("recompiles", n - prev, fn=label)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def snapshot(runtime_gauges: bool = True) -> dict:
    """A JSON-able snapshot of every registered series.

    Shape::

        {"mode": "on", "time_unix": ...,
         "metrics": {name: {"kind": ..., "points": [
             {"labels": {...}, "value": v}                    # counter/gauge
             {"labels": {...}, "buckets": [...], "bucket_counts": [...],
              "sum": s, "count": n}                           # histogram
         ]}}}
    """
    if runtime_gauges:
        capture_runtime_gauges()
    out: dict = {"mode": config.mode(), "time_unix": time.time(),
                 "metrics": {}}
    with _lock:
        for name in sorted(_registry):
            m = _registry[name]
            points: List[dict] = []
            for key in sorted(m.points):
                labels = dict(key)
                if m.kind == _HISTOGRAM:
                    counts, total, n = m.points[key]
                    points.append({
                        "labels": labels,
                        "buckets": list(m.buckets),
                        "bucket_counts": list(counts),
                        "sum": total,
                        "count": n,
                    })
                else:
                    points.append({"labels": labels,
                                   "value": m.points[key]})
            out["metrics"][name] = {"kind": m.kind, "points": points}
    return out


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, kind: str) -> str:
    base = _NAME_RE.sub("_", name)
    if not base.startswith("raft_tpu_"):
        base = "raft_tpu_" + base
    if kind == _COUNTER and not base.endswith("_total"):
        base += "_total"
    return base


def _prom_labels(labels: _LabelKey, extra: str = "") -> str:
    parts = [
        '%s="%s"' % (_NAME_RE.sub("_", k),
                     v.replace("\\", r"\\").replace('"', r'\"')
                      .replace("\n", r"\n"))
        for k, v in labels
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_metrics_map(metrics_map: dict) -> str:
    """Render a snapshot-shaped metrics map (``snapshot()['metrics']``
    or :func:`raft_tpu.obs.federation.merge_metric_maps` output) as
    Prometheus text exposition 0.0.4: one ``# TYPE`` line per metric,
    cumulative ``le`` buckets + ``_sum``/``_count`` for histograms.
    THE one rendering path — the live exporter and the federated one
    both delegate here, so naming/escaping rules cannot diverge.
    Underscore-prefixed entries (federation meta like ``_conflicts``)
    and unknown kinds are skipped."""
    lines: List[str] = []
    for name in sorted(metrics_map):
        if name.startswith("_"):
            continue
        m = metrics_map[name]
        kind = m.get("kind")
        if kind not in (_COUNTER, _GAUGE, _HISTOGRAM):
            continue
        pname = _prom_name(name, kind)
        lines.append(f"# TYPE {pname} {kind}")
        for p in m.get("points", ()):
            key = tuple(sorted(
                (str(k), str(v)) for k, v in p.get("labels", {}).items()))
            if kind == _HISTOGRAM:
                counts = p.get("bucket_counts", [])
                buckets = p.get("buckets", [])
                cum = 0
                for edge, c in zip(buckets, counts):
                    cum += c
                    le = 'le="%s"' % _fmt(edge)
                    lines.append(
                        f"{pname}_bucket{_prom_labels(key, le)} {cum}")
                if len(counts) > len(buckets):
                    cum += counts[-1]
                inf_le = 'le="+Inf"'
                lines.append(
                    f"{pname}_bucket{_prom_labels(key, inf_le)} {cum}")
                lines.append(f"{pname}_sum{_prom_labels(key)}"
                             f" {_fmt(p.get('sum', 0.0))}")
                lines.append(f"{pname}_count{_prom_labels(key)}"
                             f" {p.get('count', 0)}")
            else:
                lines.append(f"{pname}{_prom_labels(key)}"
                             f" {_fmt(p.get('value', 0.0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def export_prometheus() -> str:
    """The live registry in Prometheus text exposition format (0.0.4)
    — :func:`render_metrics_map` over a point-in-time snapshot. Serve
    it from any HTTP handler (or write it to a textfile-collector drop)
    to scrape a long-running job."""
    return render_metrics_map(snapshot(runtime_gauges=False)["metrics"])
