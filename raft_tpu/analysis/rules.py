"""graft-lint rule registry, findings, and inline suppressions.

The JAX port of the reference loses its compile-time invariant net (the
``RAFT_EXPLICIT_INSTANTIATE_ONLY`` template guards of
``util/raft_explicit.hpp`` fail the *build* when a hot path drifts from
the vetted instantiations). This module is the registry half of the
rebuilt net: every TPU-correctness hazard class we have actually hit
gets a rule id, and every intentional exception gets an inline,
*reasoned* suppression instead of silence.

Suppression syntax (same line as the finding or the line above)::

    x = np.asarray(counts)  # graft-lint: allow-host-sync build-time packing

``allow-<slug> <reason>`` — the reason is required; a bare allow is
itself reported (rule GL000) so suppressions stay auditable.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional

# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str            # "GL001"
    slug: str          # "host-sync" — the token used in suppressions
    summary: str       # one line for --list-rules / docs
    rationale: str     # why this class bites on TPU
    engine: str = "ast"  # which engine emits it: ast|jaxpr|races|kern


RULES: Dict[str, Rule] = {}
_SLUG_TO_ID: Dict[str, str] = {}


def register_rule(rule_id: str, slug: str, summary: str,
                  rationale: str = "", engine: str = "ast") -> Rule:
    if rule_id in RULES:
        raise ValueError(f"duplicate rule id {rule_id}")
    if slug in _SLUG_TO_ID:
        raise ValueError(f"duplicate rule slug {slug}")
    rule = Rule(rule_id, slug, summary, rationale, engine)
    RULES[rule_id] = rule
    _SLUG_TO_ID[slug] = rule_id
    return rule


def rule_for_slug(slug: str) -> Optional[Rule]:
    rid = _SLUG_TO_ID.get(slug)
    return RULES[rid] if rid else None


register_rule(
    "GL000", "bare-suppression",
    "suppression without a reason",
    "a suppression that does not say why cannot be audited; the reference's "
    "template guards force a comment at every explicit instantiation",
)
register_rule(
    "GL001", "host-sync",
    "host synchronisation on a device value (.item()/float()/np.asarray) "
    "or inside traced scope",
    "each sync stalls the TPU pipeline for a host round trip; TPU-KNN "
    "(arxiv 2206.14286) holds peak FLOP/s only with zero host round trips "
    "per batch",
)
register_rule(
    "GL002", "tracer-branch",
    "Python control flow on a traced value inside jit/pallas scope",
    "branching on a tracer either raises ConcretizationTypeError or forces "
    "a silent host sync + recompile per branch outcome",
)
register_rule(
    "GL003", "int-float-ordering",
    "float32/bf16 cast of a >=32-bit integer value feeding an ordering op "
    "(sort/top_k/argmin/select_k)",
    "float32 has a 24-bit mantissa: ids/counts above 2^24 collapse to equal "
    "keys and the selection silently reorders (the ADVICE-r5 class, fixed "
    "in PR 1 by integer-domain select)",
)
register_rule(
    "GL004", "f64",
    "float64 in potential device code paths",
    "with jax x64 disabled (our default), f64 requests silently downcast to "
    "f32 on device — the computed result differs from the written intent; "
    "host-side NumPy f64 is fine but must say so",
)
register_rule(
    "GL007", "recompile",
    "redundant retraces across a shape sweep (jaxpr engine only)",
    "TPU-KNN holds peak FLOP/s only when steady-state serving never "
    "recompiles; a repeat sweep over identical shapes must add zero "
    "traces",
    engine="jaxpr",
)
register_rule(
    "GL008", "unclassified-swallow",
    "bare `except Exception` around device compute that neither calls "
    "resilience.classify() nor re-raises",
    "XLA serves transient, OOM, and dead-backend failures through ONE "
    "exception type; a blanket swallow turns a retryable fault into silent "
    "data loss and an OOM into a wrong-answer fallback. Route device-compute "
    "failures through raft_tpu.resilience (classify/run) or re-raise; "
    "genuinely fallback-only sites suppress with a reason",
)
register_rule(
    "GL009", "unspanned-entry",
    "public neighbors search/build — or serve/ serving-surface — entry "
    "point without an obs.span",
    "graft-scope (docs/observability.md) is only as complete as its "
    "coverage: a public search/build path (or a serve/ submit/publish/"
    "delete/upsert/compact/swap/probe/restart surface, where per-request "
    "latency IS the "
    "product — docs/serving.md) that opens no span produces latency and "
    "query counts attributed to nobody, which is exactly the blind spot "
    "the reference's NVTX-everywhere convention prevents; open an "
    "obs.span/obs.entry_span or suppress with a reason",
)
register_rule(
    "GL005", "undated-perf",
    "quantified performance claim without a date/round/artifact citation",
    "undated claims outlive the code they measured (VERDICT weak #7); every "
    "number must name its round or artifact so staleness is detectable",
)
register_rule(
    "GL010", "unguarded-shared-state",
    "shared mutable attribute accessed outside its guarding lock "
    "(inferred from `with self.<lock>:` write sites or declared via "
    "`#: guarded-by(<lock>)`)",
    "the serving tier is multi-threaded: an attribute ever written under "
    "a lock is shared state, and a thread-reachable read or any write "
    "outside that lock is exactly the unpinned-handle / stale-flag class "
    "every post-review fix in PRs 5-6 chased by hand; methods named "
    "*_locked assert a caller-holds-lock contract instead",
    engine="races",
)
register_rule(
    "GL011", "check-then-act",
    "check and act on the same shared attribute in different lock "
    "regions (Event.is_set/flag/dict-membership test in one critical "
    "section, mutation in another or in none)",
    "the lock was released between the check and the act, so the "
    "condition can be invalidated in between — the PR-5 compact() "
    "single-flight bug class (an Event check-then-set admitted "
    "duplicate background compactions); make it one critical section "
    "or a real test-and-set",
    engine="races",
)
register_rule(
    "GL012", "device-work-under-lock",
    "blocking device work (jax.* calls, block_until_ready, device_put, "
    "index build/extend helpers) inside a `with <lock>:` body",
    "device dispatch, compiles, and uploads take milliseconds to "
    "minutes; under a lock they convert every concurrent "
    "delete/upsert/dispatch into tail latency — the side-build-under-"
    "the-mutation-RLock class PR 5's sixth review pass fixed; snapshot "
    "under the lock, compute outside",
    engine="races",
)
register_rule(
    "GL013", "lock-order-cycle",
    "a cycle in the static lock-acquisition graph (nested `with` over "
    "distinct locks, reported as the cycle path)",
    "two code paths acquiring the same pair of locks in opposite "
    "orders deadlock under the right interleaving; the static graph "
    "catches lexically-visible cycles, the RAFT_TPU_THREADSAN lock "
    "sanitizer (analysis/lockwatch.py) catches the rest at test time",
    engine="races",
)
register_rule(
    "GL014", "unjoined-thread",
    "threading.Thread created neither daemon=True nor joined",
    "a non-daemon thread nobody joins outlives close()/shutdown, pins "
    "its closure (device arrays, servers) and can hang interpreter "
    "exit — the serving tier's convention is daemon threads plus "
    "explicit close/join lifecycles",
    engine="races",
)
register_rule(
    "GL006", "blockspec",
    "pallas_call blocks + scratch over the per-core VMEM budget "
    "(computed by the kern engine's abstract evaluation; literal-dim "
    "screen kept as the fallback for unresolvable sites)",
    "TPU tiles are (8,128) f32 / (16,128) bf16 / (32,128) int8; blocks "
    "past ~16 MB VMEM per core fail to lower or thrash. The kern engine "
    "(analysis/kernels.py) accounts real block/scratch bytes under every "
    "shape binding a contract or dispatch-table winner can inject; the "
    "pre-engine literal heuristic survives only for call sites the "
    "evaluator cannot resolve",
    engine="kern",
)
register_rule(
    "GL015", "kernel-oob",
    "Pallas index map reaching past the array, a floor-divided grid "
    "dropping remainder rows, or a reachable non-divisible tail tile "
    "with no mask in the kernel (kern engine)",
    "a BlockSpec index map that exceeds the padded array shape reads "
    "(or writes) out of bounds; a ceil-divided grid whose divisor does "
    "not divide the axis makes the tail tile's pad region reachable — "
    "without an in-kernel mask (jnp.where/pl.when on a bound compare) "
    "pad garbage can win the reduction, the tail-masking bug class every "
    "fused kernel here has hit at least once",
    engine="kern",
)
register_rule(
    "GL016", "tile-align",
    "kernel block dim off the dtype's (sublane, 128) tile — computed "
    "values included — with the offending dim named (kern engine)",
    "Mosaic requires block dims divisible by the dtype tile ((8,128) "
    "f32, (16,128) bf16, (32,128) int8), equal to the array dim, or 1; "
    "anything else relayouts or fails to lower. GL006's literal screen "
    "could not see computed geometry (tile variables, tuning winners, "
    "helper-derived candidate widths) — this rule evaluates it",
    engine="kern",
)
register_rule(
    "GL017", "grid-hazard",
    "output ref revisited across grid steps without a revisiting-safe "
    "write pattern (kern engine)",
    "an output block whose index map ignores a grid dimension is "
    "visited once per step of that dimension: a plain overwrite loses "
    "every partial result but the last, and read-modify-write "
    "accumulation without a first-step init (pl.when on program_id) "
    "reads uninitialized VMEM — both are silent wrong-answer classes "
    "invisible in interpret mode when the test grid is 1",
    engine="kern",
)
register_rule(
    "GL019", "untraced-rpc",
    "transport call/call_async site in serve/ or comms/ whose payload "
    "does not thread the graft-trace context field",
    "the serving path is multi-process (PR 6): an RPC that drops the "
    "(trace_id, parent_span_id) field severs the query's identity at "
    "the process boundary, and its worker-side spans/flight events "
    "become unattributable fragments — exactly the blind spot "
    "graft-trace (docs/observability.md §distributed-tracing) closes. "
    "Thread the payload through obs.trace.traced_payload(); "
    "control-plane RPCs that belong to no query — and pass-through "
    "sites whose payload was threaded upstream — suppress with a "
    "reason naming where the threading happens",
)
register_rule(
    "GL018", "mxu-dtype",
    "in-kernel dot with mismatched operand dtypes, or low-precision "
    "operands without preferred_element_type (kern engine)",
    "the MXU runs one native pass per operand dtype pair: mismatched "
    "operands silently promote (multi-pass, off the fast path), and a "
    "bf16/int8 contraction without preferred_element_type=f32 keeps the "
    "accumulator low-precision — the 2^24 ordering-collapse class's "
    "matmul cousin",
    engine="kern",
)
register_rule(
    "GL020", "unbalanced-acquire",
    "manual lock.acquire() with a path (early return or uncovered "
    "exception) that exits the function still holding the lock",
    "a `with` block cannot leak; a manual acquire()/release() pair can "
    "— one early return or one exception between them and every later "
    "acquirer deadlocks, the worst failure mode the serving tier has "
    "(no wrong answer, just a hang the sanitizer's hold budget needs "
    "30s to even name). Intentional ownership transfers (acquire here, "
    "release in the caller's finally) suppress with a reason naming "
    "the releasing site",
    engine="races",
)
register_rule(
    "GL021", "untested-lock-edge",
    "static lock-order edge never exercised under the runtime "
    "sanitizer (reconciliation mode; report-only)",
    "the static graph claims an acquisition order the threadsan suite "
    "never witnessed: either dead code, an imprecise static edge, or — "
    "worst — a real ordering no test drives, which is exactly where "
    "inversions ship. Advisory: it gates nothing, it names the "
    "coverage debt",
    engine="races",
)
register_rule(
    "GL023", "undocumented-metric",
    "obs metric emitted in package code with no catalog row in "
    "docs/observability.md (or a dynamically-built name the check "
    "cannot read)",
    "the metric catalog is the operator's contract: a counter/gauge/"
    "histogram that ships without a row is a dashboard nobody can "
    "interpret and an alert nobody wires — graft-gauge's recall gauges "
    "(ISSUE 19) exist precisely so thresholds can be stated against "
    "documented semantics. Add the row (name, labels, who emits it); "
    "a deliberately internal/experimental series suppresses with a "
    "reason saying why operators never see it",
)
register_rule(
    "GL024", "hand-wired-pipeline",
    "serve/comms code calls a multi-stage search entry point "
    "(search_refined, a kernel-internal _pq_search/_ivf_search/"
    "_beam_search, or an algorithm's .search) without dispatching "
    "through plan.compile",
    "ISSUE 20 made pipeline composition data: serve adapters and "
    "sharded variants compose stages as compiled plans "
    "(docs/plans.md), so validation, warmup, rung variants, and the "
    "bitwise plan-vs-legacy matrix all see one program. A hand-wired "
    "call re-plumbs the stages invisibly — it drifts from the plan "
    "the tests pin and grows a bespoke surface per feature. Route "
    "through plan.compile (or the serve handle's compiled-plan "
    "cache); a deliberate single-stage fast path suppresses with a "
    "reason naming why no multi-stage plan applies",
)
register_rule(
    "GL022", "unmodeled-lock-edge",
    "runtime-observed lock-order edge absent from the static model "
    "(reconciliation mode)",
    "the sanitizer WATCHED this order happen under test and the "
    "whole-program model cannot see it — a soundness gap (unresolved "
    "dynamic dispatch, an unannotated generic, a closure) that means "
    "GL013's cycle search is blind on these nodes; fix the model or "
    "annotate the path, never suppress the evidence",
    engine="races",
)


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    rule: str                  # rule id, e.g. "GL003"
    path: str
    line: int
    message: str
    engine: str = "ast"        # "ast" | "jaxpr" | "races" | "kern"
    suppressed: bool = False
    reason: str = ""           # the suppression's reason when suppressed
    advisory: bool = False     # report-only: never gates the exit code

    @property
    def slug(self) -> str:
        return RULES[self.rule].slug

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "slug": self.slug,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "engine": self.engine,
            "suppressed": self.suppressed,
            "reason": self.reason,
            "advisory": self.advisory,
        }

    def render(self) -> str:
        mark = " [suppressed: %s]" % self.reason if self.suppressed else ""
        adv = " [advisory]" if self.advisory else ""
        return (f"{self.path}:{self.line}: {self.rule} ({self.slug}) "
                f"{self.message}{adv}{mark}")


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*allow-([a-z0-9][a-z0-9-]*)(?:\s+(\S.*?))?\s*$"
)


@dataclasses.dataclass
class Suppression:
    slug: str
    reason: str
    line: int
    used: bool = False


def scan_suppressions(source: str) -> List[Suppression]:
    """Parse ``# graft-lint: allow-<slug> <reason>`` markers from source.

    Tokenize-based so markers quoted inside string literals/docstrings
    (e.g. documentation showing the syntax) do not register as live
    suppressions; falls back to a line scan only when the file does not
    tokenize."""
    out: List[Suppression] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                out.append(Suppression(
                    m.group(1), (m.group(2) or "").strip(), tok.start[0]))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                out.append(Suppression(
                    m.group(1), (m.group(2) or "").strip(), lineno))
    return out


def apply_suppressions(
    findings: Iterable[Finding], suppressions: List[Suppression], path: str
) -> List[Finding]:
    """Mark findings covered by a suppression on the same or previous line.

    Bare suppressions (no reason) and suppressions for unknown slugs are
    reported as GL000 findings; unused suppressions are left alone (a
    rule may legitimately stop firing after a refactor).
    """
    by_line: Dict[int, List[Suppression]] = {}
    out: List[Finding] = []
    for s in suppressions:
        by_line.setdefault(s.line, []).append(s)
        if rule_for_slug(s.slug) is None:
            out.append(Finding(
                "GL000", path, s.line,
                f"suppression names unknown rule slug {s.slug!r}",
            ))
        elif not s.reason:
            out.append(Finding(
                "GL000", path, s.line,
                f"allow-{s.slug} has no reason; write "
                f"'# graft-lint: allow-{s.slug} <why>'",
            ))
    for f in findings:
        for cand_line in (f.line, f.line - 1):
            hit = next(
                (s for s in by_line.get(cand_line, ()) if s.slug == f.slug),
                None,
            )
            if hit is not None:
                f.suppressed = True
                f.reason = hit.reason or "(no reason given)"
                hit.used = True
                break
        out.append(f)
    return out


def stale_suppressions(path: str, source: str,
                       findings: Iterable[Finding],
                       engines_run: Iterable[str]) -> List[Finding]:
    """GL000 findings for suppressions that no longer suppress anything
    (``--strict-suppressions``).

    A suppression that outlives its finding is debt with a reason
    attached: the next reader trusts a hazard note describing code that
    no longer exists. Only slugs whose owning engine actually RAN are
    judged — an ast-only run cannot call a races suppression stale, it
    simply never looked."""
    engines = set(engines_run)
    matched = set()
    for f in findings:
        if f.suppressed and f.path == path:
            matched.add((f.line, f.slug))
            matched.add((f.line - 1, f.slug))
    out: List[Finding] = []
    for s in scan_suppressions(source):
        rule = rule_for_slug(s.slug)
        if rule is None or rule.engine not in engines:
            continue
        if (s.line, s.slug) not in matched:
            out.append(Finding(
                "GL000", path, s.line,
                f"stale suppression: allow-{s.slug} matches no current "
                f"{rule.id} finding on this line — the hazard it "
                f"documents is gone; delete the marker"))
    return out
