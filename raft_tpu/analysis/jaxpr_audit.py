"""graft-lint engine 2: jaxpr auditor over the public entry points.

Where :mod:`raft_tpu.analysis.lint` screens *syntax*, this engine traces
the registered entry points under tiny CPU-concrete indexes (no TPU, no
execution of the hot loop — ``jax.make_jaxpr`` only runs trace-time
Python) and walks the closed jaxprs for hazards the AST cannot see
through aliasing:

* **GL003** — ``convert_element_type`` from a >=32-bit integer to a
  float whose mantissa cannot hold it (f32: 24 bits), where the
  converted value flows through order-preserving ops into an ordering
  primitive (``sort`` / ``top_k`` / ``approx_top_k`` / ``argmin`` /
  ``argmax`` / ``reduce_min`` / ``reduce_max``). This is the exact
  >2^24 id-collapse class ADVICE r5 called out and PR 1 fixed in
  ``select_k``; the auditor keeps it fixed everywhere.
* **GL004** — any float64 value materialising in the traced graph.
  Note: under *disabled* x64 (the repo default) f64 requests downcast at
  trace time and never reach the jaxpr — there the AST rule is the only
  screen; this check guards x64-enabled runs.
* **GL001** — callback/transfer primitives (``pure_callback`` etc.)
  inside traced code: host round trips hiding in a "compiled" path. A
  ``ConcretizationTypeError`` while tracing is reported the same way —
  it means query-path Python branched on a traced value.
* **GL007** — the recompile audit: a repeated shape sweep through
  ``select_k`` must add zero traces (steady-state serving never
  recompiles — TPU-KNN's zero-recompile requirement).

Entry points register with :func:`register_entry`; each may carry an
``allow={rule_id: reason}`` dict — the audit-side analog of the inline
``# graft-lint: allow-*`` comment, needed because jaxpr findings have no
source line to anchor a comment to.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu.analysis.rules import Finding

# mantissa bits (incl. the implicit leading 1) per float dtype
_MANTISSA = {"float64": 53, "float32": 24, "bfloat16": 8, "float16": 11}

_ORDERING_PRIMS = {
    "sort", "top_k", "approx_top_k", "argmin", "argmax",
    "reduce_min", "reduce_max",
}
# ops through which an exact-int-in-float value stays an ordering key
_STRUCTURAL_PRIMS = {
    "neg", "reshape", "broadcast_in_dim", "transpose", "slice",
    "dynamic_slice", "squeeze", "rev", "copy", "concatenate", "gather",
    "select_n", "convert_element_type", "pad", "stop_gradient",
    "expand_dims", "add", "sub", "mul", "max", "min",
}
_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call",
}
_TRANSFER_PRIMS = {"device_put"}

# sub-jaxpr carrying params, by name
_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                    "branches")


# ---------------------------------------------------------------------------
# entry-point registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EntryPoint:
    name: str
    build: Callable[[], Tuple[Callable, tuple]]   # -> (fn, traced_args)
    allow: Dict[str, str]                          # rule id -> reason


ENTRY_POINTS: Dict[str, EntryPoint] = {}


def register_entry(name: str, allow: Optional[Dict[str, str]] = None):
    def deco(build):
        ENTRY_POINTS[name] = EntryPoint(name, build, dict(allow or {}))
        return build
    return deco


def _rng(shape, seed=0, dtype="float32"):
    import numpy as np
    return np.random.default_rng(seed).standard_normal(shape).astype(dtype)


@register_entry("select_k")
def _ep_select_k():
    import jax.numpy as jnp
    from raft_tpu.matrix.select_k import select_k

    v = jnp.asarray(_rng((4, 256)))
    return (lambda x: select_k(x, 16)), (v,)


@register_entry("pairwise")
def _ep_pairwise():
    import jax.numpy as jnp
    from raft_tpu.distance.pairwise import pairwise_distance

    x = jnp.asarray(_rng((8, 16)))
    y = jnp.asarray(_rng((32, 16), seed=1))
    return (lambda a, b: pairwise_distance(a, b, "sqeuclidean")), (x, y)


@register_entry("brute_force")
def _ep_brute_force():
    import jax.numpy as jnp
    from raft_tpu.neighbors import brute_force

    idx = brute_force.build(_rng((128, 16)), metric="sqeuclidean")
    q = jnp.asarray(_rng((4, 16), seed=1))
    return (lambda queries: brute_force.search(idx, queries, 8)), (q,)


@register_entry("ivf_flat")
def _ep_ivf_flat():
    import jax.numpy as jnp
    from raft_tpu.neighbors import ivf_flat

    params = ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=2)
    idx = ivf_flat.build(params, _rng((128, 16)))
    sp = ivf_flat.SearchParams(n_probes=2, scan_impl="xla")
    q = jnp.asarray(_rng((4, 16), seed=1))
    return (lambda queries: ivf_flat.search(sp, idx, queries, 4)), (q,)


@register_entry("ivf_pq")
def _ep_ivf_pq():
    import jax.numpy as jnp
    from raft_tpu.neighbors import ivf_pq

    params = ivf_pq.IndexParams(n_lists=4, pq_dim=4, kmeans_n_iters=2)
    idx = ivf_pq.build(params, _rng((256, 16)))
    sp = ivf_pq.SearchParams(n_probes=2, scan_impl="xla")
    q = jnp.asarray(_rng((4, 16), seed=1))
    return (lambda queries: ivf_pq.search(sp, idx, queries, 4)), (q,)


@register_entry("cagra")
def _ep_cagra():
    import jax.numpy as jnp
    from raft_tpu.neighbors import brute_force, cagra

    data = _rng((128, 16))
    _, nbrs = brute_force.knn(data, data, 5)       # k=deg+1, col 0 = self
    idx = cagra.from_graph(data, nbrs[:, 1:], "sqeuclidean")
    sp = cagra.SearchParams(itopk_size=16, scan_impl="xla")
    q = jnp.asarray(_rng((4, 16), seed=1))
    return (lambda queries: cagra.search(sp, idx, queries, 4)), (q,)


# ---------------------------------------------------------------------------
# jaxpr walk
# ---------------------------------------------------------------------------


def _dtype_name(aval) -> str:
    return getattr(getattr(aval, "dtype", None), "name", "")


def _is_wide_int(aval) -> bool:
    name = _dtype_name(aval)
    return name.startswith(("int", "uint")) and name[-2:] in ("32", "64")


class _Auditor:
    """Taint-tracking walk over one closed jaxpr (recursing into
    sub-jaxprs with taint mapped through call boundaries)."""

    def __init__(self, entry: str):
        self.entry = entry
        self.findings: List[Finding] = []
        self.f64_count = 0

    def _emit(self, rule: str, message: str) -> None:
        self.findings.append(
            Finding(rule, f"<jaxpr:{self.entry}>", 0, message, engine="jaxpr")
        )

    def walk(self, closed_jaxpr, taint: Optional[Dict] = None) -> Dict:
        """taint: var -> origin string for tainted *invars*; returns taint
        for outvars (positional list mapped by caller)."""
        jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
        t: Dict = dict(taint or {})
        # closure constants + traced args: a device_put of these is the
        # one-time upload XLA hoists out of the steady-state loop, not a
        # mid-graph transfer
        boundary = {id(v) for v in list(jaxpr.constvars) + list(jaxpr.invars)}

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_taints = []
            for v in eqn.invars:
                origin = t.get(id(v)) if not self._is_literal(v) else None
                in_taints.append(origin)

            # GL004: f64 output anywhere in the graph
            for ov in eqn.outvars:
                if _dtype_name(ov.aval) == "float64":
                    self.f64_count += 1

            # GL001: host callbacks / transfers in traced code. device_put
            # of a constant or of a traced input is the benign one-time
            # upload; only mid-graph transfers count.
            if prim in _CALLBACK_PRIMS:
                self._emit("GL001",
                           f"{self.entry}: traced graph contains host "
                           f"round-trip primitive {prim!r}")
            elif prim in _TRANSFER_PRIMS and any(
                    not self._is_literal(v) and id(v) not in boundary
                    for v in eqn.invars):
                self._emit("GL001",
                           f"{self.entry}: mid-graph {prim!r} on a derived "
                           "value — a transfer inside the hot loop")

            # GL003 taint source: wide-int -> narrow-float convert
            out_taint: Optional[str] = None
            if prim == "convert_element_type" and eqn.invars:
                src = eqn.invars[0].aval
                dst = eqn.outvars[0].aval
                if _is_wide_int(src):
                    bits = 64 if _dtype_name(src).endswith("64") else 32
                    mant = _MANTISSA.get(_dtype_name(dst), 0)
                    if mant and mant < bits - (0 if _dtype_name(src).startswith("u") else 1):
                        out_taint = (f"{_dtype_name(src)}->{_dtype_name(dst)} "
                                     f"(mantissa {mant} < {bits}-bit payload)")

            # GL003 sink: ordering primitive consuming a tainted operand
            if prim in _ORDERING_PRIMS:
                for v, origin in zip(eqn.invars, in_taints):
                    if origin:
                        self._emit("GL003",
                                   f"{self.entry}: ordering primitive "
                                   f"{prim!r} consumes an integer value "
                                   f"converted {origin}; keys above 2^24 "
                                   "collapse — select in integer domain")

            # recurse into sub-jaxprs, mapping taint through the call
            sub_results = self._walk_subjaxprs(eqn, t, in_taints)
            if sub_results is not None:
                for ov, origin in zip(eqn.outvars, sub_results):
                    if origin:
                        t[id(ov)] = origin
                continue

            # taint propagation through structural/order-preserving ops
            if out_taint is None and prim in _STRUCTURAL_PRIMS:
                out_taint = next((o for o in in_taints if o), None)
            if out_taint is not None:
                for ov in eqn.outvars:
                    t[id(ov)] = out_taint

        return {id(v): t.get(id(v)) for v in jaxpr.outvars if not self._is_literal(v)}

    @staticmethod
    def _is_literal(v) -> bool:
        return type(v).__name__ == "Literal"

    def _walk_subjaxprs(self, eqn, t: Dict, in_taints: List) -> Optional[List]:
        """Recurse into any sub-jaxpr params; returns outvar taints
        (positional) when sub-jaxprs were found, else None."""
        subs = []
        for key in _SUBJAXPR_PARAMS:
            val = eqn.params.get(key)
            if val is None:
                continue
            if key == "branches":
                subs.extend(val)
            else:
                subs.append(val)
        if not subs:
            return None
        out_taints: List = [None] * len(eqn.outvars)
        for sub in subs:
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            # map outer operand taint onto inner invars (positional; scan
            # prepends consts/carry — zip from the tail is close enough
            # for a screen, so align from the end)
            inner_taint: Dict = {}
            invars = list(inner.invars)
            operands = list(eqn.invars)
            for iv, (ov, origin) in zip(reversed(invars),
                                        reversed(list(zip(operands, in_taints)))):
                if origin:
                    inner_taint[id(iv)] = origin
            result = self.walk(sub, inner_taint)
            inner_outs = list(inner.outvars)
            for pos, iv in enumerate(inner_outs[-len(eqn.outvars):] if eqn.outvars else []):
                origin = result.get(id(iv))
                if origin and pos < len(out_taints):
                    out_taints[pos] = out_taints[pos] or origin
        return out_taints


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def audit_entry_point(name: str) -> List[Finding]:
    """Trace one registered entry point and walk its jaxpr."""
    import jax

    entry = ENTRY_POINTS[name]
    auditor = _Auditor(name)
    try:
        fn, args = entry.build()
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow trace failures become GL-findings for the report, not execution faults
        kind = type(e).__name__
        rule = "GL002" if "Concretization" in kind or "Tracer" in kind else "GL001"
        auditor._emit(rule,
                      f"{name}: tracing failed with {kind}: {e}"[:500])
        return auditor.findings
    auditor.walk(closed)
    if auditor.f64_count:
        auditor._emit("GL004",
                      f"{name}: {auditor.f64_count} float64 value(s) in the "
                      "traced graph (silently downcast under disabled x64)")
    findings = auditor.findings
    for f in findings:
        reason = entry.allow.get(f.rule)
        if reason:
            f.suppressed = True
            f.reason = reason
    return findings


def audit_entry_points(names: Optional[Sequence[str]] = None) -> List[Finding]:
    names = list(names) if names else sorted(ENTRY_POINTS)
    out: List[Finding] = []
    for n in names:
        out.extend(audit_entry_point(n))
    return out


# ---------------------------------------------------------------------------
# recompile audit
# ---------------------------------------------------------------------------

_DEFAULT_SWEEP = ((4, 512), (4, 1024), (8, 1024), (4, 2048), (16, 4096))


def audit_select_k_recompiles(
    shapes: Sequence[Tuple[int, int]] = _DEFAULT_SWEEP, k: int = 16
) -> Tuple[List[Finding], dict]:
    """Run the select_k shape sweep twice; the second pass must add zero
    traces (steady-state serving never recompiles). Returns (findings,
    report)."""
    import importlib

    import jax
    import jax.numpy as jnp

    # the package re-exports the function under the module's name
    sk_mod = importlib.import_module("raft_tpu.matrix.select_k")

    tracked = [sk_mod._select_k, sk_mod._tournament_topk]
    if not all(hasattr(f, "_cache_size") for f in tracked):
        return [], {"status": "skipped",
                    "detail": "no _cache_size on this jax version"}

    jax.clear_caches()

    def total() -> int:
        return sum(f._cache_size() for f in tracked)

    def sweep(seed: int) -> None:
        for i, (b, n) in enumerate(shapes):
            v = jnp.asarray(_rng((b, n), seed=seed * 100 + i))
            sk_mod.select_k(v, k)

    sweep(0)
    first = total()
    sweep(1)
    delta = total() - first
    report = {
        "status": "ok" if delta == 0 else "fail",
        "shapes": list(map(list, shapes)),
        "compiles_first_sweep": first,
        "retraces_second_sweep": delta,
    }
    findings: List[Finding] = []
    if delta:
        findings.append(Finding(
            "GL007", "<jaxpr:select_k>", 0,
            f"select_k shape sweep retraced {delta} time(s) on identical "
            "shapes — steady-state serving would recompile", engine="jaxpr"))
    return findings, report


def run_audit(names: Optional[Sequence[str]] = None,
              recompile: bool = True) -> Tuple[List[Finding], dict]:
    findings = audit_entry_points(names)
    report: dict = {"entry_points": sorted(names or ENTRY_POINTS)}
    if recompile:
        rf, rr = audit_select_k_recompiles()
        findings.extend(rf)
        report["recompile"] = rr
    return findings, report
