"""raft_tpu.analysis — static analysis for TPU correctness hazards.

Two engines, one rule set (see ``docs/static_analysis.md``):

* :mod:`raft_tpu.analysis.lint` — AST lint over package source
  (GL001-GL006: host syncs, tracer branches, int->float ordering
  casts, f64, undated perf claims, off-tile BlockSpecs).
* :mod:`raft_tpu.analysis.jaxpr_audit` — traces the registered public
  entry points on CPU and walks the jaxprs (GL001/GL003/GL004 with
  real dataflow, plus the GL007 recompile audit).

CLI: ``graft-lint`` (console script) or ``python scripts/graft_lint.py``.
The tier-1 gate test (``tests/test_graft_lint.py``) runs both engines
over ``raft_tpu/`` and fails on any unsuppressed finding — the JAX-port
analog of the reference failing the build on an unvetted template
instantiation (``util/raft_explicit.hpp``).
"""

from raft_tpu.analysis.rules import RULES, Finding, Rule  # noqa: F401
from raft_tpu.analysis.lint import lint_file, lint_paths, lint_source  # noqa: F401
from raft_tpu.analysis.jaxpr_audit import (  # noqa: F401
    ENTRY_POINTS,
    audit_entry_point,
    audit_entry_points,
    audit_select_k_recompiles,
    run_audit,
)
