"""raft_tpu.analysis — static + dynamic analysis for correctness hazards.

Four engines, one rule set (see ``docs/static_analysis.md``):

* :mod:`raft_tpu.analysis.lint` — AST lint over package source
  (GL001-GL005, GL008-GL009: host syncs, tracer branches, int->float
  ordering casts, f64, undated perf claims, unclassified swallows,
  unspanned entries).
* :mod:`raft_tpu.analysis.jaxpr_audit` — traces the registered public
  entry points on CPU and walks the jaxprs (GL001/GL003/GL004 with
  real dataflow, plus the GL007 recompile audit).
* :mod:`raft_tpu.analysis.races` — graft-race: WHOLE-PROGRAM
  lock-discipline lint over the threaded serving tier (GL010-GL014,
  GL020: unguarded shared state, check-then-act, device work under
  lock, interprocedural lock-order cycles, unjoined threads,
  unbalanced manual acquires), built on a project call graph + type
  model (:mod:`raft_tpu.analysis.callgraph`) and per-function lock
  summaries (:mod:`raft_tpu.analysis.summaries`); its dynamic
  complement is the ``RAFT_TPU_THREADSAN=1`` lock-order sanitizer
  (:mod:`raft_tpu.analysis.lockwatch`) the serve/fabric/comms/core
  tiers construct their locks through, and ``--reconcile`` diffs the
  two graphs (GL022 soundness gaps / GL021 coverage debt).
* :mod:`raft_tpu.analysis.kernels` — graft-kern: the Pallas kernel
  verifier (GL006, GL015-GL018: computed VMEM accounting, index-map
  bounds/tail masks, tile alignment, grid-revisit hazards, MXU dtype
  audit) by abstract interpretation of every ``pl.pallas_call`` site
  under the shape bindings its :mod:`~raft_tpu.analysis.contracts`
  declare; its dynamic complement is the kernel-contract adversarial
  sweep (``tests/test_kernel_contracts.py`` on CPU,
  ``scripts/tpu_parity.py`` on chip).

CLI: ``graft-lint`` (console script) or ``python scripts/graft_lint.py``;
``--engine=all`` is the full static gate. The tier-1 gate tests
(``tests/test_graft_lint.py``) run every engine over ``raft_tpu/`` and
fail on any unsuppressed finding — the JAX-port analog of the reference
failing the build on an unvetted template instantiation
(``util/raft_explicit.hpp``).
"""

from raft_tpu.analysis.rules import RULES, Finding, Rule  # noqa: F401
from raft_tpu.analysis.lint import lint_file, lint_paths, lint_source  # noqa: F401
from raft_tpu.analysis.jaxpr_audit import (  # noqa: F401
    ENTRY_POINTS,
    audit_entry_point,
    audit_entry_points,
    audit_select_k_recompiles,
    run_audit,
)
from raft_tpu.analysis import contracts  # noqa: F401
from raft_tpu.analysis import lockwatch  # noqa: F401
from raft_tpu.analysis.kernels import (  # noqa: F401
    lint_file as kern_lint_file,
    lint_paths as kern_lint_paths,
    lint_source as kern_lint_source,
)
from raft_tpu.analysis.races import (  # noqa: F401
    lint_file as race_lint_file,
    lint_paths as race_lint_paths,
    lint_source as race_lint_source,
)
from raft_tpu.analysis.callgraph import CallGraph, build_project  # noqa: F401
from raft_tpu.analysis.summaries import (  # noqa: F401
    LockSummaries,
    build_summaries,
)
