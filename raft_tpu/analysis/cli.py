"""graft-lint command line.

::

    graft-lint [paths...]                  # AST lint (default: raft_tpu/)
    graft-lint --engine=both raft_tpu/     # AST + jaxpr audit
    graft-lint --engine=races raft_tpu/    # lock-discipline lint only
    graft-lint --engine=both,races raft_tpu/   # the full tier-1 gate
    graft-lint --format=json raft_tpu/    # machine-readable
    graft-lint --engine=races --reconcile LOCKGRAPH.json raft_tpu/
    graft-lint --strict-suppressions raft_tpu/   # stale allow- markers
    graft-lint --emit-lock-hierarchy raft_tpu/   # markdown lock graph
    graft-lint --list-rules

``--engine`` takes a comma list of ``ast`` / ``jaxpr`` / ``races`` /
``kern``; ``both`` keeps meaning ``ast,jaxpr`` (its pre-races spelling)
and ``all`` is every engine. ``--reconcile`` implies ``races``.

Exit codes: 0 clean (suppressed findings allowed), 1 unsuppressed
findings, 2 internal/usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graft-lint",
        description="AST + jaxpr static analysis for TPU correctness "
                    "hazards (docs/static_analysis.md)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: raft_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--engine", default="ast",
                    help="comma list of ast|jaxpr|races|kern (ast = "
                         "source lint, fast; jaxpr = trace the "
                         "entry-point registry; races = lock-discipline "
                         "lint; kern = Pallas kernel verifier); "
                         "'both' = ast,jaxpr; 'all' = every engine")
    ap.add_argument("--rules", default=None,
                    help="comma list of rule ids to run (AST engine), "
                         "e.g. GL001,GL005")
    ap.add_argument("--entry-points", default=None,
                    help="comma list of jaxpr entry points "
                         "(default: all registered)")
    ap.add_argument("--no-recompile-audit", action="store_true",
                    help="skip the select_k shape-sweep recompile audit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text format)")
    ap.add_argument("--reconcile", metavar="ARTIFACT", default=None,
                    help="diff the static lock graph against a runtime "
                         "lockwatch graph JSON (lockwatch.export_graph "
                         "/ RAFT_TPU_THREADSAN_EXPORT): runtime edges "
                         "the model misses are GL022 (hard), static "
                         "edges never exercised are GL021 (advisory); "
                         "implies the races engine")
    ap.add_argument("--strict-suppressions", action="store_true",
                    help="report suppressions that no longer suppress "
                         "anything as GL000 (judged only for rules "
                         "whose engine ran)")
    ap.add_argument("--emit-lock-hierarchy", action="store_true",
                    help="print the whole-program lock hierarchy "
                         "(markdown; the generated source of "
                         "docs/serving.md's hierarchy section) and "
                         "exit")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from raft_tpu.analysis.rules import RULES

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  allow-{rule.slug:<20} {rule.summary}")
        return 0

    engines: set = set()
    for tok in args.engine.split(","):
        tok = tok.strip()
        if tok == "both":
            engines |= {"ast", "jaxpr"}
        elif tok == "all":
            engines |= {"ast", "jaxpr", "races", "kern"}
        elif tok in ("ast", "jaxpr", "races", "kern"):
            engines.add(tok)
        elif tok:
            print(f"unknown engine {tok!r} (want ast|jaxpr|races|kern|"
                  f"both|all, comma-separable)", file=sys.stderr)
            return 2
    if not engines:
        engines = {"ast"}
    if args.reconcile is not None:
        engines.add("races")     # reconciliation IS a races-engine pass

    if args.paths:
        paths = args.paths
    else:
        # installed console script may run from anywhere: fall back to the
        # package's own location when cwd has no raft_tpu/ checkout
        from pathlib import Path

        if Path("raft_tpu").is_dir():
            paths = ["raft_tpu/"]
        else:
            import raft_tpu

            paths = [str(Path(raft_tpu.__file__).parent)]
    rules = set(args.rules.split(",")) if args.rules else None
    if rules is not None:
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    if args.emit_lock_hierarchy:
        try:
            from raft_tpu.analysis.summaries import build_summaries

            print(build_summaries(paths).render_hierarchy())
            return 0
        except Exception as e:  # noqa: BLE001 — same contract as engines
            print(f"graft-lint internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2

    findings = []
    report: dict = {}
    try:
        if "ast" in engines:
            from raft_tpu.analysis.lint import lint_paths

            findings.extend(lint_paths(paths, rules))
        if "races" in engines:
            from raft_tpu.analysis.races import lint_paths as race_paths

            findings.extend(race_paths(paths, rules,
                                       reconcile=args.reconcile))
        if "kern" in engines:
            from raft_tpu.analysis.kernels import lint_paths as kern_paths

            findings.extend(kern_paths(paths, rules))
        if "jaxpr" in engines:
            from raft_tpu.analysis.jaxpr_audit import run_audit

            names = args.entry_points.split(",") if args.entry_points else None
            jf, report = run_audit(
                names, recompile=not args.no_recompile_audit)
            findings.extend(jf)
    except Exception as e:  # noqa: BLE001 — engines must not crash the CLI
        print(f"graft-lint internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.strict_suppressions:
        findings.extend(_stale_suppression_pass(paths, findings, engines))

    open_findings = [f for f in findings
                     if not f.suppressed and not f.advisory]
    advisory = [f for f in findings if not f.suppressed and f.advisory]
    suppressed = [f for f in findings if f.suppressed]

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in open_findings],
            "advisory": [f.to_dict() for f in advisory],
            "suppressed": [f.to_dict() for f in suppressed],
            "counts": {"open": len(open_findings),
                       "advisory": len(advisory),
                       "suppressed": len(suppressed)},
            "report": report,
        }, indent=1))
    else:
        for f in open_findings:
            print(f.render())
        for f in advisory:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f.render())
        rec = report.get("recompile")
        tail = f"; recompile audit: {rec['status']}" if rec else ""
        adv = f", {len(advisory)} advisory" if advisory else ""
        print(f"graft-lint: {len(open_findings)} finding(s){adv}, "
              f"{len(suppressed)} suppressed{tail}")

    return 1 if open_findings else 0


def _stale_suppression_pass(paths, findings, engines):
    """--strict-suppressions: GL000 per suppression that suppressed
    nothing this run (only for rules whose engine ran)."""
    from pathlib import Path

    from raft_tpu.analysis.rules import stale_suppressions

    out = []
    seen = set()
    for raw in paths:
        p = Path(raw)
        files = sorted(f for f in p.rglob("*.py")
                       if "__pycache__" not in f.parts) \
            if p.is_dir() else [p]
        for f in files:
            if f in seen or f.suffix != ".py":
                continue
            seen.add(f)
            try:
                source = f.read_text()
            except (OSError, UnicodeDecodeError):
                continue
            out.extend(stale_suppressions(str(f), source, findings,
                                          engines))
    return out


if __name__ == "__main__":
    sys.exit(main())
