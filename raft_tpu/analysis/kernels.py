"""graft-kern engine 4: static Pallas kernel verifier.

GL006's literal-BlockSpec heuristic could only judge geometry written
as integer literals — exactly the form docs/kernels.md BANS for real
kernels (tile budgets must be expression-derived). This engine closes
that hole by *abstract interpretation*: for every ``pl.pallas_call``
site it mini-interprets the enclosing function under a set of concrete
shape **bindings** — drawn from the kernel's registered contract
(:mod:`raft_tpu.analysis.contracts`), from the tuning layer's
tile-candidate enumeration (``tuning.kernel_shape_candidates()`` — the
values a dispatch-table winner string like ``fused_fold:2048`` can
inject), and from literal defaults — evaluating BlockSpec shapes,
index maps, grids, scratch shapes, and out_shapes the way the tracer
would, including calls into the module's own pure geometry helpers
(``candidate_width``, ``fold_depth``, ``packed_row_layout``, ...).

Checks per resolved site (rule catalog in docs/static_analysis.md):

GL006  exact VMEM accounting — blocks + scratch at their real dtypes
       against the per-core budget (replaces the literal heuristic;
       the literal screen remains only for sites the evaluator cannot
       resolve).
GL015  index-map bounds — every BlockSpec index map evaluated over the
       grid corner extents must stay inside the (padded) array shape —
       and reachable non-divisible tails (a grid extent computed as
       ``ceil(n/t)`` with ``n % t != 0`` under some binding) require
       tail-mask evidence in the kernel body; floor-divided extents
       that drop remainder rows are flagged outright.
GL016  tile alignment — block dims checked against the real Mosaic
       rule per dtype ((8,128) f32, (16,128) bf16, (32,128) int8):
       a dim is legal when it is a multiple of the minimum, is 1, or
       equals the full array dim; violations name the dim.
GL017  grid hazards — an output ref whose index map ignores a grid
       dimension of extent > 1 is revisited across steps; plain
       overwrites lose partial results and read-modify-write
       accumulation without a first-step init reads uninitialized
       memory.
GL018  MXU dtype audit — ``dot_general``/``jnp.dot`` operands with
       provably different dtypes (silent promotion off the MXU), or
       sub-f32 operands with no ``preferred_element_type`` (accumulator
       stays low-precision).

Interpretation is *per concrete binding*: guards that ``raise`` under a
binding prune it (the kernel's own eligibility checks are respected),
so findings come with a witness binding in the message. The same
contract cases also drive the dynamic interpret-mode sweep
(``tests/test_kernel_contracts.py``) — static engine and dynamic sweep
cross-check each other.
"""

from __future__ import annotations

import ast
import dataclasses
import importlib
import itertools
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from raft_tpu.analysis.contracts import (
    LANE,
    SUBLANE_BY_ITEMSIZE,
    dtype_itemsize,
    static_cases,
)
from raft_tpu.analysis.rules import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)

_VMEM_BUDGET_BYTES = 16 * 1024 * 1024   # ~VMEM per core (pallas guide)
_MAX_BINDINGS = 128                      # per site
_MAX_STEPS = 4000                        # interpreter fuel per binding
_MAX_LOOP = 256

_BLOCKSPEC_NAMES = ("pl.BlockSpec", "pallas.BlockSpec", "BlockSpec")
_VMEM_SCRATCH_NAMES = ("pltpu.VMEM", "tpu.VMEM")
_PALLAS_CALL_NAMES = ("pl.pallas_call", "pallas_call")
_GRIDSPEC_NAMES = ("pltpu.PrefetchScalarGridSpec", "PrefetchScalarGridSpec")
_SDS_NAMES = ("jax.ShapeDtypeStruct", "ShapeDtypeStruct")
_DOT_NAMES = ("jax.lax.dot_general", "lax.dot_general", "jnp.dot",
              "jnp.matmul", "jnp.einsum")

# fallback candidates for free dim names at UNCONTRACTED sites (fixture
# files / future kernels); contracted sites bind from their contract
_DEFAULT_DIMS: Dict[str, Tuple] = {
    "k": (1, 10, 129),
    "m": (16,), "n": (1000,), "d": (32,),
    "cap": (256,), "G": (8,), "nb": (4,), "C": (4,),
    "metric_kind": (0, 1),
}

_DTYPE_NAMES = {
    "jnp.float32": "float32", "np.float32": "float32",
    "jnp.bfloat16": "bfloat16", "jnp.float16": "float16",
    "jnp.int32": "int32", "np.int32": "int32", "jnp.uint32": "uint32",
    "jnp.int8": "int8", "jnp.uint8": "uint8", "jnp.int16": "int16",
    "jnp.bool_": "bool", "jnp.float64": "float64", "np.float64": "float64",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------


class _Unknown:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<?>"


UNKNOWN = _Unknown()


class IntV(int):
    """A concrete int carrying one step of divisibility provenance:
    ``kind`` is "ceil"/"floor" when the value came directly from
    ``ceil(num/den)`` / ``num // den``; ``tail`` records whether
    ``num % den != 0`` under the active binding."""

    kind = None
    tail = False
    num = None
    den = None

    @classmethod
    def div(cls, value, kind, num, den):
        v = cls(value)
        v.kind = kind
        v.tail = (num % den) != 0 if den else False
        v.num, v.den = int(num), int(den)
        return v


@dataclasses.dataclass
class Arr:
    """An array value: shape entries are ints, dim-name strings (bound
    lazily against the binding), or UNKNOWN; dtype is a dtype name
    string, a ("dtype_of", name) token, or None when unknown."""

    shape: Optional[list] = None     # mutable: unpacking refines it
    dtype: object = None


@dataclasses.dataclass
class Lam:
    node: ast.Lambda
    env: dict


@dataclasses.dataclass
class FnV:
    node: ast.FunctionDef


@dataclasses.dataclass
class PartialV:
    fn: object
    kwargs: dict


@dataclasses.dataclass
class RealFn:
    """A helper resolved to the real imported callable (raft_tpu
    modules only) — called with concrete args, guarded."""

    fn: object


@dataclasses.dataclass
class BlockV:
    shape: Optional[tuple]          # tuple of int/UNKNOWN, or None
    index_map: Optional[Lam]
    lineno: int
    node: ast.Call = None


@dataclasses.dataclass
class ScratchV:
    shape: Optional[tuple]
    dtype: object
    lineno: int
    node: ast.Call = None


@dataclasses.dataclass
class SDSV:                          # jax.ShapeDtypeStruct
    shape: Optional[tuple]
    dtype: object


@dataclasses.dataclass
class GridSpecV:
    num_scalar_prefetch: int
    grid: tuple
    in_specs: list
    out_specs: list
    scratch: list


@dataclasses.dataclass
class SiteEval:
    """One pallas_call site fully evaluated under one binding."""

    binding: dict
    kernel: object                   # FnV | PartialV | UNKNOWN
    grid: tuple
    in_specs: list
    out_specs: list
    out_shapes: list                 # SDSV per output
    scratch: list
    inputs: list                     # Arr/UNKNOWN per runtime operand
    num_prefetch: int = 0


class _Infeasible(Exception):
    """The binding violates a guard the function itself raises on."""


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _OutOfFuel(Exception):
    pass


# ---------------------------------------------------------------------------
# the mini-interpreter
# ---------------------------------------------------------------------------


class Interp:
    def __init__(self, tree: ast.Module, module_name: Optional[str]):
        self.tree = tree
        self.module_name = module_name
        self.fns: Dict[str, ast.FunctionDef] = {}
        self.consts: Dict[str, object] = {}
        self._imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, attr)
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                self.fns[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant):
                self.consts[node.targets[0].id] = node.value.value
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith("raft_tpu"):
                for alias in node.names:
                    self._imports[alias.asname or alias.name] = (
                        node.module, alias.name)
        self.fuel = 0
        self.sites: Dict[ast.Call, SiteEval] = {}
        self.binding: dict = {}

    # -- entry -------------------------------------------------------------

    def run_function(self, fn: ast.FunctionDef, binding: dict,
                     arrays: Dict[str, tuple]) -> dict:
        """Interpret ``fn`` under ``binding``; populates ``self.sites``
        for pallas_call nodes reached. Returns the final env."""
        self.fuel = _MAX_STEPS
        self.binding = binding
        env = self._param_env(fn, binding, arrays)
        try:
            self._exec(fn.body, env)
        except _Return:
            pass
        return env

    def _param_env(self, fn: ast.FunctionDef, binding: dict,
                   arrays: Dict[str, tuple]) -> dict:
        env: dict = {}
        args = fn.args
        params = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        defaults: Dict[str, object] = {}
        pos = args.posonlyargs + args.args
        for a, dflt in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            defaults[a.arg] = self._eval(dflt, {})
        for a, dflt in zip(args.kwonlyargs, args.kw_defaults):
            if dflt is not None:
                defaults[a.arg] = self._eval(dflt, {})
        for p in params:
            name = p.arg
            if name in binding:
                v = binding[name]
                if isinstance(v, Arr):
                    env[name] = Arr(shape=list(v.shape) if v.shape else None,
                                    dtype=v.dtype)
                elif v is True and name in arrays:
                    env[name] = self._mk_arr(name, binding, arrays)
                elif v is None or v is False:
                    env[name] = None if name in arrays or v is None else v
                elif isinstance(v, bool):
                    env[name] = v
                elif isinstance(v, (int, str, float)):
                    env[name] = v
                else:
                    env[name] = UNKNOWN
            elif name in arrays:
                dflt = defaults.get(name, "__missing__")
                env[name] = (None if dflt is None
                             else self._mk_arr(name, binding, arrays))
            elif name in defaults:
                env[name] = defaults[name]
            else:
                env[name] = UNKNOWN
        return env

    def _mk_arr(self, name: str, binding: dict,
                arrays: Dict[str, tuple]) -> Arr:
        shape_decl = binding.get(f"{name}_shape", arrays.get(name))
        shape = None
        if shape_decl is not None:
            shape = [binding.get(d, d) if isinstance(d, str) else int(d)
                     for d in shape_decl]
            shape = [s if isinstance(s, (int, str)) else UNKNOWN
                     for s in shape]
        dtype = binding.get(f"{name}_dtype", binding.get("dtype"))
        return Arr(shape=shape, dtype=dtype)

    # -- statements --------------------------------------------------------

    def _tick(self):
        self.fuel -= 1
        if self.fuel <= 0:
            raise _OutOfFuel()

    def _exec(self, stmts: Sequence[ast.stmt], env: dict) -> None:
        for s in stmts:
            self._exec_one(s, env)

    def _exec_one(self, s: ast.stmt, env: dict) -> None:
        self._tick()
        if isinstance(s, ast.Assign):
            val = self._eval(s.value, env)
            for t in s.targets:
                self._assign(t, val, env, s.value)
        elif isinstance(s, ast.AugAssign):
            cur = self._eval(s.target, env) if isinstance(
                s.target, ast.Name) else UNKNOWN
            rhs = self._eval(s.value, env)
            val = self._binop(type(s.op), cur, rhs)
            if isinstance(s.target, ast.Name):
                env[s.target.id] = val
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None and isinstance(s.target, ast.Name):
                env[s.target.id] = self._eval(s.value, env)
        elif isinstance(s, ast.Expr):
            self._eval(s.value, env)
        elif isinstance(s, ast.If):
            cond = self._truth(self._eval(s.test, env))
            if cond is UNKNOWN:
                self._exec_both(s.body, s.orelse, env)
            elif cond:
                self._exec(s.body, env)
            else:
                self._exec(s.orelse, env)
        elif isinstance(s, ast.While):
            it = 0
            while True:
                cond = self._truth(self._eval(s.test, env))
                if cond is UNKNOWN:
                    self._poison_assigned(s.body, env)
                    break
                if not cond:
                    break
                self._exec(s.body, env)
                it += 1
                if it > _MAX_LOOP:
                    self._poison_assigned(s.body, env)
                    break
        elif isinstance(s, ast.For):
            seq = self._eval(s.iter, env)
            if isinstance(seq, (list, tuple)) and len(seq) <= _MAX_LOOP:
                for item in seq:
                    self._assign(s.target, item, env, s.iter)
                    self._exec(s.body, env)
            else:
                self._assign(s.target, UNKNOWN, env, s.iter)
                self._poison_assigned(s.body, env)
        elif isinstance(s, ast.Raise):
            raise _Infeasible()
        elif isinstance(s, ast.Assert):
            cond = self._truth(self._eval(s.test, env))
            if cond is False:
                raise _Infeasible()
        elif isinstance(s, ast.Return):
            raise _Return(self._eval(s.value, env) if s.value else None)
        elif isinstance(s, ast.ImportFrom):
            if s.module and s.module.startswith("raft_tpu"):
                for alias in s.names:
                    env[alias.asname or alias.name] = self._resolve_import(
                        s.module, alias.name)
        elif isinstance(s, (ast.FunctionDef, ast.Import, ast.Pass,
                            ast.With, ast.Try, ast.Delete, ast.Global,
                            ast.Nonlocal)):
            if isinstance(s, ast.FunctionDef):
                env[s.name] = FnV(s)
            elif isinstance(s, ast.With):
                self._exec(s.body, env)
            elif isinstance(s, ast.Try):
                self._exec(s.body, env)
        # other statements: ignored

    def _exec_both(self, body, orelse, env: dict) -> None:
        e1 = dict(env)
        e2 = dict(env)
        try:
            self._exec(body, e1)
        except _Infeasible:
            e1 = None
        try:
            self._exec(orelse, e2)
        except _Infeasible:
            e2 = None
        if e1 is None and e2 is None:
            raise _Infeasible()
        if e1 is None:
            env.update(e2)
            return
        if e2 is None:
            env.update(e1)
            return
        for k in set(e1) | set(e2):
            a, b = e1.get(k, UNKNOWN), e2.get(k, UNKNOWN)
            env[k] = a if _same(a, b) else UNKNOWN

    def _poison_assigned(self, body, env: dict) -> None:
        for sub in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            env[n.id] = UNKNOWN

    def _assign(self, target: ast.AST, val, env: dict,
                value_node: ast.AST) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
            # name-provenance: `n = X.shape[0]` names X's dim 0 "n"
            self._note_shape_name(value_node, (target.id,), env, single=True)
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [t.id if isinstance(t, ast.Name) else None
                     for t in target.elts]
            if isinstance(val, (tuple, list)) and len(val) == len(target.elts):
                for t, v in zip(target.elts, val):
                    if isinstance(t, ast.Name):
                        env[t.id] = v
            else:
                for t in target.elts:
                    if isinstance(t, ast.Name):
                        env[t.id] = self.binding.get(t.id, UNKNOWN)
            self._note_shape_name(value_node, tuple(names), env, single=False)

    def _note_shape_name(self, value_node, names, env, single: bool) -> None:
        """Refine an Arr's symbolic shape from unpack targets:
        ``m, d = q.shape`` establishes q.shape == (m, d); ``n =
        x.shape[0]`` establishes x.shape[0] == n. Unbound dim names
        resolve through the active binding."""
        node = value_node
        idx = None
        if single and isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, int):
            idx = node.slice.value
            node = node.value
        if not (isinstance(node, ast.Attribute) and node.attr == "shape"):
            return
        arr = self._eval(node.value, env)
        if not isinstance(arr, Arr):
            return
        if single:
            if idx is None:
                return
            name = names[0]
            if arr.shape is None:
                arr.shape = [UNKNOWN] * (idx + 1)
            while len(arr.shape) <= idx:
                arr.shape.append(UNKNOWN)
            if arr.shape[idx] is UNKNOWN and name:
                arr.shape[idx] = self.binding.get(name, name)
                env[name] = self.binding.get(name, UNKNOWN)
        else:
            if arr.shape is None:
                arr.shape = [UNKNOWN] * len(names)
            if len(arr.shape) == len(names):
                for i, name in enumerate(names):
                    if arr.shape[i] is UNKNOWN and name:
                        arr.shape[i] = self.binding.get(name, name)
                        env[name] = self.binding.get(name, UNKNOWN)

    # -- expressions -------------------------------------------------------

    def _truth(self, v):
        if v is UNKNOWN:
            return UNKNOWN
        if isinstance(v, Arr):
            return UNKNOWN
        try:
            return bool(v)
        except Exception:  # noqa: BLE001 - abstract value truthiness
            return UNKNOWN

    def _eval(self, node: ast.AST, env: dict):
        self._tick()
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.consts:
                return self.consts[node.id]
            if node.id in self.fns:
                return FnV(self.fns[node.id])
            if node.id in self._imports:
                return self._resolve_import(*self._imports[node.id])
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return [self._eval(e, env) for e in node.elts] \
                if isinstance(node, ast.List) \
                else tuple(self._eval(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            out = {}
            for k, v in zip(node.keys, node.values):
                kk = self._eval(k, env) if k is not None else UNKNOWN
                out[kk if not isinstance(kk, _Unknown) else object()] = \
                    self._eval(v, env)
            return out
        if isinstance(node, ast.Lambda):
            return Lam(node, dict(env))
        if isinstance(node, ast.BinOp):
            return self._binop(type(node.op), self._eval(node.left, env),
                               self._eval(node.right, env), node)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env)
            if v is UNKNOWN:
                return UNKNOWN
            try:
                if isinstance(node.op, ast.USub):
                    # the ceil-div idiom: -(-n // t) — keep provenance
                    if isinstance(v, IntV) and v.kind == "neg_floor":
                        return IntV.div(-int(v), "ceil", v.num, v.den)
                    return -v
                if isinstance(node.op, ast.UAdd):
                    return +v
                if isinstance(node.op, ast.Not):
                    t = self._truth(v)
                    return UNKNOWN if t is UNKNOWN else not t
                if isinstance(node.op, ast.Invert):
                    return ~v
            except Exception:  # noqa: BLE001
                return UNKNOWN
            return UNKNOWN
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, env) for v in node.values]
            truths = [self._truth(v) for v in vals]
            if isinstance(node.op, ast.And):
                if False in truths:
                    return False
                return UNKNOWN if UNKNOWN in truths else vals[-1]
            if True in truths:
                return next(v for v, t in zip(vals, truths) if t is True)
            return UNKNOWN if UNKNOWN in truths else vals[-1]
        if isinstance(node, ast.Compare):
            left = self._eval(node.left, env)
            result = True
            for op, comp in zip(node.ops, node.comparators):
                right = self._eval(comp, env)
                r = self._compare(op, left, right)
                if r is UNKNOWN:
                    return UNKNOWN
                result = result and r
                left = right
            return result
        if isinstance(node, ast.IfExp):
            cond = self._truth(self._eval(node.test, env))
            if cond is UNKNOWN:
                a = self._eval(node.body, env)
                b = self._eval(node.orelse, env)
                return a if _same(a, b) else UNKNOWN
            return self._eval(node.body if cond else node.orelse, env)
        if isinstance(node, ast.Attribute):
            return self._attr(node, env)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env)
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._comprehension(node, env)
        if isinstance(node, ast.JoinedStr):
            return UNKNOWN
        return UNKNOWN

    def _binop(self, op, a, b, node=None):
        if a is UNKNOWN or b is UNKNOWN:
            return UNKNOWN
        try:
            if op is ast.Add:
                return a + b
            if op is ast.Sub:
                return a - b
            if op is ast.Mult:
                return a * b
            if op is ast.FloorDiv:
                v = a // b
                # the ceil-div idiom -(-a // b) surfaces here with a
                # negative numerator; tag plain positive floor-divs
                if isinstance(a, int) and isinstance(b, int) and b > 0:
                    if a >= 0:
                        return IntV.div(v, "floor", a, b)
                    return IntV.div(v, "neg_floor", -a, b)
                return v
            if op is ast.Mod:
                return a % b
            if op is ast.Div:
                return a / b
            if op is ast.Pow:
                return a ** b if abs(b) < 64 else UNKNOWN
            if op is ast.LShift:
                return a << b if b < 64 else UNKNOWN
            if op is ast.RShift:
                return a >> b
            if op is ast.BitOr:
                return a | b
            if op is ast.BitAnd:
                return a & b
            if op is ast.BitXor:
                return a ^ b
        except Exception:  # noqa: BLE001
            return UNKNOWN
        return UNKNOWN

    def _compare(self, op, a, b):
        if isinstance(op, (ast.Is, ast.IsNot)):
            if a is UNKNOWN or b is UNKNOWN:
                return UNKNOWN
            r = (a is None and b is None) or (a is b)
            if isinstance(a, Arr) and b is None:
                r = False
            if isinstance(b, Arr) and a is None:
                r = False
            return r if isinstance(op, ast.Is) else not r
        if a is UNKNOWN or b is UNKNOWN or isinstance(a, Arr) \
                or isinstance(b, Arr):
            return UNKNOWN
        try:
            if isinstance(op, ast.Eq):
                return a == b
            if isinstance(op, ast.NotEq):
                return a != b
            if isinstance(op, ast.Lt):
                return a < b
            if isinstance(op, ast.LtE):
                return a <= b
            if isinstance(op, ast.Gt):
                return a > b
            if isinstance(op, ast.GtE):
                return a >= b
            if isinstance(op, ast.In):
                return a in b
            if isinstance(op, ast.NotIn):
                return a not in b
        except Exception:  # noqa: BLE001
            return UNKNOWN
        return UNKNOWN

    def _attr(self, node: ast.Attribute, env: dict):
        base = self._eval(node.value, env)
        if isinstance(base, Arr):
            if node.attr == "shape":
                if base.shape is None:
                    return UNKNOWN
                return tuple(self.binding.get(d, UNKNOWN)
                             if isinstance(d, str) else d
                             for d in base.shape)
            if node.attr == "dtype":
                return base.dtype if base.dtype is not None else UNKNOWN
            if node.attr == "ndim":
                return len(base.shape) if base.shape is not None else UNKNOWN
        dotted = _dotted(node)
        if dotted in _DTYPE_NAMES:
            return _DTYPE_NAMES[dotted]
        if isinstance(base, dict) and node.attr in base:
            return base[node.attr]
        return UNKNOWN

    def _subscript(self, node: ast.Subscript, env: dict):
        base = self._eval(node.value, env)
        if base is UNKNOWN:
            return UNKNOWN
        if isinstance(base, Arr):
            return Arr(shape=None, dtype=base.dtype)
        sl = node.slice
        if isinstance(sl, ast.Slice):
            lo = self._eval(sl.lower, env) if sl.lower else None
            hi = self._eval(sl.upper, env) if sl.upper else None
            if lo is UNKNOWN or hi is UNKNOWN:
                return UNKNOWN
            try:
                return base[slice(lo, hi)]
            except Exception:  # noqa: BLE001
                return UNKNOWN
        idx = self._eval(sl, env)
        if idx is UNKNOWN:
            return UNKNOWN
        try:
            return base[idx]
        except Exception:  # noqa: BLE001
            return UNKNOWN

    def _comprehension(self, node, env: dict):
        if len(node.generators) != 1:
            return UNKNOWN
        gen = node.generators[0]
        seq = self._eval(gen.iter, env)
        if not isinstance(seq, (list, tuple, range)) or len(seq) > _MAX_LOOP:
            return UNKNOWN
        out = []
        for item in seq:
            inner = dict(env)
            self._assign(gen.target, item, inner, gen.iter)
            keep = True
            for cond in gen.ifs:
                t = self._truth(self._eval(cond, inner))
                if t is UNKNOWN:
                    return UNKNOWN
                keep = keep and t
            if keep:
                out.append(self._eval(node.elt, inner))
        return out

    def _resolve_import(self, module: str, attr: str):
        try:
            return RealFn(getattr(importlib.import_module(module), attr))
        except Exception:  # noqa: BLE001 - unresolvable helper
            return UNKNOWN

    # -- calls -------------------------------------------------------------

    def _call(self, node: ast.Call, env: dict):
        fname = _dotted(node.func) or ""

        # method calls on abstract values
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            base = self._eval(node.func.value, env)
            if isinstance(base, Arr):
                if meth == "reshape":
                    shape = [self._eval(a, env) for a in node.args]
                    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
                        shape = list(shape[0])
                    if all(isinstance(s, int) for s in shape):
                        return Arr(shape=list(shape), dtype=base.dtype)
                    return Arr(shape=None, dtype=base.dtype)
                if meth == "astype":
                    dt = self._eval(node.args[0], env) if node.args else None
                    return Arr(shape=list(base.shape) if base.shape else None,
                               dtype=dt if isinstance(dt, str) else
                               (dt if isinstance(dt, tuple) else None))
                return Arr(shape=None, dtype=base.dtype)
            if isinstance(base, list):
                if meth == "append":
                    base.append(self._eval(node.args[0], env))
                    return None
                if meth == "extend":
                    v = self._eval(node.args[0], env)
                    if isinstance(v, (list, tuple)):
                        base.extend(v)
                    return None
                if meth == "pop":
                    idx = self._eval(node.args[0], env) if node.args else -1
                    try:
                        return base.pop(idx)
                    except Exception:  # noqa: BLE001
                        return UNKNOWN
            if isinstance(base, int) and meth == "bit_length":
                return int(base).bit_length()

        args = [self._eval(a, env) for a in node.args]
        # splat starred args
        flat_args: list = []
        for a, n in zip(args, node.args):
            if isinstance(n, ast.Starred) and isinstance(a, (list, tuple)):
                flat_args.extend(a)
            else:
                flat_args.append(a)
        kwargs = {kw.arg: self._eval(kw.value, env)
                  for kw in node.keywords if kw.arg}

        if fname in _BLOCKSPEC_NAMES:
            shape = None
            imap = None
            if node.args:
                v = flat_args[0]
                if isinstance(v, (tuple, list)):
                    shape = tuple(v)
                elif v is not UNKNOWN and isinstance(v, Lam):
                    imap = v        # BlockSpec(index_map) legacy order
            if len(node.args) >= 2 and isinstance(args[1], Lam):
                imap = args[1]
            if isinstance(kwargs.get("index_map"), Lam):
                imap = kwargs["index_map"]
            if isinstance(kwargs.get("block_shape"), (tuple, list)):
                shape = tuple(kwargs["block_shape"])
            return BlockV(shape, imap, node.lineno, node)
        if fname in _VMEM_SCRATCH_NAMES:
            shape = flat_args[0] if flat_args else kwargs.get("shape")
            dtype = flat_args[1] if len(flat_args) > 1 else kwargs.get("dtype")
            return ScratchV(tuple(shape) if isinstance(shape, (tuple, list))
                            else None, dtype, node.lineno, node)
        if fname in _SDS_NAMES:
            shape = flat_args[0] if flat_args else kwargs.get("shape")
            dtype = flat_args[1] if len(flat_args) > 1 else kwargs.get("dtype")
            return SDSV(tuple(shape) if isinstance(shape, (tuple, list))
                        else None, dtype)
        if fname in _GRIDSPEC_NAMES:
            return GridSpecV(
                num_scalar_prefetch=int(kwargs.get("num_scalar_prefetch", 0))
                if isinstance(kwargs.get("num_scalar_prefetch", 0), int)
                else 0,
                grid=kwargs.get("grid") or (),
                in_specs=kwargs.get("in_specs") or [],
                out_specs=kwargs.get("out_specs") or [],
                scratch=list(kwargs.get("scratch_shapes") or []),
            )
        if fname in _PALLAS_CALL_NAMES:
            return self._eval_site(node, flat_args, kwargs)
        if fname in ("functools.partial", "partial"):
            return PartialV(flat_args[0] if flat_args else UNKNOWN, kwargs)
        if fname in ("pl.cdiv", "cdiv"):
            if len(flat_args) == 2 and all(
                    isinstance(a, int) for a in flat_args):
                a, b = flat_args
                return IntV.div(-(-a // b), "ceil", a, b)
            return UNKNOWN
        if fname == "jnp.pad" or fname == "np.pad":
            return self._eval_pad(node, flat_args, env)
        if fname in ("jnp.zeros", "jnp.ones", "jnp.empty", "jnp.full",
                     "np.zeros", "np.ones", "np.empty", "np.full"):
            shape = flat_args[0] if flat_args else None
            if isinstance(shape, int):
                shape = (shape,)
            dt = kwargs.get("dtype")
            if len(flat_args) > 1 and isinstance(flat_args[-1], str):
                dt = flat_args[-1]
            return Arr(shape=list(shape) if isinstance(shape, (tuple, list))
                       and all(isinstance(s, int) for s in shape) else None,
                       dtype=dt if isinstance(dt, str) else None)
        if fname in ("int", "bool", "float", "str"):
            v = flat_args[0] if flat_args else 0
            if v is UNKNOWN or isinstance(v, Arr):
                return UNKNOWN
            try:
                return {"int": int, "bool": bool, "float": float,
                        "str": str}[fname](v)
            except Exception:  # noqa: BLE001
                return UNKNOWN
        if fname in ("len",):
            v = flat_args[0] if flat_args else UNKNOWN
            if isinstance(v, (list, tuple, dict, str)):
                return len(v)
            if isinstance(v, Arr) and v.shape is not None:
                return len(v.shape)
            return UNKNOWN
        if fname in ("max", "min", "abs", "sum"):
            if any(a is UNKNOWN or isinstance(a, Arr) for a in flat_args):
                return UNKNOWN
            try:
                vals = (flat_args[0] if len(flat_args) == 1
                        and isinstance(flat_args[0], (list, tuple))
                        else flat_args)
                return {"max": max, "min": min, "abs": abs,
                        "sum": sum}[fname](vals)
            except Exception:  # noqa: BLE001
                return UNKNOWN
        if fname == "range":
            if all(isinstance(a, int) for a in flat_args) and flat_args:
                r = range(*flat_args)
                return r if len(r) <= _MAX_LOOP else UNKNOWN
            return UNKNOWN
        if fname == "list":
            v = flat_args[0] if flat_args else []
            return list(v) if isinstance(v, (list, tuple)) else UNKNOWN
        if fname == "tuple":
            v = flat_args[0] if flat_args else ()
            return tuple(v) if isinstance(v, (list, tuple)) else UNKNOWN

        callee = self._eval(node.func, env)
        if isinstance(callee, _SiteBound):
            # pl.pallas_call(...)(*operands): record the runtime inputs
            callee.site.inputs = flat_args
            return Arr(shape=None, dtype=None)
        if isinstance(callee, FnV):
            return self._call_local(callee.node, flat_args, kwargs)
        if isinstance(callee, RealFn):
            if any(a is UNKNOWN or isinstance(a, (Arr, Lam, FnV))
                   for a in flat_args) or any(
                    v is UNKNOWN or isinstance(v, (Arr, Lam, FnV))
                    for v in kwargs.values()):
                return UNKNOWN
            try:
                return callee.fn(*flat_args, **kwargs)
            except Exception:  # noqa: BLE001 - helper rejected the binding
                raise _Infeasible()
        # array-producing jnp/jax calls and everything else
        if fname.startswith(("jnp.", "jax.", "lax.")):
            return Arr(shape=None, dtype=None)
        return UNKNOWN

    def _call_local(self, fn: ast.FunctionDef, args: list, kwargs: dict):
        env: dict = {}
        fargs = fn.args
        pos = list(fargs.posonlyargs) + list(fargs.args)
        defaults = list(fargs.defaults)
        for i, p in enumerate(pos):
            if i < len(args):
                env[p.arg] = args[i]
            elif p.arg in kwargs:
                env[p.arg] = kwargs[p.arg]
            else:
                di = i - (len(pos) - len(defaults))
                env[p.arg] = (self._eval(defaults[di], {})
                              if 0 <= di < len(defaults) else UNKNOWN)
        for p, d in zip(fargs.kwonlyargs, fargs.kw_defaults):
            env[p.arg] = kwargs.get(
                p.arg, self._eval(d, {}) if d is not None else UNKNOWN)
        try:
            self._exec(fn.body, env)
        except _Return as r:
            return r.value
        return None

    def _eval_pad(self, node: ast.Call, args: list, env: dict):
        if len(args) < 2 or not isinstance(args[0], Arr):
            return Arr(shape=None, dtype=None)
        base, pads = args[0], args[1]
        if base.shape is None or not isinstance(pads, (tuple, list)):
            return Arr(shape=None, dtype=base.dtype)
        if all(isinstance(p, int) for p in pads) and len(pads) == 2:
            pads = [pads]                       # 1-D form
        shape = []
        for dim, p in zip(base.shape, pads):
            d = self.binding.get(dim, dim) if isinstance(dim, str) else dim
            if isinstance(d, int) and isinstance(p, (tuple, list)) \
                    and len(p) == 2 and all(isinstance(x, int) for x in p):
                shape.append(d + p[0] + p[1])
            else:
                shape.append(UNKNOWN)
        if len(shape) != len(base.shape):
            return Arr(shape=None, dtype=base.dtype)
        return Arr(shape=shape, dtype=base.dtype)

    def _eval_site(self, node: ast.Call, args: list, kwargs: dict):
        kernel = args[0] if args else UNKNOWN
        gs = kwargs.get("grid_spec")
        if isinstance(gs, GridSpecV):
            grid = gs.grid
            in_specs, out_specs = gs.in_specs, gs.out_specs
            scratch = gs.scratch
            prefetch = gs.num_scalar_prefetch
        else:
            grid = kwargs.get("grid") or ()
            in_specs = kwargs.get("in_specs") or []
            out_specs = kwargs.get("out_specs") or []
            scratch = list(kwargs.get("scratch_shapes") or [])
            prefetch = 0
        if isinstance(grid, int):
            grid = (grid,)
        out_shape = kwargs.get("out_shape")
        out_shapes = (list(out_shape) if isinstance(out_shape, (list, tuple))
                      else [out_shape] if isinstance(out_shape, SDSV) else [])
        if isinstance(out_specs, BlockV):
            out_specs = [out_specs]
        if isinstance(in_specs, BlockV):
            in_specs = [in_specs]
        se = SiteEval(
            binding=dict(self.binding), kernel=kernel,
            grid=tuple(grid) if isinstance(grid, (tuple, list)) else (),
            in_specs=list(in_specs) if isinstance(in_specs, (list, tuple))
            else [],
            out_specs=list(out_specs) if isinstance(out_specs, (list, tuple))
            else [],
            out_shapes=out_shapes, scratch=scratch, inputs=[],
            num_prefetch=prefetch,
        )
        self.sites[node] = se
        return _SiteBound(se)


@dataclasses.dataclass
class _SiteBound:
    """The value of ``pl.pallas_call(...)`` — calling it records the
    runtime operands on the SiteEval."""

    site: SiteEval


def _same(a, b) -> bool:
    if a is b:
        return True
    if isinstance(a, (int, str, bool, float)) and \
            isinstance(b, (int, str, bool, float)):
        return a == b
    return False


# ---------------------------------------------------------------------------
# kernel-side models
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RefInfo:
    """One positional ref of the kernel callable: its role in the
    pallas_call signature and the dtype/block the site declares."""

    kind: str                       # "prefetch" | "in" | "out" | "scratch"
    index: int
    dtype: Optional[str]
    block: Optional[tuple]


_LOW_PRECISION = {"bfloat16", "float16", "int8", "uint8", "int16"}


def _iter_stmts(body):
    for s in body:
        yield s
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(s, attr, None)
            if sub:
                yield from _iter_stmts(sub)


def _fmt_binding(binding: dict, limit: int = 7) -> str:
    items = [f"{k}={v}" for k, v in sorted(binding.items())
             if isinstance(v, (int, str, bool)) and not k.endswith("_shape")]
    out = ", ".join(items[:limit])
    if len(items) > limit:
        out += ", ..."
    return out or "literal shapes"


def _shape_ints(shape) -> Optional[tuple]:
    if shape is None:
        return None
    out = []
    for d in shape:
        if isinstance(d, bool) or not isinstance(d, int):
            return None
        out.append(int(d))
    return tuple(out)


def _dtype_name(v) -> Optional[str]:
    if isinstance(v, str):
        return v
    return None


# ---------------------------------------------------------------------------
# the verifier
# ---------------------------------------------------------------------------


class FileKernelVerifier:
    def __init__(self, path: str, source: str,
                 rules: Optional[Set[str]] = None):
        self.path = path
        self.source = source
        self.rules = rules
        self.tree = ast.parse(source, filename=path)
        self.findings: List[Finding] = []
        self._emitted: Set[tuple] = set()
        self.module_name = self._module_name(path)
        # spec Call nodes covered by a site whose geometry the engine
        # fully resolved — exempt from the literal fallback screen
        self._resolved_spec_nodes: Set[ast.Call] = set()
        self._site_parents: Dict[ast.Call, ast.FunctionDef] = {}
        self.report: Dict[str, object] = {"sites": 0, "resolved": 0}

    @staticmethod
    def _module_name(path: str) -> Optional[str]:
        parts = Path(path).parts
        if "raft_tpu" not in parts:
            return None
        i = len(parts) - 1 - parts[::-1].index("raft_tpu")
        mod = list(parts[i:])
        if not mod[-1].endswith(".py"):
            return None
        mod[-1] = mod[-1][:-3]
        if mod[-1] == "__init__":
            mod.pop()
        return ".".join(mod)

    # -- plumbing ----------------------------------------------------------

    def _emit(self, rule: str, line: int, key: tuple, message: str) -> None:
        if self.rules is not None and rule not in self.rules:
            return
        dedup = (rule, line) + key
        if dedup in self._emitted:
            return
        self._emitted.add(dedup)
        self.findings.append(Finding(rule, self.path, line, message,
                                     engine="kern"))

    def run(self) -> List[Finding]:
        self._find_sites()
        fns: Dict[ast.FunctionDef, List[ast.Call]] = {}
        for call, fn in self._site_parents.items():
            fns.setdefault(fn, []).append(call)
        for fn, calls in fns.items():
            self._verify_function(fn, calls)
        self._literal_screen()
        sup = scan_suppressions(self.source)
        return apply_suppressions(self.findings, sup, self.path)

    def _find_sites(self) -> None:
        stack: List[ast.FunctionDef] = []

        def walk(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node)
            if isinstance(node, ast.Call) and \
                    (_dotted(node.func) or "") in _PALLAS_CALL_NAMES and stack:
                self._site_parents[node] = stack[-1]
            for child in ast.iter_child_nodes(node):
                walk(child)
            if is_fn:
                stack.pop()

        walk(self.tree)
        self.report["sites"] = len(self._site_parents)

    # -- bindings ----------------------------------------------------------

    def _bindings_for(self, fn: ast.FunctionDef) -> List[Tuple[dict, dict]]:
        """(binding, arrays) pairs to evaluate ``fn`` under: the bare
        literal binding first, then bindings lifted from the function's
        own intra-module call sites (computed shapes flow in from the
        caller — the class the literal heuristic could never see), then
        every matching contract's static cases augmented with tuning
        tile candidates."""
        out: List[Tuple[dict, dict]] = [({}, {})]
        out += [(b, {}) for b in self._callsite_bindings(fn)]
        names_in_fn = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        aug = self._augmentation_domains(names_in_fn)
        for c in _module_contracts(self.module_name):
            arrays = dict(c.arrays)
            for case in static_cases(c):
                missing = {k: v for k, v in aug.items() if k not in case}
                for combo in _corner_product(missing):
                    b = dict(case)
                    b.update(combo)
                    out.append((b, arrays))
                    if len(out) >= _MAX_BINDINGS:
                        return out
        if len(out) == 1:
            # uncontracted site: fall back to the generic dim table
            for combo in _corner_product(aug, full_first=True):
                if combo:
                    out.append((combo, {}))
                if len(out) >= 32:
                    break
        return out

    def _callsite_bindings(self, fn: ast.FunctionDef) -> List[dict]:
        """Concrete bindings lifted from intra-module calls of ``fn``:
        literal/computable ints, strings, bools, and literal-shaped
        arrays (``jnp.zeros((300, 128))``) flow into the parameters."""
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        bindings: List[dict] = []
        seen: Set[tuple] = set()
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Name) and
                    node.func.id == fn.name):
                continue
            interp = Interp(self.tree, self.module_name)
            interp.fuel = _MAX_STEPS
            b: dict = {}
            try:
                for i, a in enumerate(node.args[:len(params)]):
                    v = interp._eval(a, {})
                    if isinstance(v, (int, str, bool, Arr)) or v is None:
                        b[params[i]] = v
                for kw in node.keywords:
                    if kw.arg:
                        v = interp._eval(kw.value, {})
                        if isinstance(v, (int, str, bool, Arr)) or v is None:
                            b[kw.arg] = v
            except (_Infeasible, _OutOfFuel):
                continue
            if not b:
                continue
            key = tuple(sorted((k, repr(v)) for k, v in b.items()))
            if key not in seen:
                seen.add(key)
                bindings.append(b)
            if len(bindings) >= 8:
                break
        return bindings

    def _augmentation_domains(self, names: Set[str]) -> Dict[str, tuple]:
        domains: Dict[str, tuple] = {}
        try:
            from raft_tpu import tuning

            for k, v in tuning.kernel_shape_candidates().items():
                if k in names:
                    domains[k] = tuple(v)
        except Exception:  # noqa: BLE001 - tuning unavailable: defaults only
            pass
        for k, v in _DEFAULT_DIMS.items():
            if k in names and k not in domains:
                domains[k] = v
        return domains

    # -- per-function verification ----------------------------------------

    def _verify_function(self, fn: ast.FunctionDef,
                         calls: List[ast.Call]) -> None:
        resolved: Set[ast.Call] = set()
        for binding, arrays in self._bindings_for(fn):
            interp = Interp(self.tree, self.module_name)
            try:
                interp.run_function(fn, binding, arrays)
            except (_Infeasible, _OutOfFuel):
                continue
            except RecursionError:
                continue
            for call in calls:
                se = interp.sites.get(call)
                if se is None:
                    continue
                if self._check_site(call, se, interp):
                    resolved.add(call)
                    self._mark_resolved(se)
        self.report["resolved"] = self.report.get("resolved", 0) + \
            len(resolved)

    def _mark_resolved(self, se: SiteEval) -> None:
        """Exempt from the literal fallback screen exactly the spec
        nodes this resolved evaluation CHECKED (BlockV/ScratchV carry
        their Call node) — never the whole enclosing function: a
        literal spec the interpreter never reached (dead branch,
        poisoned loop) must still hit the literal screen."""
        for sp in list(se.in_specs) + list(se.out_specs) + list(se.scratch):
            node = getattr(sp, "node", None)
            if node is not None:
                self._resolved_spec_nodes.add(node)

    # -- site checks -------------------------------------------------------

    def _check_site(self, call: ast.Call, se: SiteEval,
                    interp: Interp) -> bool:
        """Run every rule the binding resolves; returns True when the
        site's geometry was fully concrete (VMEM accounting complete)."""
        grid = se.grid
        grid_ints = all(isinstance(g, int) and not isinstance(g, bool)
                        for g in grid)
        specs: List[Tuple[str, int, object]] = []   # (role, idx, spec)
        for i, sp in enumerate(se.in_specs):
            specs.append(("in", i, sp))
        for i, sp in enumerate(se.out_specs):
            specs.append(("out", i, sp))
        for i, sp in enumerate(se.scratch):
            specs.append(("scratch", i, sp))

        witness = _fmt_binding(se.binding)
        operands = se.inputs[se.num_prefetch:] if se.inputs else []

        total_bytes = 0
        complete = grid_ints and bool(se.out_shapes)
        for role, i, sp in specs:
            block, dtype, arr_shape, line = self._spec_facts(
                role, i, sp, se, operands)
            bl = _shape_ints(block)
            if bl is None:
                complete = False
                continue
            itemsize = dtype_itemsize(dtype) if dtype else 4
            nelem = 1
            for d in bl:
                nelem *= max(int(d), 1)
            total_bytes += nelem * itemsize
            self._check_alignment(role, i, bl, dtype, arr_shape, line,
                                  witness)
            if grid_ints and isinstance(sp, BlockV):
                self._check_bounds(role, i, sp, bl, arr_shape, grid,
                                   interp, line, witness)
        if complete and total_bytes > _VMEM_BUDGET_BYTES:
            self._emit(
                "GL006", call.lineno, ("vmem",),
                f"pallas_call blocks + scratch total "
                f"~{total_bytes / 2**20:.1f} MiB, over the "
                f"~{_VMEM_BUDGET_BYTES // 2**20} MiB per-core VMEM budget "
                f"(witness: {witness})")

        kfn, statics = self._kernel_fn(se, interp)
        if grid_ints:
            self._check_tails(call, se, kfn, statics, interp, witness)
        if kfn is not None:
            kenv = self._kernel_env(kfn, statics, se, operands, interp)
            self._check_grid_hazards(call, se, kfn, kenv, interp, witness)
            self._check_dots(kfn, kenv, interp)
        return complete

    def _spec_facts(self, role: str, i: int, sp, se: SiteEval,
                    operands: list):
        """(block_shape, dtype, array_shape, lineno) for one spec."""
        if isinstance(sp, ScratchV):
            return sp.shape, _dtype_name(sp.dtype), None, sp.lineno
        if not isinstance(sp, BlockV):
            return None, None, None, 0
        arr_shape = None
        dtype = None
        if role == "in" and i < len(operands):
            op = operands[i]
            if isinstance(op, Arr):
                dtype = _dtype_name(op.dtype)
                if op.shape is not None:
                    resolved = [se.binding.get(d, d) if isinstance(d, str)
                                else d for d in op.shape]
                    arr_shape = _shape_ints(resolved)
        elif role == "out" and i < len(se.out_shapes):
            sds = se.out_shapes[i]
            if isinstance(sds, SDSV):
                dtype = _dtype_name(sds.dtype)
                arr_shape = _shape_ints(sds.shape)
        block = sp.shape
        if block is None and arr_shape is not None:
            block = arr_shape          # whole-array spec
        return block, dtype, arr_shape, sp.lineno

    def _check_alignment(self, role: str, i: int, block: tuple,
                         dtype: Optional[str], arr_shape: Optional[tuple],
                         line: int, witness: str) -> None:
        if not block:
            return
        itemsize = dtype_itemsize(dtype) if dtype else 4
        sub = SUBLANE_BY_ITEMSIZE[itemsize]
        dt = dtype or "f32-assumed"
        checks = [(len(block) - 1, LANE, "lane")]
        if len(block) >= 2:
            checks.append((len(block) - 2, sub, "sublane"))
        for dim, mult, kind in checks:
            v = block[dim]
            if v == 1 or v % mult == 0:
                continue
            if arr_shape is not None and dim < len(arr_shape) and \
                    arr_shape[dim] == v:
                continue               # block == array dim: always legal
            self._emit(
                "GL016", line, (role, i, kind),
                f"{role}-spec {i} block dim {dim} = {v} is off the "
                f"({sub}, {LANE}) tile for dtype {dt} ({kind} axis): "
                f"not 1, not a multiple of {mult}, and not the array "
                f"dim — forces a relayout or fails to lower "
                f"(witness: {witness})")

    def _check_bounds(self, role: str, i: int, sp: BlockV, block: tuple,
                      arr_shape: Optional[tuple], grid: tuple,
                      interp: Interp, line: int, witness: str) -> None:
        if arr_shape is None or sp.index_map is None or not grid:
            return
        corners = itertools.product(*[(0, int(g) - 1) for g in grid])
        max_idx: List[Optional[int]] = [None] * len(block)
        for corner in itertools.islice(corners, 64):
            res = self._eval_index_map(sp.index_map, corner, interp)
            if res is None:
                return                  # data-dependent map: dynamic job
            for d, v in enumerate(res[:len(block)]):
                if isinstance(v, int) and not isinstance(v, bool):
                    cur = max_idx[d]
                    max_idx[d] = v if cur is None else max(cur, v)
        for d in range(min(len(block), len(arr_shape))):
            if max_idx[d] is None:
                continue
            reach = (max_idx[d] + 1) * block[d]
            if reach > arr_shape[d]:
                self._emit(
                    "GL015", line, ("oob", role, i, d),
                    f"{role}-spec {i} index map reaches block "
                    f"{max_idx[d]} on dim {d}: elements up to {reach} "
                    f"but the array dim is {arr_shape[d]} — out-of-"
                    f"bounds read/write (witness: {witness})")

    def _eval_index_map(self, lam: Lam, corner: tuple,
                        interp: Interp) -> Optional[tuple]:
        params = [a.arg for a in lam.node.args.args]
        env = dict(lam.env)
        for j, p in enumerate(params):
            env[p] = corner[j] if j < len(corner) else UNKNOWN
        interp.fuel = max(interp.fuel, 500)
        try:
            res = interp._eval(lam.node.body, env)
        except (_Infeasible, _OutOfFuel):
            return None
        if isinstance(res, int) and not isinstance(res, bool):
            res = (res,)
        if not isinstance(res, tuple):
            return None
        if any(not isinstance(v, int) or isinstance(v, bool) for v in res):
            return None
        return res

    # -- tails -------------------------------------------------------------

    def _check_tails(self, call: ast.Call, se: SiteEval,
                     kfn: Optional[ast.FunctionDef], statics: dict,
                     interp: Interp, witness: str) -> None:
        for g, ext in enumerate(se.grid):
            if not isinstance(ext, IntV) or not ext.tail:
                continue
            rem = ext.num % ext.den if ext.den else 0
            if ext.kind == "floor":
                self._emit(
                    "GL015", call.lineno, ("floor", g),
                    f"grid dim {g} extent is {ext.num} // {ext.den} with "
                    f"remainder {rem}: the array's last {rem} elements on "
                    f"that axis are never visited by the grid "
                    f"(witness: {witness})")
            elif ext.kind == "ceil":
                if kfn is not None and self._has_mask_evidence(kfn, interp):
                    continue
                kname = kfn.name if kfn is not None else "<unresolved>"
                self._emit(
                    "GL015", call.lineno, ("tail", g),
                    f"grid dim {g} extent is ceil({ext.num} / {ext.den}) "
                    f"with {ext.num} % {ext.den} = {rem}: the tail tile is "
                    f"reachable but kernel {kname}() shows no tail mask "
                    f"(no jnp.where/pl.when guarded by a bound compare) — "
                    f"pad garbage can win the reduction "
                    f"(witness: {witness})")

    _IDX_CALLS = ("broadcasted_iota", "iota", "program_id")

    def _has_mask_evidence(self, kfn: ast.FunctionDef,
                           interp: Interp) -> bool:
        """A tail mask must gate on an INDEX-derived value (iota /
        program_id, or a name computed from one) — a numeric clamp like
        ``where(dist < 0, 0, dist)`` has an inequality but masks
        nothing positional, so it is not evidence."""
        bodies = [kfn]
        called = {(_dotted(sub.func) or "").rsplit(".", 1)[-1]
                  for sub in ast.walk(kfn) if isinstance(sub, ast.Call)}
        for name in called:
            if name in interp.fns:
                bodies.append(interp.fns[name])

        idx_names: Set[str] = set()

        def has_idx(node: ast.AST) -> bool:
            for s in ast.walk(node):
                if isinstance(s, ast.Call) and (
                        _dotted(s.func) or "").rsplit(".", 1)[-1] \
                        in self._IDX_CALLS:
                    return True
                if isinstance(s, ast.Name) and s.id in idx_names:
                    return True
            return False

        # fixed point over assignments: index carriers (col = iota + off)
        # and boolean masks derived from them (valid = col < size)
        for _ in range(4):
            grew = False
            for body in bodies:
                for sub in ast.walk(body):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1 and \
                            isinstance(sub.targets[0], ast.Name) and \
                            has_idx(sub.value):
                        if sub.targets[0].id not in idx_names:
                            idx_names.add(sub.targets[0].id)
                            grew = True
            if not grew:
                break

        for body in bodies:
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call) or not sub.args:
                    continue
                fname = _dotted(sub.func) or ""
                is_when = fname in ("pl.when", "pltpu.when")
                is_where = fname.rsplit(".", 1)[-1] == "where"
                if not (is_when or is_where):
                    continue
                test = sub.args[0]
                if not has_idx(test):
                    continue
                has_cmp = any(isinstance(c, ast.Compare)
                              for c in ast.walk(test))
                named_mask = isinstance(test, ast.Name) and \
                    test.id in idx_names
                if has_cmp or named_mask:
                    return True
        return False

    # -- kernel resolution -------------------------------------------------

    def _kernel_fn(self, se: SiteEval, interp: Interp
                   ) -> Tuple[Optional[ast.FunctionDef], dict]:
        k = se.kernel
        statics: dict = {}
        if isinstance(k, PartialV):
            statics = {n: v for n, v in k.kwargs.items()}
            k = k.fn
        if isinstance(k, FnV):
            return k.node, statics
        return None, statics

    def _kernel_env(self, kfn: ast.FunctionDef, statics: dict,
                    se: SiteEval, operands: list, interp: Interp) -> dict:
        refs: List[RefInfo] = []
        for i in range(se.num_prefetch):
            refs.append(RefInfo("prefetch", i, "int32", None))
        for i, sp in enumerate(se.in_specs):
            dtype = None
            if i < len(operands) and isinstance(operands[i], Arr):
                dtype = _dtype_name(operands[i].dtype)
            refs.append(RefInfo(
                "in", i, dtype,
                sp.shape if isinstance(sp, BlockV) else None))
        for i, sp in enumerate(se.out_specs):
            dtype = None
            if i < len(se.out_shapes) and isinstance(se.out_shapes[i], SDSV):
                dtype = _dtype_name(se.out_shapes[i].dtype)
            refs.append(RefInfo(
                "out", i, dtype,
                sp.shape if isinstance(sp, BlockV) else None))
        for i, sp in enumerate(se.scratch):
            refs.append(RefInfo(
                "scratch", i,
                _dtype_name(sp.dtype) if isinstance(sp, ScratchV) else None,
                sp.shape if isinstance(sp, ScratchV) else None))

        env: dict = {}
        args = kfn.args
        pos = [a.arg for a in args.posonlyargs + args.args]
        ri = 0
        for name in pos:
            if name in statics:
                env[name] = statics[name]
            elif ri < len(refs):
                env[name] = refs[ri]
                ri += 1
            else:
                env[name] = UNKNOWN
        if args.vararg is not None:
            env[args.vararg.arg] = list(refs[ri:])
        for a in args.kwonlyargs:
            if a.arg in statics:
                env[a.arg] = statics[a.arg]
        interp.fuel = 20000
        interp.binding = se.binding
        try:
            interp._exec(kfn.body, env)
        except (_Return, _Infeasible, _OutOfFuel, RecursionError):
            pass
        return env

    # -- GL017 grid hazards ------------------------------------------------

    def _check_grid_hazards(self, call: ast.Call, se: SiteEval,
                            kfn: ast.FunctionDef, kenv: dict,
                            interp: Interp, witness: str) -> None:
        grid = se.grid
        if not grid or not all(isinstance(g, int) and not isinstance(g, bool)
                               for g in grid):
            return
        revisit_dims_per_out: Dict[int, List[int]] = {}
        for i, sp in enumerate(se.out_specs):
            if not isinstance(sp, BlockV) or sp.index_map is None:
                continue
            params = [a.arg for a in sp.index_map.node.args.args]
            gparams = params[:len(grid)]
            used = {n.id for n in ast.walk(sp.index_map.node.body)
                    if isinstance(n, ast.Name)}
            unused = [g for g, p in enumerate(gparams)
                      if p not in used and grid[g] > 1]
            if unused:
                revisit_dims_per_out[i] = unused
        if not revisit_dims_per_out:
            return

        writes = self._ref_writes(kfn, kenv)
        for i, dims in revisit_dims_per_out.items():
            for node, is_aug, reads_ref, nm in writes:
                ri = kenv.get(nm)
                if not isinstance(ri, RefInfo) or ri.kind != "out" \
                        or ri.index != i:
                    continue
                dim_s = ",".join(str(d) for d in dims)
                if is_aug or reads_ref:
                    if not self._has_init_guard_for(kfn, nm):
                        self._emit(
                            "GL017", node.lineno, ("uninit", i),
                            f"output ref {nm!r} is revisited across grid "
                            f"dim(s) {dim_s} and accumulated into, but the "
                            f"kernel has no first-step init "
                            f"(pl.when/program_id guard): pallas outputs "
                            f"start uninitialized (witness: {witness})")
                else:
                    self._emit(
                        "GL017", node.lineno, ("overwrite", i),
                        f"output ref {nm!r} is plainly overwritten while "
                        f"its index map ignores grid dim(s) {dim_s} "
                        f"(extent > 1): each revisit clobbers the "
                        f"previous step's result — accumulate with a "
                        f"first-step init or index the block by that "
                        f"grid dim (witness: {witness})")

    def _has_init_guard_for(self, kfn: ast.FunctionDef, nm: str) -> bool:
        """First-step-init evidence is PER REF: a ``@pl.when(...)``
        guarded function must write THIS ref — an unrelated guard (or
        another output's init) must not launder an uninitialized
        accumulator."""
        for sub in ast.walk(kfn):
            if not isinstance(sub, ast.FunctionDef):
                continue
            guarded = any(
                isinstance(deco, ast.Call) and
                (_dotted(deco.func) or "") in ("pl.when", "pltpu.when")
                for deco in sub.decorator_list)
            if not guarded:
                continue
            for w in ast.walk(sub):
                targets = []
                if isinstance(w, ast.Assign):
                    targets = w.targets
                elif isinstance(w, ast.AugAssign):
                    targets = [w.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == nm:
                        return True
        return False

    def _ref_writes(self, kfn: ast.FunctionDef, kenv: dict
                    ) -> List[tuple]:
        out = []
        for sub in ast.walk(kfn):
            targets = []
            value = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AugAssign):
                targets, value = [sub.target], sub.value
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    nm = t.value.id
                    if not isinstance(kenv.get(nm), RefInfo):
                        continue
                    reads = any(
                        isinstance(n, ast.Name) and n.id == nm
                        for n in ast.walk(value)) if value is not None \
                        else False
                    out.append((sub, isinstance(sub, ast.AugAssign),
                                reads, nm))
        return out

    # -- GL018 MXU dtype audit ---------------------------------------------

    def _check_dots(self, kfn: ast.FunctionDef, kenv: dict,
                    interp: Interp) -> None:
        dtenv: Dict[str, Optional[str]] = {}
        for name, v in kenv.items():
            if isinstance(v, RefInfo):
                dtenv[name] = v.dtype
            elif isinstance(v, Arr):
                dtenv[name] = _dtype_name(v.dtype)
            elif isinstance(v, str) and v in _DTYPE_NAMES.values():
                dtenv[name] = v
        for stmt in _iter_stmts(kfn.body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                dtenv[stmt.targets[0].id] = self._expr_dtype(
                    stmt.value, dtenv)
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and \
                        (_dotted(sub.func) or "") in _DOT_NAMES and \
                        len(sub.args) >= 2:
                    self._check_one_dot(sub, dtenv)

    def _check_one_dot(self, node: ast.Call, dtenv: dict) -> None:
        a = self._expr_dtype(node.args[0], dtenv)
        b = self._expr_dtype(node.args[1], dtenv)
        preferred = any(kw.arg == "preferred_element_type"
                        for kw in node.keywords)
        fname = _dotted(node.func)
        if a and b and a != b:
            self._emit(
                "GL018", node.lineno, ("mismatch",),
                f"{fname}() operand dtypes differ ({a} vs {b}): the "
                f"contraction silently promotes off the MXU's native "
                f"pass — cast both operands to one matmul dtype")
        elif not preferred and ((a in _LOW_PRECISION) or
                                (b in _LOW_PRECISION)):
            self._emit(
                "GL018", node.lineno, ("accum",),
                f"{fname}() on {a or b} operands without "
                f"preferred_element_type: the accumulator stays "
                f"low-precision — pass preferred_element_type="
                f"jnp.float32 to accumulate in f32 on the MXU")

    def _expr_dtype(self, node: ast.AST, dtenv: dict) -> Optional[str]:
        if isinstance(node, ast.Name):
            return dtenv.get(node.id)
        if isinstance(node, ast.Subscript):
            return self._expr_dtype(node.value, dtenv)
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d in _DTYPE_NAMES:
                return _DTYPE_NAMES[d]
            if node.attr == "dtype":
                return self._expr_dtype(node.value, dtenv)
            return None
        if isinstance(node, ast.Call):
            fname = _dotted(node.func) or ""
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args:
                arg = node.args[0]
                d = _dotted(arg)
                if d in _DTYPE_NAMES:
                    return _DTYPE_NAMES[d]
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str):
                    return arg.value
                if isinstance(arg, ast.Attribute) and arg.attr == "dtype":
                    return self._expr_dtype(arg.value, dtenv)
                return None
            if fname in _DOT_NAMES:
                for kw in node.keywords:
                    if kw.arg == "preferred_element_type":
                        d = _dotted(kw.value)
                        return _DTYPE_NAMES.get(d or "", None)
                return None
            if fname in ("jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty"):
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        return _DTYPE_NAMES.get(_dotted(kw.value) or "")
                for arg in node.args[1:]:
                    d = _DTYPE_NAMES.get(_dotted(arg) or "")
                    if d:
                        return d
                return None
            if fname.rsplit(".", 1)[-1] == "where" and len(node.args) >= 3:
                a = self._expr_dtype(node.args[1], dtenv)
                b = self._expr_dtype(node.args[2], dtenv)
                return a if a == b else None
            return None
        if isinstance(node, ast.BinOp):
            a = self._expr_dtype(node.left, dtenv)
            b = self._expr_dtype(node.right, dtenv)
            if a and b:
                return a if a == b else None
            return a or b
        return None

    # -- literal fallback screen (retired GL006 heuristic) -----------------

    def _literal_screen(self) -> None:
        """The pre-engine literal heuristic, kept only for spec calls
        the evaluator could not resolve: off-tile literal dims and
        per-function literal VMEM totals (GL006)."""
        fn_totals: Dict[ast.FunctionDef, List[int]] = {}
        stack: List[ast.FunctionDef] = []

        def walk(node):
            is_fn = isinstance(node, ast.FunctionDef)
            if is_fn:
                stack.append(node)
            if isinstance(node, ast.Call):
                fname = _dotted(node.func) or ""
                if fname in _BLOCKSPEC_NAMES + _VMEM_SCRATCH_NAMES and \
                        node.args and node not in self._resolved_spec_nodes:
                    dims = _const_int_tuple(node.args[0])
                    if dims is not None:
                        kind = ("BlockSpec" if fname in _BLOCKSPEC_NAMES
                                else "VMEM scratch")
                        self._literal_spec(node, dims, kind)
                        if stack and all(d is not None for d in dims):
                            n = 1
                            for d in dims:
                                n *= d
                            fn_totals.setdefault(stack[-1], []).append(4 * n)
            for child in ast.iter_child_nodes(node):
                walk(child)
            if is_fn:
                stack.pop()

        walk(self.tree)
        for fn, sizes in fn_totals.items():
            total = sum(sizes)
            if total > _VMEM_BUDGET_BYTES:
                self._emit(
                    "GL006", fn.lineno, ("literal-vmem", fn.name),
                    f"{len(sizes)} literal BlockSpec/VMEM blocks in "
                    f"{fn.name}() total ~{total / 2**20:.1f} MiB, over "
                    f"the ~{_VMEM_BUDGET_BYTES // 2**20} MiB VMEM budget")

    def _literal_spec(self, node: ast.Call, dims: list, kind: str) -> None:
        last = dims[-1]
        if last is not None and last != 1 and last % LANE != 0:
            self._emit(
                "GL006", node.lineno, ("literal-lane",),
                f"{kind} trailing dim {last} is not a multiple of "
                f"{LANE} (TPU lane width): forces relayout")
        if len(dims) >= 2:
            sub = dims[-2]
            if sub is not None and sub != 1 and sub % 8 != 0:
                self._emit(
                    "GL006", node.lineno, ("literal-sublane",),
                    f"{kind} sublane dim {sub} is not a multiple of 8 "
                    f"(f32 tile; bf16 needs 16, int8 32): forces relayout")


def _const_int_tuple(node: ast.AST) -> Optional[List[Optional[int]]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: List[Optional[int]] = []
    for el in node.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, int):
            out.append(el.value)
        else:
            out.append(None)
    return out


def _corner_product(domains: Dict[str, tuple],
                    full_first: bool = False) -> List[dict]:
    """Bounded cartesian product over candidate domains: first/last of
    each tuple (the geometry corners) plus, when ``full_first``, the
    full first-choice binding."""
    if not domains:
        return [{}]
    corners = {k: tuple(dict.fromkeys((v[0], v[-1])))
               for k, v in domains.items() if v}
    keys = sorted(corners)
    out = []
    if full_first:
        out.append({k: domains[k][0] for k in keys})
    for combo in itertools.product(*[corners[k] for k in keys]):
        out.append(dict(zip(keys, combo)))
        if len(out) >= 64:
            break
    return [dict(t) for t in dict.fromkeys(
        tuple(sorted(c.items())) for c in out)]


# ---------------------------------------------------------------------------
# contract loading
# ---------------------------------------------------------------------------

_CONTRACTS_STATE = {"loaded": False}


def _module_contracts(module_name: Optional[str]):
    if module_name is None:
        return []
    from raft_tpu.analysis import contracts as _c

    if not _CONTRACTS_STATE["loaded"]:
        try:
            _c.load_all()
        except Exception:  # noqa: BLE001 - heavy deps missing: lint without contracts
            pass
        _CONTRACTS_STATE["loaded"] = True
    return _c.contracts_for_module(module_name)


# ---------------------------------------------------------------------------
# public API (mirrors analysis.lint / analysis.races)
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Set[str]] = None) -> List[Finding]:
    return FileKernelVerifier(path, source, rules).run()


def lint_file(path, rules: Optional[Set[str]] = None) -> List[Finding]:
    p = Path(path)
    try:
        source = p.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("GL000", str(p), 0, f"unreadable: {e}",
                        engine="kern")]
    try:
        return lint_source(source, str(p), rules)
    except SyntaxError as e:
        return [Finding("GL000", str(p), e.lineno or 0,
                        f"syntax error: {e.msg}", engine="kern")]


def lint_paths(paths: Sequence, rules: Optional[Set[str]] = None
               ) -> List[Finding]:
    """Kernel-verify files and directories (``**/*.py``, no __pycache__)."""
    findings: List[Finding] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files = sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        else:
            files = [p]
        for f in files:
            findings.extend(lint_file(f, rules))
    return findings
