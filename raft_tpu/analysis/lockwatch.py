"""graft-race engine 2 (dynamic): the in-test lock-order sanitizer.

The static half (:mod:`raft_tpu.analysis.races`) reads lock discipline
off the syntax; this half *observes* it. Under ``RAFT_TPU_THREADSAN=1``
the threaded tiers (serve engine, registry, mutation overlay, fabric
router, comms worker groups, core token table) construct their locks
through the factories here instead of ``threading`` directly, and every
acquisition is checked against two invariants while tier-1's
``serve``/``multihost`` suites run:

* **acquisition order** — each observed "acquired B while holding A"
  adds edge A→B to a process-global order graph (keyed by the lock's
  declared *name*, so every ``MutableState`` instance contributes to
  one ``serve.mutation`` node). An acquisition that would close a cycle
  raises :class:`LockOrderInversion` naming the full cycle path — the
  deterministic, single-run analog of a deadlock that needs an unlucky
  interleaving to actually wedge;
* **hold time** — a lock held longer than the budget
  (``RAFT_TPU_THREADSAN_BUDGET_MS``, default 30s) raises
  :class:`HoldBudgetExceeded` at release. The budget is a watchdog for
  the GL012 class at runtime: a device build/compile that creeps under
  a lock shows up as a breach long before it shows up as a production
  stall. The default is sized for CPU-host test compiles; deployments
  tighten it per-SLO.

On either failure the acquisition graph is pushed through the
graft-scope flight recorder (``lockwatch_failure`` event + an auto
``flight.dump`` in flight mode), so a wedged run leaves the order
evidence next to the error.

Off mode (the default) is free: the factories return plain
``threading`` primitives — no wrapper, no per-acquire bookkeeping.

Scope notes:

* graph nodes are lock *names*, not instances: two same-named locks
  (two servers' registries) merge — deliberately, since the hierarchy
  is a class-level contract. Reentrant re-acquisition of the *same
  instance* is never an edge.
* ``threading.Condition`` built over a sanitized lock keeps working:
  ``wait()`` releases through the wrapper (the held-set and hold timer
  stay honest across the park/wake cycle).
* obs/tuning/resilience internals keep plain locks on purpose — they
  are leaf-level, never nest into the serving hierarchy, and wrapping
  them would put the sanitizer inside its own failure-dump path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_VAR = "RAFT_TPU_THREADSAN"
BUDGET_ENV_VAR = "RAFT_TPU_THREADSAN_BUDGET_MS"
# generous by default: tier-1 CPU hosts pay first-call XLA compiles in
# paths that legitimately run under a lock at test scale (e.g. a fabric
# bootstrap awaiting worker prepare under the swap lock); the invariant
# being enforced is "no UNBOUNDED work under a lock", not a latency SLO
DEFAULT_BUDGET_MS = 30_000.0


class LockOrderInversion(RuntimeError):
    """An acquisition that closes a cycle in the observed lock-order
    graph. ``cycle`` carries the path (lock names, first and last equal)."""

    def __init__(self, msg: str, cycle: List[str]):
        super().__init__(msg)
        self.cycle = list(cycle)


class HoldBudgetExceeded(RuntimeError):
    """A lock held past the sanitizer's hold-time budget."""

    def __init__(self, msg: str, name: str, held_ms: float):
        super().__init__(msg)
        self.lock_name = name
        self.held_ms = held_ms


def enabled() -> bool:
    """True when the sanitizer is on (read at lock construction)."""
    return os.environ.get(ENV_VAR, "") not in ("", "0", "off", "false")


def budget_ms() -> float:
    raw = os.environ.get(BUDGET_ENV_VAR, "")
    if not raw:
        return DEFAULT_BUDGET_MS
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_BUDGET_MS


# ---------------------------------------------------------------------------
# sanitizer state
# ---------------------------------------------------------------------------

_tls = threading.local()                 # .held: List[_SanLockBase]
_state_lock = threading.Lock()
# name -> {successor name -> first-observed site string}
_order: Dict[str, Dict[str, str]] = {}
_counts = {"inversions": 0, "budget_breaches": 0, "acquires": 0}


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def order_graph() -> Dict[str, Dict[str, str]]:
    """A copy of the observed acquisition-order graph
    (``{holder: {acquired: first_seen_site}}``)."""
    with _state_lock:
        return {a: dict(bs) for a, bs in _order.items()}


def stats() -> dict:
    with _state_lock:
        return dict(_counts)


def reset() -> None:
    """Drop the observed graph and counters (tests)."""
    with _state_lock:
        _order.clear()
        for k in _counts:
            _counts[k] = 0


EXPORT_ENV_VAR = "RAFT_TPU_THREADSAN_EXPORT"


def export_graph(path: Optional[str] = None, merge: bool = True) -> str:
    """Write the observed acquisition graph as the JSON artifact
    ``graft-lint --engine=races --reconcile <path>`` consumes.

    ``path`` defaults to :data:`EXPORT_ENV_VAR`. With ``merge`` (the
    default) an existing artifact's edges are unioned in first, so a
    sharded test run — or several suites exporting at exit — ACCUMULATES
    coverage instead of each process clobbering the last; first-seen
    sites are kept for edges both halves observed. Returns the path."""
    import json

    target = path or os.environ.get(EXPORT_ENV_VAR, "")
    if not target:
        raise ValueError(
            f"export_graph needs a path (argument or {EXPORT_ENV_VAR})")
    graph = order_graph()
    if merge and os.path.exists(target):
        try:
            with open(target) as fh:
                prior = json.load(fh)
            prior_graph = prior.get("graph", prior) \
                if isinstance(prior, dict) else {}
            for a, succs in prior_graph.items():
                items = succs.items() if isinstance(succs, dict) \
                    else [(b, "") for b in succs]
                mine = graph.setdefault(a, {})
                for b, site in items:
                    mine.setdefault(b, site if isinstance(site, str)
                                    else "")
        except (OSError, ValueError):
            pass                 # unreadable prior artifact: overwrite
    with open(target, "w") as fh:
        json.dump({"graph": {a: dict(sorted(bs.items()))
                             for a, bs in sorted(graph.items())},
                   "stats": stats()}, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return target


def _export_at_exit() -> None:  # pragma: no cover - exercised via env
    try:
        export_graph()
    except Exception:  # noqa: BLE001 — exit-hook export is best-effort; a failed write must not mask the test result
        pass


if enabled() and os.environ.get(EXPORT_ENV_VAR, ""):
    import atexit

    atexit.register(_export_at_exit)


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """Shortest observed-order path src -> ... -> dst (BFS). Caller
    holds ``_state_lock``."""
    if src == dst:
        return [src]
    frontier: List[List[str]] = [[src]]
    seen = {src}
    while frontier:
        nxt: List[List[str]] = []
        for path in frontier:
            for succ in _order.get(path[-1], ()):
                if succ == dst:
                    return path + [succ]
                if succ not in seen:
                    seen.add(succ)
                    nxt.append(path + [succ])
        frontier = nxt
    return None


def _site() -> str:
    """The nearest caller frame outside the lock machinery."""
    import sys

    try:
        f = sys._getframe(1)
    except (AttributeError, ValueError):  # pragma: no cover - exotic runtime
        return "<unknown>"
    while f is not None and "lockwatch" in (f.f_code.co_filename or ""):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}"


def _dump_failure(kind: str, detail: dict) -> None:
    """Push the acquisition graph through graft-scope: a breadcrumb
    event always, a full flight dump once in flight mode. Never raises
    — the sanitizer's own failure is the payload, not the plumbing."""
    try:
        from raft_tpu import obs
        from raft_tpu.obs import config as _obs_config

        obs.counter("lockwatch.failures", kind=kind)
        # field name `failure`, not `kind`: flight.record's own first
        # parameter is `kind` and a kwarg collision would TypeError
        obs.event("lockwatch_failure", failure=kind,
                  order_graph={a: sorted(bs) for a, bs in
                               order_graph().items()},
                  **detail)
        if _obs_config.FLIGHT:
            from raft_tpu.obs import flight

            flight.dump(reason=f"lockwatch:{kind}")
    except Exception:  # noqa: BLE001 — failure reporting is best-effort; the sanitizer exception itself is the signal
        pass


def _record_acquired(lock: "_SanLockBase") -> None:
    """Post-acquire bookkeeping: order edges from every held lock to
    this one, cycle check, held-set push. On inversion the fresh
    acquisition is RELEASED before raising so the failing thread does
    not wedge everyone else on its way out."""
    held = _held()
    site = ""                # resolved lazily: frame walking on every
    #                          hot-path acquire is measurable overhead,
    #                          and it only matters for NEW edges/failures
    cycle: Optional[List[str]] = None       # closed path, first == last
    offender: Optional[str] = None
    with _state_lock:
        _counts["acquires"] += 1
        for h in held:
            if h.name == lock.name and h is not lock:
                # two distinct same-named locks nested: with no
                # intra-class tiebreak (e.g. by id) this is AB/BA-prone
                cycle = [lock.name, lock.name]
                offender = h.name
                break
            succ = _order.setdefault(h.name, {})
            if lock.name not in succ:
                back = _find_path(lock.name, h.name)
                if back is not None:
                    # acquire(X) while holding Y, with X -> ... -> Y
                    # already observed: the closing edge Y -> X is this
                    # very acquisition
                    cycle = back + [lock.name]
                    offender = h.name
                    break
                if not site:
                    site = _site()
                succ[lock.name] = site
        if cycle is None:
            held.append(lock)
            lock._held_list = held
            return
        _counts["inversions"] += 1
        edges = {a: dict(bs) for a, bs in _order.items()}
    # failure path: undo the acquisition, report, raise
    lock._inner_release_all()
    if not site:
        site = _site()
    first_seen = [
        f"{a} -> {b} (first seen {edges[a][b]})"
        for a, b in zip(cycle, cycle[1:])
        if b in edges.get(a, {})
    ]
    path = " -> ".join(cycle)
    msg = (f"lock order inversion: acquiring {lock.name!r} while holding "
           f"{offender!r} at {site}, but the opposite order is already "
           f"established; cycle: {path}"
           + ("".join("\n  " + s for s in first_seen) if first_seen else ""))
    _dump_failure("inversion", {
        "cycle": path, "acquiring": lock.name, "holding": offender,
        "site": site,
    })
    raise LockOrderInversion(msg, cycle)


def _pop_held(lock: "_SanLockBase") -> None:
    """Drop the held-set entry. MUST run while the inner primitive is
    still owned: releasing first opens a window where the next owner's
    fresh entry (``_held_list`` reassigned by its ``_record_acquired``)
    is the one this thread deletes — silently blinding the sanitizer
    for that whole hold. Popping from the list captured at acquire,
    under the state lock, also makes cross-thread releases safe."""
    with _state_lock:
        lst = lock._held_list
        lock._held_list = None
        if lst is not None and lock in lst:
            lst.remove(lock)


def _check_budget(lock: "_SanLockBase", t0: float) -> None:
    """Hold-budget check; runs AFTER the inner release so the raise
    leaves the lock free."""
    held_ms = (time.perf_counter() - t0) * 1e3
    limit = budget_ms()
    if held_ms <= limit:
        return
    with _state_lock:
        _counts["budget_breaches"] += 1
    site = _site()
    _dump_failure("hold_budget", {
        "lock": lock.name, "held_ms": round(held_ms, 3),
        "budget_ms": limit, "site": site,
    })
    raise HoldBudgetExceeded(
        f"lock {lock.name!r} held for {held_ms:.1f} ms, over the "
        f"{limit:.0f} ms sanitizer budget ({BUDGET_ENV_VAR}); released at "
        f"{site} — move the blocking work outside the critical section",
        lock.name, held_ms)


class _SanLockBase:
    """Shared acquire/release instrumentation over an inner primitive."""

    __slots__ = ("name", "_inner", "_t0", "_held_list")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner
        self._t0 = 0.0          # owner-written only (under the lock)
        # the acquiring thread's held list: release() removes from THIS
        # list (under _state_lock) so a cross-thread release — legal
        # for a plain Lock — cannot leave a phantom hold on the
        # acquirer's stack
        self._held_list = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._t0 = time.perf_counter()
            _record_acquired(self)
        return ok

    def release(self) -> None:
        t0 = self._t0
        _pop_held(self)          # before the inner release: we still
        #                          own it, so no successful acquirer
        #                          can be racing the bookkeeping
        self._inner.release()
        _check_budget(self, t0)

    def locked(self) -> bool:
        return self._inner.locked()

    def _inner_release_all(self) -> None:
        """Failure-path unwind of the acquisition that just succeeded."""
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class SanLock(_SanLockBase):
    """Sanitized ``threading.Lock``."""

    __slots__ = ()

    def __init__(self, name: str):
        super().__init__(name, threading.Lock())


class SanRLock(_SanLockBase):
    """Sanitized ``threading.RLock``: recursive re-acquisition by the
    owner is tracked (never an order edge) and the hold timer spans the
    OUTERMOST acquire/release pair. Implements the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` trio so ``threading.Condition``
    can park on it."""

    __slots__ = ("_depth_tls",)

    def __init__(self, name: str):
        super().__init__(name, threading.RLock())
        self._depth_tls = threading.local()

    def _depth(self) -> int:
        return getattr(self._depth_tls, "n", 0)

    def _set_depth(self, n: int) -> None:
        self._depth_tls.n = n

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return False
        depth = self._depth() + 1
        self._set_depth(depth)
        if depth == 1:
            self._t0 = time.perf_counter()
            _record_acquired(self)
        return True

    def release(self) -> None:
        depth = self._depth() - 1
        self._set_depth(depth)
        t0 = self._t0
        if depth == 0:
            _pop_held(self)      # while still owned — see _pop_held
        self._inner.release()
        if depth == 0:
            _check_budget(self, t0)

    def _inner_release_all(self) -> None:
        self._set_depth(self._depth() - 1)
        self._inner.release()

    # -- Condition integration ---------------------------------------------

    def _release_save(self):
        depth = self._depth()
        t0 = self._t0           # read while still owned: after the full
        #                         release another thread may overwrite it
        self._set_depth(0)
        _pop_held(self)          # while still owned — see _pop_held
        saved = self._inner._release_save()
        _check_budget(self, t0)
        return (saved, depth)

    def _acquire_restore(self, state) -> None:
        saved, depth = state
        self._inner._acquire_restore(saved)
        self._set_depth(depth)
        self._t0 = time.perf_counter()
        _record_acquired(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


# ---------------------------------------------------------------------------
# the factories the threaded tiers construct through
# ---------------------------------------------------------------------------


def make_lock(name: str):
    """A ``threading.Lock`` — sanitized under ``RAFT_TPU_THREADSAN=1``.

    ``name`` is the lock's node in the order graph and in the
    documented hierarchy (docs/serving.md): every instance of a class
    shares one name."""
    return SanLock(name) if enabled() else threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — sanitized under ``RAFT_TPU_THREADSAN=1``."""
    return SanRLock(name) if enabled() else threading.RLock()


def make_condition(lock=None, name: str = "condition"):
    """A ``threading.Condition`` over ``lock`` (or a fresh
    :func:`make_lock`); waits release/reacquire through the wrapper, so
    the held-set stays honest across the park."""
    return threading.Condition(lock if lock is not None
                               else make_lock(name))


def make_flag_lock(name: str):
    """A single-flight handoff FLAG: acquired with a non-blocking
    try-acquire by one thread and released by another when the
    background work completes (the serve engine's ``compacting``
    guard). Deliberately a plain ``threading.Lock`` even under the
    sanitizer: a lock that is only ever try-acquired cannot contribute
    to a deadlock cycle (nobody blocks on it), its hold legitimately
    spans minutes of background build, and its cross-thread handoff
    would otherwise read as a phantom hold on the acquirer's stack."""
    del name
    return threading.Lock()
