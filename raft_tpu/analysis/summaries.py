"""Per-function lock summaries over the project call graph (ISSUE 17).

For every function the :mod:`callgraph` model knows, this module
computes a **lock summary**:

* ``direct``   — lock nodes the function acquires lexically (``with``
  blocks, plus manual ``acquire()`` whose receiver resolves — a manual
  acquire opens a held region until its same-function ``release()``);
* ``may_acquire`` — the transitive closure over resolvable callees,
  propagated to fixpoint (mutual recursion converges, it never spins);
* ``holds_on_entry`` — the ``*_locked`` suffix contract: the caller
  holds the class lock, so the function's own acquisitions are edges
  from the *call site's* held set, which the interprocedural expansion
  attributes caller-side;
* the **held set at every call site**, which is where the
  whole-program edges come from.

The resulting project acquisition graph uses exactly the runtime
sanitizer's semantics (:mod:`raft_tpu.analysis.lockwatch`): an
acquisition adds an edge from EVERY currently-held lock, nodes are lock
*names* (``serve.mutation``, not instances), conditions alias to the
lock they wrap, and flag locks (try-acquire handoffs) are not nodes at
all. That shared vocabulary is what makes static↔dynamic
**reconciliation** a set diff: a runtime-observed edge absent here is a
soundness gap (GL022), a static edge never exercised under threadsan
is sanitizer-coverage debt (GL021, report-only).

Known blind spots (the reconciliation pass is the audit for all of
them): nested closures are not separate summary nodes (their lexical
acquisitions are invisible unless the enclosing function holds the
region), a manual acquire held ACROSS a return (ownership transfer)
stops contributing once the function exits, and unannotated generics
do not resolve.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from raft_tpu.analysis.callgraph import CallGraph, FuncDecl, build_project


@dataclasses.dataclass(frozen=True)
class LockEdge:
    """One acquisition-order edge ``a -> b`` with its first-seen site."""

    a: str
    b: str
    path: str
    line: int
    via: str


class LockSummaries:
    """Whole-program lock summaries + the project acquisition graph."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.direct: Dict[FuncDecl, Set[str]] = {}
        self.may_acquire: Dict[FuncDecl, Set[str]] = {}
        self.holds_on_entry: Dict[FuncDecl, bool] = {}
        # fn -> [(callee candidates, held lock names, line)]
        self._call_sites: Dict[FuncDecl, List[
            Tuple[List[FuncDecl], Tuple[str, ...], int]]] = {}
        self._edges: Dict[Tuple[str, str], LockEdge] = {}
        # lock name -> first construction/acquisition site (GL021 anchor)
        self.acquire_sites: Dict[str, Tuple[str, int]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, graph: CallGraph) -> "LockSummaries":
        s = cls(graph)
        for fn in s._all_fns():
            s.direct[fn] = set()
            s._call_sites[fn] = []
            s.holds_on_entry[fn] = fn.name.endswith("_locked")
            s._walk_fn(fn)
        s._fixpoint()
        s._expand_call_edges()
        return s

    def _all_fns(self) -> List[FuncDecl]:
        out: List[FuncDecl] = []
        for mod in self.graph.modules.values():
            out.extend(mod.functions.values())
            for cd in mod.classes.values():
                out.extend(cd.methods.values())
        return out

    # -- per-function walk -------------------------------------------------

    def _acquired(self, fn: FuncDecl, name: str, line: int,
                  held: Sequence[str]) -> None:
        self.direct[fn].add(name)
        self.acquire_sites.setdefault(name, (fn.module.path, line))
        if name in held:
            # reentrant by name: the sanitizer records NO edges for a
            # re-acquisition of a held lock (RLock depth > 1 never
            # reaches _record_acquired) — mirroring that here keeps the
            # static graph diffable against the runtime one
            return
        for h in held:
            if h != name:
                self._edges.setdefault(
                    (h, name),
                    LockEdge(h, name, fn.module.path, line,
                             "nested acquisition"))

    @staticmethod
    def _nonblocking(call: ast.Call) -> bool:
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return True
        return any(kw.arg == "blocking" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is False for kw in call.keywords)

    def _walk_fn(self, fn: FuncDecl) -> None:
        g = self.graph
        held: List[str] = []
        manual: List[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    decl = g.lock_node(item.context_expr, fn)
                    if decl is not None and decl.kind != "flag":
                        self._acquired(fn, decl.name, node.lineno, held)
                        held.append(decl.name)
                        pushed += 1
                for child in node.body:
                    visit(child)
                for _ in range(pushed):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    decl = g.lock_node(f.value, fn)
                    if decl is not None and decl.kind != "flag" and \
                            not self._nonblocking(node) and \
                            decl.name not in held:
                        self._acquired(fn, decl.name, node.lineno, held)
                        held.append(decl.name)
                        manual.append(decl.name)
                elif isinstance(f, ast.Attribute) and f.attr == "release":
                    decl = g.lock_node(f.value, fn)
                    if decl is not None and decl.name in manual:
                        manual.remove(decl.name)
                        if decl.name in held:
                            held.remove(decl.name)
                else:
                    callees = g.resolve_call(node, fn)
                    if callees:
                        self._call_sites[fn].append(
                            (callees, tuple(held), node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child)

        body = fn.node.body if not isinstance(fn.node, ast.Lambda) \
            else [fn.node.body]
        for child in body:
            visit(child)

    # -- interprocedural closure -------------------------------------------

    def _fixpoint(self) -> None:
        for fn in self.direct:
            self.may_acquire[fn] = set(self.direct[fn])
        changed = True
        while changed:
            changed = False
            for fn, sites in self._call_sites.items():
                acc = self.may_acquire[fn]
                for callees, _held, _line in sites:
                    for c in callees:
                        extra = self.may_acquire.get(c, set())
                        if not extra <= acc:
                            acc |= extra
                            changed = True

    def _expand_call_edges(self) -> None:
        for fn, sites in self._call_sites.items():
            for callees, held, line in sites:
                if not held:
                    continue
                for c in callees:
                    for m in self.may_acquire.get(c, ()):
                        if m in held:
                            continue       # reentrant — see _acquired
                        for h in held:
                            if h != m:
                                self._edges.setdefault(
                                    (h, m),
                                    LockEdge(h, m, fn.module.path, line,
                                             f"call to {c.name}()"))

    # -- results -----------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], LockEdge]:
        """The project acquisition graph (lockwatch semantics)."""
        return dict(self._edges)

    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self._edges)

    def cycles(self) -> List[List[str]]:
        """Every distinct lock-order cycle in the project graph, as
        closed paths (first == last), deduped by node set."""
        graph: Dict[str, List[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, []).append(b)
        for succs in graph.values():
            succs.sort()
        out: List[List[str]] = []
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            path: List[str] = []

            def dfs(n: str) -> Optional[List[str]]:
                if n in path:
                    return path[path.index(n):] + [n]
                if n not in graph:
                    return None
                path.append(n)
                for succ in graph[n]:
                    cyc = dfs(succ)
                    if cyc is not None:
                        return cyc
                path.pop()
                return None

            cyc = dfs(start)
            if cyc is not None and frozenset(cyc) not in reported:
                reported.add(frozenset(cyc))
                out.append(cyc)
        return out

    # -- static <-> dynamic reconciliation ---------------------------------

    def reconcile(self, runtime_graph: Dict[str, dict]
                  ) -> Tuple[List[Tuple[str, str, str]],
                             List[LockEdge]]:
        """Diff the runtime acquisition graph against the static model.

        ``runtime_graph`` is ``lockwatch.order_graph()`` shaped —
        ``{holder: {acquired: first_seen_site}}`` (a plain list of
        successors is accepted too). Returns ``(missing, untested)``:

        * ``missing`` — runtime edges absent from the static model,
          each ``(a, b, site)``: the sanitizer OBSERVED an order the
          model cannot see — a soundness gap in the static analysis
          (or an unmodeled dynamic dispatch); hard finding;
        * ``untested`` — static edges never exercised under threadsan:
          hierarchy claims with no runtime witness (coverage debt,
          report-only)."""
        static = self.edge_set()
        missing: List[Tuple[str, str, str]] = []
        runtime: Set[Tuple[str, str]] = set()
        for a, succs in sorted(runtime_graph.items()):
            items = succs.items() if isinstance(succs, dict) \
                else [(b, "") for b in succs]
            for b, site in sorted(items):
                runtime.add((a, b))
                if (a, b) not in static:
                    missing.append((a, b, site if isinstance(site, str)
                                    else ""))
        untested = [e for (a, b), e in sorted(self._edges.items())
                    if (a, b) not in runtime]
        return missing, untested

    # -- hierarchy rendering (docs/serving.md §11) -------------------------

    def render_hierarchy(self) -> str:
        """The documented lock hierarchy, generated from the static
        graph: every order edge with its first-seen site, grouped by
        holder, plus the leaf locks (never held across another
        acquisition). Deterministic output — docs and the drift test
        compare it verbatim."""
        by_holder: Dict[str, List[LockEdge]] = {}
        for e in self._edges.values():
            by_holder.setdefault(e.a, []).append(e)
        nodes: Set[str] = set()
        for (a, b) in self._edges:
            nodes.add(a)
            nodes.add(b)
        lines: List[str] = []
        for a in sorted(by_holder):
            lines.append(f"- `{a}` precedes:")
            for e in sorted(by_holder[a], key=lambda e: e.b):
                site = f"{_short(e.path)}:{e.line}"
                lines.append(f"  - `{e.b}` ({e.via} at {site})")
        leaves = sorted(n for n in nodes if n not in by_holder)
        if leaves:
            lines.append("- leaf locks (never held across another "
                         "acquisition): " +
                         ", ".join(f"`{n}`" for n in leaves))
        return "\n".join(lines)


def _short(path: str) -> str:
    """Repo-relative spelling of a module path when possible."""
    for marker in ("raft_tpu/", "raft_tpu\\"):
        i = path.find(marker)
        if i >= 0:
            return path[i:].replace("\\", "/")
    return path


def build_summaries(paths: Sequence) -> LockSummaries:
    """Convenience: project model + summaries in one call."""
    return LockSummaries.build(build_project(paths))
