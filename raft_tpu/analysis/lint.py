"""graft-lint engine 1: AST lint over package source.

Static screens for the TPU hazard classes in :mod:`raft_tpu.analysis.rules`.
Everything here is a *heuristic over syntax* — the precise, shape-aware
version of GL003/GL004 lives in :mod:`raft_tpu.analysis.jaxpr_audit`,
which walks real jaxprs, and Pallas kernel geometry (GL006,
GL015-GL018) lives in :mod:`raft_tpu.analysis.kernels`, which
abstractly evaluates it. The engines overlap on purpose: the AST pass
sees code the tracer never reaches (error branches, dead configs), the
jaxpr pass sees through aliasing the AST cannot.

Traced-scope detection: a function is considered traced when it is
decorated with ``jax.jit`` (directly or via ``functools.partial``), is
passed callable-first to ``pl.pallas_call`` / ``lax.scan`` /
``lax.while_loop`` / ``lax.cond`` / ``lax.switch`` / ``lax.fori_loop`` /
``jax.vmap`` / ``jax.jit``, or is lexically nested inside a traced
function. ``static_argnums`` named in the jit decorator demote those
positional params from the traced-param set.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import (
    Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple,
)

from raft_tpu.analysis.rules import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)

# Module aliases treated as "device" roots: an expression mentioning one
# of these produces/consumes device arrays.
_DEVICE_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu"}
_NUMPY_ROOTS = {"np", "numpy"}

# callables whose callable-argument(s) run under trace
_TRACING_CALLERS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jit": (0,),
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "lax.switch": (1,),
    "jax.lax.associative_scan": (0,),
    "lax.associative_scan": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "pl.pallas_call": (0,),
    "pallas_call": (0,),
}

_ORDERING_CALLS = {
    "jnp.sort", "jnp.argsort", "jnp.lexsort", "jnp.argmin", "jnp.argmax",
    "jnp.searchsorted",
    "jax.lax.top_k", "lax.top_k", "jax.lax.sort", "lax.sort",
    "jax.lax.approx_min_k", "lax.approx_min_k",
    "jax.lax.approx_max_k", "lax.approx_max_k",
}
# local helpers that select/order — matched on the trailing name so both
# `select_k(...)` and `matrix.select_k(...)` hit
_ORDERING_SUFFIXES = ("select_k", "merge_topk", "top_k", "knn_merge_parts")

_NARROW_FLOAT_ATTRS = {
    "jnp.float32", "np.float32", "numpy.float32",
    "jnp.bfloat16", "jnp.float16", "np.float16",
}
_NARROW_FLOAT_STRINGS = {"float32", "bfloat16", "float16", "f32", "bf16"}

_F64_ATTRS = {"jnp.float64", "np.float64", "numpy.float64",
              "jnp.double", "np.double", "numpy.double"}

_INT_PRODUCERS = {
    "jnp.arange", "jnp.argsort", "jnp.argmin", "jnp.argmax", "jnp.bincount",
    "jnp.searchsorted", "jnp.nonzero", "jnp.flatnonzero",
    "jax.lax.iota", "lax.iota", "jax.lax.broadcasted_iota",
}
_INT_DTYPE_ATTRS = {"jnp.int32", "jnp.int64", "np.int32", "np.int64",
                    "jnp.uint32", "jnp.uint64", "np.uint32", "np.uint64"}
# names that *smell* like >= 32-bit integer payloads (ids/positions)
_INT_NAME_RE = re.compile(
    r"(^|_)(idx|idxs|ids?|indices|index|labels?|perm|order|ranks?|offsets?|"
    r"rows?|cols?|positions?|sizes?|counts?)(_|$)", re.IGNORECASE,
)

# GL005 ---------------------------------------------------------------------

_PERF_CLAIM_RE = re.compile(
    r"""
    (?: \d[\d.,]*\s*k?\s*QPS )                                  # 14.7k QPS
  | (?: \d[\d.,]*\s*[x×]\s*(?:QPS|recall) )                     # 1.2x QPS
  | (?: ~?\s*\d[\d.]*\s*[x×]\s*(?:faster|slower|speedup|
        throughput|the\ bandwidth) )                            # ~7x faster
  | (?: ~?\s*\d[\d.]*\s*[x×]-?(?:wider|narrower|bigger|larger|
        smaller)\b [^.]{0,80} \b(?:cost|cheap|free|fast|slow|
        wall-?clock|latency|same)\b )     # "the 2x-wider matmul can
                                          # cost the same wall-clock"
                                          # (the PR-5/6 serving class;
                                          # [^.] spans the line wrap)
  | (?: \d[\d.,]*\s*[GMT]B/s )                                  # 123 GB/s
  | (?: \d[\d.,]*\s*[GT]FLOP )                                  # 9 GFLOP/s
  | (?: [+\-]\d[\d.]*\s*%\s*(?:QPS|recall|throughput|latency) ) # +20% QPS
    """,
    re.VERBOSE | re.IGNORECASE,
)
_DATED_RE = re.compile(
    r"""
    \br[1-9]\d?\b                     # round marker: r2, r5 ...
  | \bround\s+[1-9]\d?\b              # spelled-out round marker
  | \b(?:BENCH|SWEEP|LATENCY|DEEP100M|MULTICHIP|SHARDED|
       PALLAS_PARITY|SELECT_CROSSOVER)_r?\d* \b                 # artifacts
  | \b20\d\d\b                        # a year
  | \b[\w/]+\.json\b                  # an artifact file
    """,
    re.VERBOSE,
)

# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """'jnp.asarray' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _contains_device_expr(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            root = sub
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _DEVICE_ROOTS:
                return True
    return False


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# -- GL023 catalog loading --------------------------------------------------

# a metric name as the catalog (and obs.metrics) spells it
_METRIC_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_.]*")
# resolved doc path -> (mtime_ns, documented-name set); mtime-keyed so a
# test that rewrites a planted catalog sees the rewrite
_METRIC_CATALOG_CACHE: Dict[str, Tuple[int, FrozenSet[str]]] = {}


def documented_metric_names(doc_text: str) -> FrozenSet[str]:
    """Every metric name docs/observability.md catalogs: a backticked
    token shaped like a metric name, with any example-label suffix
    (``serve.batches_total{bucket}``) stripped. Table rows and prose
    rows both count — the contract is the name being findable, not the
    markdown construct holding it."""
    names: Set[str] = set()
    for tok in re.findall(r"`([^`\n]+)`", doc_text):
        tok = tok.split("{", 1)[0].strip()
        if _METRIC_NAME_RE.fullmatch(tok):
            names.add(tok)
    return frozenset(names)


def _metric_catalog_for(path) -> Optional[FrozenSet[str]]:
    """Walk up from the linted file for a ``docs/observability.md``;
    None when no ancestor has one (nothing to check against)."""
    try:
        cur = Path(path).resolve().parent
    except OSError:
        return None
    for d in (cur, *cur.parents):
        doc = d / "docs" / "observability.md"
        try:
            if not doc.is_file():
                continue
            mtime = doc.stat().st_mtime_ns
            cached = _METRIC_CATALOG_CACHE.get(str(doc))
            if cached is not None and cached[0] == mtime:
                return cached[1]
            names = documented_metric_names(doc.read_text())
        except (OSError, UnicodeDecodeError):
            return None
        _METRIC_CATALOG_CACHE[str(doc)] = (mtime, names)
        return names
    return None


@dataclasses.dataclass
class _FnInfo:
    node: ast.AST                       # FunctionDef / Lambda
    traced: bool = False
    traced_params: Set[str] = dataclasses.field(default_factory=set)


# ---------------------------------------------------------------------------
# the linter
# ---------------------------------------------------------------------------


class FileLinter:
    def __init__(self, path: str, source: str, rules: Optional[Set[str]] = None):
        self.path = path
        self.source = source
        self.rules = rules          # None = all
        self.findings: List[Finding] = []
        self.tree = ast.parse(source, filename=path)
        self._fn_infos: Dict[ast.AST, _FnInfo] = {}
        self._fn_stack: List[_FnInfo] = []

    # -- plumbing ----------------------------------------------------------

    def _emit(self, rule: str, node_or_line, message: str) -> None:
        if self.rules is not None and rule not in self.rules:
            return
        line = node_or_line if isinstance(node_or_line, int) else node_or_line.lineno
        self.findings.append(Finding(rule, self.path, line, message))

    def run(self) -> List[Finding]:
        self._mark_traced_functions()
        self._lint_tree()
        self._lint_comments_and_docstrings()
        self._check_unspanned_entries()
        self._check_untraced_rpc()
        self._check_undocumented_metric()
        self._check_hand_wired_pipeline()
        # nested defs are revisited by the per-function GL003 pass; dedupe
        seen: Set[Tuple[str, int, str]] = set()
        unique: List[Finding] = []
        for f in self.findings:
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        sup = scan_suppressions(self.source)
        return apply_suppressions(self.findings, sup, self.path)

    # -- traced-scope discovery -------------------------------------------

    def _decorator_static_argnums(self, deco: ast.AST) -> Tuple[bool, Set[int], Set[str]]:
        """(is_jit, static positions, static names) for one decorator."""
        name = _dotted(deco)
        if name in ("jax.jit", "jit"):
            return True, set(), set()
        if isinstance(deco, ast.Call):
            fname = _dotted(deco.func)
            if fname in ("jax.jit", "jit"):
                call = deco
            elif fname in ("functools.partial", "partial") and deco.args and \
                    _dotted(deco.args[0]) in ("jax.jit", "jit"):
                call = deco
            else:
                return False, set(), set()
            nums: Set[int] = set()
            names: Set[str] = set()
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) and isinstance(el.value, int):
                            nums.add(el.value)
                elif kw.arg == "static_argnames":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            names.add(el.value)
            return True, nums, names
        return False, set(), set()

    def _mark_traced_functions(self) -> None:
        defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                info = _FnInfo(node)
                self._fn_infos[node] = info
                if not isinstance(node, ast.Lambda):
                    defs_by_name.setdefault(node.name, []).append(node)

        # 1) jit decorators
        for node, info in self._fn_infos.items():
            if isinstance(node, ast.Lambda):
                continue
            for deco in node.decorator_list:
                is_jit, nums, names = self._decorator_static_argnums(deco)
                if is_jit:
                    info.traced = True
                    info.traced_params = self._param_names(node, nums, names)

        # 2) callables handed to tracing callers (by name or inline lambda)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            positions = _TRACING_CALLERS.get(fname or "")
            if not positions:
                continue
            for pos in positions:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                targets: List[ast.AST] = []
                if isinstance(arg, ast.Name):
                    targets = defs_by_name.get(arg.id, [])
                elif isinstance(arg, ast.Lambda):
                    targets = [arg]
                elif isinstance(arg, (ast.List, ast.Tuple)):    # lax.switch
                    for el in arg.elts:
                        if isinstance(el, ast.Name):
                            targets += defs_by_name.get(el.id, [])
                        elif isinstance(el, ast.Lambda):
                            targets.append(el)
                for t in targets:
                    info = self._fn_infos[t]
                    info.traced = True
                    if not info.traced_params:
                        info.traced_params = self._param_names(t, set(), set())

        # 3) lexical nesting: children of traced functions are traced
        def propagate(node: ast.AST, inherited: bool) -> None:
            info = self._fn_infos.get(node)
            here = inherited
            if info is not None:
                info.traced = info.traced or inherited
                here = info.traced
                if info.traced and not info.traced_params:
                    info.traced_params = self._param_names(node, set(), set())
            for child in ast.iter_child_nodes(node):
                propagate(child, here)

        propagate(self.tree, False)

    @staticmethod
    def _param_names(node: ast.AST, static_nums: Set[int], static_names: Set[str]) -> Set[str]:
        args = node.args
        out: Set[str] = set()
        for i, a in enumerate(args.posonlyargs + args.args):
            if i in static_nums or a.arg in static_names:
                continue
            out.add(a.arg)
        for a in args.kwonlyargs:
            if a.arg not in static_names:
                out.add(a.arg)
        out.discard("self")
        return out

    def _in_traced_scope(self) -> bool:
        return any(f.traced for f in self._fn_stack)

    def _traced_params(self) -> Set[str]:
        for f in reversed(self._fn_stack):
            if f.traced:
                return f.traced_params
        return set()

    # -- main walk ---------------------------------------------------------

    def _lint_tree(self) -> None:
        self._walk(self.tree)

    def _walk(self, node: ast.AST) -> None:
        info = self._fn_infos.get(node)
        if info is not None:
            self._fn_stack.append(info)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_gl003_function(node)
        try:
            self._visit(node)
            for child in ast.iter_child_nodes(node):
                self._walk(child)
        finally:
            if info is not None:
                self._fn_stack.pop()

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._check_host_sync_call(node)
            self._check_f64_call(node)
        elif isinstance(node, ast.Attribute):
            self._check_f64_attr(node)
        elif isinstance(node, (ast.If, ast.While)):
            self._check_tracer_branch(node.test, kind="branch")
        elif isinstance(node, ast.For):
            self._check_tracer_branch(node.iter, kind="iteration")
        elif isinstance(node, ast.Try):
            self._check_unclassified_swallow(node)

    # -- GL001 host-sync ---------------------------------------------------

    def _check_host_sync_call(self, node: ast.Call) -> None:
        fname = _dotted(node.func)
        in_traced = self._in_traced_scope()

        # .item() / .tolist() force a device->host transfer wherever they run
        if isinstance(node.func, ast.Attribute) and node.func.attr in ("item", "tolist") \
                and not node.args and not node.keywords:
            where = "inside traced scope" if in_traced else "on a device value"
            self._emit("GL001", node,
                       f".{node.func.attr}() {where}: device->host sync; hoist "
                       "to host-side setup or batch it out of the hot path")
            return

        if fname in ("np.asarray", "np.array", "numpy.asarray", "numpy.array",
                     "np.copy", "numpy.copy"):
            if in_traced:
                self._emit("GL001", node,
                           f"{fname}() inside traced scope materialises the "
                           "tracer on host (breaks tracing or constant-folds)")
            elif node.args and _contains_device_expr(node.args[0]):
                self._emit("GL001", node,
                           f"{fname}() of a jax expression blocks on "
                           "device->host transfer")
            return

        if fname in ("float", "int", "bool") and node.args:
            arg = node.args[0]
            if _contains_device_expr(arg):
                self._emit("GL001", node,
                           f"{fname}() of a jax expression forces a blocking "
                           "device->host sync")
            elif in_traced and isinstance(arg, ast.Name) and \
                    arg.id in self._traced_params():
                self._emit("GL001", node,
                           f"{fname}({arg.id}) on a traced parameter inside "
                           "jit scope: concretisation error or silent "
                           "trace-time constant")

    # -- GL002 tracer control flow ----------------------------------------

    _METADATA_ATTRS = {"dtype", "shape", "ndim", "size", "itemsize", "aval"}
    _METADATA_CALLS = {
        "jnp.issubdtype", "jnp.result_type", "jnp.promote_types",
        "jnp.dtype", "jnp.finfo", "jnp.iinfo", "jnp.isdtype", "jnp.ndim",
        "jnp.shape", "len", "isinstance", "getattr", "hasattr",
    }

    def _is_none_checked_names(self, test: ast.AST) -> Set[str]:
        """Names only compared against None (`x is None` is a static
        structural check, not a value branch)."""
        out: Set[str] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops):
                operands = [sub.left] + list(sub.comparators)
                if any(isinstance(o, ast.Constant) and o.value is None
                       for o in operands):
                    for o in operands:
                        out |= _names_in(o)
        return out

    def _check_tracer_branch(self, test: ast.AST, kind: str) -> None:
        if not self._in_traced_scope():
            return
        # branches on trace-time metadata (dtype/shape/ndim) are static
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr in self._METADATA_ATTRS:
                return
            if isinstance(sub, ast.Call) and \
                    (_dotted(sub.func) or "") in self._METADATA_CALLS:
                return
        device_call = any(
            isinstance(sub, ast.Call) and _contains_device_expr(sub.func)
            for sub in ast.walk(test)
        )
        if device_call:
            self._emit("GL002", test,
                       f"Python {kind} on a jax expression inside traced "
                       "scope; use lax.cond/lax.while_loop/jnp.where")
            return
        hits = (_names_in(test) & self._traced_params()) \
            - self._is_none_checked_names(test)
        if hits:
            self._emit("GL002", test,
                       f"Python {kind} on traced parameter(s) "
                       f"{sorted(hits)} inside traced scope; use "
                       "lax.cond/lax.select or mark the arg static")

    # -- GL003 int->float ordering ----------------------------------------

    def _is_narrow_float_cast(self, node: ast.Call) -> Optional[ast.AST]:
        """The value being cast when `node` narrows to f32/bf16/f16."""
        fname = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
                and node.args:
            dt = node.args[0]
            if _dotted(dt) in _NARROW_FLOAT_ATTRS or (
                    isinstance(dt, ast.Constant) and dt.value in _NARROW_FLOAT_STRINGS):
                return node.func.value
        if fname in _NARROW_FLOAT_ATTRS and node.args:
            return node.args[0]
        if fname in ("jnp.asarray", "jnp.array") and len(node.args) >= 2:
            dt = node.args[1]
            if _dotted(dt) in _NARROW_FLOAT_ATTRS or (
                    isinstance(dt, ast.Constant) and dt.value in _NARROW_FLOAT_STRINGS):
                return node.args[0]
        return None

    def _int_hinted(self, node: ast.AST, int_vars: Set[str]) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                    sub.id in int_vars or _INT_NAME_RE.search(sub.id)):
                return True
            if isinstance(sub, ast.Call):
                fn = _dotted(sub.func)
                if fn in _INT_PRODUCERS:
                    return True
                if isinstance(sub.func, ast.Attribute) and sub.func.attr == "astype" \
                        and sub.args and _dotted(sub.args[0]) in _INT_DTYPE_ATTRS:
                    return True
        return False

    def _int_producer_expr(self, node: ast.AST, int_vars: Set[str]) -> bool:
        """Is `node` *itself* (not merely containing) an int-array value?
        Deliberately does not see through jnp.where/comparisons/boolean
        masks — a mask built FROM ids is not an id payload."""
        if isinstance(node, ast.Name):
            return node.id in int_vars
        if isinstance(node, ast.Call):
            fn = _dotted(node.func)
            if fn in _INT_PRODUCERS:
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
                    and node.args and _dotted(node.args[0]) in _INT_DTYPE_ATTRS:
                return True
            for kw in node.keywords:
                if kw.arg == "dtype" and _dotted(kw.value) in _INT_DTYPE_ATTRS:
                    return True
        if isinstance(node, ast.BinOp):
            return self._int_producer_expr(node.left, int_vars) or \
                self._int_producer_expr(node.right, int_vars)
        if isinstance(node, ast.Subscript):
            return self._int_producer_expr(node.value, int_vars)
        return False

    def _check_gl003_function(self, fn: ast.FunctionDef) -> None:
        # pass 1: names DIRECTLY assigned an integer-array expression
        int_vars: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name):
                if self._int_producer_expr(sub.value, int_vars):
                    int_vars.add(sub.targets[0].id)

        # pass 2: narrow casts of int-hinted values -> record tainted names
        tainted: Dict[str, int] = {}     # name -> cast line
        direct: List[Tuple[ast.Call, int]] = []
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            src = self._is_narrow_float_cast(sub)
            if src is None or not self._int_hinted(src, int_vars):
                continue
            direct.append((sub, sub.lineno))
        # map casts assigned to a name
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name):
                for cast_node, line in direct:
                    if cast_node in ast.walk(sub.value):
                        tainted[sub.targets[0].id] = line

        if not direct:
            return

        # pass 3: ordering sinks consuming a tainted cast (nested or by name)
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            fname = _dotted(sub.func) or ""
            is_sink = fname in _ORDERING_CALLS or any(
                fname == s or fname.endswith("." + s) for s in _ORDERING_SUFFIXES
            )
            if not is_sink:
                continue
            for argnode in list(sub.args) + [kw.value for kw in sub.keywords]:
                for cast_node, line in direct:
                    if cast_node in ast.walk(argnode):
                        self._emit("GL003", sub,
                                   f"ordering op {fname}() consumes a >=32-bit "
                                   "integer value cast to narrow float "
                                   f"(cast at line {line}): keys above 2^24 "
                                   "collapse; select in integer domain")
                names = _names_in(argnode) & set(tainted)
                for nm in names:
                    self._emit("GL003", sub,
                               f"ordering op {fname}() consumes {nm!r}, a "
                               ">=32-bit integer value cast to narrow float "
                               f"at line {tainted[nm]}: keys above 2^24 "
                               "collapse; select in integer domain")

    # -- GL008 unclassified swallow ---------------------------------------

    _BROAD_EXC = {"Exception", "BaseException"}

    def _check_unclassified_swallow(self, node: ast.Try) -> None:
        """``except Exception`` (or bare/``BaseException``) whose try body
        touches device compute and whose handler neither re-raises nor
        routes through ``resilience.classify()`` swallows the transient /
        OOM / dead-backend distinction the resilience layer exists for."""
        if not any(_contains_device_expr(s) for s in node.body):
            return
        for handler in node.handlers:
            if handler.type is None:
                names = set(self._BROAD_EXC)
            elif isinstance(handler.type, ast.Tuple):
                # `except (ValueError, Exception):` is just as broad
                names = {_dotted(el) or "" for el in handler.type.elts}
            else:
                names = {_dotted(handler.type) or ""}
            if not (names & self._BROAD_EXC):
                continue
            body_nodes = [x for s in handler.body for x in ast.walk(s)]
            if any(isinstance(x, ast.Raise) for x in body_nodes):
                continue                 # re-raised (possibly converted)
            calls_classify = any(
                isinstance(x, ast.Call)
                and (_dotted(x.func) or "").rsplit(".", 1)[-1] == "classify"
                for x in body_nodes
            )
            if calls_classify:
                continue
            self._emit("GL008", handler,
                       "bare `except Exception` swallows device-compute "
                       "failure without resilience.classify(): transient/"
                       "OOM/dead-backend collapse into one silent fallback; "
                       "classify, re-raise, or suppress with a reason")

    # -- GL009 unspanned entry points --------------------------------------

    # public serving-surface method/function prefixes that count as entry
    # points in serve/ modules (docs/serving.md): the request path, the
    # mutation path, and the swap/warmup control plane
    _SERVE_ENTRY_PREFIXES = (
        "search", "build", "submit", "publish", "delete", "upsert",
        "compact", "swap", "warmup", "create_index", "add_index",
        "load_index",
        # the multi-host fabric's control plane (ISSUE 6): recovery
        # actions are serving-surface latency too — an unobserved
        # probe/restart is a blind spot exactly when the cluster is
        # degraded and observability matters most
        "probe", "restart",
        # graft-helm (ISSUE 18): membership mutation and shard movement
        # are the cluster's most disruptive actions — every
        # scale/rebalance/balance decision must leave a span
        "scale", "rebalance", "balance",
    )

    def _check_unspanned_entries(self) -> None:
        """Public module-level ``search*``/``build*`` functions in
        ``neighbors/`` modules, public ``fused_*`` kernel entry points
        in ``ops/`` modules (the Pallas hot paths — an unobserved
        kernel dispatch is a blind spot exactly where compile/variant
        attribution matters most) — and, in ``serve/`` modules, public
        functions AND class methods on the serving surface
        (:data:`_SERVE_ENTRY_PREFIXES`) — must open a graft-scope span
        (``obs.span`` / ``obs.entry_span`` — any call whose final dotted
        component ends in ``span`` counts): an unobserved entry point is
        a hole in the latency/count coverage docs/observability.md
        documents. Param-computation helpers suppress with a reason."""
        parts = Path(self.path).parts
        in_serve = "serve" in parts
        in_ops = "ops" in parts
        if "neighbors" not in parts and not in_serve and not in_ops:
            return
        prefixes = (self._SERVE_ENTRY_PREFIXES if in_serve
                    else ("fused",) if in_ops
                    else ("search", "build"))
        candidates = [n for n in self.tree.body
                      if isinstance(n, ast.FunctionDef)]
        if in_serve:
            # the serving surface is method-shaped (Server.submit,
            # Registry.publish, ...); neighbors/ stays module-function-only
            for cls in self.tree.body:
                if isinstance(cls, ast.ClassDef) \
                        and not cls.name.startswith("_"):
                    candidates.extend(
                        n for n in cls.body
                        if isinstance(n, ast.FunctionDef))
        for node in candidates:
            name = node.name
            if name.startswith("_"):
                continue
            # word-boundary prefix match: "deleted_rows" is an accounting
            # getter, not the "delete" entry point
            if not any(name == p or name.startswith(p + "_")
                       for p in prefixes):
                continue
            has_span = any(
                isinstance(sub, ast.Call)
                and (_dotted(sub.func) or "").rsplit(".", 1)[-1]
                    .endswith("span")
                for sub in ast.walk(node)
            )
            if not has_span:
                self._emit("GL009", node,
                           f"public entry point {name}() opens no obs.span: "
                           "its latency and query counts are attributed to "
                           "nobody; wrap the body in obs.entry_span/obs.span "
                           "or suppress with a reason")

    # -- GL019 untraced RPC ------------------------------------------------

    # transport method-attribute names that fan an RPC across a process
    # boundary (comms/procgroup.py's ProcGroup/LocalGroup surface)
    _RPC_CALL_ATTRS = ("call", "call_async")
    # helpers that inject the graft-trace context into a payload
    _TRACE_HELPERS = ("traced_payload", "with_trace")

    def _is_traced_payload_expr(self, expr: Optional[ast.AST],
                                traced_names: Set[str]) -> bool:
        """Does this payload expression carry the trace-context field?

        Accepted evidence: a (possibly nested) call to one of
        :data:`_TRACE_HELPERS`; a name previously assigned from one; or
        a dict literal spelling the wire field key. A payload forwarded
        through a function parameter is NOT evidence — the pass-through
        site says so with a reasoned suppression, so the audit trail
        names where the threading actually happened."""
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            if expr.id in traced_names:
                return True
        if isinstance(expr, ast.Dict):
            for key in expr.keys:
                if isinstance(key, ast.Constant) and key.value == "trace":
                    return True
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                fname = _dotted(sub.func) or ""
                if fname.rsplit(".", 1)[-1] in self._TRACE_HELPERS:
                    return True
        return False

    def _check_untraced_rpc(self) -> None:
        """GL019: in ``serve/`` and ``comms/`` modules, every transport
        ``call``/``call_async`` site — shape ``<obj>.call(rank,
        "method", payload)`` — must thread the graft-trace context
        field through its payload, or suppress with a reason
        (control-plane RPCs that belong to no query)."""
        if self.rules is not None and "GL019" not in self.rules:
            return
        parts = Path(self.path).parts
        if "serve" not in parts and "comms" not in parts:
            return
        # enclosing-function index: a call's payload evidence (params,
        # traced-name assignments) is scoped to the function holding it
        encl: Dict[ast.AST, Optional[ast.AST]] = {}

        def _index(node: ast.AST, fn: Optional[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                encl[child] = fn
                _index(child,
                       child if isinstance(
                           child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)) else fn)

        _index(self.tree, None)
        fn_evidence: Dict[Optional[ast.AST], Set[str]] = {}

        def _evidence(fn: Optional[ast.AST]) -> Set[str]:
            hit = fn_evidence.get(fn)
            if hit is not None:
                return hit
            traced: Set[str] = set()
            scope = fn if fn is not None else self.tree
            for sub in ast.walk(scope):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and self._is_traced_payload_expr(sub.value,
                                                         traced):
                    traced.add(sub.targets[0].id)
            fn_evidence[fn] = traced
            return traced

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._RPC_CALL_ATTRS):
                continue
            # the transport shape: (rank, method[, payload]) with the
            # method a string literal (the common call site) or a
            # forwarded name (a wrapper like fabric._call_control) —
            # what separates an RPC fan-out from every other .call()
            if len(node.args) < 2:
                continue
            marg = node.args[1]
            if isinstance(marg, ast.Constant) and isinstance(marg.value,
                                                             str):
                method = marg.value
            elif isinstance(marg, ast.Name):
                method = f"<{marg.id}>"
            else:
                continue
            payload = node.args[2] if len(node.args) >= 3 else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "payload"), None)
            traced = _evidence(encl.get(node))
            if self._is_traced_payload_expr(payload, traced):
                continue
            self._emit("GL019", node,
                       f"transport {node.func.attr}() RPC {method!r} "
                       "does not thread the graft-trace context: wrap "
                       "the payload in obs.trace.traced_payload(...) so "
                       "the worker's spans share the query's trace id, "
                       "or suppress with a reason for control-plane "
                       "RPCs that belong to no query")

    # -- GL023 undocumented metric -----------------------------------------

    # the obs.metrics emission surface: the three writers whose first
    # positional arg IS the metric name
    _METRIC_EMITTERS = ("counter", "gauge", "observe")

    def _metric_call_name(self, node: ast.Call) -> Optional[ast.AST]:
        """Return the metric-name argument node if ``node`` is an obs
        metric emission, else None.

        Accepted shapes: ``<…>.obs.counter(...)`` / ``<…>.metrics.
        gauge(...)`` (the two import idioms in the tree), plus bare
        ``counter(...)``/``gauge(...)``/``observe(...)`` — but only in
        modules under ``obs/`` itself, where the writers are local
        names; elsewhere a bare name is someone else's function."""
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in self._METRIC_EMITTERS:
            owner = (_dotted(fn.value) or "").rsplit(".", 1)[-1]
            if owner not in ("obs", "metrics"):
                return None
        elif (isinstance(fn, ast.Name) and fn.id in self._METRIC_EMITTERS
              and "obs" in Path(self.path).parts):
            pass
        else:
            return None
        if node.args:
            return node.args[0]
        return next((kw.value for kw in node.keywords
                     if kw.arg == "name"), None)

    def _check_undocumented_metric(self) -> None:
        """GL023: every obs metric name emitted in package code
        (``raft_tpu`` in the path) must have a catalog row in
        docs/observability.md — the operator contract the dashboards
        and alert thresholds are written against. A metric name that
        is not a string literal is flagged too: the catalog check
        cannot read it, and neither can the operator grepping for it."""
        if self.rules is not None and "GL023" not in self.rules:
            return
        if "raft_tpu" not in Path(self.path).parts:
            return
        sites: List[Tuple[ast.Call, Optional[ast.AST]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                arg = self._metric_call_name(node)
                if arg is not None:
                    sites.append((node, arg))
        if not sites:
            return
        catalog = _metric_catalog_for(self.path)
        if catalog is None:
            # no docs/observability.md above this file (detached
            # fixture tree): there is no contract to check against
            return
        for node, arg in sites:
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                self._emit("GL023", node,
                           "metric name is built dynamically: the "
                           "catalog check (and the operator's grep) "
                           "cannot read it — emit a literal name per "
                           "series, or suppress with a reason naming "
                           "the catalog rows it expands to")
                continue
            if arg.value not in catalog:
                self._emit("GL023", node,
                           f"metric {arg.value!r} has no catalog row in "
                           "docs/observability.md: add one (name, "
                           "labels, who emits it) so dashboards and "
                           "alerts have a contract, or suppress with a "
                           "reason for a deliberately internal series")

    # -- GL024 hand-wired pipeline -----------------------------------------

    # the multi-stage entry points and kernel internals a serve/comms
    # adapter must not call directly (ISSUE 20): composition belongs in
    # a compiled plan, not re-plumbed per call site
    _PIPELINE_INTERNALS = frozenset({
        "search_refined", "search_refined_stream", "_pq_search",
        "_ivf_search", "_beam_search", "_beam_search_pallas"})
    _SEARCH_OWNERS = frozenset({
        "ivf_pq", "ivf_flat", "brute_force", "cagra", "hybrid"})

    def _is_plan_dispatch(self, node: ast.Call) -> bool:
        """A call that routes through graft-plan: ``plan.compile(...)``
        / ``compile_plan(...)`` (any plan-module alias), or the serve
        handle's compiled-plan cache (``self.compiled(k, rung)``)."""
        name = _dotted(node.func) or ""
        last = name.rsplit(".", 1)[-1]
        if last == "compile_plan" or last == "compiled":
            return True
        if last == "compile" and "plan" in name.rsplit(".", 2)[-2:][0]:
            return True
        return False

    def _check_hand_wired_pipeline(self) -> None:
        """GL024: serve adapters and sharded variants dispatch search
        pipelines through ``plan.compile`` (ISSUE 20). A top-level
        function (with its nested closures) that calls a multi-stage
        entry point or kernel internal directly, and never touches the
        plan compiler, is a hand-wired pipeline — the drift class the
        plan IR exists to end."""
        if self.rules is not None and "GL024" not in self.rules:
            return
        parts = Path(self.path).parts
        if "raft_tpu" not in parts or not {"serve", "comms"} & set(parts):
            return

        def roots(body):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield stmt
                elif isinstance(stmt, ast.ClassDef):
                    yield from roots(stmt.body)

        for fn in roots(self.tree.body):
            sites: List[Tuple[ast.Call, str]] = []
            dispatches_plan = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_plan_dispatch(node):
                    dispatches_plan = True
                    continue
                name = _dotted(node.func) or ""
                bits = name.rsplit(".", 2)
                last = bits[-1]
                if last in self._PIPELINE_INTERNALS:
                    sites.append((node, name))
                elif (last == "search" and len(bits) >= 2
                        and bits[-2] in self._SEARCH_OWNERS):
                    sites.append((node, name))
            if dispatches_plan:
                continue
            for node, name in sites:
                self._emit("GL024", node,
                           f"hand-wired pipeline: {name}() called "
                           "directly from serve/comms dispatch — "
                           "compose the stages as a plan and route "
                           "through plan.compile (docs/plans.md), or "
                           "suppress with a reason naming why this is "
                           "a deliberate single-stage fast path")

    # -- GL004 f64 ---------------------------------------------------------

    def _check_f64_attr(self, node: ast.Attribute) -> None:
        if _dotted(node) in _F64_ATTRS:
            self._emit("GL004", node,
                       f"{_dotted(node)} in package code: silently downcast "
                       "on device under disabled x64; if intentionally "
                       "host-side, suppress with a reason")

    def _check_f64_call(self, node: ast.Call) -> None:
        is_dtype_sink = (
            isinstance(node.func, ast.Attribute) and node.func.attr in (
                "astype", "asarray", "array", "zeros", "ones", "full",
                "empty", "arange")
        )
        if not is_dtype_sink:
            return
        for cand in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(cand, ast.Constant) and cand.value in ("float64", "f64", "double"):
                self._emit("GL004", node,
                           "dtype 'float64' requested: silently downcast on "
                           "device under disabled x64")

    # -- GL006 (retired here) ----------------------------------------------
    # The literal BlockSpec/VMEM screen that lived here through r6 moved
    # into the kern engine (analysis/kernels.py) as the FALLBACK for
    # pallas_call sites whose geometry the abstract evaluator cannot
    # resolve; resolved sites get exact computed accounting instead
    # (GL006/GL015-GL018, docs/static_analysis.md §engine-4).

    # -- GL005 undated perf claims ----------------------------------------

    def _lint_comments_and_docstrings(self) -> None:
        if self.rules is not None and "GL005" not in self.rules:
            return
        blocks: List[Tuple[int, str]] = []   # (start line, text)

        # contiguous comment runs
        run_start, run_lines, run_text = None, 0, []
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                line = tok.start[0]
                if run_start is not None and line == run_lines + 1:
                    run_text.append((line, tok.string))
                    run_lines = line
                else:
                    if run_text:
                        blocks.append((run_text[0][0],
                                       "\n".join(t for _, t in run_text)))
                    run_text = [(line, tok.string)]
                    run_start, run_lines = line, line
        if run_text:
            blocks.append((run_text[0][0], "\n".join(t for _, t in run_text)))

        # docstrings
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Module, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                body = getattr(node, "body", [])
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant) and \
                        isinstance(body[0].value.value, str):
                    blocks.append((body[0].lineno, body[0].value.value))

        for start, text in blocks:
            if "graft-lint:" in text and "allow-undated-perf" in text:
                continue    # suppression handled by line machinery
            m = _PERF_CLAIM_RE.search(text)
            if m and not _DATED_RE.search(text):
                claim_line = start + text[: m.start()].count("\n")
                self._emit("GL005", claim_line,
                           f"perf claim {m.group(0).strip()!r} has no "
                           "date/round/artifact citation (add e.g. "
                           "'(r5, BENCH_r05.json)')")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Set[str]] = None) -> List[Finding]:
    return FileLinter(path, source, rules).run()


def lint_file(path, rules: Optional[Set[str]] = None) -> List[Finding]:
    p = Path(path)
    try:
        source = p.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("GL000", str(p), 0, f"unreadable: {e}")]
    try:
        return lint_source(source, str(p), rules)
    except SyntaxError as e:
        return [Finding("GL000", str(p), e.lineno or 0, f"syntax error: {e.msg}")]


def lint_paths(paths: Sequence, rules: Optional[Set[str]] = None) -> List[Finding]:
    """Lint files and directories (``**/*.py``, skipping __pycache__)."""
    findings: List[Finding] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files = sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        else:
            files = [p]
        for f in files:
            findings.extend(lint_file(f, rules))
    return findings
