"""Project-wide call graph + type model for whole-program race analysis.

The per-file engine (:mod:`raft_tpu.analysis.races`) resolves
``self.X`` receivers and one same-class call hop; everything across a
module boundary was explicitly the dynamic sanitizer's job. This module
is the static half of closing that gap (ISSUE 17): it parses every
``*.py`` under the linted roots ONCE and builds

* a **module index** — imports (``from m import X`` aliasing), classes,
  module-level functions, module-level locks;
* a **lock model** — every ``threading``/``lockwatch`` lock, rlock,
  condition, and flag constructed anywhere, keyed by the same *name*
  the runtime sanitizer uses (``lockwatch.make_lock("serve.engine")``
  parses its literal, so every ``Server`` instance is one
  ``serve.engine`` node, exactly as in :func:`lockwatch.order_graph`);
  conditions alias to the lock they wrap, flags are excluded from the
  order graph (they are try-acquire handoffs, never blockable — see
  ``lockwatch.make_flag_lock``);
* a **type model** — a deliberately small annotation-driven inference:
  parameter/return annotations (string forms included), ``self.attr =
  ClassName(...)`` constructor assignments, ``Dict[K, V]`` /
  ``List[X]`` container value extraction (``.get()``/subscript), and
  attribute chains through typed receivers, iterated to fixpoint so
  ``serving.registry = server.registry`` with ``server: "Server"``
  resolves two hops deep;
* **call resolution** — same-module, imported-module, and typed-method
  calls resolve to :class:`FuncDecl` nodes; ``ClassName(...)`` resolves
  to ``__init__``; a module-level ``{"key": ClassA, ...}[k](...)``
  dispatch dict resolves to the union of its classes;
* **thread roots** — functions handed to ``Thread(target=...)``,
  executor ``.submit``/``call_soon``/``run_in_executor``, or escaping
  as callback values, closed to a project-wide reachable set.

Everything stays a heuristic over syntax (the honest caveat every
engine here carries): unannotated generics (``Generation.handle``) do
not resolve, dynamic dispatch is invisible, and the model trusts
annotations. The reconciliation pass (``graft-lint --reconcile``) is
the audit: a runtime-observed lock edge the model missed is reported
as a soundness gap, not silently absorbed.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# factory classification by dotted-name LAST segment, so
# ``lockwatch.make_lock``, ``make_lock`` (from-import), and any future
# re-export all classify identically (the PR-17 alias fix: the old
# exact-match tables missed from-imported factories entirely)
_LOCK_LAST = {"Lock": "lock", "RLock": "rlock",
              "make_lock": "lock", "make_rlock": "rlock"}
_COND_LAST = {"Condition", "make_condition"}
_FLAG_LAST = {"make_flag_lock"}
_EVENT_LAST = {"Event", "Semaphore", "BoundedSemaphore"}

_LOCKISH_ATTR_RE = re.compile(r"(^|_)(r?lock|mutex|cond(ition)?)$")

_SELF_NAMES = {"self", "cls"}

_CONTAINER_DICT = {"Dict", "dict", "Mapping", "MutableMapping",
                   "DefaultDict", "OrderedDict"}
_CONTAINER_LIST = {"List", "list", "Sequence", "MutableSequence",
                   "Tuple", "tuple", "Set", "set", "FrozenSet",
                   "frozenset", "Deque", "deque", "Iterable",
                   "Iterator"}
_UNION_HEADS = {"Optional", "Union"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass(eq=False)
class LockDecl:
    """One lock/condition/flag construction site."""

    attr: str                 # attribute or variable name at the site
    name: str                 # graph node (lockwatch name or fallback)
    kind: str                 # "lock" | "rlock" | "condition" | "flag"
    path: str
    line: int


@dataclasses.dataclass(eq=False)
class ClassDecl:
    module: "ModuleDecl"
    name: str
    node: ast.ClassDef
    methods: Dict[str, "FuncDecl"] = dataclasses.field(default_factory=dict)
    lock_attrs: Dict[str, LockDecl] = dataclasses.field(default_factory=dict)
    event_attrs: Set[str] = dataclasses.field(default_factory=set)
    # inferred `self.<attr>` types — grown to fixpoint by CallGraph
    attr_types: Dict[str, Set["TypeRef"]] = dataclasses.field(
        default_factory=dict)
    bases: List[str] = dataclasses.field(default_factory=list)

    @property
    def qualname(self) -> str:
        return f"{self.module.name}.{self.name}"


@dataclasses.dataclass(frozen=True)
class TypeRef:
    """An inferred type: an instance of ``cls``, optionally wrapped in
    a container whose element/value type it is."""

    cls: ClassDecl
    container: Optional[str] = None        # None | "list" | "dict"


@dataclasses.dataclass(eq=False)
class FuncDecl:
    module: "ModuleDecl"
    cls: Optional[ClassDecl]
    name: str
    node: ast.AST                          # FunctionDef | AsyncFunctionDef

    @property
    def qualname(self) -> str:
        if self.cls is not None:
            return f"{self.cls.qualname}.{self.name}"
        return f"{self.module.name}.{self.name}"


@dataclasses.dataclass(eq=False)
class ModuleDecl:
    name: str
    path: str
    tree: ast.Module
    source: str
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassDecl] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FuncDecl] = dataclasses.field(default_factory=dict)
    module_locks: Dict[str, LockDecl] = dataclasses.field(
        default_factory=dict)
    # module-level `{"k": ClassA, ...}` dispatch dicts: var -> class names
    class_dicts: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict)


# ---------------------------------------------------------------------------
# lock-construction classification
# ---------------------------------------------------------------------------


def _literal_name(call: ast.Call) -> Optional[str]:
    """The lock's declared sanitizer name: first positional string, or
    ``name=`` keyword."""
    for a in call.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def classify_lock_call(call: ast.AST) -> Optional[Tuple[str, Optional[str],
                                                        Optional[ast.AST]]]:
    """Classify a constructor call as ``(kind, declared_name,
    alias_arg)``; ``None`` when it is not a lock-family factory.

    ``alias_arg`` is the lock expression a Condition wraps (so the
    caller can alias the condition to its lock's node), including the
    nested ``make_condition(make_lock("x"))`` form, whose inner literal
    is returned directly as ``declared_name``."""
    if not isinstance(call, ast.Call):
        return None
    dotted = _dotted(call.func)
    if not dotted:
        return None
    last = dotted.rsplit(".", 1)[-1]
    if last in _LOCK_LAST:
        return _LOCK_LAST[last], _literal_name(call), None
    if last in _FLAG_LAST:
        return "flag", _literal_name(call), None
    if last in _COND_LAST:
        args = list(call.args) + [kw.value for kw in call.keywords
                                  if kw.arg in (None, "lock")]
        for a in args:
            inner = classify_lock_call(a)
            if inner is not None and inner[0] in ("lock", "rlock"):
                return "condition", inner[1], None
            if isinstance(a, (ast.Attribute, ast.Name)):
                return "condition", _literal_name(call), a
        return "condition", _literal_name(call), None
    if last in _EVENT_LAST:
        return "event", None, None
    return None


# ---------------------------------------------------------------------------
# the project model
# ---------------------------------------------------------------------------


class CallGraph:
    """The whole-program model: modules, classes, types, calls, locks."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleDecl] = {}
        # dotted-suffix index for import resolution across root spellings
        # ("raft_tpu.serve.registry" vs a scan rooted at raft_tpu/)
        self._suffixes: Dict[str, List[ModuleDecl]] = {}
        self.thread_roots: Set[FuncDecl] = set()
        self.reachable: Set[FuncDecl] = set()
        self._fn_of_node: Dict[ast.AST, FuncDecl] = {}
        self._param_types: Dict[FuncDecl, Dict[str, Set[TypeRef]]] = {}
        self._local_types: Dict[FuncDecl, Dict[str, Set[TypeRef]]] = {}
        # call-site argument types flowed onto UNannotated params
        self._param_extra: Dict[FuncDecl, Dict[str, Set[TypeRef]]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, paths: Sequence) -> "CallGraph":
        g = cls()
        for mod_name, path in _iter_py_files(paths):
            g._add_module(mod_name, path)
        g._index_suffixes()
        g._collect_decls()
        g._infer_types()
        g._collect_thread_roots()
        return g

    def _add_module(self, name: str, path: Path) -> None:
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (OSError, UnicodeDecodeError, SyntaxError):
            return
        self.modules[name] = ModuleDecl(name, str(path), tree, source)

    def _index_suffixes(self) -> None:
        for mod in self.modules.values():
            parts = mod.name.split(".")
            for i in range(len(parts)):
                self._suffixes.setdefault(
                    ".".join(parts[i:]), []).append(mod)

    def module_for(self, dotted: str) -> Optional[ModuleDecl]:
        """Resolve a dotted import target to a scanned module — exact
        name first, then the longest unique suffix match (a scan rooted
        inside the package sees shorter names than the import spells)."""
        mod = self.modules.get(dotted)
        if mod is not None:
            return mod
        parts = dotted.split(".")
        for i in range(len(parts)):
            cands = self._suffixes.get(".".join(parts[i:]), [])
            if len(cands) == 1:
                return cands[0]
            if cands:
                return None            # ambiguous suffix: stay honest
        return None

    # -- declaration pass --------------------------------------------------

    def _collect_decls(self) -> None:
        for mod in self.modules.values():
            self._collect_imports(mod)
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._collect_class(mod, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    fd = FuncDecl(mod, None, node.name, node)
                    mod.functions[node.name] = fd
                    self._fn_of_node[node] = fd
                elif isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    self._collect_module_assign(
                        mod, node.targets[0].id, node.value, node.lineno)

    @staticmethod
    def _collect_imports(mod: ModuleDecl) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or
                                alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    def _collect_module_assign(self, mod: ModuleDecl, name: str,
                               value: ast.AST, line: int) -> None:
        lk = classify_lock_call(value)
        if lk is not None and lk[0] in ("lock", "rlock", "condition"):
            kind, declared, _alias = lk
            mod.module_locks[name] = LockDecl(
                name, declared or f"{mod.name}.{name}", kind,
                mod.path, line)
        elif isinstance(value, ast.Dict) and value.values and all(
                isinstance(v, ast.Name) for v in value.values):
            mod.class_dicts[name] = [v.id for v in value.values
                                     if isinstance(v, ast.Name)]

    def _collect_class(self, mod: ModuleDecl, node: ast.ClassDef) -> None:
        cd = ClassDecl(mod, node.name, node)
        cd.bases = [d for d in (_dotted(b) for b in node.bases) if d]
        mod.classes[node.name] = cd
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fd = FuncDecl(mod, cd, sub.name, sub)
                cd.methods[sub.name] = fd
                self._fn_of_node[sub] = fd
            elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                self._classify_attr_lock(cd, sub.targets[0].id, sub.value,
                                         sub.lineno)
        for m in cd.methods.values():
            for sub in ast.walk(m.node):
                tgt = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    tgt = sub.target
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in _SELF_NAMES:
                    self._classify_attr_lock(cd, tgt.attr, sub.value,
                                             sub.lineno)

    def _classify_attr_lock(self, cd: ClassDecl, attr: str,
                            value: ast.AST, line: int) -> None:
        lk = classify_lock_call(value)
        if lk is None:
            return
        kind, declared, alias_arg = lk
        if kind == "event":
            cd.event_attrs.add(attr)
            return
        name = declared
        if name is None and alias_arg is not None:
            # Condition(self.L): alias to the wrapped lock's node
            if isinstance(alias_arg, ast.Attribute) and \
                    isinstance(alias_arg.value, ast.Name) and \
                    alias_arg.value.id in _SELF_NAMES:
                wrapped = cd.lock_attrs.get(alias_arg.attr)
                name = wrapped.name if wrapped else \
                    f"{cd.name}.{alias_arg.attr}"
        if name is None:
            name = f"{cd.name}.{attr}"
        cd.lock_attrs.setdefault(
            attr, LockDecl(attr, name, kind, cd.module.path, line))

    # -- type inference ----------------------------------------------------

    def resolve_class(self, mod: ModuleDecl,
                      dotted: str) -> Optional[ClassDecl]:
        """Resolve a (possibly dotted) class reference as seen from
        ``mod``: own classes, then imports, then a module-suffix walk."""
        if dotted in mod.classes:
            return mod.classes[dotted]
        target = mod.imports.get(dotted, dotted)
        # target like "pkg.module.Class" or "pkg.module"
        head, _, last = target.rpartition(".")
        if head:
            m = self.module_for(head)
            if m is not None and last in m.classes:
                return m.classes[last]
        if "." in dotted:
            # "module.Class" spelled through an imported module alias
            mhead, _, mlast = dotted.rpartition(".")
            mtarget = mod.imports.get(mhead.split(".")[0])
            if mtarget:
                tail = mhead.split(".", 1)[1] if "." in mhead else ""
                full = mtarget + ("." + tail if tail else "")
                m = self.module_for(full)
                if m is not None and mlast in m.classes:
                    return m.classes[mlast]
        m = self.module_for(target)
        return None if m is None else m.classes.get(dotted.rsplit(
            ".", 1)[-1])

    def parse_annotation(self, node: Optional[ast.AST],
                         mod: ModuleDecl) -> Set[TypeRef]:
        if node is None:
            return set()
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            try:
                node = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return set()
        if isinstance(node, (ast.Name, ast.Attribute)):
            dotted = _dotted(node)
            if not dotted:
                return set()
            cls = self.resolve_class(mod, dotted)
            return {TypeRef(cls)} if cls else set()
        if isinstance(node, ast.Subscript):
            head = _dotted(node.value) or ""
            last = head.rsplit(".", 1)[-1]
            sl = node.slice
            elts = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
            if last in _UNION_HEADS:
                out: Set[TypeRef] = set()
                for e in elts:
                    out |= self.parse_annotation(e, mod)
                return out
            if last in _CONTAINER_DICT and len(elts) == 2:
                return {TypeRef(t.cls, "dict")
                        for t in self.parse_annotation(elts[1], mod)}
            if last in _CONTAINER_LIST and elts:
                return {TypeRef(t.cls, "list")
                        for t in self.parse_annotation(elts[0], mod)}
        return set()

    def param_types(self, fn: FuncDecl) -> Dict[str, Set[TypeRef]]:
        ann = self._param_types.get(fn)
        if ann is None:
            ann = {}
            node = fn.node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = list(node.args.posonlyargs) + list(node.args.args) + \
                    list(node.args.kwonlyargs)
                for a in args:
                    if a.arg in _SELF_NAMES:
                        continue
                    t = self.parse_annotation(a.annotation, fn.module)
                    if t:
                        ann[a.arg] = t
            self._param_types[fn] = ann
        extra = self._param_extra.get(fn)
        if not extra:
            return ann
        out = {k: set(v) for k, v in ann.items()}
        for k, v in extra.items():
            if k not in ann:        # annotations stay authoritative
                out.setdefault(k, set()).update(v)
        return out

    def return_types(self, fn: FuncDecl) -> Set[TypeRef]:
        node = fn.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return self.parse_annotation(node.returns, fn.module)
        return set()

    def local_types(self, fn: FuncDecl) -> Dict[str, Set[TypeRef]]:
        """Per-function local variable types: annotations win, then
        single-target assignment inference, iterated twice so chains
        (``w = self._workers[r]`` feeding ``w.lock``) resolve."""
        cached = self._local_types.get(fn)
        if cached is not None:
            return cached
        env: Dict[str, Set[TypeRef]] = dict(self.param_types(fn))
        for _ in range(2):
            changed = False
            for sub in ast.walk(fn.node):
                name = None
                types: Set[TypeRef] = set()
                if isinstance(sub, ast.AnnAssign) and \
                        isinstance(sub.target, ast.Name):
                    name = sub.target.id
                    types = self.parse_annotation(sub.annotation,
                                                  fn.module)
                elif isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1 and \
                        isinstance(sub.targets[0], ast.Name):
                    name = sub.targets[0].id
                    types = self.infer_expr(sub.value, fn, env)
                elif isinstance(sub, ast.For) and \
                        isinstance(sub.target, ast.Name):
                    name = sub.target.id
                    types = {TypeRef(t.cls)
                             for t in self.infer_expr(sub.iter, fn, env)
                             if t.container is not None}
                if name and types and env.get(name) != types:
                    env[name] = types
                    changed = True
            if not changed:
                break
        self._local_types[fn] = env
        return env

    def infer_expr(self, expr: ast.AST, fn: FuncDecl,
                   env: Optional[Dict[str, Set[TypeRef]]] = None
                   ) -> Set[TypeRef]:
        if env is None:
            env = self.local_types(fn)
        if isinstance(expr, ast.Name):
            if expr.id in _SELF_NAMES and fn.cls is not None:
                return {TypeRef(fn.cls)}
            return set(env.get(expr.id, ()))
        if isinstance(expr, ast.Attribute):
            out: Set[TypeRef] = set()
            for t in self.infer_expr(expr.value, fn, env):
                if t.container is None:
                    out |= t.cls.attr_types.get(expr.attr, set())
            return out
        if isinstance(expr, ast.Subscript):
            return {TypeRef(t.cls)
                    for t in self.infer_expr(expr.value, fn, env)
                    if t.container is not None}
        if isinstance(expr, ast.Call):
            return self._infer_call(expr, fn, env)
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
            return {TypeRef(t.cls, "list")
                    for t in self.infer_expr(expr.elt, fn, env)
                    if t.container is None}
        if isinstance(expr, ast.List) and expr.elts:
            return {TypeRef(t.cls, "list")
                    for t in self.infer_expr(expr.elts[0], fn, env)
                    if t.container is None}
        if isinstance(expr, ast.IfExp):
            return self.infer_expr(expr.body, fn, env) | \
                self.infer_expr(expr.orelse, fn, env)
        if isinstance(expr, ast.Await):
            return self.infer_expr(expr.value, fn, env)
        return set()

    def _infer_call(self, call: ast.Call, fn: FuncDecl,
                    env: Dict[str, Set[TypeRef]]) -> Set[TypeRef]:
        func = call.func
        # `.get(k)` on a dict-typed receiver -> the value type
        if isinstance(func, ast.Attribute) and func.attr == "get":
            vals = {TypeRef(t.cls)
                    for t in self.infer_expr(func.value, fn, env)
                    if t.container == "dict"}
            if vals:
                return vals
        # `DISPATCH[k](...)` over a module-level class dict -> union
        if isinstance(func, ast.Subscript) and \
                isinstance(func.value, ast.Name):
            names = fn.module.class_dicts.get(func.value.id)
            if names:
                out: Set[TypeRef] = set()
                for n in names:
                    cls = self.resolve_class(fn.module, n)
                    if cls:
                        out.add(TypeRef(cls))
                return out
        dotted = _dotted(func)
        if dotted:
            cls = self.resolve_class(fn.module, dotted)
            if cls is not None:
                return {TypeRef(cls)}
        out = set()
        for callee in self.resolve_call(call, fn, env):
            out |= self.return_types(callee)
        return out

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, call: ast.Call, fn: FuncDecl,
                     env: Optional[Dict[str, Set[TypeRef]]] = None
                     ) -> List[FuncDecl]:
        """Callee candidates of one call site (constructor calls
        resolve to ``__init__``)."""
        env = self.local_types(fn) if env is None else env
        func = call.func
        out: List[FuncDecl] = []
        if isinstance(func, ast.Name):
            cls = self.resolve_class(fn.module, func.id)
            if cls is not None:
                init = cls.methods.get("__init__")
                return [init] if init else []
            fd = fn.module.functions.get(func.id)
            if fd is not None:
                return [fd]
            target = fn.module.imports.get(func.id)
            if target:
                head, _, last = target.rpartition(".")
                m = self.module_for(head) if head else None
                if m is not None and last in m.functions:
                    return [m.functions[last]]
            return []
        if isinstance(func, ast.Subscript) and \
                isinstance(func.value, ast.Name):
            for n in fn.module.class_dicts.get(func.value.id, ()):
                cls = self.resolve_class(fn.module, n)
                init = cls.methods.get("__init__") if cls else None
                if init:
                    out.append(init)
            return out
        if not isinstance(func, ast.Attribute):
            return []
        attr = func.attr
        base = func.value
        if isinstance(base, ast.Name):
            if base.id in _SELF_NAMES and fn.cls is not None:
                m = self._method_on(fn.cls, attr)
                if m is not None:
                    return [m]
            # imported module function: `lockwatch.make_lock(...)`
            target = fn.module.imports.get(base.id)
            if target:
                m = self.module_for(target)
                if m is not None:
                    if attr in m.functions:
                        return [m.functions[attr]]
                    if attr in m.classes:
                        init = m.classes[attr].methods.get("__init__")
                        return [init] if init else []
        for t in self.infer_expr(base, fn, env):
            if t.container is not None:
                continue
            m = self._method_on(t.cls, attr)
            if m is not None:
                out.append(m)
        return out

    def _method_on(self, cls: ClassDecl, name: str,
                   _depth: int = 0) -> Optional[FuncDecl]:
        if name in cls.methods:
            return cls.methods[name]
        if _depth >= 3:
            return None
        for b in cls.bases:
            base = self.resolve_class(cls.module, b)
            if base is not None:
                m = self._method_on(base, name, _depth + 1)
                if m is not None:
                    return m
        return None

    def _infer_types(self) -> None:
        """Grow ``ClassDecl.attr_types`` from ``self.attr = <expr>``
        sites AND flow call-site argument types onto unannotated
        parameters, to fixpoint (attr chains across classes need 2-3
        rounds; bounded to keep pathological graphs cheap).

        The argument flow is what types ``Generation.handle``: no
        annotation anywhere, but every ``publish(name, handle)`` caller
        passes a ``_Handle``, so the param — and through ``self.handle
        = handle``, the attribute — gets the callers' union."""
        for _ in range(4):
            changed = False
            for mod in self.modules.values():
                for cd in mod.classes.values():
                    for meth in cd.methods.values():
                        changed |= self._infer_attr_assigns(cd, meth)
                for fn in self._module_fns(mod):
                    changed |= self._propagate_call_args(fn)
            if not changed:
                break
            self._local_types.clear()

    def _propagate_call_args(self, fn: FuncDecl) -> bool:
        changed = False
        env = self.local_types(fn)
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            for callee in self.resolve_call(sub, fn, env):
                changed |= self._bind_args(sub, fn, env, callee)
        return changed

    def _bind_args(self, call: ast.Call, fn: FuncDecl,
                   env: Dict[str, Set[TypeRef]],
                   callee: FuncDecl) -> bool:
        node = callee.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        self.param_types(callee)               # prime annotation cache
        ann = self._param_types[callee]
        params = [a.arg for a in (list(node.args.posonlyargs) +
                                  list(node.args.args))]
        offset = 1 if params and params[0] in _SELF_NAMES else 0
        changed = False
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                break
            j = i + offset
            if j >= len(params):
                break
            changed |= self._add_param_extra(
                callee, params[j], ann, self.infer_expr(a, fn, env))
        names = set(params) | {a.arg for a in node.args.kwonlyargs}
        for kw in call.keywords:
            if kw.arg and kw.arg in names:
                changed |= self._add_param_extra(
                    callee, kw.arg, ann,
                    self.infer_expr(kw.value, fn, env))
        return changed

    def _add_param_extra(self, callee: FuncDecl, pname: str,
                         ann: Dict[str, Set[TypeRef]],
                         types: Set[TypeRef]) -> bool:
        if not types or pname in ann or pname in _SELF_NAMES:
            return False
        have = self._param_extra.setdefault(
            callee, {}).setdefault(pname, set())
        if types <= have:
            return False
        have |= types
        return True

    def _infer_attr_assigns(self, cd: ClassDecl, fn: FuncDecl) -> bool:
        changed = False
        env = self.local_types(fn)
        for sub in ast.walk(fn.node):
            tgt, value, ann = None, None, None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt, value = sub.targets[0], sub.value
            elif isinstance(sub, ast.AnnAssign):
                tgt, value, ann = sub.target, sub.value, sub.annotation
            if not (isinstance(tgt, ast.Attribute) and
                    isinstance(tgt.value, ast.Name) and
                    tgt.value.id in _SELF_NAMES):
                continue
            types = self.parse_annotation(ann, cd.module) if ann is not None \
                else set()
            if not types and value is not None:
                types = self.infer_expr(value, fn, env)
            if types:
                have = cd.attr_types.setdefault(tgt.attr, set())
                if not types <= have:
                    have |= types
                    changed = True
        return changed

    # -- thread roots ------------------------------------------------------

    def _collect_thread_roots(self) -> None:
        for mod in self.modules.values():
            for fn in self._module_fns(mod):
                for sub in ast.walk(fn.node):
                    if isinstance(sub, ast.Call):
                        self._root_scan_call(sub, fn)
        # close reachability over resolvable calls
        frontier = list(self.thread_roots)
        self.reachable = set(frontier)
        while frontier:
            fn = frontier.pop()
            for sub in ast.walk(fn.node):
                if not isinstance(sub, ast.Call):
                    continue
                for callee in self.resolve_call(sub, fn):
                    if callee not in self.reachable:
                        self.reachable.add(callee)
                        frontier.append(callee)

    def _module_fns(self, mod: ModuleDecl) -> Iterable[FuncDecl]:
        for fd in mod.functions.values():
            yield fd
        for cd in mod.classes.values():
            for fd in cd.methods.values():
                yield fd

    def _mark_root(self, expr: ast.AST, fn: FuncDecl) -> None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id in _SELF_NAMES and fn.cls is not None:
                m = self._method_on(fn.cls, expr.attr)
                if m is not None:
                    self.thread_roots.add(m)
                return
            for t in self.infer_expr(expr.value, fn):
                if t.container is None:
                    m = self._method_on(t.cls, expr.attr)
                    if m is not None:
                        self.thread_roots.add(m)
        elif isinstance(expr, ast.Name):
            fd = fn.module.functions.get(expr.id)
            if fd is not None:
                self.thread_roots.add(fd)

    def _root_scan_call(self, call: ast.Call, fn: FuncDecl) -> None:
        dotted = _dotted(call.func) or ""
        last = dotted.rsplit(".", 1)[-1]
        if dotted.endswith("Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    self._mark_root(kw.value, fn)
        elif last in ("submit", "call_soon", "run_in_executor") and \
                call.args:
            self._mark_root(call.args[0], fn)
        else:
            # escaping callback: `self.m` (or a typed `obj.m`) passed as
            # a VALUE — it may run on any thread later
            for arg in list(call.args) + [kw.value
                                          for kw in call.keywords]:
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id in _SELF_NAMES and \
                        fn.cls is not None and \
                        arg.attr in fn.cls.methods:
                    self.thread_roots.add(fn.cls.methods[arg.attr])

    # -- lock-expression resolution ----------------------------------------

    def lock_node(self, expr: ast.AST,
                  fn: FuncDecl) -> Optional[LockDecl]:
        """Resolve a with-item / acquire receiver expression to its
        lock declaration. Returns ``None`` for non-lock expressions;
        flag locks resolve (kind ``"flag"``) so callers can exempt
        them."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in _SELF_NAMES and fn.cls is not None:
            decl = fn.cls.lock_attrs.get(expr.attr)
            if decl is not None:
                return decl
            if _LOCKISH_ATTR_RE.search(expr.attr):
                return LockDecl(expr.attr,
                                f"{fn.cls.name}.{expr.attr}", "lock",
                                fn.module.path, expr.lineno)
            return None
        if isinstance(expr, ast.Name):
            decl = fn.module.module_locks.get(expr.id)
            if decl is not None:
                return decl
            target = fn.module.imports.get(expr.id)
            if target:
                head, _, last = target.rpartition(".")
                m = self.module_for(head) if head else None
                if m is not None and last in m.module_locks:
                    return m.module_locks[last]
            if _LOCKISH_ATTR_RE.search(expr.id):
                return LockDecl(expr.id, expr.id, "lock",
                                fn.module.path, expr.lineno)
            return None
        if isinstance(expr, ast.Attribute):
            base, attr = expr.value, expr.attr
            for t in self.infer_expr(base, fn):
                if t.container is not None:
                    continue
                decl = t.cls.lock_attrs.get(attr)
                if decl is not None:
                    return decl
            if _LOCKISH_ATTR_RE.search(attr):
                dotted = _dotted(expr) or attr
                # typed receiver without a known lock attr: name by
                # class so instances merge; untyped: name by the path
                for t in self.infer_expr(base, fn):
                    if t.container is None:
                        return LockDecl(attr, f"{t.cls.name}.{attr}",
                                        "lock", fn.module.path,
                                        expr.lineno)
                return LockDecl(attr, dotted, "lock", fn.module.path,
                                expr.lineno)
        return None

    def fn_for_node(self, node: ast.AST) -> Optional[FuncDecl]:
        return self._fn_of_node.get(node)


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------


def _iter_py_files(paths: Sequence) -> List[Tuple[str, Path]]:
    """``(module_name, path)`` pairs for every ``*.py`` under the given
    roots. A directory that is itself a package (has ``__init__.py``)
    contributes its own name as the leading module component, so a scan
    of ``raft_tpu/`` yields ``raft_tpu.serve.engine`` — the exact names
    the package's imports spell."""
    out: List[Tuple[str, Path]] = []
    seen: Set[Path] = set()
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            prefix = p.name if (p / "__init__.py").exists() else ""
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts or f in seen:
                    continue
                seen.add(f)
                rel = f.relative_to(p).with_suffix("")
                parts = [x for x in rel.parts if x != "__init__"]
                name = ".".join(([prefix] if prefix else []) + list(parts)) \
                    or prefix or f.stem
                out.append((name, f))
        elif p.suffix == ".py" and p not in seen:
            seen.add(p)
            out.append((p.stem, p))
    return out


def build_project(paths: Sequence) -> CallGraph:
    """Build the whole-program model over the given roots."""
    return CallGraph.build(paths)
