"""graft-kern kernel contracts: declared invariants + adversarial sweeps.

Every hand-written Pallas kernel in ``ops/`` (and the kernel-shaped
selection rungs of ``matrix/select_k.py``) registers a
:class:`KernelContract` at import time declaring the invariants its
padding/masking logic promises — which dims may carry a non-divisible
tail (``tail_rows="masked"``), the supported ``k_range``, the dtypes it
is exact (or recall-banded) over, and the symbolic shapes of its array
arguments. The contract is consumed from BOTH sides of the gate, so the
static engine and the dynamic sweep cross-check each other
(docs/static_analysis.md §engine-4):

* **statically** — :mod:`raft_tpu.analysis.kernels` evaluates each
  ``pl.pallas_call`` site's block geometry/index maps/VMEM under the
  contract's shape cases (GL006/GL015-GL018);
* **dynamically** — ``tests/test_kernel_contracts.py`` (marker
  ``kernel_contract``, tier-1) drives every registered kernel in
  interpret mode over :func:`adversarial_cases` — non-divisible rows,
  ``k == n``, ``k == 1``, single-row batches, sublane-boundary ±1
  shapes, lane-boundary k, each declared dtype — against XLA oracles;
  ``scripts/tpu_parity.py`` reruns the same cases compiled on a chip.

This module is deliberately dependency-light (no jax import) so kernel
modules can register contracts at import time with zero cost; the
drivers that actually run kernels live in
:mod:`raft_tpu.analysis.contract_drivers` and are resolved lazily from
the contract's ``driver`` dotted name.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

# minimum sublane multiple per dtype itemsize (the Mosaic tile rule:
# f32 (8, 128), bf16 (16, 128), int8 (32, 128) — pallas guide)
SUBLANE_BY_ITEMSIZE = {8: 8, 4: 8, 2: 16, 1: 32}
_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}
LANE = 128


def dtype_itemsize(name: str) -> int:
    return _ITEMSIZE.get(str(name), 4)


def dtype_sublane(name: str) -> int:
    return SUBLANE_BY_ITEMSIZE[dtype_itemsize(name)]


@dataclasses.dataclass(frozen=True)
class KernelContract:
    """Declared invariants for one kernel entry point.

    ``base`` is the canonical small case the sweep perturbs; keys are
    the kernel's own shape-parameter names (the sweep and the static
    engine bind them into the enclosing function by NAME, so they must
    match the source). ``arms`` are static-variant overlays (e.g.
    ``{"variant": "fold"}``) each of which gets its own shape sweep;
    ``k_max`` inside an arm caps ``k_range`` for that arm. ``arrays``
    maps array-argument names to symbolic shapes (dim names from the
    case, or literal ints) — the static engine uses them to apply the
    real Mosaic block rule (a block dim equal to the array dim is
    legal at any size) and the drivers use them to materialize inputs.
    """

    name: str
    module: str                     # defining module (static-engine key)
    entry: str                      # public entry-point attribute
    driver: str                     # "pkg.mod:fn" resolved lazily
    tail_rows: str                  # "masked" | "padded" | "rejected"
    k_range: Tuple[int, int]
    dtypes: Tuple[str, ...]
    exactness: str                  # "bitwise" | "recall"
    base: Mapping[str, object]
    rows_key: Optional[str] = None  # the dim k selects over
    batch_key: Optional[str] = None  # the query-batch dim
    k_key: Optional[str] = "k"
    recall_floor: float = 0.99
    arms: Tuple[Mapping[str, object], ...] = ({},)
    arrays: Mapping[str, Tuple[object, ...]] = dataclasses.field(
        default_factory=dict)
    dims: Mapping[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict)     # extra static-engine candidates
    derive: Optional[Callable[[dict], dict]] = None
    case_filter: Optional[Callable[[dict], bool]] = None
    extra_cases: Tuple[Mapping[str, object], ...] = ()
    notes: str = ""

    def resolve_driver(self) -> Callable:
        mod, _, fn = self.driver.partition(":")
        return getattr(importlib.import_module(mod), fn)


_REGISTRY: Dict[str, KernelContract] = {}


def kernel_contract(name: str, **kw) -> KernelContract:
    """Register (or re-register on module reload) a kernel contract."""
    c = KernelContract(name=name, **kw)
    _REGISTRY[name] = c
    return c


def contracts() -> Dict[str, KernelContract]:
    """All registered contracts. Importing :mod:`raft_tpu.ops` and
    :mod:`raft_tpu.matrix.select_k` populates the registry; call
    :func:`load_all` first when running standalone."""
    return dict(_REGISTRY)


def contracts_for_module(module: str) -> List[KernelContract]:
    return [c for c in _REGISTRY.values() if c.module == module]


def load_all() -> Dict[str, KernelContract]:
    """Import every module known to declare contracts, then return
    the registry (the harness/static-engine entry point)."""
    for mod in (
        "raft_tpu.ops.fused_topk",
        "raft_tpu.ops.ivf_scan",
        "raft_tpu.ops.beam_step",
        "raft_tpu.ops.graph_join",
        "raft_tpu.matrix.select_k",
    ):
        importlib.import_module(mod)
    return contracts()


# ---------------------------------------------------------------------------
# adversarial sweep generation
# ---------------------------------------------------------------------------


def _finish(c: KernelContract, case: dict) -> Optional[dict]:
    if c.derive is not None:
        case = c.derive(dict(case)) or case
    if c.case_filter is not None and not c.case_filter(case):
        return None
    return case


def adversarial_cases(c: KernelContract,
                      dtypes: Optional[Sequence[str]] = None,
                      ) -> List[dict]:
    """The contract's adversarial shape sweep.

    Per (arm, dtype): ``k == 1``, ``k == k_max``, ``k == rows`` (the
    whole-row edge), non-divisible rows, a single-row batch,
    sublane-boundary ±1 row counts for the dtype's tile, and
    lane-boundary k (63/64/65/129 clipped to the arm's range). Non-
    primary dtypes run a reduced spot set (k=1 / k_max) so the sweep
    stays tier-1-sized; ``extra_cases`` are appended verbatim per
    dtype-0. Cases are deduplicated preserving order.
    """
    out: List[dict] = []
    seen = set()
    use_dtypes = tuple(dtypes) if dtypes is not None else c.dtypes

    def emit(case: dict) -> None:
        case = _finish(c, case)
        if case is None:
            return
        key = tuple(sorted((k, repr(v)) for k, v in case.items()))
        if key not in seen:
            seen.add(key)
            out.append(case)

    for arm in (c.arms or ({},)):
        for di, dtype in enumerate(use_dtypes):
            base = dict(c.base)
            base.update(arm)
            base.pop("k_max", None)
            base["dtype"] = dtype
            lo, hi = c.k_range
            hi = min(hi, int(arm.get("k_max", hi)))
            spot_only = di > 0
            if c.k_key is None:
                emit(dict(base))
                continue
            ks = [lo, hi] if spot_only else [lo, hi, 1]
            for k in ks:
                if lo <= k <= hi:
                    emit({**base, c.k_key: int(k)})
            if spot_only:
                continue
            rows = int(base.get(c.rows_key, 0)) if c.rows_key else 0
            if c.rows_key and rows:
                # k == rows: every slot must fill, none past the end
                kr = min(hi, rows)
                emit({**base, c.k_key: kr, c.rows_key: kr})
                # non-divisible rows (tail tile reachable): an odd
                # prime-ish count defeats every pow2 tile size
                emit({**base, c.k_key: min(hi, 10), c.rows_key: rows + 13})
                # sublane-boundary ±1 for this dtype's tile
                s = dtype_sublane(dtype)
                for r in (s - 1, s, s + 1):
                    if r >= lo:
                        emit({**base, c.k_key: min(hi, max(lo, 1)),
                              c.rows_key: int(r)})
            if c.batch_key:
                emit({**base, c.k_key: min(hi, 10), c.batch_key: 1})
            # lane-boundary k: the fold/candidate-buffer overflow class
            for k in (63, 64, 65, 129):
                if lo <= k <= hi and (not c.rows_key or k <= rows):
                    emit({**base, c.k_key: int(k)})
    for extra in c.extra_cases:
        emit(dict(extra))
    return out


def static_cases(c: KernelContract, cap: int = 48) -> List[dict]:
    """The static engine's binding list: the adversarial sweep's cases
    (first dtype only beyond the boundary set), capped."""
    cases = adversarial_cases(c)
    return cases[:cap]
